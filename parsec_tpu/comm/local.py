"""Single-process loopback comm engine.

Mirrors the reference's inline-progress path: with one rank the comm
engine runs inline on the calling thread (scheduling.c:555-563) and no
messages leave the process. Used by tests and as the default when no
fabric is configured. Multi-"rank" loopback (several Contexts in one
process exchanging activations through shared queues) exercises the full
remote-dep protocol without a network, the way the reference's tests run
2-8 MPI ranks on one node (SURVEY §4).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, List, Optional

from . import device_plane
from .engine import AMTag, CommEngine


class _Fabric:
    """Shared mailbox fabric connecting loopback ranks in one process."""

    def __init__(self, nb_ranks: int):
        self.nb_ranks = nb_ranks
        self.queues: List[queue.Queue] = [queue.Queue() for _ in range(nb_ranks)]
        self.engines: List[Optional["LocalCommEngine"]] = [None] * nb_ranks
        self.mem: Dict[int, Any] = {}
        self._mem_next = 0
        self._lock = threading.Lock()
        self.barrier = threading.Barrier(nb_ranks)

    def register_mem(self, buf: Any) -> int:
        with self._lock:
            h = self._mem_next
            self._mem_next += 1
            self.mem[h] = buf
            return h


class LocalCommEngine(CommEngine):
    def __init__(self, rank: int = 0, nb_ranks: int = 1,
                 fabric: Optional[_Fabric] = None):
        super().__init__(rank, nb_ranks)
        self.fabric = fabric or _Fabric(nb_ranks)
        self.fabric.engines[rank] = self
        # taskpool name -> this rank's termdet monitor (the reference keys
        # remote activity per taskpool id; waves are per-taskpool)
        self._termdet_monitors: Dict[str, object] = {}
        # activations for taskpools this rank has not registered yet, parked
        # until add_taskpool (reference: unknown-taskpool noobj fifo,
        # remote_dep_mpi.c:1857-1869) — dropping them would lose the dep
        self._parked: Dict[str, List[tuple]] = {}
        self._progress_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @classmethod
    def make_fabric(cls, nb_ranks: int) -> List["LocalCommEngine"]:
        fab = _Fabric(nb_ranks)
        return [cls(r, nb_ranks, fab) for r in range(nb_ranks)]

    # -- lifecycle: dedicated progress thread (remote_dep_dequeue_main
    # analog, remote_dep_mpi.c:461) ---------------------------------------
    def enable(self) -> None:
        super().enable()
        if self.nb_ranks > 1 and self._progress_thread is None:
            self._stop.clear()
            t = threading.Thread(target=self._progress_main,
                                 name=f"parsec-comm-{self.rank}", daemon=True)
            self._progress_thread = t
            t.start()

    def disable(self) -> None:
        super().disable()
        self._stop.set()
        if self._progress_thread is not None:
            self._progress_thread.join(timeout=2.0)
            self._progress_thread = None

    def _progress_main(self) -> None:
        while not self._stop.is_set():
            self.progress(block_s=0.05)

    # -- AMs --------------------------------------------------------------
    def send_am(self, tag: int, dst_rank: int, msg: Any) -> None:
        self.fabric.queues[dst_rank].put((tag, self.rank, msg))

    def progress(self, block_s: float = 0.0) -> int:
        n = 0
        q = self.fabric.queues[self.rank]
        while True:
            try:
                tag, src, msg = q.get(timeout=block_s) if block_s and n == 0 \
                    else q.get_nowait()
            except queue.Empty:
                return n
            cb = self._am_callbacks.get(tag)
            if cb is not None:
                cb(src, msg)
            n += 1

    # -- one-sided over the shared heap -----------------------------------
    def mem_register(self, buffer: Any) -> int:
        return self.fabric.register_mem(buffer)

    def mem_unregister(self, handle: int) -> None:
        self.fabric.mem.pop(handle, None)

    def put(self, local_handle: int, remote_rank: int, remote_handle: int,
            on_local_done: Optional[Callable] = None,
            on_remote_done_tag: Optional[int] = None) -> None:
        self.fabric.mem[remote_handle] = self.fabric.mem[local_handle]
        if on_local_done is not None:
            on_local_done()
        if on_remote_done_tag is not None:
            self.send_am(on_remote_done_tag, remote_rank, remote_handle)

    def get(self, remote_rank: int, remote_handle: int, local_handle: int,
            on_done: Optional[Callable] = None) -> None:
        self.fabric.mem[local_handle] = self.fabric.mem[remote_handle]
        if on_done is not None:
            on_done()

    # -- runtime services -------------------------------------------------
    def remote_dep_activate(self, task, ref, target_rank: int) -> None:
        """Loopback remote-dep: ship (class name, locals, flow, value) to
        the owning rank's engine, which re-activates it there (the wire
        protocol's eager path — remote_dep_wire_activate + inline payload,
        remote_dep.h:41-48)."""
        self.remote_dep_activate_multi(task, target_rank, [ref])

    def remote_dep_activate_multi(self, task, target_rank: int,
                                  refs) -> None:
        """Packed multi-target activation: N deps of ONE produced value
        to one rank ride a single loopback message carrying the payload
        once (the reference's one-data-per-(dep, rank) aggregation).

        Device-direct (``comm.device_direct`` + a registered comm mesh,
        compiled/spmd.py): a device-resident value moves as an XLA
        device-to-device ``device_put`` onto the CONSUMER rank's device
        (the ICI edge on real hardware) and the activation is accounted
        at its CONTROL-frame size — the payload never touches host
        memory or the wire counters."""
        tp = task.taskpool
        monitor = tp.monitor
        monitor.outgoing_message_start(target_rank)
        value = refs[0].value
        targets = self._targets_of(refs)
        msg = {"taskpool": tp.name, "targets": targets}
        dev = device_plane.direct_device_for(target_rank)
        if dev is not None and device_plane.has_device(value):
            value = device_plane.place_value(value, dev)
            msg["dev_direct"] = True
            nbytes = device_plane.control_bytes(targets)
        else:
            nbytes = self.payload_bytes(value)
        msg["value"] = value
        self.record_msg("sent", "activate", target_rank, nbytes)
        self._span_sent(self._span_attach(tp, task, msg), target_rank,
                        nbytes)
        self.send_am(AMTag.ACTIVATE, target_rank, msg)
        monitor.outgoing_message_end(target_rank)

    def remote_dep_broadcast(self, task, rank_refs) -> None:
        """Tree-routed broadcast over the loopback fabric: same
        participant-list/topology contract as the socket engine
        (remote_dep.c:334-413) — the root sends one message per TREE
        EDGE, receivers re-forward to their children before releasing
        locally. Loopback has no failure detection, so reparenting
        never fires here."""
        from .collectives import bcast_live_children
        tp = task.taskpool
        monitor = tp.monitor
        msg, parts, topo, fanout = self._bcast_envelope(tp, rank_refs)
        value = next(iter(rank_refs.values()))[0].value
        msg["value"] = value
        nbytes = self.payload_bytes(value)
        direct = device_plane.has_device(value)
        bsp = self._span_attach(tp, task, msg)
        for c in bcast_live_children(topo, parts, self.rank, fanout,
                                     self.peer_alive):
            monitor.outgoing_message_start(c)
            cmsg, cnb = msg, nbytes
            if direct:
                dev = device_plane.direct_device_for(c)
                if dev is not None:
                    # per-TREE-EDGE device-to-device copy: each child
                    # gets the value on ITS device, the wire carries
                    # only the control frame
                    cmsg = dict(msg)
                    cmsg["value"] = device_plane.place_value(value, dev)
                    cmsg["dev_direct"] = True
                    cnb = device_plane.control_bytes(
                        msg.get("targets_by_rank", {}))
            self.record_msg("sent", "bcast", c, cnb)
            self._span_sent(bsp, c, cnb)
            self.send_am(AMTag.ACTIVATE, c, cmsg)
            monitor.outgoing_message_end(c)

    def install_activate_handler(self, context) -> None:
        """Wire the ACTIVATE AM into a context: reconstruct the
        SuccessorRefs and count the deps on the local taskpool replica
        (remote_dep_mpi_save_activate_cb analog); broadcast messages
        re-forward down the tree before the local release."""
        from ..core.taskpool import SuccessorRef
        from .collectives import BcastTopology, bcast_live_children

        def _on_activate(src_rank: int, msg: Dict) -> None:
            with context._lock:
                tp = next((t for t in context._active_taskpools
                           if t.name == msg["taskpool"]), None)
                if tp is None:
                    # taskpool not registered here yet: park the activation
                    # (drained by register_termdet when add_taskpool runs)
                    self._parked.setdefault(msg["taskpool"], []).append(
                        (src_rank, msg))
                    return
            tp.monitor.incoming_message_start(src_rank)
            value = msg["value"]
            direct = msg.get("dev_direct", False)
            nbytes = device_plane.control_bytes(msg["targets_by_rank"]
                                                if "bcast" in msg
                                                else msg["targets"]) \
                if direct else self.payload_bytes(value)
            if "bcast" in msg:
                b = msg["bcast"]
                children = bcast_live_children(
                    BcastTopology(b["topo"]), b["parts"], self.rank,
                    b.get("fanout", 0), self.peer_alive)
                if children and context.pins is not None:
                    context.pins.bcast_fwd(tp.name, src_rank, children,
                                           nbytes)
                for c in children:
                    tp.monitor.outgoing_message_start(c)
                    cmsg = msg
                    if direct:
                        dev = device_plane.direct_device_for(c)
                        if dev is not None:
                            # forwarded tree edge: re-place the payload
                            # onto the CHILD's device (D2D), bytes stay
                            # off the wire accounting
                            cmsg = dict(msg)
                            cmsg["value"] = device_plane.place_value(
                                value, dev)
                    self.record_msg("sent", "bcast", c, nbytes)
                    self._span_sent(msg.get("span"), c, nbytes)
                    self.send_am(AMTag.ACTIVATE, c, cmsg)
                    tp.monitor.outgoing_message_end(c)
                self.record_msg("recv", "bcast", src_rank, nbytes)
            else:
                self.record_msg("recv", "activate", src_rank, nbytes)
            targets = self._msg_targets(msg)
            ready = []
            for t in targets:
                tc = tp.get_task_class(t["class"])
                ref = SuccessorRef(task_class=tc,
                                   locals=tuple(t["locals"]),
                                   flow_name=t["flow"], value=value,
                                   dep_index=t["dep_index"],
                                   priority=t["priority"])
                new_task = tp.activate_dep(ref)
                if new_task is not None:
                    ready.append(new_task)
            self._span_recv(msg, src_rank, nbytes, ready)
            if ready:
                context.schedule(None, ready)
            tp.monitor.incoming_message_end(src_rank)

        self.tag_register(AMTag.ACTIVATE, _on_activate)

        def _on_dtd_control(src_rank: int, msg: Dict) -> None:
            tp = context.find_taskpool(msg["taskpool"], active_only=False)
            if tp is not None and hasattr(tp, "_on_dtd_control"):
                tp._on_dtd_control(src_rank, msg)

        self.tag_register(AMTag.DTD_CONTROL, _on_dtd_control)

    def taskpool_registered(self, tp) -> None:
        """Called by Context.add_taskpool once ``tp`` is visible in
        _active_taskpools: re-deliver activations that arrived early."""
        parked = self._parked.pop(tp.name, [])
        cb = self._am_callbacks.get(AMTag.ACTIVATE)
        for (src_rank, msg) in parked:
            cb(src_rank, msg)

    def sync(self) -> None:
        """Real barrier across loopback ranks (each rank runs on its own
        thread): required by collective protocols like DTD flush."""
        if self.nb_ranks > 1:
            self.fabric.barrier.wait(timeout=60.0)

    # -- termdet services -------------------------------------------------
    def register_termdet(self, name: str, monitor) -> None:
        """Called by Context.add_taskpool: associates this rank's monitor
        for taskpool ``name`` so waves/triggers can reach every replica."""
        monitor._termdet_name = name
        self._termdet_monitors[name] = monitor

    def _peer_monitors(self, name: str):
        return [(e, e._termdet_monitors.get(name))
                for e in self.fabric.engines if e is not None]

    def start_termdet_wave(self, monitor) -> None:
        """Synchronous loopback wave: sum every rank's (sent, received,
        idle) for the monitor's taskpool; a rank that has not registered
        its replica yet counts as busy (the wave fails and is retried on a
        later transition). A successful wave terminates ALL replicas."""
        name = getattr(monitor, "_termdet_name", None)
        peers = self._peer_monitors(name) if name is not None else []
        monitors = [m for (_, m) in peers]
        if name is None or any(m is None for m in monitors) \
                or len(monitors) < self.nb_ranks:
            monitor.wave_result(0, 1, False)     # unready fabric: fail wave
            return
        contributions = [m.local_wave_contribution() for m in monitors]
        total_sent = sum(c[0] for c in contributions)
        total_recv = sum(c[1] for c in contributions)
        all_idle = all(c[2] for c in contributions)
        for m in monitors:
            m.wave_result(total_sent, total_recv, all_idle)

    def broadcast_user_trigger(self, monitor) -> None:
        name = getattr(monitor, "_termdet_name", None)
        if name is None:
            return
        for e, peer in self._peer_monitors(name):
            if peer is not None and peer is not monitor:
                peer.trigger(propagate=False)
