"""Abstract communication engine (parsec_comm_engine.h:161-183 analog).

The reference engine contract: active-message tag registration/callbacks,
memory register/retrieve, one-sided put/get with local+remote completion
callbacks, pack/unpack, progress, sync. Tags below
``PARSEC_CE_REMOTE_DEP_MAX_CTRL_TAG`` are reserved for the runtime
(parsec_comm_engine.h:29-38); termdet modules own dedicated tags.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Dict, List, Optional


class AMTag(enum.IntEnum):
    """Reserved active-message tags (parsec_comm_engine.h:29-38 analog)."""
    ACTIVATE = 0          # REMOTE_DEP_ACTIVATE_TAG
    GET_DATA = 1          # REMOTE_DEP_GET_DATA_TAG
    PUT_DATA = 2          # REMOTE_DEP_PUT_DATA_TAG
    TERMDET_FOURCOUNTER = 3
    TERMDET_USER_TRIGGER = 4
    DTD_CONTROL = 5
    BARRIER = 6
    FIRST_USER_TAG = 8

MAX_REGISTERED_TAGS = 32     # PARSEC_MAX_REGISTERED_TAGS (parsec_comm_engine.h:24)


class CommEngine:
    """Engine contract. Rank-count/rank identity + AM + one-sided ops.

    Implementations: :class:`~parsec_tpu.comm.local.LocalCommEngine`
    (single-process loopback for tests and inline progress) and future
    DCN transports. The compiled SPMD path bypasses this engine entirely —
    tile payloads move as XLA collectives over ICI.
    """

    def __init__(self, rank: int = 0, nb_ranks: int = 1):
        self.rank = rank
        self.nb_ranks = nb_ranks
        self._am_callbacks: Dict[int, Callable] = {}
        self._enabled = False
        # flying-message counters (remote_dep.h:355-365 analog) — SDE
        # gauges and the comm trace read these
        self.stats = {"activations_sent": 0, "activations_recv": 0,
                      "bytes_sent": 0, "bytes_recv": 0}
        self._stats_lock = threading.Lock()
        self._trace = None

    # -- instrumentation (profiling msg-size info, remote_dep.h:374-384) --
    def install_trace(self, trace) -> None:
        """Attach a profiling.trace.Trace: every activation send/recv is
        recorded with its payload size (the reference's MPI_ACTIVATE
        events + msg_size info struct that check-comms.py asserts on)."""
        self._trace = trace

    @staticmethod
    def payload_bytes(value: Any) -> int:
        """Best-effort payload size of an activation value. Containers
        (the transformer chain ships (acc, m, l) state tuples) count the
        sum of their elements."""
        if value is None:
            return 0
        nb = getattr(value, "nbytes", None)
        if nb is not None:
            return int(nb)
        if isinstance(value, (bytes, bytearray)):
            return len(value)
        if isinstance(value, (tuple, list)):
            return sum(CommEngine.payload_bytes(v) for v in value)
        if isinstance(value, dict):
            return sum(CommEngine.payload_bytes(v) for v in value.values())
        if isinstance(value, str):
            return len(value.encode())
        # scalar payloads (chain-of-scalars taskpools): a wire estimate so
        # byte stats/check-comms assertions see nonzero traffic
        return 8

    def record_msg(self, direction: str, kind: str, peer: int,
                   nbytes: int) -> None:
        with self._stats_lock:
            if direction == "sent":
                self.stats["activations_sent"] += 1
                self.stats["bytes_sent"] += nbytes
            else:
                self.stats["activations_recv"] += 1
                self.stats["bytes_recv"] += nbytes
        if self._trace is not None:
            self._trace.event(f"comm_{kind}", direction, stream_id=-1,
                              object_id=peer, info={"msg_size": nbytes})

    # -- lifecycle --------------------------------------------------------
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    # -- active messages --------------------------------------------------
    def tag_register(self, tag: int, cb: Callable[[int, Any], None]) -> None:
        if len(self._am_callbacks) >= MAX_REGISTERED_TAGS:
            raise RuntimeError("AM tag space exhausted")
        self._am_callbacks[tag] = cb

    def tag_unregister(self, tag: int) -> None:
        self._am_callbacks.pop(tag, None)

    def send_am(self, tag: int, dst_rank: int, msg: Any) -> None:
        raise NotImplementedError

    # -- one-sided --------------------------------------------------------
    def mem_register(self, buffer: Any) -> Any:
        """Returns an opaque memory handle exchangeable over AMs."""
        raise NotImplementedError

    def mem_unregister(self, handle: Any) -> None:
        raise NotImplementedError

    def put(self, local_handle: Any, remote_rank: int, remote_handle: Any,
            on_local_done: Optional[Callable] = None,
            on_remote_done_tag: Optional[int] = None) -> None:
        raise NotImplementedError

    def get(self, remote_rank: int, remote_handle: Any, local_handle: Any,
            on_done: Optional[Callable] = None) -> None:
        raise NotImplementedError

    # -- progress ---------------------------------------------------------
    def progress(self) -> int:
        """Advance pending communications; returns #completions."""
        return 0

    def sync(self) -> None:
        pass

    # -- runtime services built on the engine -----------------------------
    def remote_dep_activate(self, task, ref, target_rank: int) -> None:
        """parsec_remote_dep_activate analog — forward one satisfied dep to
        the rank owning the successor."""
        raise NotImplementedError

    def start_termdet_wave(self, monitor) -> None:
        raise NotImplementedError

    def broadcast_user_trigger(self, monitor) -> None:
        raise NotImplementedError
