"""Abstract communication engine (parsec_comm_engine.h:161-183 analog).

The reference engine contract: active-message tag registration/callbacks,
memory register/retrieve, one-sided put/get with local+remote completion
callbacks, pack/unpack, progress, sync. Tags below
``PARSEC_CE_REMOTE_DEP_MAX_CTRL_TAG`` are reserved for the runtime
(parsec_comm_engine.h:29-38); termdet modules own dedicated tags.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class AMTag(enum.IntEnum):
    """Reserved active-message tags (parsec_comm_engine.h:29-38 analog)."""
    ACTIVATE = 0          # REMOTE_DEP_ACTIVATE_TAG
    GET_DATA = 1          # REMOTE_DEP_GET_DATA_TAG
    PUT_DATA = 2          # REMOTE_DEP_PUT_DATA_TAG
    TERMDET_FOURCOUNTER = 3
    TERMDET_USER_TRIGGER = 4
    DTD_CONTROL = 5
    BARRIER = 6
    TILE_FETCH = 7        # one-sided collection-tile GET (RMA analog)
    BYE = 8               # orderly-shutdown notice (MPI_Finalize analog):
    #                       a peer closing WITHOUT it is a failure
    DATA_SEG = 9          # pipelined payload segment of an activation
    #                       stream (segmented rendezvous / broadcast edge)
    RECOVER = 10          # fault-recovery control plane: completed-set
    #                       allgather across the live rank set
    #                       (data/recovery.exchange_completed)
    CLOCK = 11            # clock-offset pingpong (distributed-trace
    #                       timestamp alignment, profiling/spans.py)
    ELASTIC = 12          # elastic-capacity control plane: autoscaler
    #                       heartbeats, drain/adopt/migrate commands and
    #                       their acks (serving/elastic.py)
    FIRST_USER_TAG = 13

MAX_REGISTERED_TAGS = 32     # PARSEC_MAX_REGISTERED_TAGS (parsec_comm_engine.h:24)


class CommEngine:
    """Engine contract. Rank-count/rank identity + AM + one-sided ops.

    Implementations: :class:`~parsec_tpu.comm.local.LocalCommEngine`
    (single-process loopback for tests and inline progress) and future
    DCN transports. The compiled SPMD path bypasses this engine entirely —
    tile payloads move as XLA collectives over ICI.
    """

    def __init__(self, rank: int = 0, nb_ranks: int = 1):
        self.rank = rank
        self.nb_ranks = nb_ranks
        self._am_callbacks: Dict[int, Callable] = {}
        self._enabled = False
        # flying-message counters (remote_dep.h:355-365 analog) — SDE
        # gauges and the comm trace read these
        self.stats = {"activations_sent": 0, "activations_recv": 0,
                      "bytes_sent": 0, "bytes_recv": 0}
        # per-message-kind wire accounting (profiling msg-size info,
        # remote_dep.h:374-384): "activate" = p2p activation payloads,
        # "bcast" = tree-edge broadcast payloads (the root's entry IS
        # its data-plane egress), "seg" = pipelined payload segments
        # (wire-level), "put"/"get" = classic rendezvous legs. The
        # counters live in the shared metrics registry
        # (profiling/metrics.py — the live /metrics export surface);
        # the per-engine ``stats_by_kind`` dict accessor remains as a
        # VIEW over this engine's own children, distinguished from
        # same-rank siblings (loopback fabrics) by the engine label.
        from ..profiling import metrics as metrics_mod
        self._engine_id = str(metrics_mod.next_engine_id())
        # profiling.metrics=0 (the bench A/B baseline): count into a
        # PRIVATE unexported registry instead — stats_by_kind keeps its
        # accounting contract either way, but the kill switch really
        # does keep the global export surface out of the hot path
        wire_reg = metrics_mod.registry() if metrics_mod.enabled() \
            else metrics_mod.MetricsRegistry()
        self._m_msgs = wire_reg.counter(
            "parsec_wire_msgs_total",
            "wire messages by kind (activate/bcast/seg/put/get)",
            ("rank", "engine", "kind", "dir"))
        self._m_bytes = wire_reg.counter(
            "parsec_wire_bytes_total", "wire payload bytes by kind",
            ("rank", "engine", "kind", "dir"))
        self._kind_children: Dict[Tuple[str, str], tuple] = {}
        self._stats_lock = threading.Lock()
        self._trace = None
        # one-sided tile-fetch service (RMA GET over AMs): exposed
        # collections by name + in-flight fetch futures
        self._exposed_colls: Dict[str, Any] = {}
        self._fetch_futures: Dict[int, Any] = {}
        # req ids whose reply should stage per segment into device
        # memory (fetch_tiles(stage=True) — the HBM remote stage-in);
        # transports without segmented replies simply never read it
        self._fetch_stage: Dict[int, bool] = {}
        self._fetch_next = 0
        self._fetch_lock = threading.Lock()
        self.tag_register(AMTag.TILE_FETCH, self._on_tile_fetch)

    # -- instrumentation (profiling msg-size info, remote_dep.h:374-384) --
    def install_trace(self, trace) -> None:
        """Attach a profiling.trace.Trace: every activation send/recv is
        recorded with its payload size (the reference's MPI_ACTIVATE
        events + msg_size info struct that check-comms.py asserts on)."""
        self._trace = trace

    @staticmethod
    def payload_bytes(value: Any) -> int:
        """Best-effort payload size of an activation value. Containers
        (the transformer chain ships (acc, m, l) state tuples) count the
        sum of their elements."""
        if value is None:
            return 0
        nb = getattr(value, "nbytes", None)
        if nb is not None:
            return int(nb)
        if isinstance(value, (bytes, bytearray)):
            return len(value)
        if isinstance(value, (tuple, list)):
            return sum(CommEngine.payload_bytes(v) for v in value)
        if isinstance(value, dict):
            return sum(CommEngine.payload_bytes(v) for v in value.values())
        if isinstance(value, str):
            return len(value.encode())
        # scalar payloads (chain-of-scalars taskpools): a wire estimate so
        # byte stats/check-comms assertions see nonzero traffic
        return 8

    def _kind_counters(self, kind: str, direction: str) -> tuple:
        """This engine's (msgs, bytes) registry children for one
        (kind, direction) — resolved once, then a lock-free dict hit."""
        key = (kind, direction)
        pair = self._kind_children.get(key)
        if pair is None:
            with self._stats_lock:
                pair = self._kind_children.get(key)
                if pair is None:
                    labels = {"rank": str(self.rank),
                              "engine": self._engine_id,
                              "kind": kind, "dir": direction}
                    pair = self._kind_children[key] = (
                        self._m_msgs.labels(**labels),
                        self._m_bytes.labels(**labels))
        return pair

    def record_msg(self, direction: str, kind: str, peer: int,
                   nbytes: int) -> None:
        with self._stats_lock:
            if kind in ("activate", "bcast"):
                # only activation-class messages feed the aggregate
                # payload-level counters: segments and rendezvous legs
                # carry bytes of an already-counted activation, so
                # adding them would double-count every large payload
                # (and break the one-message-per-(value, rank) dedup
                # assertions)
                if direction == "sent":
                    self.stats["activations_sent"] += 1
                    self.stats["bytes_sent"] += nbytes
                else:
                    self.stats["activations_recv"] += 1
                    self.stats["bytes_recv"] += nbytes
        m_msgs, m_bytes = self._kind_counters(kind, direction)
        m_msgs.inc()
        m_bytes.inc(nbytes)
        if self._trace is not None:
            self._trace.event(f"comm_{kind}", direction, stream_id=-1,
                              object_id=peer, info={"msg_size": nbytes})

    @property
    def stats_by_kind(self) -> Dict[str, Dict[str, int]]:
        """Per-kind wire accounting VIEW over this engine's registry
        counters (the ad-hoc dict this used to be now lives in the
        shared metrics registry; shape unchanged:
        ``{kind: {sent_msgs, sent_bytes, recv_msgs, recv_bytes}}``)."""
        out: Dict[str, Dict[str, int]] = {}
        with self._stats_lock:
            items = list(self._kind_children.items())
        for (kind, direction), (m_msgs, m_bytes) in items:
            bk = out.setdefault(kind, {"sent_msgs": 0, "sent_bytes": 0,
                                       "recv_msgs": 0, "recv_bytes": 0})
            bk[f"{direction}_msgs"] = int(m_msgs.value())
            bk[f"{direction}_bytes"] = int(m_bytes.value())
        return out

    def clock_meta(self, root: int = 0) -> Dict[str, float]:
        """Clock-alignment metadata for dumped traces: the offset of
        this process's ``perf_counter`` domain to ``root``'s. Engines
        whose ranks share one process (loopback) share one clock —
        offset 0; the socket engine measures it over the wire."""
        return {"clock_offset_s": 0.0}

    # -- lifecycle --------------------------------------------------------
    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False
        # unexport this engine's wire-counter children (the per-engine
        # label would otherwise grow the registry across engine churn —
        # one engine per run in harness loops). The child objects stay
        # alive in _kind_children, so post-run stats_by_kind reads keep
        # working.
        with self._stats_lock:
            keys = [(kind, direction)
                    for (kind, direction) in self._kind_children]
        for kind, direction in keys:
            labels = {"rank": str(self.rank), "engine": self._engine_id,
                      "kind": kind, "dir": direction}
            self._m_msgs.remove(**labels)
            self._m_bytes.remove(**labels)

    # -- active messages --------------------------------------------------
    def tag_register(self, tag: int, cb: Callable[[int, Any], None]) -> None:
        if len(self._am_callbacks) >= MAX_REGISTERED_TAGS:
            raise RuntimeError("AM tag space exhausted")
        self._am_callbacks[tag] = cb

    def tag_unregister(self, tag: int) -> None:
        self._am_callbacks.pop(tag, None)

    def send_am(self, tag: int, dst_rank: int, msg: Any) -> None:
        raise NotImplementedError

    # -- one-sided --------------------------------------------------------
    def mem_register(self, buffer: Any) -> Any:
        """Returns an opaque memory handle exchangeable over AMs."""
        raise NotImplementedError

    def mem_unregister(self, handle: Any) -> None:
        raise NotImplementedError

    def put(self, local_handle: Any, remote_rank: int, remote_handle: Any,
            on_local_done: Optional[Callable] = None,
            on_remote_done_tag: Optional[int] = None) -> None:
        raise NotImplementedError

    def get(self, remote_rank: int, remote_handle: Any, local_handle: Any,
            on_done: Optional[Callable] = None) -> None:
        raise NotImplementedError

    # -- one-sided tile fetch (RMA GET over AMs) --------------------------
    # The reference's rendezvous GET moves registered remote memory
    # (remote_dep_mpi.c:1594-1729). The runtime analog here: a worker
    # fetches a remote COLLECTION tile by (name, key); the owner's comm
    # thread reads its collection and replies. Safe whenever dataflow
    # ordering (e.g. a CTL-gather) guarantees the owner's tile is final
    # — the direct-memory gathered-operand pattern of reference JDF
    # bodies, made rank-correct.

    def expose_collection(self, dc, scope: str = "") -> None:
        """Make ``dc`` fetchable from other ranks (weakly held). The
        wire identity is ``(scope, dc.name)`` — the scope is the owning
        taskpool's name (taskpool names are already the cross-rank
        registry identity), so same-named collections of different
        taskpools never alias. A live identity clash is a user error
        (duplicate taskpool name) and raises rather than silently
        serving the wrong tiles."""
        import weakref
        ident = (scope, dc.name)
        old = self._exposed_colls.get(ident)
        if old is not None:
            cur = old()
            if cur is not None and cur is not dc:
                raise ValueError(
                    f"collection identity {ident!r} already exposed by "
                    f"a different live collection; tile-fetch "
                    f"identities must be unique per rank")
        self._exposed_colls[ident] = weakref.ref(dc)

    def _on_tile_fetch(self, src: int, msg: Any) -> None:
        if msg.get("reply"):
            with self._fetch_lock:
                fut = self._fetch_futures.pop(msg["req"], None)
            if fut is not None:
                if "error" in msg:
                    fut.set(("error", msg["error"]))
                else:
                    fut.set(("ok", msg["value"]))
            return
        try:
            import numpy as np
            ident = (msg.get("scope", ""), msg["name"])
            ref = self._exposed_colls.get(ident)
            dc = ref() if ref is not None else None
            if dc is None:
                raise KeyError(f"collection {ident!r} not exposed "
                               f"on rank {self.rank}")
            value = np.asarray(dc.data_of(tuple(msg["key"])))
            reply = {"reply": True, "req": msg["req"], "value": value}
        except Exception as exc:  # noqa: BLE001 — cross the wire, not die
            reply = {"reply": True, "req": msg["req"],
                     "error": str(exc)[:500]}
        self.send_am(AMTag.TILE_FETCH, src, reply)

    def fetch_tile(self, dc, key, owner: int, timeout: float = 120.0,
                   scope: str = "", stage: bool = False):
        """Blocking GET of tile ``key`` of collection ``dc`` from
        ``owner`` (local reads short-circuit). ``scope`` must match the
        owner's :meth:`expose_collection` scope (the taskpool name).
        The caller is responsible for ordering (the tile must be final
        on the owner)."""
        return self.fetch_tiles(dc, [(key, owner)], timeout=timeout,
                                scope=scope, stage=stage)[0]

    def fetch_tiles(self, dc, keys_owners, timeout: float = 120.0,
                    scope: str = "", stage: bool = False) -> list:
        """Concurrent multi-tile GET: fire every request, then wait —
        one link round trip for the batch instead of one per tile
        (sequential blocking fetches on a ~100 ms-class link serialize
        brutally). ``keys_owners``: iterable of (key, owner); local
        tiles resolve inline. Returns values in order. ``stage=True``
        asks transports with segmented replies to reassemble each tile
        with per-segment H2D straight into device memory (the HBM
        remote stage-in — the value then arrives as a device array)."""
        from ..core.future import Future
        slots: list = []
        reqs: list = []
        for key, owner in keys_owners:
            if owner == self.rank or self.nb_ranks == 1:
                slots.append(("local", dc.data_of(key), key, owner))
                continue
            fut = Future()
            fut.owner = owner     # failure detection fails futures by peer
            if not self.peer_alive(owner):
                # a dead owner's frame would be dropped and the future
                # never fulfilled — fail NOW instead of timing out
                fut.set(("error", f"peer rank {owner} is dead"))
                slots.append(("fut", (fut, None), key, owner))
                continue
            with self._fetch_lock:
                req = self._fetch_next
                self._fetch_next += 1
                self._fetch_futures[req] = fut
                if stage:
                    self._fetch_stage[req] = True
            reqs.append(req)
            self.send_am(AMTag.TILE_FETCH, owner,
                         {"name": dc.name, "scope": scope,
                          "key": tuple(key), "req": req})
            if not self.peer_alive(owner):
                # peer died between the pre-check and the send: the
                # engine's death sweep may have run before this future
                # was registered — fail it here (pop guards against
                # double-set by the sweep)
                with self._fetch_lock:
                    popped = self._fetch_futures.pop(req, None)
                if popped is not None:
                    popped.set(("error", f"peer rank {owner} is dead"))
            slots.append(("fut", (fut, req), key, owner))
        out = []
        try:
            for kind, payload, key, owner in slots:
                if kind == "local":
                    out.append(payload)
                    continue
                fut, req = payload
                status, value = fut.get(timeout=timeout)
                if status == "error":
                    raise RuntimeError(
                        f"tile fetch ({dc.name!r}, {key}) from rank "
                        f"{owner} failed: {value}")
                out.append(value)
        finally:
            # reply handler pops on fulfillment; a timeout/error on ANY
            # slot must not leak the remaining futures (or let stale
            # late replies fulfill abandoned ones)
            with self._fetch_lock:
                for req in reqs:
                    self._fetch_futures.pop(req, None)
                    self._fetch_stage.pop(req, None)
        return out

    def peer_alive(self, rank: int) -> bool:
        """False once ``rank`` is known dead (failure detection).
        Engines without failure detection report every peer alive."""
        return True

    def world_status(self) -> Dict[str, Any]:
        """Capacity view of the rank set (the ``statusz`` capacity
        block and the elastic controller both read this): configured =
        the world size this engine was built with, world = the current
        (possibly grown) size, plus live / departed (orderly drain) /
        dead (failure) partitions. Engines without failure detection or
        elasticity report a full static mesh."""
        return {"configured": self.nb_ranks, "world": self.nb_ranks,
                "live": list(range(self.nb_ranks)), "departed": [],
                "dead": []}

    def recover_exchange(self, token: str, payload: Any, dead_ranks,
                         timeout: float = 60.0) -> Dict[int, Any]:
        """Allgather ``payload`` across the LIVE rank set (everyone
        minus ``dead_ranks``) under a caller-chosen ``token`` — the
        completed-set exchange of survivor-side recovery
        (data/recovery.exchange_completed). Engines without failure
        handling only support the trivial single-rank case."""
        if self.nb_ranks <= 1:
            return {self.rank: payload}
        raise NotImplementedError

    def acknowledge_failure(self) -> None:
        """Shrink-mode continuation (ULFM agreement analog): the caller
        has planned around the recorded dead peers — clear the sticky
        failure so NEW taskpools (the replay pool) may register. The
        dead set itself stays: sends toward dead ranks keep dropping
        and broadcast trees keep routing around them."""

    # -- progress ---------------------------------------------------------
    def progress(self) -> int:
        """Advance pending communications; returns #completions."""
        return 0

    def sync(self) -> None:
        pass

    # -- runtime services built on the engine -----------------------------
    def remote_dep_activate_multi(self, task, target_rank: int,
                                  refs) -> None:
        """Forward SEVERAL satisfied deps that share one produced value
        to one rank. The reference sends one data per (dep, rank)
        (remote_dep.c aggregated activations); transports that can pack
        a multi-target activation override this — the base engine loops
        the single-dep path."""
        for ref in refs:
            self.remote_dep_activate(task, ref, target_rank)

    # -- request-scoped wire spans (profiling/spans.py) -------------------
    def _span_attach(self, tp, task, msg) -> Optional[Dict]:
        """Attach request-span context to an outgoing activation msg:
        ``msg["span"] = {rid, id, parent, src}`` — the hop's span id is
        minted HERE (sender side), parented to the sending task's span
        (or the submission root for startup/eager pushes). Returns the
        span dict, or None when tracing is off or the taskpool carries
        no trace_rid (non-serving traffic stays byte-identical). ONE
        builder for every transport, like _targets_of."""
        if self._trace is None:
            return None
        rid = getattr(tp, "trace_rid", None)
        if rid is None:
            return None
        from ..profiling.spans import next_span_id
        prof = getattr(task, "prof", None) or {}
        b = prof.get("b")         # the trace hook's fused begin stamp
        sp = {"rid": prof.get("rid", rid),
              "id": next_span_id(self.rank),
              "parent": (b[0] if b is not None
                         else getattr(tp, "root_span", None)),
              "src": self.rank}
        msg["span"] = sp
        return sp

    def _span_sent(self, sp: Optional[Dict], dst: int,
                   nbytes: int) -> None:
        """Record one tree-edge/wire send of span ``sp`` toward
        ``dst`` (forwarding nodes call this too — the sent/recv pair
        per edge is what the critpath wire share is computed from)."""
        if sp is None or self._trace is None:
            return
        self._trace.event("wire", "sent", object_id=dst,
                          info={"rid": sp["rid"], "span": sp["id"],
                                "parent": sp["parent"],
                                "src": self.rank, "dst": dst,
                                "nbytes": nbytes})

    def _span_recv(self, msg, src: int, nbytes: int, tasks) -> None:
        """Receive side of a wire hop: record the edge's ``recv`` event
        and parent every task the payload released to the hop's span —
        the cross-rank causal edge of the request tree."""
        sp = msg.get("span")
        if sp is None or self._trace is None:
            return
        self._trace.event("wire", "recv", object_id=src,
                          info={"rid": sp["rid"], "span": sp["id"],
                                "parent": sp["parent"], "src": src,
                                "dst": self.rank, "nbytes": nbytes})
        now = time.perf_counter()
        for t in tasks:
            t.prof["parent_span"] = sp["id"]
            t.prof["rid"] = sp["rid"]
            t.prof["q_t0"] = now       # queue wait starts at release

    @staticmethod
    def _targets_of(refs) -> list:
        """Wire shape of a packed activation's target list — ONE
        definition for every transport (loopback and socket engines
        must never desynchronize on the dep-addressing fields)."""
        return [{"class": ref.task_class.name,
                 "locals": tuple(ref.locals), "flow": ref.flow_name,
                 "dep_index": ref.dep_index,
                 "priority": ref.priority} for ref in refs]

    def _bcast_envelope(self, tp, rank_refs):
        """Wire envelope of one broadcast: the participant list every
        node rebuilds the identical tree from, plus the per-rank packed
        targets — ONE builder for every transport (a parts-ordering or
        key drift between engines would mis-route the tree). Returns
        ``(msg, parts, topology, fanout)``; the caller attaches the
        payload (inline value or stream header)."""
        from .collectives import resolve_fanout, resolve_topology
        topo = resolve_topology(tp)
        fanout = resolve_fanout()
        parts = [self.rank] + sorted(rank_refs)
        targets_by_rank = {r: self._targets_of(refs)
                           for r, refs in rank_refs.items()}
        msg = {"taskpool": tp.name,
               "bcast": {"parts": parts, "topo": topo.value,
                         "fanout": fanout},
               "targets_by_rank": targets_by_rank,
               # per-peer aggregation ranks a packed msg by its most
               # urgent target (remote_dep_mpi.c:1089-1139)
               "priority": max(t["priority"]
                               for ts in targets_by_rank.values()
                               for t in ts)}
        return msg, parts, topo, fanout

    def _msg_targets(self, msg) -> list:
        """This rank's targets of a packed/broadcast activation msg."""
        if "targets" in msg:
            return msg["targets"]
        return msg.get("targets_by_rank", {}).get(self.rank, [])

    def remote_dep_broadcast(self, task, rank_refs) -> None:
        """Route ONE produced value to its consumers on several ranks.
        ``rank_refs``: ``{target_rank: [SuccessorRef, ...]}`` — every ref
        carries the same value. Transports with a tree data plane
        override this (payload travels each tree edge exactly once,
        remote_dep.c:334-413); the base engine falls back to one packed
        activation per rank (star from the producer)."""
        for target_rank, refs in rank_refs.items():
            self.remote_dep_activate_multi(task, target_rank, refs)

    def remote_dep_activate(self, task, ref, target_rank: int) -> None:
        """parsec_remote_dep_activate analog — forward one satisfied dep to
        the rank owning the successor."""
        raise NotImplementedError

    def start_termdet_wave(self, monitor) -> None:
        raise NotImplementedError

    def broadcast_user_trigger(self, monitor) -> None:
        raise NotImplementedError


def resolve_column_tiles(task, dc, keys, dtype=None) -> list:
    """Resolve a task body's gathered operands: local tiles read from
    the collection, remote tiles fetched CONCURRENTLY through the
    owner's comm thread (``CommEngine.fetch_tiles``) under the caller's
    dataflow-ordering guarantee (CTL-gather). The shared helper of the
    direct-memory gathered-operand pattern (build_potrf_left UPDATE,
    build_geqrf_hh PANEL/REDUCE).

    With an HBM manager active (``device.hbm_budget_mb``) and staging
    on, remote tiles are treated as a STAGE-IN SOURCE: the segmented
    fetch lands per segment in device memory and the tile is accounted
    straight into its HBM slot (``HBMManager.fetch_tiles``) — no host
    copy is materialized between the wire and the chip, and device-
    resident operands are returned as device arrays (owner-computes
    reads of remote tiles stop paying the host round trip)."""
    import numpy as np
    dtype = dtype or np.float32
    ctx = task.taskpool.context
    if ctx is None or ctx.nb_ranks <= 1:
        return [np.asarray(dc.data_of(k), dtype=dtype) for k in keys]
    pairs = [(k, dc.rank_of(k)) for k in keys]
    hbm = getattr(ctx, "hbm", None)
    from . import device_plane
    if hbm is not None and device_plane.pipeline_enabled() and \
            ctx.stage_reads:
        vals = hbm.fetch_tiles(dc, pairs, ctx.comm,
                               scope=task.taskpool.name)
        out = []
        for v in vals:
            if device_plane.is_device_array(v):
                out.append(v if str(v.dtype) == str(np.dtype(dtype))
                           else v.astype(dtype))
            else:
                out.append(np.asarray(v, dtype=dtype))
        return out
    vals = ctx.comm.fetch_tiles(dc, pairs, scope=task.taskpool.name)
    return [np.asarray(v, dtype=dtype) for v in vals]
