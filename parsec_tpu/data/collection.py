"""Data collections.

Reference: include/parsec/data_distribution.h:26-100 — a collection is a
vtable of ``rank_of(key)``, ``vpid_of(key)`` and ``data_of(key)`` supplied
by the user, with registered ids so multiple taskpools can reference the
same collection (data_distribution.c).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, Iterable, Optional

_dc_ids = itertools.count(1)


class DataCollection:
    """Base collection vtable (parsec_data_collection_t analog)."""

    #: scratch collections carry intra-DAG temporaries (e.g. QR factor
    #: tiles); compiled executors neither read their host tiles nor
    #: write results back
    scratch = False

    def __init__(self, name: str = "dc", nodes: int = 1, myrank: int = 0):
        self.name = name
        self.dc_id = next(_dc_ids)
        self.nodes = nodes
        self.myrank = myrank

    # -- vtable -----------------------------------------------------------
    def rank_of(self, key) -> int:
        return 0

    def vpid_of(self, key) -> int:
        return 0

    def data_of(self, key) -> Any:
        """Current value of the datum at ``key`` (local keys only)."""
        raise NotImplementedError

    def write_tile(self, key, value) -> None:
        """Store a new version at ``key`` (terminal output deps)."""
        raise NotImplementedError

    def keys(self) -> Iterable:
        raise NotImplementedError

    def is_local(self, key) -> bool:
        return self.rank_of(key) == self.myrank


class LocalCollection(DataCollection):
    """Dict-backed single-rank collection — the simplest data_of/write
    storage, used by tests and as DTD scratch space. ``myrank`` is the
    OWNING rank: in a multi-rank context, tasks whose placement derives
    from a local collection (serving decode pools on a worker rank of
    an elastic mesh) must land on the rank that holds the tiles — the
    old hardwired ``rank_of == 0`` silently shipped every such task to
    rank 0."""

    def __init__(self, name: str = "local", init: Optional[Dict] = None,
                 myrank: int = 0):
        super().__init__(name=name, myrank=myrank)
        self._store: Dict[Any, Any] = dict(init or {})
        self._lock = threading.Lock()

    def rank_of(self, key) -> int:
        return self.myrank

    def data_of(self, key) -> Any:
        with self._lock:
            return self._store.get(key)

    def write_tile(self, key, value) -> None:
        with self._lock:
            self._store[key] = value

    def keys(self):
        with self._lock:
            return list(self._store.keys())

    def drop_tile(self, key) -> None:
        """Forget the tile at ``key`` (no-op when absent) — long-lived
        serving collections reclaim finished requests' tiles."""
        with self._lock:
            self._store.pop(key, None)
