"""Arenas: pooled allocators for communication temporaries.

Reference: parsec/arena.c (295 LoC) — arenas are size+alignment-classed
allocators with freelist caching, used to allocate buffers for incoming
remote data; global caps ``arena_max_used`` / ``arena_max_cached`` bound
total live and cached memory (parsec.c:674-679). An
``parsec_arena_datatype_t`` pairs an arena with a datatype and is
registered per taskpool (parsec_internal.h:41-45).

TPU analog: host-side staging buffers are numpy arrays of one
(shape, dtype) class; device residency is managed by jax, so arenas only
serve the host/comm path (deserialized remote tiles, scratch staging).
Freelist reuse avoids allocator churn on the comm thread exactly like the
reference's elem_cache.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import mca_param

mca_param.register("arena.max_cached_bytes", 1 << 28,
                   help="global cap on bytes cached in arena freelists")
mca_param.register("arena.max_used_bytes", 0,
                   help="global cap on live arena bytes (0 = unlimited)")


class _ArenaStats:
    """Global accounting shared by all arenas (the reference's
    arena_max_used/arena_max_cached counters)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.used_bytes = 0
        self.cached_bytes = 0


_global = _ArenaStats()


def global_stats() -> Dict[str, int]:
    with _global.lock:
        return {"used_bytes": _global.used_bytes,
                "cached_bytes": _global.cached_bytes}


class Arena:
    """One size-class of pooled host buffers (parsec_arena_t analog).

    ``allocate()`` returns a zeroed numpy array of the arena's
    (shape, dtype), reusing a cached buffer when available;
    ``release(buf)`` returns it to the freelist subject to the global
    cached-bytes cap. The used-bytes cap makes over-allocation fail fast
    instead of silently exhausting host memory.
    """

    def __init__(self, shape: Tuple[int, ...], dtype=np.float32,
                 name: str = "arena"):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.name = name
        self.elem_bytes = int(np.prod(self.shape)) * self.dtype.itemsize
        self._freelist: List[np.ndarray] = []
        self._lock = threading.Lock()
        self.nb_allocated = 0      # total constructed (not from cache)
        self.nb_reused = 0

    def allocate(self) -> np.ndarray:
        max_used = int(mca_param.get("arena.max_used_bytes", 0))
        with self._lock:
            buf = self._freelist.pop() if self._freelist else None
        if buf is not None:
            with _global.lock:
                if max_used and \
                        _global.used_bytes + self.elem_bytes > max_used:
                    over = True
                else:
                    over = False
                    _global.cached_bytes -= self.elem_bytes
                    _global.used_bytes += self.elem_bytes
            if over:
                with self._lock:
                    self._freelist.append(buf)
                raise MemoryError(
                    f"arena {self.name}: used-bytes cap {max_used} exceeded")
            with self._lock:
                self.nb_reused += 1
            buf.fill(0)
            return buf
        with _global.lock:
            if max_used and _global.used_bytes + self.elem_bytes > max_used:
                raise MemoryError(
                    f"arena {self.name}: used-bytes cap {max_used} exceeded")
            _global.used_bytes += self.elem_bytes
        with self._lock:
            self.nb_allocated += 1
        return np.zeros(self.shape, dtype=self.dtype)

    def release(self, buf: np.ndarray) -> None:
        if buf.shape != self.shape or buf.dtype != self.dtype:
            raise ValueError(
                f"arena {self.name}: buffer {buf.shape}/{buf.dtype} does not "
                f"belong to class {self.shape}/{self.dtype}")
        max_cached = int(mca_param.get("arena.max_cached_bytes", 1 << 28))
        with _global.lock:
            _global.used_bytes -= self.elem_bytes
            cache_it = _global.cached_bytes + self.elem_bytes <= max_cached
            if cache_it:
                _global.cached_bytes += self.elem_bytes
        if cache_it:
            with self._lock:
                self._freelist.append(buf)

    @property
    def nb_cached(self) -> int:
        with self._lock:
            return len(self._freelist)


@dataclass
class ArenaDatatype:
    """(arena, datatype) pair (parsec_arena_datatype_t analog) — the
    datatype is a ReshapeSpec or dtype describing the wire layout."""
    arena: Arena
    datatype: Any = None


class ArenaRegistry:
    """Per-taskpool arena-datatype registry (the reference indexes these
    per taskpool, or in a hash table for DTD)."""

    def __init__(self) -> None:
        self._by_id: Dict[Any, ArenaDatatype] = {}
        self._lock = threading.Lock()

    def register(self, adt_id, adt: ArenaDatatype) -> None:
        with self._lock:
            self._by_id[adt_id] = adt

    def get(self, adt_id) -> Optional[ArenaDatatype]:
        with self._lock:
            return self._by_id.get(adt_id)
