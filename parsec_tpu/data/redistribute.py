"""Redistribution engine: copy data between tiled collections with
different tile sizes and distributions.

Reference: data_dist/matrix/redistribute/ — a generic
collection→collection redistribute shipped both as a PTG taskpool
(redistribute.jdf + reshuffle variant) and as a DTD version.

Two paths, mirroring the reference:

- :func:`build_redistribute_ptg` — geometry-preserving redistribute
  (same tile grid, any pair of distributions): one COPY task per tile,
  placed on the *destination* owner so the dataflow layer moves each tile
  exactly once (the reshuffle case).
- :func:`insert_redistribute_dtd` — fully general: different tile sizes
  and offsets; each destination tile gathers its overlapping source
  fragments (up to 4 per dst tile when tile sizes differ, more for
  extreme ratios), assembled host-side. Dynamic fragment counts need
  runtime task construction — exactly why the reference also ships a DTD
  version.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..dsl import dtd, ptg
from .matrix import TiledMatrix


def build_redistribute_ptg(src: TiledMatrix, dst: TiledMatrix,
                           name: str = "redistribute") -> ptg.Taskpool:
    """Same-geometry redistribute (tile-grid-preserving reshuffle).

    Two task classes per tile — READ placed on the *source* owner (its
    collection read is local), WRITE on the *destination* owner (its
    terminal write-back is local) — so in distributed mode each tile
    crosses ranks exactly once, as a task-sourced dependency the comm
    layer delivers. Collection reads/writes are always owner-local, the
    invariant the host runtime's owner-computes placement relies on.
    """
    if (src.mt, src.nt, src.mb, src.nb) != (dst.mt, dst.nt, dst.mb, dst.nb):
        raise ValueError("PTG redistribute needs matching tile geometry; "
                         "use insert_redistribute_dtd for general reshapes")
    tp = ptg.Taskpool(name, S=src, D=dst)
    READ = tp.task_class(
        "READ", params=("i", "j"),
        space=lambda g: iter(list(g.S.keys())),
        affinity=lambda g, i, j: (g.S, (i, j)),
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, i, j: (g.S, (i, j)),
            ins=[ptg.In(data=lambda g, i, j: (g.S, (i, j)))],
            outs=[ptg.Out(dst=("WRITE", lambda g, i, j: (i, j), "T"))])])
    WRITE = tp.task_class(
        "WRITE", params=("i", "j"),
        space=lambda g: iter(list(g.D.keys())),
        affinity=lambda g, i, j: (g.D, (i, j)),
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, i, j: (g.D, (i, j)),
            ins=[ptg.In(src=("READ", lambda g, i, j: (i, j), "T"))],
            outs=[ptg.Out(data=lambda g, i, j: (g.D, (i, j)))])])

    @READ.body
    def _read(task, T):
        return T

    @WRITE.body
    def _write(task, T):
        return T

    return tp


def build_rebalance(src: TiledMatrix, new_dist, my_rank: int = 0,
                    name: str = "rebalance"):
    """Elastic-capacity rebalance of a DISTRIBUTED collection onto a
    changed rank set (ISSUE 11 scale-up): build the destination matrix
    with the same tile geometry under ``new_dist`` (e.g. a block-cyclic
    map over the ENLARGED live set) and the PTG redistribute taskpool
    that moves every tile to its new owner — each tile crosses ranks
    exactly once, as task-sourced dependencies the comm layer delivers
    over the grown mesh. Every rank must build and register the SAME
    pool (same ``name``); after ``ctx.wait()`` the returned ``dst`` is
    the rebalanced collection. (Rank-local tenant shards migrate
    through the checkpoint vehicle instead — ``serving/elastic.py``.)

    Returns ``(taskpool, dst)``."""
    dst = TiledMatrix(src.m, src.n, src.mb, src.nb, dist=new_dist,
                      myrank=my_rank, name=f"{src.name}@rebal")
    return build_redistribute_ptg(src, dst, name=name), dst


def _overlaps(lo: int, hi: int, tile: int):
    """Tile indices whose [idx*tile, (idx+1)*tile) intersects [lo, hi)."""
    return range(lo // tile, (hi - 1) // tile + 1)


def insert_redistribute_dtd(tp: "dtd.Taskpool", src: TiledMatrix,
                            dst: TiledMatrix,
                            src_off: Tuple[int, int] = (0, 0),
                            dst_off: Tuple[int, int] = (0, 0),
                            extent: Optional[Tuple[int, int]] = None) -> None:
    """Insert redistribution tasks copying the ``extent``-sized submatrix
    at ``src_off`` of ``src`` to ``dst_off`` of ``dst``; arbitrary tile
    sizes on both sides. One task per destination tile (affinity = dst
    owner) gathers the overlapping source fragments.
    """
    if min(src_off) < 0 or min(dst_off) < 0:
        raise ValueError("offsets must be non-negative")
    if extent is None:
        extent = (min(src.m - src_off[0], dst.m - dst_off[0]),
                  min(src.n - src_off[1], dst.n - dst_off[1]))
    em, en = extent
    if em <= 0 or en <= 0:
        return
    if src_off[0] + em > src.m or src_off[1] + en > src.n:
        raise ValueError("extent exceeds source matrix")
    if dst_off[0] + em > dst.m or dst_off[1] + en > dst.n:
        raise ValueError("extent exceeds destination matrix")

    for di in _overlaps(dst_off[0], dst_off[0] + em, dst.mb):
        for dj in _overlaps(dst_off[1], dst_off[1] + en, dst.nb):
            # destination-tile region clipped to the copied extent,
            # in global dst coordinates
            r0 = max(di * dst.mb, dst_off[0])
            r1 = min((di + 1) * dst.mb, dst_off[0] + em)
            c0 = max(dj * dst.nb, dst_off[1])
            c1 = min((dj + 1) * dst.nb, dst_off[1] + en)
            # same region in src coordinates
            sr0 = r0 - dst_off[0] + src_off[0]
            sr1 = r1 - dst_off[0] + src_off[0]
            sc0 = c0 - dst_off[1] + src_off[1]
            sc1 = c1 - dst_off[1] + src_off[1]
            frags = [(si, sj)
                     for si in _overlaps(sr0, sr1, src.mb)
                     for sj in _overlaps(sc0, sc1, src.nb)]
            # static per-task geometry: one (dst-slice, src-slice) pair per
            # fragment, precomputed so the body is pure assembly
            plan = []
            for (si, sj) in frags:
                fr0 = max(sr0, si * src.mb)
                fr1 = min(sr1, (si + 1) * src.mb)
                fc0 = max(sc0, sj * src.nb)
                fc1 = min(sc1, (sj + 1) * src.nb)
                dst_sl = (slice(fr0 - src_off[0] + dst_off[0] - di * dst.mb,
                                fr1 - src_off[0] + dst_off[0] - di * dst.mb),
                          slice(fc0 - src_off[1] + dst_off[1] - dj * dst.nb,
                                fc1 - src_off[1] + dst_off[1] - dj * dst.nb))
                src_sl = (slice(fr0 - si * src.mb, fr1 - si * src.mb),
                          slice(fc0 - sj * src.nb, fc1 - sj * src.nb))
                plan.append((dst_sl, src_sl))

            def assemble(*vals, _plan=tuple(plan)):
                *fragments, target = vals
                out = np.array(np.asarray(target), copy=True)
                for (dsl_, ssl), frag in zip(_plan, fragments):
                    out[dsl_] = np.asarray(frag)[ssl]
                return out

            args = [dtd.TileArg(src, k, dtd.INPUT) for k in frags]
            args.append(dtd.TileArg(dst, (di, dj), dtd.INOUT, affinity=True))
            tp.insert_task(assemble, *args, name=f"redist({di},{dj})")
