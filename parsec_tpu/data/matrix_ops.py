"""Library matrix operations shipped as PTG taskpools.

Reference: data_dist/matrix/{apply.jdf, map_operator.c, reduce_row.jdf,
reduce_col.jdf, broadcast.jdf} — small parameterized task graphs the
reference ships as library helpers over tiled matrices.

TPU-first divergence: reductions are *binomial trees* expressed in closed
form (log-depth wavefronts that the compiled executor can batch per level),
not linear chains; broadcast reuses the collective topologies of
:mod:`parsec_tpu.comm.collectives` so the same tree shape serves both the
host runtime and the compiled SPMD lowering.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ..comm.collectives import BcastTopology, bcast_tree_children, bcast_tree_parent
from ..dsl import ptg
from .collection import DataCollection
from .matrix import TiledMatrix


def _uplo_keys(A: TiledMatrix, uplo: str) -> List[Tuple[int, int]]:
    if uplo == "lower":
        return [(i, j) for (i, j) in A.keys() if j <= i]
    if uplo == "upper":
        return [(i, j) for (i, j) in A.keys() if i <= j]
    if uplo != "all":
        raise ValueError(f"uplo must be lower/upper/all, not {uplo!r}")
    return list(A.keys())


def build_apply(A: TiledMatrix, op: Callable, uplo: str = "all",
                name: str = "apply") -> ptg.Taskpool:
    """Apply ``op(tile, i, j) -> tile`` to every (uplo-selected) tile of
    ``A`` in place (apply.jdf analog: one independent task per tile)."""
    keys = _uplo_keys(A, uplo)
    tp = ptg.Taskpool(name, A=A, keys=keys)
    APPLY = tp.task_class(
        "APPLY", params=("i", "j"),
        space=lambda g: iter(g.keys),
        affinity=lambda g, i, j: (g.A, (i, j)),
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, i, j: (g.A, (i, j)),
            ins=[ptg.In(data=lambda g, i, j: (g.A, (i, j)))],
            outs=[ptg.Out(data=lambda g, i, j: (g.A, (i, j)))])])

    # needs task.locals → opts out of the shared jit cache (batchable=False)
    @APPLY.body(batchable=False)
    def _body(task, T):
        i, j = task.locals
        return op(T, i, j)

    return tp


def build_map_operator(src: TiledMatrix, dst: TiledMatrix, op: Callable,
                       name: str = "map_operator") -> ptg.Taskpool:
    """``dst(i,j) = op(src_tile, dst_tile)`` over all tiles
    (map_operator.c analog — binary operator over two collections)."""
    if (src.mt, src.nt) != (dst.mt, dst.nt):
        raise ValueError("map_operator: tile grids must match")
    tp = ptg.Taskpool(name, S=src, D=dst)
    MAP = tp.task_class(
        "MAP", params=("i", "j"),
        space=lambda g: iter(list(g.D.keys())),
        affinity=lambda g, i, j: (g.D, (i, j)),
        flows=[
            ptg.FlowSpec(
                "S", ptg.READ,
                tile=lambda g, i, j: (g.S, (i, j)),
                ins=[ptg.In(data=lambda g, i, j: (g.S, (i, j)))]),
            ptg.FlowSpec(
                "D", ptg.RW,
                tile=lambda g, i, j: (g.D, (i, j)),
                ins=[ptg.In(data=lambda g, i, j: (g.D, (i, j)))],
                outs=[ptg.Out(data=lambda g, i, j: (g.D, (i, j)))]),
        ])

    @MAP.body
    def _body(task, S, D):
        return {"D": op(S, D)}

    return tp


def build_broadcast(A: TiledMatrix, root: Tuple[int, int] = (0, 0),
                    topology: BcastTopology = BcastTopology.BINOMIAL,
                    name: str = "broadcast") -> ptg.Taskpool:
    """Copy the value of tile ``root`` into every tile of ``A`` down a
    collective tree (broadcast.jdf analog). The tree is the same
    star/chain/binomial shape the comm layer uses for activation
    propagation (remote_dep.c:334-372), rebuilt identically from the
    participant list at every node."""
    root = tuple(root)
    keys = [root] + [k for k in sorted(A.keys()) if k != root]
    part = list(range(len(keys)))  # linearized participant ids; 0 = root

    tp = ptg.Taskpool(name, A=A, keys=keys, part=part, topo=topology)
    B = tp.task_class(
        "B", params=("x",),
        space=lambda g: ((x,) for x in g.part),
        affinity=lambda g, x: (g.A, g.keys[x]),
        flows=[ptg.FlowSpec(
            "V", ptg.RW,
            tile=lambda g, x: (g.A, g.keys[x]),
            ins=[ptg.In(data=lambda g, x: (g.A, g.keys[x]),
                        guard=lambda g, x: x == 0),
                 ptg.In(src=("B",
                             lambda g, x: (bcast_tree_parent(g.topo, g.part, x),),
                             "V"),
                        guard=lambda g, x: x > 0)],
            outs=[ptg.Out(dst=("B",
                               lambda g, x: [(c,) for c in
                                             bcast_tree_children(g.topo, g.part, x)],
                               "V")),
                  ptg.Out(data=lambda g, x: (g.A, g.keys[x]),
                          guard=lambda g, x: x > 0)])])

    @B.body
    def _body(task, V):
        return V

    return tp


# ---------------------------------------------------------------------------
# Binomial-tree reduction (reduce_row.jdf / reduce_col.jdf analog)
# ---------------------------------------------------------------------------

def _lsb(x: int) -> int:
    """Index of the lowest set bit (x > 0)."""
    return (x & -x).bit_length() - 1


def _owner_exists(j: int, s: int, n: int) -> bool:
    """R(j, s) exists iff j owns a combine at step s: j aligned to
    2^(s+1) and its partner j + 2^s is inside the group."""
    return j % (1 << (s + 1)) == 0 and j + (1 << s) < n


def _last_owner_step(j: int, n: int) -> int:
    """Largest s with R(j, s) existing, or -1 if j never owns (j is only
    ever a leaf partner)."""
    s, last = 0, -1
    while (1 << s) < n:
        if _owner_exists(j, s, n):
            last = s
        s += 1
    return last


def build_reduce(A: TiledMatrix, op: Callable, axis: str = "row",
                 dst: Optional[DataCollection] = None,
                 name: str = "reduce") -> ptg.Taskpool:
    """Tree-reduce tiles of ``A`` with ``op(acc, part) -> acc``.

    ``axis="row"``: reduce each row's tiles into ``dst[(i, 0)]``
    (reduce_row.jdf analog); ``axis="col"``: each column into
    ``dst[(0, j)]`` (reduce_col.jdf); ``axis="all"``: every tile into
    ``dst[(0, 0)]``. ``dst`` defaults to ``A`` itself.

    Unlike the reference's chain reductions, the tree is binomial: task
    R(grp, j, s) combines the accumulator at linear index ``j`` with the
    one at ``j + 2^s``, giving log-depth wavefronts.
    """
    dst = dst if dst is not None else A
    if axis == "row":
        groups = [([(i, j) for j in range(A.nt)], (i, 0))
                  for i in range(A.mt)]
    elif axis == "col":
        groups = [([(i, j) for i in range(A.mt)], (0, j))
                  for j in range(A.nt)]
    elif axis == "all":
        groups = [(sorted(A.keys()), (0, 0))]
    else:
        raise ValueError(f"axis must be row/col/all, not {axis!r}")

    tp = ptg.Taskpool(name, A=A, dst=dst, groups=groups)
    n_of = lambda g, grp: len(g.groups[grp][0])
    key_of = lambda g, grp, j: g.groups[grp][0][j]

    def space(g):
        for grp, (keys, _out) in enumerate(g.groups):
            n = len(keys)
            if n == 1:
                yield (grp, 0, 0)  # degenerate: single COPY-like step
                continue
            s = 0
            while (1 << s) < n:
                for j in range(0, n, 1 << (s + 1)):
                    if _owner_exists(j, s, n):
                        yield (grp, j, s)
                s += 1

    def acc_in_data(g, grp, j, s):
        return (g.A, key_of(g, grp, j))

    def part_src_params(g, grp, j, s):
        j2 = j + (1 << s)
        return (grp, j2, _last_owner_step(j2, n_of(g, grp)))

    def part_from_task(g, grp, j, s):
        """Partner value comes from a task iff the partner owned some
        earlier combine; otherwise it is a leaf read from A."""
        if s == 0 or n_of(g, grp) == 1:
            return False
        j2 = j + (1 << s)
        return _last_owner_step(j2, n_of(g, grp)) >= 0

    def acc_next(g, grp, j, s):
        return (grp, j, s + 1)

    def as_partner(g, grp, j, s):
        """After its last owning step, a nonzero j feeds the PART flow of
        the owner at step lsb(j)."""
        sp = _lsb(j)
        return (grp, j - (1 << sp), sp)

    R = tp.task_class(
        "R", params=("grp", "j", "s"),
        space=space,
        affinity=lambda g, grp, j, s: (g.A, key_of(g, grp, j)),
        flows=[
            ptg.FlowSpec(
                "ACC", ptg.RW,
                tile=lambda g, grp, j, s: (g.A, key_of(g, grp, j)),
                ins=[ptg.In(data=acc_in_data,
                            guard=lambda g, grp, j, s: s == 0),
                     ptg.In(src=("R", lambda g, grp, j, s: (grp, j, s - 1),
                                 "ACC"),
                            guard=lambda g, grp, j, s: s > 0)],
                outs=[ptg.Out(dst=("R", acc_next, "ACC"),
                              guard=lambda g, grp, j, s:
                                  _owner_exists(j, s + 1, n_of(g, grp))),
                      ptg.Out(dst=("R", as_partner, "PART"),
                              guard=lambda g, grp, j, s: j > 0 and
                                  not _owner_exists(j, s + 1, n_of(g, grp))),
                      ptg.Out(data=lambda g, grp, j, s:
                                  (g.dst, g.groups[grp][1]),
                              guard=lambda g, grp, j, s: j == 0 and
                                  not _owner_exists(0, s + 1, n_of(g, grp)))]),
            ptg.FlowSpec(
                "PART", ptg.READ,
                tile=lambda g, grp, j, s:
                    (g.A, key_of(g, grp, min(j + (1 << s),
                                             n_of(g, grp) - 1))),
                ins=[ptg.In(data=lambda g, grp, j, s:
                                (g.A, key_of(g, grp,
                                             min(j + (1 << s),
                                                 n_of(g, grp) - 1))),
                            guard=lambda g, grp, j, s:
                                n_of(g, grp) > 1 and
                                not part_from_task(g, grp, j, s)),
                     ptg.In(src=("R", part_src_params, "ACC"),
                            guard=part_from_task)]),
        ])

    # host-side branch on a possibly-absent flow → not jit-batchable
    @R.body(batchable=False)
    def _body(task, ACC, PART=None):
        if PART is None:  # degenerate single-tile group
            return {"ACC": ACC}
        return {"ACC": op(ACC, PART)}

    return tp
