"""Checkpoint/resume for data collections.

The reference has NO checkpoint/restart (SURVEY §5: "Absent. No
checkpoint/restart, no elasticity") — this subsystem goes beyond parity.
Model: the runtime quiesces between taskpools (``context.wait`` or
``dtd.flush``), at which point all state lives in the data collections;
a checkpoint snapshots named collections plus an application cursor
(e.g. the outer-iteration index), and resume restores the tiles and
returns the cursor. Orbax-style atomicity: each step writes to a
temporary directory that is renamed into place only when complete, so a
crash mid-save never corrupts the latest durable step.

Works for any :class:`~parsec_tpu.data.collection.DataCollection` whose
tiles are numpy/jax arrays or scalars. In a multi-rank run each rank
saves only the tiles it owns (``is_local``) into a per-rank file inside
the shared step directory.
"""

from __future__ import annotations

import json
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_STEP_RE = re.compile(r"^step_(\d+)$")
_RANK_RE = re.compile(r"\.rank(\d+)(?:\.|$)")


def _rank_of_file(name: str) -> int:
    """Numeric rank id of a per-rank checkpoint file (``meta.rank10.json``
    → 10). Lexicographic ordering puts ``rank10`` before ``rank2``, so
    every "pick a representative rank file" site must sort by THIS."""
    m = _RANK_RE.search(name)
    return int(m.group(1)) if m else -1


def _key_to_str(key: Tuple) -> str:
    return json.dumps(list(key))


def _str_to_key(s: str) -> Tuple:
    return tuple(json.loads(s))


class CheckpointManager:
    """Versioned, atomic checkpoints of data collections.

    Usage::

        mgr = CheckpointManager("/path/ckpt")
        mgr.save(step, {"A": A, "X": X}, meta={"iter": step})
        ...
        step = mgr.latest_step()
        meta = mgr.restore(step, {"A": A, "X": X})
    """

    def __init__(self, directory: str, my_rank: int = 0,
                 nb_ranks: int = 1):
        self.directory = directory
        self.my_rank = my_rank
        self.nb_ranks = nb_ranks
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------- paths
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def steps(self, complete_only: bool = True) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                step = int(m.group(1))
                if not complete_only or self.is_complete(step):
                    out.append(step)
        return sorted(out)

    def is_complete(self, step: int) -> bool:
        """Every rank recorded its done sentinel (the saved meta carries
        the rank count)."""
        d = self._step_dir(step)
        if not os.path.isdir(d):
            return False
        names = os.listdir(d)
        done = sum(1 for n in names if n.startswith("done.rank"))
        metas = [n for n in names if n.startswith("meta.rank")]
        if not metas or done == 0:
            return False
        # numeric rank order (sorted(metas)[0] would pick "rank10"
        # before "rank2"): the representative meta is the lowest RANK's
        with open(os.path.join(d, min(metas, key=_rank_of_file))) as fh:
            expected = json.load(fh).get("nb_ranks", 1)
        return done >= expected

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    # -------------------------------------------------------------- save
    def save(self, step: int, collections: Dict[str, Any],
             meta: Optional[Dict] = None) -> str:
        """Snapshot ``collections`` (name → DataCollection) as ``step``.
        Atomic: written under ``step_N.tmp`` then renamed. Returns the
        final step directory."""
        final = self._step_dir(step)
        tmp = final + f".tmp.{self.my_rank}"
        # a leftover tmp from a crashed prior save of this step would
        # smuggle stale tiles into the durable checkpoint — start clean
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        for name, dc in collections.items():
            arrays: Dict[str, np.ndarray] = {}
            for key in dc.keys():
                if not dc.is_local(key):
                    continue
                val = dc.data_of(key)
                if val is None:
                    continue
                arrays[_key_to_str(tuple(key))] = np.asarray(val)
            np.savez(os.path.join(tmp, f"{name}.rank{self.my_rank}.npz"),
                     **arrays)
        with open(os.path.join(tmp, f"meta.rank{self.my_rank}.json"),
                  "w") as fh:
            json.dump({"step": step, "meta": meta or {},
                       "nb_ranks": self.nb_ranks,
                       "collections": sorted(collections)}, fh)
        # completeness sentinel: written last inside tmp, so it only
        # becomes visible together with this rank's full payload
        with open(os.path.join(tmp, f"done.rank{self.my_rank}"), "w"):
            pass
        if os.path.isdir(final):
            # another save of the same step (or another rank finishing
            # first): merge our files into it
            self._merge_into(tmp, final)
        else:
            try:
                os.replace(tmp, final)
            except OSError:
                self._merge_into(tmp, final)
        return final

    def _merge_into(self, tmp: str, final: str) -> None:
        """Move tmp's files into ``final``, the done.rank sentinel LAST:
        a crash mid-merge must never leave the sentinel visible without
        this rank's full .npz/meta payload (is_complete would report a
        step that restore() silently under-populates)."""
        sentinel = f"done.rank{self.my_rank}"
        # a prior save of the same step may have left OUR sentinel in
        # final — drop it first, or a crash mid-merge leaves the stale
        # sentinel vouching for a mix of old and new payload files
        try:
            os.remove(os.path.join(final, sentinel))
        except FileNotFoundError:
            pass
        for f in sorted(os.listdir(tmp), key=lambda f: f == sentinel):
            os.replace(os.path.join(tmp, f), os.path.join(final, f))
        shutil.rmtree(tmp, ignore_errors=True)

    # ----------------------------------------------------------- restore
    def restore(self, step: int, collections: Dict[str, Any],
                only_rank: Optional[int] = None) -> Dict:
        """Write the saved tiles of ``step`` back into ``collections``
        (every rank file present is applied — a single-process resume of
        a multi-rank checkpoint sees all tiles). ``only_rank`` restricts
        the restore to the files one rank saved — the shard-adoption
        path: a replacement rank adopts a dead rank's tiles without
        pulling every other rank's shard through its memory. Returns the
        saved meta dict."""
        d = self._step_dir(step)
        if not os.path.isdir(d):
            raise FileNotFoundError(f"no checkpoint step {step} in "
                                    f"{self.directory}")
        if not self.is_complete(step):
            raise RuntimeError(
                f"checkpoint step {step} is incomplete (a rank crashed "
                f"mid-save); pick an earlier step")
        for name, dc in collections.items():
            found = False
            for fname in sorted(os.listdir(d), key=_rank_of_file):
                if not (fname.startswith(name + ".rank") and
                        fname.endswith(".npz")):
                    continue
                if only_rank is not None and \
                        _rank_of_file(fname) != only_rank:
                    continue
                found = True
                with np.load(os.path.join(d, fname)) as data:
                    for kstr in data.files:
                        key = _str_to_key(kstr)
                        val = data[kstr]
                        if val.ndim == 0:
                            val = val[()]
                        dc.write_tile(key, val)
            if not found:
                raise KeyError(
                    f"checkpoint step {step} has no data for "
                    f"collection {name!r}")
        meta_path = os.path.join(d, f"meta.rank{self.my_rank}.json")
        if not os.path.exists(meta_path):
            ranks = [f for f in os.listdir(d)
                     if f.startswith("meta.rank")]
            # numeric rank order: sorted()[0] would hand back rank10's
            # meta on a 12-rank step instead of the lowest rank's
            meta_path = os.path.join(d, min(ranks, key=_rank_of_file))
        with open(meta_path) as fh:
            return json.load(fh)["meta"]

    # ------------------------------------------------------------- prune
    def prune(self, keep: int = 2) -> None:
        """Delete all but the newest ``keep`` complete steps.

        Retention contract: ``keep`` must be >= 1 — the latest durable
        step is the recovery anchor and pruning may never delete it
        (``keep=0`` used to silently delete EVERY step via the
        ``[:-0]`` → ``[:None]`` slice; it now raises). Incomplete steps
        (another rank mid-save, or a crash) are never touched: deleting
        a step a peer is still merging into would corrupt its save."""
        if keep < 1:
            raise ValueError(
                f"prune(keep={keep}): at least the latest checkpoint "
                f"step must be retained (keep >= 1)")
        for step in self.steps()[:-keep]:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
