"""Data items and per-device copies.

Reference: parsec_data_t = key + owner + array of per-device
parsec_data_copy_t with MESI-like coherency INVALID/OWNED/EXCLUSIVE/SHARED
(data_internal.h:35-81, data.h:27-32) and version counters.

In the TPU runtime, values are immutable functional arrays, so the copy
table tracks *where* a version materializes (host numpy vs device
jax.Array) rather than guarding against concurrent mutation. The version
counter still orders successive writers of the same logical datum — the
invariant checked by tests mirroring the reference's coherency tests.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Dict, Optional


class CoherencyState(enum.IntEnum):
    INVALID = 0
    OWNED = 1
    EXCLUSIVE = 2
    SHARED = 3


class DataCopy:
    """One materialization of a data version on a device
    (parsec_data_copy_t analog)."""

    __slots__ = ("device_index", "value", "version", "coherency", "dtt")

    def __init__(self, device_index: int, value: Any, version: int = 0,
                 coherency: CoherencyState = CoherencyState.OWNED,
                 dtt: Any = None):
        self.device_index = device_index
        self.value = value
        self.version = version
        self.coherency = coherency
        self.dtt = dtt          # datatype/layout tag (reshape engine)


class Data:
    """A logical datum (parsec_data_t analog): key + owner + copies."""

    def __init__(self, key, owner_device: int = 0, collection=None):
        self.key = key
        self.owner_device = owner_device
        self.collection = collection
        self.version = 0
        self._copies: Dict[int, DataCopy] = {}
        self._lock = threading.Lock()

    def get_copy(self, device_index: int = 0) -> Optional[DataCopy]:
        with self._lock:
            return self._copies.get(device_index)

    def newest_copy(self) -> Optional[DataCopy]:
        with self._lock:
            if not self._copies:
                return None
            return max(self._copies.values(), key=lambda c: c.version)

    def attach_copy(self, device_index: int, value: Any,
                    coherency: CoherencyState = CoherencyState.SHARED) -> DataCopy:
        with self._lock:
            cp = DataCopy(device_index, value, self.version, coherency)
            self._copies[device_index] = cp
            return cp

    def write(self, device_index: int, value: Any) -> DataCopy:
        """A new version produced on ``device_index``: bump the version,
        invalidate other copies (MESI writer takes EXCLUSIVE)."""
        with self._lock:
            self.version += 1
            for cp in self._copies.values():
                cp.coherency = CoherencyState.INVALID
            cp = DataCopy(device_index, value, self.version,
                          CoherencyState.EXCLUSIVE)
            self._copies[device_index] = cp
            return cp
