"""Tiled matrices and block-cyclic distributions.

Reference: parsec_tiled_matrix_t (data_dist/matrix/matrix.h:98-124) and the
distributions under data_dist/matrix/: 2D-block-cyclic with k-cyclicity and
process-grid offsets (two_dim_rectangle_cyclic.c:109, grid_2Dcyclic.c),
symmetric 2D-BC, tabular (arbitrary per-tile rank table,
two_dim_tabular.c), and 1D cyclic vectors.

A :class:`TiledMatrix` stores local tiles as host numpy arrays keyed by
(row, col) tile index. For the TPU execution paths it can export/import a
*stacked* representation — all local tiles as one (ntiles, mb, nb) device
array — which is what the batched wavefront executor gathers from and
scatters to (one XLA gather per wave instead of per-task host transfers).

Round-1 restriction: matrix extents must be multiples of the tile size
(ragged edge tiles planned with masked kernels).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .collection import DataCollection


class Distribution:
    """rank_of policy for 2D tile indices."""

    def rank_of(self, i: int, j: int) -> int:
        return 0

    @property
    def nodes(self) -> int:
        return 1


class TwoDimBlockCyclic(Distribution):
    """2D block cyclic over a P×Q process grid with k-cyclicity (kp, kq)
    and grid offsets (ip, jq) — two_dim_rectangle_cyclic.c:109 analog."""

    def __init__(self, P: int, Q: int, kp: int = 1, kq: int = 1,
                 ip: int = 0, jq: int = 0):
        self.P, self.Q, self.kp, self.kq, self.ip, self.jq = P, Q, kp, kq, ip, jq

    def rank_of(self, i: int, j: int) -> int:
        p = ((i // self.kp) + self.ip) % self.P
        q = ((j // self.kq) + self.jq) % self.Q
        return p * self.Q + q

    @property
    def nodes(self) -> int:
        return self.P * self.Q


class SymTwoDimBlockCyclic(TwoDimBlockCyclic):
    """Symmetric (lower/upper) 2D block cyclic: only one triangle is
    stored; rank_of mirrors across the diagonal
    (sym_two_dim_rectangle_cyclic.c analog)."""

    def __init__(self, P: int, Q: int, uplo: str = "lower", **kw):
        super().__init__(P, Q, **kw)
        self.uplo = uplo

    def stored(self, i: int, j: int) -> bool:
        return j <= i if self.uplo == "lower" else i <= j

    def rank_of(self, i: int, j: int) -> int:
        if not self.stored(i, j):
            i, j = j, i
        return super().rank_of(i, j)


class TwoDimTabular(Distribution):
    """Arbitrary per-tile rank table (two_dim_tabular.c analog) — the
    reference's escape hatch for irregular placement (and the natural
    carrier for expert-parallel-style assignment)."""

    def __init__(self, table: Dict[Tuple[int, int], int]):
        self.table = dict(table)
        self._nodes = max(self.table.values(), default=0) + 1

    def rank_of(self, i: int, j: int) -> int:
        return self.table[(i, j)]

    @property
    def nodes(self) -> int:
        return self._nodes


class TwoDimBandCyclic(Distribution):
    """Band distribution (two_dim_band analog): tiles within ``band`` of
    the diagonal are spread 1D-cyclically along the diagonal across all
    ranks (dense band work balances independently of the 2D grid), tiles
    outside the band fall back to plain 2D block cyclic."""

    def __init__(self, P: int, Q: int, band: int = 1, **kw):
        self.band = band
        self.off_band = TwoDimBlockCyclic(P, Q, **kw)

    def rank_of(self, i: int, j: int) -> int:
        if abs(i - j) <= self.band:
            # diagonal index, cyclic over the full rank set
            return (min(i, j) * (2 * self.band + 1) + (i - j + self.band)) \
                % self.off_band.nodes
        return self.off_band.rank_of(i, j)

    @property
    def nodes(self) -> int:
        return self.off_band.nodes


class OneDimCyclic(Distribution):
    """1D cyclic over rows (vector_two_dim_cyclic.c analog)."""

    def __init__(self, P: int):
        self.P = P

    def rank_of(self, i: int, j: int) -> int:
        return i % self.P

    @property
    def nodes(self) -> int:
        return self.P


class TiledMatrix(DataCollection):
    """Tiled matrix collection (parsec_tiled_matrix_t analog)."""

    def __init__(self, m: int, n: int, mb: int, nb: int,
                 dist: Optional[Distribution] = None, myrank: int = 0,
                 dtype=np.float32, name: str = "A"):
        dist = dist or Distribution()
        super().__init__(name=name, nodes=dist.nodes, myrank=myrank)
        if m % mb or n % nb:
            raise ValueError("round 1: extents must be multiples of tile size")
        self.m, self.n, self.mb, self.nb = m, n, mb, nb
        self.mt, self.nt = m // mb, n // nb
        self.dist = dist
        self.dtype = dtype
        self._tiles: Dict[Tuple[int, int], Any] = {}
        self._lock = threading.Lock()

    # -- vtable -----------------------------------------------------------
    def rank_of(self, key) -> int:
        i, j = key
        return self.dist.rank_of(i, j)

    def data_of(self, key) -> Any:
        with self._lock:
            t = self._tiles.get(tuple(key))
        if t is None:
            t = np.zeros((self.mb, self.nb), dtype=self.dtype)
            with self._lock:
                t = self._tiles.setdefault(tuple(key), t)
        return t

    def write_tile(self, key, value) -> None:
        with self._lock:
            self._tiles[tuple(key)] = value

    def keys(self) -> Iterable[Tuple[int, int]]:
        return [(i, j) for i in range(self.mt) for j in range(self.nt)]

    def local_keys(self) -> List[Tuple[int, int]]:
        return [k for k in self.keys() if self.is_local(k)]

    # -- whole-matrix host views -----------------------------------------
    @classmethod
    def from_array(cls, arr: np.ndarray, mb: int, nb: int,
                   dist: Optional[Distribution] = None, myrank: int = 0,
                   name: str = "A") -> "TiledMatrix":
        m, n = arr.shape
        tm = cls(m, n, mb, nb, dist=dist, myrank=myrank,
                 dtype=arr.dtype, name=name)
        for i in range(tm.mt):
            for j in range(tm.nt):
                tm.write_tile((i, j),
                              np.ascontiguousarray(arr[i*mb:(i+1)*mb,
                                                       j*nb:(j+1)*nb]))
        return tm

    def to_array(self) -> np.ndarray:
        out = np.zeros((self.m, self.n), dtype=self.dtype)
        for (i, j) in self.keys():
            t = np.asarray(self.data_of((i, j)))
            out[i*self.mb:(i+1)*self.mb, j*self.nb:(j+1)*self.nb] = t
        return out

    # -- stacked device representation -----------------------------------
    def tile_index(self) -> Dict[Tuple[int, int], int]:
        """Stable (i, j) → slot mapping for the stacked representation.

        Owner-computes slot order: with a multi-node distribution, tiles
        owned by the same rank occupy a CONTIGUOUS slot range (ranks in
        order). Sharding the slot axis of the stacked store over a mesh
        then places each tile on (or near) its owner device, so the SPMD
        partitioner's collectives carry only the dataflow the reference
        sends as remote deps — the "How to Scale Your Model" recipe
        applied to the block-cyclic layout."""
        keys = sorted(self.keys())
        if self.dist.nodes > 1:
            keys.sort(key=lambda k: (self.rank_of(k),) + tuple(k))
        return {k: s for s, k in enumerate(keys)}

    def to_stacked(self, device=None):
        """All tiles stacked into one (ntiles, mb, nb) jax.Array resident
        in HBM — the layout the wavefront executor gathers from."""
        import jax
        import jax.numpy as jnp
        idx = self.tile_index()
        host = np.stack([np.asarray(self.data_of(k))
                         for k in sorted(idx, key=idx.get)])
        arr = jnp.asarray(host)
        if device is not None:
            arr = jax.device_put(arr, device)
        return arr, idx

    def from_stacked(self, arr, idx: Dict[Tuple[int, int], int]) -> None:
        host = np.asarray(arr)
        for k, s in idx.items():
            self.write_tile(k, host[s])

    # -- recursive subdivision --------------------------------------------
    def subtile(self, key: Tuple[int, int], mb: int, nb: int,
                name: Optional[str] = None) -> "SubtileView":
        """View one tile as a finer-tiled matrix for recursive algorithms
        (subtile.c analog): a POTRF tile body can run a nested tiled POTRF
        over the subdivision on the recursive device."""
        return SubtileView(self, key, mb, nb, name=name)


class SubtileView(TiledMatrix):
    """Recursive subdivision of a single parent tile (subtile.c analog).

    Sub-tiles are slices of a private working copy of the parent tile;
    :meth:`flush` writes the assembled result back to the parent — the
    nested taskpool runs entirely on the view, then commits once.
    """

    def __init__(self, parent: TiledMatrix, key: Tuple[int, int],
                 mb: int, nb: int, name: Optional[str] = None):
        self.parent = parent
        self.parent_key = tuple(key)
        base = np.array(np.asarray(parent.data_of(key)), copy=True)
        super().__init__(base.shape[0], base.shape[1], mb, nb,
                         dtype=base.dtype,
                         name=name or f"{parent.name}[{key}]")
        self._base = base

    def data_of(self, key) -> Any:
        i, j = key
        with self._lock:
            t = self._tiles.get((i, j))
        if t is None:
            t = np.ascontiguousarray(
                self._base[i*self.mb:(i+1)*self.mb,
                           j*self.nb:(j+1)*self.nb])
            with self._lock:
                t = self._tiles.setdefault((i, j), t)
        return t

    def flush(self) -> None:
        """Commit the subdivided result into the parent tile."""
        self.parent.write_tile(self.parent_key, self.to_array())

    def to_array(self) -> np.ndarray:
        out = np.array(self._base, copy=True)
        with self._lock:
            items = list(self._tiles.items())
        for (i, j), t in items:
            out[i*self.mb:(i+1)*self.mb, j*self.nb:(j+1)*self.nb] = \
                np.asarray(t)
        return out
