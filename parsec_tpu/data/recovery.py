"""Lineage-based tile recovery and sub-DAG replay.

The reference PaRSEC has no checkpoint/restart or elasticity (SURVEY §5)
— a dead rank kills the job via MPI's default error handler. This module
closes the detect→recover loop instead: owner-computes over closed-form
PTG flow specs means every lost tile has a *recomputable producer* — the
insight behind lineage recovery in Spark RDDs (Zaharia et al., NSDI'12)
— and the materialized instance DAG (:mod:`parsec_tpu.analysis.model`)
is exactly the lineage graph.

Model of the world after a failure:

- every collection tile owned by a dead rank is LOST (its current value
  is gone with the process);
- every surviving rank's tiles hold whatever the partial execution left
  in them — versions identified by how many of the tile's (dependency-
  ordered) terminal writers completed;
- values in flight task→task died with the aborted taskpool;
- each survivor knows exactly which of its local tasks completed
  (``Taskpool.completed_tasks``); the dead rank's completion record is
  lost, so ALL of its tasks are conservatively treated as not-run.

:func:`plan_recovery` walks the instance DAG backwards from the lost
state to the *minimal affected sub-DAG*: every task that never completed,
every writer of a lost tile, plus the backward closure of producers whose
output values cannot be rematerialized from a surviving tile at the right
version (a completed producer whose flow value was terminally written to
a surviving, current tile is a CUT POINT — replay reads the tile instead
of re-running the producer). :func:`build_replay_taskpool` then emits a
fresh PTG taskpool that runs exactly that sub-DAG, sourcing cut inputs
from surviving tiles (remote ones through the one-sided tile-fetch path)
and version-0 inputs from a *shadow* snapshot (the latest complete
:class:`~parsec_tpu.data.checkpoint.CheckpointManager` step, or re-loaded
input data) — so replay never restarts the whole DAG from scratch.

Survivor-side continuation follows the ULFM model (Bland et al.,
EuroMPI'12): the rank set either *shrinks* (a survivor adopts the dead
rank's shard via :func:`remap_collection_ranks` + :func:`adopt_shard`) or
a replacement rank *rejoins* (``SocketCommEngine(..., rejoin=True)``)
and adopts the dead rank's slot and 2D-block-cyclic shard.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from ..analysis.model import Model, _norm, _tile_key, build_model
from .collection import LocalCollection

TaskKey = Tuple[str, Tuple]           # (class name, coords)
TileKey = Tuple[str, Tuple]           # (collection label, key)


class RecoveryError(RuntimeError):
    """The failure is not recoverable by sub-DAG replay (non-PTG
    classes, truncated model, unordered writers, reshape deps, ...) —
    the caller should fall back to a full restart from the latest
    checkpoint."""


@dataclass
class RecoveryPlan:
    """The minimal affected sub-DAG and how to feed it.

    ``input_mode`` maps ``(class, coords, flow)`` of every replayed
    instance to how that flow's input is sourced in the replay pool:

    - ``("src",)`` — from its (replayed) producer, through normal
      dataflow;
    - ``("tile", label, key, "live")`` — rematerialized from the
      surviving collection tile (a lineage cut point);
    - ``("tile", label, key, "shadow")`` — from the version-0 shadow
      snapshot (checkpoint / re-loaded input);
    - ``("new",)`` — the original ``In(new=...)`` constructor;
    - ``None`` — no active input (the original guard, or a dropped CTL
      edge from a completed, non-replayed producer).
    """

    taskpool_name: str
    dead_ranks: FrozenSet[int]
    replay: Dict[str, List[Tuple]]              # class -> sorted coords
    replay_index: Set[TaskKey]
    input_mode: Dict[Tuple[str, Tuple, str], Optional[Tuple]]
    shadow_tiles: Set[TileKey]
    lost_tiles: Dict[str, Set[Tuple]]           # label -> keys
    collections: Dict[str, Any] = field(default_factory=dict, repr=False)
    replayed_tasks: int = 0
    total_tasks: int = 0

    @property
    def lost_work_fraction(self) -> float:
        return self.replayed_tasks / max(self.total_tasks, 1)


def _node_key(m: Model, n: int) -> TaskKey:
    node = m.nodes[n]
    return (node.tc.name, node.coords)


def plan_recovery(tp, dead_ranks, completed, max_tasks: int = 0
                  ) -> RecoveryPlan:
    """Compute the minimal replay sub-DAG of ``tp`` after ``dead_ranks``
    died mid-execution.

    ``completed``: the union of every SURVIVOR's
    ``Taskpool.completed_tasks`` (see :func:`exchange_completed`) —
    ``(class_name, coords)`` pairs. The dead ranks' completion records
    are lost; their tasks are conservatively replayed in full.

    The plan is a pure function of (flow specs, dead set, completed
    set), so every rank computes an identical plan from the allgathered
    inputs — no plan coordination message is needed.
    """
    from ..dsl.ptg import taskpool_uses_reshape
    m = build_model(tp, max_tasks=max_tasks or 1_000_000)
    if m.skipped_classes:
        raise RecoveryError(
            f"taskpool {tp.name}: non-PTG task classes "
            f"{m.skipped_classes} have no closed-form lineage")
    if m.truncated:
        raise RecoveryError(f"taskpool {tp.name}: instance DAG "
                            f"enumeration truncated — cannot plan replay")
    if taskpool_uses_reshape(tp):
        raise RecoveryError(
            f"taskpool {tp.name}: reshape deps are not replayable "
            f"(cut values would skip the conversion chain)")
    order, on_cycle = m.topo_order()
    if on_cycle:
        raise RecoveryError(f"taskpool {tp.name}: dependency cycle")
    pos = {n: i for i, n in enumerate(order)}
    g = tp.g
    dead = frozenset(int(r) for r in dead_ranks)
    nb_nodes = len(m.nodes)
    completed_keys = {(c, tuple(p)) for (c, p) in completed}

    dead_nodes = set()
    for n in range(nb_nodes):
        node = m.nodes[n]
        if node.tc.affinity_rank(node.coords) in dead:
            dead_nodes.add(n)
    # a dead rank's completion record died with it — distrust it even
    # if the caller's set mentions its tasks
    completed_nodes = {n for n in range(nb_nodes)
                       if n not in dead_nodes
                       and _node_key(m, n) in completed_keys}

    # ---- tile geography --------------------------------------------------
    all_tiles = set(m.writes) | set(m.reads)
    lost: Set[TileKey] = set()
    for tk in all_tiles:
        dc = m.collections.get(tk[0])
        if dc is not None and dc.rank_of(tk[1]) in dead:
            lost.add(tk)

    # dependency-ordered writer chain per tile (the lint's WAW check
    # guarantees consecutive writers are ordered on clean pools)
    writers: Dict[TileKey, List[int]] = {}
    for tk, accs in m.writes.items():
        ws = sorted({a.node for a in accs}, key=pos.get)
        for a, b in zip(ws, ws[1:]):
            if not m.reaches(a, b):
                raise RecoveryError(
                    f"tile {tk}: writers {m.nodes[a].label} and "
                    f"{m.nodes[b].label} are unordered (WAW hazard) — "
                    f"tile versions are schedule-dependent")
        writers[tk] = ws

    # current version of each SURVIVING tile = #completed writers; the
    # completed writers must form a dependency prefix, else (or when any
    # writer sat on a dead rank) the version is unknowable → rebuild
    rebuilt: Set[TileKey] = set()       # survivors to rewrite from v0
    cur_version: Dict[TileKey, int] = {}
    for tk, ws in writers.items():
        if tk in lost:
            continue
        flags = [w in completed_nodes for w in ws]
        k = sum(flags)
        if any(w in dead_nodes for w in ws) or \
                flags != [True] * k + [False] * (len(ws) - k):
            rebuilt.add(tk)
        else:
            cur_version[tk] = k
    for tk in all_tiles:
        cur_version.setdefault(tk, 0)

    def version_before(n: int, tk: TileKey) -> int:
        """How many writers of ``tk`` are dependency-ordered before
        ``n`` — the tile version a read by ``n`` observes."""
        return sum(1 for w in writers.get(tk, ())
                   if w != n and m.reaches(w, n))

    # ---- phase 1: grow the replay set to its least fixpoint --------------
    R: Set[int] = set()
    work: List[int] = []

    def add(n: int) -> None:
        if n in R:
            return
        R.add(n)
        work.append(n)
        # a COMPLETED writer re-running rewinds its tile to an earlier
        # version — every later writer must re-run too or the final
        # value regresses: rebuild the whole tile
        if n in completed_nodes:
            for tk in m.node_writes.get(n, ()):
                if tk not in lost:
                    rebuilt.add(tk)
                for w in writers.get(tk, ()):
                    add(w)

    for n in range(nb_nodes):
        if n in dead_nodes or n not in completed_nodes:
            add(n)
    for tk in lost:
        for w in writers.get(tk, ()):
            add(w)
    for tk in list(rebuilt):
        for w in writers.get(tk, ()):
            add(w)

    def producer_cut_tile(pi: int, src_flow: str) -> Optional[TileKey]:
        """The surviving tile holding producer ``pi``'s ``src_flow``
        value at the CURRENT version, or None when the value is not
        rematerializable (no active terminal write / tile lost or
        rebuilt / overwritten by a later completed writer)."""
        node = m.nodes[pi]
        for spec in node.tc.spec_list:
            if spec.name != src_flow:
                continue
            for d in spec.outs:
                if d.data is None or not d.active(g, node.coords):
                    continue
                dc, key = d.data(g, *node.coords)
                tk = _tile_key(dc, key)
                if tk in lost or tk in rebuilt:
                    continue
                ws = writers.get(tk, ())
                v = cur_version.get(tk, 0)
                if v >= 1 and v <= len(ws) and ws[v - 1] == pi:
                    return tk
        return None

    def process(n: int) -> None:
        """Apply the growth rules to one replay-set member: pull
        producers whose value cannot be rematerialized, rebuild tiles
        read at a version that is neither current nor input state."""
        node = m.nodes[n]
        tc, p = node.tc, node.coords
        for spec in tc.spec_list:
            dep = tc._active_in(g, spec, p)
            if dep is None or dep.new is not None or dep.gather:
                continue
            if dep.data is not None:
                dc, key = dep.data(g, *p)
                tk = _tile_key(dc, key)
                if tk in lost or tk in rebuilt:
                    continue
                v = version_before(n, tk)
                cur = cur_version.get(tk, 0)
                if v != cur and v != 0:
                    # mid-chain version neither current nor input state:
                    # rebuild the tile from v0 (its completed writers
                    # join the replay through add()'s rebuild rule)
                    rebuilt.add(tk)
                    for w in writers.get(tk, ()):
                        add(w)
                continue
            cls, fn, src_flow = dep.src
            pi = m.index.get((cls, _norm(fn(g, *p))))
            if pi is None:
                raise RecoveryError(
                    f"{node.label}.{spec.name}: producer instance "
                    f"missing (phantom target)")
            if pi not in R and producer_cut_tile(pi, src_flow) is None:
                add(pi)     # value not rematerializable — recompute it

    # the worklist re-examines every added node; growing ``rebuilt`` can
    # invalidate a cut decided earlier, so sweep the whole set until no
    # rule fires (monotone → least fixpoint, order-independent)
    while work:
        while work:
            process(work.pop())
        for n in sorted(R):
            process(n)

    # ---- phase 2: assign input modes from the final replay set -----------
    input_mode: Dict[Tuple[str, Tuple, str], Optional[Tuple]] = {}
    shadow_tiles: Set[TileKey] = set()
    live_reads: List[Tuple[int, TileKey, int]] = []

    def tile_mode(n: int, tk: TileKey) -> Tuple:
        v = version_before(n, tk)
        if tk in lost or tk in rebuilt:
            if v == 0:
                shadow_tiles.add(tk)
                return ("tile", tk[0], tk[1], "shadow")
            live_reads.append((n, tk, v))
            return ("tile", tk[0], tk[1], "live")
        cur = cur_version.get(tk, 0)
        if v == cur:
            live_reads.append((n, tk, v))
            return ("tile", tk[0], tk[1], "live")
        if v == 0:
            shadow_tiles.add(tk)
            return ("tile", tk[0], tk[1], "shadow")
        raise AssertionError(
            f"unsourced tile read {tk} v={v} cur={cur}")   # phase 1 bug

    for n in sorted(R):
        node = m.nodes[n]
        tc, p = node.tc, node.coords
        for spec in tc.spec_list:
            fk = (tc.name, p, spec.name)
            dep = tc._active_in(g, spec, p)
            if dep is None:
                input_mode[fk] = None
            elif dep.new is not None:
                input_mode[fk] = ("new",)
            elif dep.data is not None:
                dc, key = dep.data(g, *p)
                input_mode[fk] = tile_mode(n, _tile_key(dc, key))
            elif dep.gather:
                input_mode[fk] = ("src",)   # producers filtered at build
            else:
                cls, fn, src_flow = dep.src
                pi = m.index[(cls, _norm(fn(g, *p)))]
                if pi in R:
                    input_mode[fk] = ("src",)
                else:
                    tk = producer_cut_tile(pi, src_flow)
                    assert tk is not None        # phase 1 invariant
                    live_reads.append((n, tk, cur_version[tk]))
                    input_mode[fk] = ("tile", tk[0], tk[1], "live")

    # ---- safety: live (non-shadow) tile reads must be ordered within
    # the REPLAY DAG — before every replayed writer that advances the
    # tile past the read version (WAR), and after every replayed writer
    # the read version depends on (RAW through a rebuilt tile). Shadow
    # reads are immune: the shadow is an immutable snapshot. Build the
    # replay adjacency from the assigned modes and check reachability.
    radj: Dict[int, List[int]] = {n: [] for n in R}
    for n in sorted(R):
        node = m.nodes[n]
        for spec in node.tc.spec_list:
            fk = (node.tc.name, node.coords, spec.name)
            mm = input_mode.get(fk)
            if mm != ("src",):
                continue
            dep = node.tc._active_in(g, spec, node.coords)
            if dep is None or dep.src is None:
                continue
            targets = dep.src[1](g, *node.coords)
            if not dep.gather:
                targets = [targets]
            elif isinstance(targets, tuple):
                targets = [targets]
            for tgt in targets:
                pi = m.index.get((dep.src[0], _norm(tgt)))
                if pi is not None and pi in R:
                    radj[pi].append(n)

    _rmemo: Dict[int, Set[int]] = {}

    def rreaches(a: int, b: int) -> bool:
        desc = _rmemo.get(a)
        if desc is None:
            desc = set()
            stack = list(radj.get(a, ()))
            while stack:
                u = stack.pop()
                if u in desc:
                    continue
                desc.add(u)
                stack.extend(radj.get(u, ()))
            _rmemo[a] = desc
        return b in desc

    for (n, tk, v) in live_reads:
        ws = writers.get(tk, ())
        for w in ws[v:]:
            if w != n and w in R and not rreaches(n, w):
                raise RecoveryError(
                    f"replayed writer {m.nodes[w].label} of tile {tk} "
                    f"is unordered with surviving-value reader "
                    f"{m.nodes[n].label} in the replay DAG (WAR) — "
                    f"fall back to a full checkpoint restart")
        for w in ws[:v]:
            if w != n and w in R and not rreaches(w, n):
                raise RecoveryError(
                    f"reader {m.nodes[n].label} of tile {tk} needs "
                    f"version {v} but replayed writer "
                    f"{m.nodes[w].label} is unordered with it in the "
                    f"replay DAG (RAW) — fall back to a full "
                    f"checkpoint restart")

    replay: Dict[str, List[Tuple]] = {}
    for n in sorted(R, key=lambda x: (m.nodes[x].tc.name,
                                      m.nodes[x].coords)):
        node = m.nodes[n]
        replay.setdefault(node.tc.name, []).append(node.coords)
    lost_by_label: Dict[str, Set[Tuple]] = {}
    for (label, key) in lost:
        lost_by_label.setdefault(label, set()).add(key)
    return RecoveryPlan(
        taskpool_name=tp.name, dead_ranks=dead,
        replay=replay,
        replay_index={_node_key(m, n) for n in R},
        input_mode=input_mode, shadow_tiles=shadow_tiles,
        lost_tiles=lost_by_label, collections=dict(m.collections),
        replayed_tasks=len(R), total_tasks=nb_nodes)


# ---------------------------------------------------------------- replay


def build_replay_taskpool(tp, plan: RecoveryPlan,
                          shadow: Optional[Dict[str, Any]] = None,
                          name: Optional[str] = None):
    """Emit the replay taskpool for ``plan``: the replayed instances of
    every class of ``tp``, with producer edges restricted to the replay
    set, cut inputs rematerialized from surviving tiles (remote ones
    through the owner's one-sided tile fetch) and version-0 inputs read
    from ``shadow`` (label → collection, see :func:`materialize_shadow`).
    Bodies, priorities and terminal writes are the original ones —
    deterministic bodies make the replayed results bitwise-identical.
    """
    from ..dsl import ptg

    shadow = shadow or {}
    rtp = ptg.Taskpool(name or f"{tp.name}@replay", **vars(tp.g))
    mode_table = plan.input_mode
    replay_index = plan.replay_index

    def _norm_c(c):
        return tuple(c) if isinstance(c, (tuple, list)) else (c,)

    def _resolve_tile(label: str, key: Tuple, where: str):
        if where == "shadow":
            sdc = shadow.get(label)
            if sdc is None:
                raise RecoveryError(
                    f"replay of {tp.name} needs a shadow (checkpoint / "
                    f"input) source for collection {label!r}")
            val = sdc.data_of(tuple(key))
            if val is None:
                raise RecoveryError(
                    f"shadow for {label!r} has no tile {key}")
            return val
        dc = plan.collections[label]
        ctx = rtp.context
        if ctx is not None and ctx.nb_ranks > 1:
            owner = dc.rank_of(key)
            if owner != ctx.my_rank:
                # surviving value on another rank: one-sided fetch under
                # the replay pool's scope; ordering is guaranteed by the
                # plan (the read version is current NOW and every
                # replayed writer of the tile depends on this reader)
                return ctx.comm.fetch_tile(dc, key, owner, scope=rtp.name)
        return dc.data_of(tuple(key))

    for tc in tp.task_classes:
        insts = tuple(plan.replay.get(tc.name, ()))
        cname = tc.name
        specs2 = []
        for s in tc.spec_list:
            fname = s.name
            ins2: List[ptg.In] = []
            for d in s.ins:
                if d.src is not None and d.gather:
                    def _filt_src(g, *p, _fn=d.src[1], _cls=d.src[0]):
                        out = _fn(g, *p)
                        if isinstance(out, tuple):
                            out = [out]
                        return [c for c in out
                                if (_cls, _norm_c(c)) in replay_index]
                    ins2.append(ptg.In(src=(d.src[0], _filt_src,
                                            d.src[2]),
                                       guard=d.guard, gather=True))
                elif d.src is not None:
                    def _g_src(g, *p, _d=d, _c=cname, _f=fname):
                        return _d.active(g, p) and \
                            mode_table.get((_c, tuple(p), _f)) == ("src",)
                    ins2.append(ptg.In(src=d.src, guard=_g_src))
                elif d.new is not None:
                    def _g_new(g, *p, _d=d, _c=cname, _f=fname):
                        return _d.active(g, p) and \
                            mode_table.get((_c, tuple(p), _f)) == ("new",)
                    ins2.append(ptg.In(new=d.new, guard=_g_new))
                # data-type ins are replaced by the resolver below
            def _g_tile(g, *p, _c=cname, _f=fname):
                mm = mode_table.get((_c, tuple(p), _f))
                return isinstance(mm, tuple) and mm[0] == "tile"

            def _new_tile(g, *p, _c=cname, _f=fname):
                _m, label, key, where = mode_table[(_c, tuple(p), _f)]
                return _resolve_tile(label, key, where)
            ins2.append(ptg.In(new=_new_tile, guard=_g_tile))

            outs2: List[ptg.Out] = []
            for d in s.outs:
                if d.data is not None:
                    outs2.append(ptg.Out(data=d.data, guard=d.guard))
                    continue
                dcls, dfn, dflow = d.dst
                def _filt_dst(g, *p, _fn=dfn, _cls=dcls, _df=dflow):
                    out = _fn(g, *p)
                    if isinstance(out, tuple):
                        out = [out]
                    return [c for c in out
                            if (_cls, _norm_c(c)) in replay_index
                            and mode_table.get(
                                (_cls, _norm_c(c), _df)) == ("src",)]
                outs2.append(ptg.Out(dst=(dcls, _filt_dst, dflow),
                                     guard=d.guard))
            specs2.append(ptg.FlowSpec(fname, s.access, ins=ins2,
                                       outs=outs2, tile=s.tile))

        new_tc = rtp.task_class(
            cname, params=tc.params,
            space=lambda g, _s=insts: iter(_s),
            flows=specs2, affinity=tc.affinity)
        # vtable pieces the builder signature doesn't carry: the bodies
        # (incarnations) and the already-bound priority/on_complete
        new_tc.incarnations = list(tc.incarnations)
        new_tc.priority_fn = tc.priority_fn
        new_tc.time_estimate = tc.time_estimate
        new_tc.on_complete = tc.on_complete
        new_tc.properties = dict(tc.properties)
    return rtp


# ------------------------------------------------------- shadow sources


def materialize_shadow(plan: RecoveryPlan,
                       source: Callable[[str, Tuple], Any]
                       ) -> Dict[str, Any]:
    """Build the shadow (version-0 / input-state) tile store the replay
    pool reads from: one immutable local collection per collection
    label, holding exactly ``plan.shadow_tiles``. ``source`` is
    ``(label, key) -> value`` — typically
    :func:`checkpoint_shadow_source` or an input (re-)loader."""
    out: Dict[str, Any] = {}
    for (label, key) in sorted(plan.shadow_tiles):
        sdc = out.get(label)
        if sdc is None:
            sdc = out[label] = LocalCollection(f"{label}@shadow")
        sdc.write_tile(tuple(key), source(label, key))
    return out


def checkpoint_shadow_source(mgr, step: int, collections: Dict[str, Any]
                             ) -> Callable[[str, Tuple], Any]:
    """Shadow source backed by checkpoint ``step``: restores every
    rank's files for ``collections`` (``{dc.name: dc}``) into a private
    store once and serves tiles from it. The live collections are
    untouched — surviving current-version tiles keep their values."""
    store = {name: LocalCollection(f"{name}@ckpt")
             for name in collections}
    mgr.restore(step, store)
    missing = object()

    def src(label: str, key: Tuple):
        sdc = store.get(label)
        val = sdc.data_of(tuple(key)) if sdc is not None else missing
        if val is None or val is missing:
            raise RecoveryError(
                f"checkpoint step {step} has no tile {key} of "
                f"collection {label!r}")
        return val
    return src


def adopt_shard(collections: Dict[str, Any], ranks,
                source: Callable[[str, Tuple], Any],
                my_rank: Optional[int] = None) -> int:
    """Restore into the live ``collections`` (``{label: dc}``) every
    tile owned by ``ranks`` — the shard-adoption step of a replacement
    (or shrink-mode adopter) rank. With ``my_rank`` given, only tiles
    the remapped distribution places on that rank are written (each
    rank adopts its own share). Returns the number of adopted tiles."""
    ranks = set(int(r) for r in ranks)
    n = 0
    for label, dc in sorted(collections.items()):
        for key in dc.keys():
            k = tuple(key) if isinstance(key, (tuple, list)) else (key,)
            if my_rank is not None and dc.rank_of(k) != my_rank:
                continue
            if _pre_remap_rank(dc, k) in ranks:
                dc.write_tile(k, source(label, k))
                n += 1
    return n


# ----------------------------------------------------- rank remapping


def remap_collection_ranks(dc, remap: Dict[int, int]):
    """Shrink-mode ownership transfer: wrap ``dc.rank_of`` so tiles of
    a dead rank resolve to their adopter. Must be applied with the SAME
    remap on EVERY rank (placement is computed independently per rank
    from rank_of). Idempotent per collection: re-remapping composes on
    the original."""
    orig = getattr(dc, "_pre_remap_rank_of", None) or dc.rank_of
    dc._pre_remap_rank_of = orig
    full = dict(getattr(dc, "_rank_remap", {}))
    full.update({int(k): int(v) for k, v in remap.items()})
    dc._rank_remap = full

    def rank_of(key, _orig=orig, _map=full):
        r = _orig(key)
        return _map.get(r, r)
    dc.rank_of = rank_of
    return dc


def clear_remap(dc):
    """Undo every :func:`remap_collection_ranks` layer on ``dc``,
    restoring the original ``rank_of``. The elastic grow path uses this
    when a previously-drained rank's slot is re-admitted and the
    collection's natural placement becomes valid again (a grow→shrink
    cycle composing remaps forever would otherwise pin every tile on
    the first adopter). No-op for collections never remapped."""
    orig = getattr(dc, "_pre_remap_rank_of", None)
    if orig is None:
        return dc
    dc.rank_of = orig
    del dc._pre_remap_rank_of
    dc._rank_remap = {}
    return dc


def _pre_remap_rank(dc, key) -> int:
    """The owner a tile had BEFORE any shrink remap — lost-tile identity
    is defined by the ORIGINAL distribution."""
    orig = getattr(dc, "_pre_remap_rank_of", None)
    return orig(key) if orig is not None else dc.rank_of(key)


def shrink_remap(nb_ranks: int, dead_ranks) -> Dict[int, int]:
    """Deterministic adopter assignment for shrink-mode recovery: dead
    rank i's shard goes to the i-th live rank round-robin — every rank
    computes the same map locally."""
    dead = sorted(set(int(r) for r in dead_ranks))
    live = [r for r in range(nb_ranks) if r not in dead]
    if not live:
        raise RecoveryError("no surviving ranks")
    return {d: live[i % len(live)] for i, d in enumerate(dead)}


# ------------------------------------------------ one-call recovery


def replay_lost_work(ctx, tp, dead_ranks, source, shrink: bool = True,
                     adopt: Optional[Dict[str, Any]] = None,
                     name: Optional[str] = None,
                     token: Optional[str] = None):
    """Survivor-side recovery in one call, after ``tp`` aborted because
    ``dead_ranks`` died: allgather the completed-task records across the
    live ranks, plan the minimal replay sub-DAG, remap the dead shard to
    a survivor (``shrink=True``, ULFM-shrink) or keep the original
    placement for an admitted replacement rank (``shrink=False``,
    rejoin), restore adopted/lost input tiles from ``source`` (``adopt``
    = ``{label: collection}``), materialize the shadow snapshot, and
    register the replay taskpool. Every live rank must make the same
    call with the same arguments; the caller then waits on the context.
    Returns ``(replay_taskpool, plan)``."""
    comm = ctx.comm
    # in rejoin mode the dead SLOT is live again (the replacement
    # participates in the exchange, contributing an empty record)
    exchange_dead = dead_ranks if shrink else ()
    completed = exchange_completed(comm, tp, exchange_dead, token=token)
    plan = plan_recovery(tp, dead_ranks, completed)
    if shrink and ctx.nb_ranks > 1:
        remap = shrink_remap(ctx.nb_ranks, dead_ranks)
        for label in sorted(plan.collections):
            remap_collection_ranks(plan.collections[label], remap)
    if adopt:
        adopt_shard(adopt, dead_ranks, source,
                    my_rank=ctx.my_rank if ctx.nb_ranks > 1 else None)
    shadow = materialize_shadow(plan, source)
    rtp = build_replay_taskpool(tp, plan, shadow=shadow, name=name)
    if comm is not None and ctx.nb_ranks > 1:
        comm.acknowledge_failure()
        # expose BEFORE the barrier: a fast rank's replay startup may
        # cut-fetch from this rank the moment its own pool registers
        for label in sorted(plan.collections):
            dc = plan.collections[label]
            if getattr(dc, "name", None):
                comm.expose_collection(dc, scope=rtp.name)
        comm.sync()
    ctx.add_taskpool(rtp)
    return rtp, plan


# ------------------------------------------------- completed exchange


def exchange_completed(comm, tp, dead_ranks, token: Optional[str] = None
                       ) -> Set[TaskKey]:
    """Union the survivors' completed-task records (the lineage input of
    :func:`plan_recovery`) across the live rank set via the engine's
    recovery exchange. Single-rank / no-comm contexts return the local
    record directly."""
    local = {(c, tuple(p)) for (c, p) in tp.completed_tasks}
    if comm is None or comm.nb_ranks <= 1:
        return local
    # the default token carries the dead set: a retried recovery (a
    # second death failed the first exchange) must not collide with the
    # failed round's coordinator state or its late result frames
    dead_tag = "-".join(str(r) for r in sorted(set(dead_ranks)))
    results = comm.recover_exchange(
        token or f"completed:{tp.name}:{dead_tag}", sorted(local),
        dead_ranks)
    merged: Set[TaskKey] = set()
    for _rank, items in results.items():
        merged.update((c, tuple(p)) for (c, p) in items)
    return merged
