"""Data layer: collections, tiled matrices, distributions.

Reference: parsec_data_t + per-device copies (data_internal.h:35-81),
data collections with user-supplied rank_of/vpid_of/data_of vtable
(include/parsec/data_distribution.h:26-100), tiled-matrix descriptors and
2D-block-cyclic distributions (data_dist/matrix/).

TPU-first divergence: a tile's device residency is not a coherency state
machine over explicit copies — tile values are immutable ``jax.Array``s
(HBM-resident) or numpy arrays (host); "coherency" reduces to which value
version a consumer was linked to, which the dataflow core guarantees.
The :class:`~parsec_tpu.data.matrix.TiledMatrix` additionally supports a
*stacked* device representation (ntiles × mb × nb as one jax.Array) used by
the batched/compiled execution path.
"""

from .collection import DataCollection, LocalCollection
from .matrix import (TiledMatrix, TwoDimBlockCyclic, SymTwoDimBlockCyclic,
                     TwoDimTabular, TwoDimBandCyclic, OneDimCyclic,
                     SubtileView)
from .data import Data, DataCopy, CoherencyState
from .arena import Arena, ArenaDatatype, ArenaRegistry
from .redistribute import build_redistribute_ptg, insert_redistribute_dtd
from .checkpoint import CheckpointManager
from .recovery import (RecoveryError, RecoveryPlan, plan_recovery,
                       build_replay_taskpool, materialize_shadow,
                       checkpoint_shadow_source, adopt_shard,
                       remap_collection_ranks, shrink_remap,
                       exchange_completed, replay_lost_work)
from .matrix_ops import (build_apply, build_broadcast, build_map_operator,
                         build_reduce)
