"""Elastic-capacity sawtooth benchmark (``bench.py --section elastic``).

The robustness proof of ISSUE 11: an open-loop decode load ramps
low → high → low while the autoscaler (``serving/elastic.py``, mode
``act``) grows the serving mesh from 2 ranks toward 4 and drains it
back to 2 — all under live traffic.

Topology: rank 0 is the front end (router + ElasticController); the
serving ranks run one :class:`~parsec_tpu.serving.decode.DecodeEngine`
per hosted tenant behind an :class:`~parsec_tpu.serving.elastic.
ElasticWorker` agent. Requests route over ``AMTag.ELASTIC`` to the
tenant's current owner; each completion returns the decode state
vector, verified BITWISE against the float32 reference replay after
the load ends. Per-rank capacity is the rank's REAL decode throughput
(``work_ms=0``, the ISSUE 15 re-capture closing ROADMAP item 4's
REMAINING note) — the autoscaler reacts to what the serving stack can
genuinely sustain; pass ``work_ms > 0`` to model service time
explicitly instead (capacity as a controlled parameter).

Tenants also carry a persistent 4-tile profile shard that MIGRATES
through the checkpoint vehicle on every rebalance; a sha256 digest at
the end proves zero bitwise divergence of persistent state across all
rescales.

Reported: per-phase offered vs completed rates (the ramp-tracking
evidence), ``ramp_tracking_pct`` (the worst phase's completed/offered
percentage), ``migration_pause_p99_ms`` (p99 of the routing-pause
windows around tenant migrations), ``bitwise`` over every finished
request + the shard digests, the world-size timeline, and
``drain_clean`` (no drained rank ever reported as a failure)."""

from __future__ import annotations

import hashlib
import multiprocessing as mp
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..comm.pingpong import _free_port_base
from ..utils.stats import pctl as _pctl

# ISSUE 12: the sawtooth runs with the device data plane ON (PR 11
# flagged tile migration paying the 107 ms host hop as its remaining
# item) — shard/KV tiles that are device-resident now take the
# pipelined segmented path instead of the blocking snapshot. The bench
# mesh itself is tpu-off with host tiles, so the pin is about capturing
# the SHIPPED default, and migration-pause p99 is re-recorded under it
# (PARITY elastic row).
_DEVICE_PLANE_KNOBS = {"comm.device_pipeline": "1"}

_TENANTS = ("t0", "t1", "t2", "t3")
_DECODE_STEPS = 8
_SHARD_TILES = 4


def _shard_tiles(tenant: str) -> Dict:
    """Deterministic tenant-profile shard (the migrated persistent
    state): 4 tiles of 64 float32s derived from the tenant name."""
    seed = int.from_bytes(hashlib.sha256(
        tenant.encode()).digest()[:4], "big")
    rng = np.random.default_rng(seed)
    return {(i,): rng.standard_normal(64).astype(np.float32)
            for i in range(_SHARD_TILES)}


def _shard_digest(tiles: Dict) -> str:
    h = hashlib.sha256()
    for k in sorted(tiles):
        h.update(np.ascontiguousarray(tiles[k]).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# worker rank
# ---------------------------------------------------------------------------

def _worker_main(rank: int, world: int, base_port: int, ckpt_dir: str,
                 work_ms: float, q, live=None) -> None:
    """One serving rank: DecodeEngine per hosted tenant, shards
    migrated through the checkpoint vehicle, completions pushed back
    to the front end with the decode state for bitwise verification."""
    import traceback
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ..comm.socket_engine import SocketCommEngine
        from ..core import context as ctx_mod
        from ..data.checkpoint import CheckpointManager
        from ..data.collection import LocalCollection
        from ..serving.decode import DecodeConfig, DecodeEngine
        from ..serving.elastic import ElasticWorker
        from ..utils import mca_param

        from ..utils.benchenv import pin_wire_bench_env
        pin_wire_bench_env(overrides=_DEVICE_PLANE_KNOBS | {"comm.elastic": 1})
        # a joiner into a LIVE mesh (live peer list provided — incl. a
        # reused drained slot like rank 1) takes the rejoin wireup; only
        # the original mesh members do the static full-mesh wireup
        engine = SocketCommEngine(rank, world, base_port=base_port,
                                  rejoin=(live is not None),
                                  join_peers=live)
        ctx = ctx_mod.init(nb_cores=2, comm=engine)
        ctx.start()
        mgr = CheckpointManager(ckpt_dir, my_rank=rank, nb_ranks=1)
        cfg = DecodeConfig()
        engines: Dict[str, DecodeEngine] = {}
        shards: Dict[str, LocalCollection] = {}
        inflight: List = []      # (PendingRequest, rid, tenant, src)
        lock = threading.Lock()
        processing: Dict[str, int] = {}

        def on_adopt(tenant: str, step) -> None:
            dc = LocalCollection(f"{tenant}_shard")
            if step is None:
                for k, v in _shard_tiles(tenant).items():
                    dc.write_tile(k, v)
            else:
                mgr.restore(step, {tenant: dc})
            shards[tenant] = dc
            eng = DecodeEngine(ctx, f"{tenant}_r{rank}s{step or 0}",
                               cfg=cfg, tenant=tenant)
            eng.start()
            engines[tenant] = eng

        def on_drop(tenant: str, step):
            # quiesce: wait for this tenant's in-flight decodes (the
            # router paused new traffic before asking)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                with lock:
                    busy = processing.get(tenant, 0) or any(
                        t == tenant for (_r, _i, t, _s) in inflight)
                if not busy:
                    break
                time.sleep(0.01)
            eng = engines.pop(tenant, None)
            if eng is not None:
                eng.close()
            dc = shards.pop(tenant)
            mgr.save(step, {tenant: dc})     # the checkpoint-cut vehicle
            return step

        def on_request(src: int, msg: Dict) -> None:
            tenant = msg["tenant"]
            with lock:
                processing[tenant] = processing.get(tenant, 0) + 1
            try:
                if work_ms > 0:
                    time.sleep(work_ms / 1e3)   # modeled service time
                eng = engines.get(tenant)
                if eng is None:
                    worker.channel.send(src, "done", rid=msg["rid"],
                                        error="tenant not here")
                    return
                try:
                    req = eng.request(msg["rid"], msg["steps"])
                except Exception as exc:  # noqa: BLE001 — admission
                    worker.channel.send(src, "done", rid=msg["rid"],
                                        error=str(exc)[:120])
                    return
                with lock:
                    inflight.append((req, msg["rid"], tenant, src))
            finally:
                with lock:
                    processing[tenant] -= 1

        def backlog() -> float:
            with lock:
                return float(len(inflight)) + worker._reqs.qsize()

        worker = ElasticWorker(ctx, controller_rank=0,
                               on_adopt=on_adopt, on_drop=on_drop,
                               on_request=on_request,
                               backlog_fn=backlog)

        def digest_op(src: int, msg: Dict) -> None:
            dc = shards.get(msg["tenant"])
            d = (None if dc is None else
                 _shard_digest({k: dc.data_of(k) for k in dc.keys()}))
            worker.channel.send(src, "ack", token=msg["token"],
                                digest=d)

        worker.channel.on("shard_digest", digest_op)

        stop = threading.Event()

        def completer() -> None:
            while not stop.is_set():
                done = []
                with lock:
                    for item in list(inflight):
                        if item[0].done_evt.is_set():
                            inflight.remove(item)
                            done.append(item)
                for req, rid, tenant, src in done:
                    eng = engines.get(tenant)
                    worker.channel.send(
                        src, "done", rid=rid,
                        state=np.asarray(req.result))
                    if eng is not None:
                        eng.release(req)
                if not done:
                    time.sleep(0.003)

        ct = threading.Thread(target=completer, daemon=True)
        ct.start()
        worker.wait_drained(timeout=600.0)
        stop.set()
        ct.join(timeout=5.0)
        for eng in engines.values():
            eng.close()
        worker.stop()
        ctx.fini()                     # orderly BYE: peers see DEPARTED
        q.put((rank, "ok", {}))
    except BaseException as exc:  # noqa: BLE001 — report to parent
        q.put((rank, "error", f"{exc}\n{traceback.format_exc()}"))


# ---------------------------------------------------------------------------
# front end: router + controller + sawtooth generator
# ---------------------------------------------------------------------------

class _Router:
    """Open-loop request router on the front-end rank: each request
    goes to its tenant's CURRENT owner; a tenant under migration parks
    its requests and flushes them to the new owner on resume (that
    window is the measured migration pause)."""

    def __init__(self, ctrl, steps: int):
        self.ctrl = ctrl
        self.steps = steps
        self.lock = threading.Lock()
        self.outstanding: Dict[int, Dict] = {}   # rid -> record
        self.completions: List[Dict] = []
        self.lost: List[int] = []
        self.rerouted = 0
        self._retries: Dict[int, int] = {}
        self.paused: set = set()
        self.parked: Dict[str, List] = {}
        ctrl.channel.on("done", self._on_done)
        ctrl.set_router(self.per_rank_outstanding, self.pause,
                        self.resume)

    # -- controller hooks -------------------------------------------------
    def per_rank_outstanding(self) -> Dict[int, float]:
        out: Dict[int, float] = {}
        with self.lock:
            for rec in self.outstanding.values():
                out[rec["rank"]] = out.get(rec["rank"], 0.0) + 1.0
        return out

    def pause(self, tenant: str) -> None:
        with self.lock:
            self.paused.add(tenant)
            self.parked.setdefault(tenant, [])

    def resume(self, tenant: str) -> None:
        with self.lock:
            self.paused.discard(tenant)
            parked = self.parked.pop(tenant, [])
        for rid, phase, t0 in parked:
            self._send(rid, tenant, phase, t0)

    # -- request path -----------------------------------------------------
    def submit(self, rid: int, tenant: str, phase: int,
               t0: Optional[float] = None) -> None:
        # arrival time stamps HERE: a request parked through a
        # migration pause must report the pause in its latency (same
        # contract as the re-route path below)
        if t0 is None:
            t0 = time.monotonic()
        with self.lock:
            if tenant in self.paused:
                self.parked[tenant].append((rid, phase, t0))
                return
        self._send(rid, tenant, phase, t0)

    def _send(self, rid: int, tenant: str, phase: int,
              t0: Optional[float] = None) -> None:
        rank = self.ctrl.owner_of(tenant)
        if rank is None:
            with self.lock:
                self.lost.append(rid)
            return
        with self.lock:
            # a re-routed request keeps its ORIGINAL t0: the reported
            # latency must include the bounced first leg — that delay
            # is exactly the migration disruption being measured
            self.outstanding[rid] = {"t0": (t0 if t0 is not None
                                            else time.monotonic()),
                                     "tenant": tenant, "rank": rank,
                                     "phase": phase}
        self.ctrl.channel.send(rank, "req", rid=rid, tenant=tenant,
                               steps=self.steps)

    def _on_done(self, src: int, msg: Dict) -> None:
        rid = msg["rid"]
        with self.lock:
            rec = self.outstanding.pop(rid, None)
        if rec is None:
            return
        if msg.get("error") is not None and "state" not in msg:
            # a request caught mid-migration bounced off the OLD owner
            # ("tenant not here"): re-route it to the current owner —
            # migration must not lose traffic, only delay it
            with self.lock:
                n = self._retries.get(rid, 0)
                if n < 3:
                    self._retries[rid] = n + 1
                    self.rerouted += 1
                else:
                    self.lost.append(rid)
                    return
            self.submit(rid, rec["tenant"], rec["phase"],
                        t0=rec["t0"])
            return
        now = time.monotonic()
        lat = now - rec["t0"]
        rec.update({"t_done": now, "latency_s": lat, "rid": rid,
                    "state": np.asarray(msg["state"])})
        with self.lock:
            self.completions.append(rec)
        self.ctrl.record_latency(lat)


def measure_elastic(low_s: float = 4.0, high_s: float = 14.0,
                    tail_s: float = 12.0, low_rate: float = 8.0,
                    high_rate: float = 70.0,
                    work_ms: float = 0.0) -> Dict:
    """The full sawtooth measurement (see module doc). Phase plan:
    ``low_rate`` for ``low_s``, ``high_rate`` for ``high_s`` (the
    autoscaler grows 2 → 4 ranks), ``low_rate`` again for ``tail_s``
    (it drains back toward 2).

    ``work_ms=0`` (the default since ISSUE 15's re-capture — the
    REMAINING note on closed ROADMAP item 4): per-rank capacity is the
    rank's REAL decode throughput (the engine's actual insert→steps→
    drain cost per request), not a modeled sleep — the autoscaler's
    backlog signals now reflect what the serving stack can genuinely
    sustain per rank. Pass a positive ``work_ms`` to restore the
    modeled-service-time shape (capacity as a controlled parameter)."""
    import tempfile
    from ..comm.socket_engine import SocketCommEngine
    from ..core import context as ctx_mod
    from ..serving import runtime as srt
    from ..serving.decode import DecodeConfig, DecodeModel, \
        reference_decode
    from ..serving.elastic import AutoscalePolicy, ElasticController
    from ..utils import mca_param

    from ..utils.benchenv import pin_wire_bench_env
    pin_wire_bench_env(overrides=_DEVICE_PLANE_KNOBS | {"comm.elastic": 1})
    mca_param.set("serving.autoscale", "act")
    mca_param.set("serving.autoscale_poll_s", 0.15)

    ckpt_dir = tempfile.mkdtemp(prefix="parsec_elastic_")
    mpx = mp.get_context("spawn")
    q = mpx.Queue()
    base_port = _free_port_base(5)
    workers = []

    def spawn(rank, world, live):
        p = mpx.Process(target=_worker_main,
                        args=(rank, world, base_port, ckpt_dir,
                              work_ms, q, live))
        p.start()
        workers.append(p)

    # the base mesh: front end + ONE serving rank (world size 2)
    spawn(1, 2, None)
    engine = SocketCommEngine(0, 2, base_port=base_port)
    ctx = ctx_mod.init(nb_cores=4, comm=engine)
    out: Dict = {}
    ctrl = None
    stop_sampler = None
    st = None
    try:
        ctx.start()
        rt = srt.enable(ctx)
        policy = AutoscalePolicy(min_ranks=1, max_ranks=3,
                                 up_backlog=6.0, down_backlog=1.0,
                                 idle_rounds=3, cooldown_s=1.2)
        ctrl = ElasticController(ctx, runtime=rt, spawn_rank=spawn,
                                 tenants=_TENANTS, policy=policy,
                                 mode="act")
        router = _Router(ctrl, _DECODE_STEPS)
        # seed the initial placement (everything on rank 1) — AFTER
        # rank 1's worker agent heartbeats: socket admission precedes
        # its ELASTIC handler registration, and an adopt op landing in
        # that window would be silently dropped (same handshake
        # grow_one performs for fresh ranks)
        ctrl._wait_agent(1)
        for t in _TENANTS:
            dst = ctrl.placement[t]
            ctrl.placement[t] = None
            ctrl.migrate_tenant(t, dst)
        seed_migrations = len(ctrl.migration_pauses_ms)

        cal = None
        if work_ms <= 0:
            # REAL-DECODE capacity (ISSUE 15 satellite): calibrate the
            # sawtooth against the single rank's measured decode
            # throughput BEFORE the autoscaler starts — the phase
            # rates were historically tuned to the modeled work_ms,
            # and real capacity varies per container; an uncalibrated
            # high phase that one rank absorbs exercises nothing.
            rid0 = 1_000_000
            t_cal = time.monotonic()
            interval = 1.0 / 300.0
            next_t = time.monotonic()
            for i in range(240):
                router.submit(rid0 + i, _TENANTS[i % len(_TENANTS)],
                              -1)
                next_t += interval
                d = next_t - time.monotonic()
                if d > 0:
                    time.sleep(d)
            deadline_c = time.monotonic() + 60.0
            while time.monotonic() < deadline_c:
                with router.lock:
                    if not router.outstanding:
                        break
                time.sleep(0.02)
            with router.lock:
                done_cal = sum(1 for c in router.completions
                               if c["phase"] == -1)
            cal = done_cal / (time.monotonic() - t_cal)
            # saturate ~2.2x one rank's real capacity so the scaler
            # MUST grow; low phases sit comfortably inside it
            high_rate = max(low_rate * 3, min(2.2 * cal, 260.0))
            low_rate = max(low_rate, round(0.25 * cal, 1))
        ctrl.start()

        # world-size timeline sampler (the ramp-tracking evidence)
        timeline: List = []
        stop_sampler = threading.Event()

        def sampler():
            t0 = time.monotonic()
            while not stop_sampler.is_set():
                ws = engine.world_status()
                timeline.append((round(time.monotonic() - t0, 2),
                                 len(ws["live"])))
                stop_sampler.wait(0.25)

        st = threading.Thread(target=sampler, daemon=True)
        st.start()

        # ------------------------------------------------ sawtooth load
        phases = [{"rate": low_rate, "dur": low_s},
                  {"rate": high_rate, "dur": high_s},
                  {"rate": low_rate, "dur": tail_s}]
        rid = 0
        t_start = time.monotonic()
        for pi, ph in enumerate(phases):
            ph["t0"] = time.monotonic() - t_start
            ph["submitted"] = 0
            interval = 1.0 / ph["rate"]
            next_t = time.monotonic()
            end_t = next_t + ph["dur"]
            while time.monotonic() < end_t:
                rid += 1
                router.submit(rid, _TENANTS[rid % len(_TENANTS)], pi)
                ph["submitted"] += 1
                next_t += interval
                delay = next_t - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                # open-loop: a late server never slows arrivals
            ph["t1"] = time.monotonic() - t_start
        peak_world = max(w for (_t, w) in timeline) if timeline else 2

        # drain the tail: outstanding requests finish (bounded)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with router.lock:
                left = len(router.outstanding)
            if left == 0:
                break
            time.sleep(0.05)
        ctrl.stop()
        stop_sampler.set()
        st.join(timeout=3.0)
        final_world = len(engine.world_status()["live"])

        # ------------------------------------------- per-phase tracking
        with router.lock:
            comps = list(router.completions)
            lost = len(router.lost)
        rows = []
        tracking = []
        for pi, ph in enumerate(phases):
            window = ph["t1"] - ph["t0"]
            in_window = [c for c in comps
                         if ph["t0"] <= (c["t_done"] - t_start)
                         < ph["t1"]]
            done_rate = len(in_window) / window if window else 0.0
            offered = ph["submitted"] / window if window else 0.0
            lats = [c["latency_s"] * 1e3 for c in comps
                    if c["phase"] == pi]
            pct = 100.0 * min(1.0, done_rate / offered) if offered \
                else 100.0
            tracking.append(pct)
            rows.append({"phase": pi,
                         "offered_per_sec": round(offered, 1),
                         "completed_per_sec": round(done_rate, 1),
                         "tracking_pct": round(pct, 1),
                         "p50_ms": round(_pctl(lats, 0.5), 1)
                         if lats else None,
                         "p99_ms": round(_pctl(lats, 0.99), 1)
                         if lats else None})

        # ------------------------------------------------- verification
        model = DecodeModel(DecodeConfig())
        bad = 0
        for c in comps:
            ref = reference_decode(model, c["rid"], _DECODE_STEPS)
            if c["state"].shape != ref.shape or \
                    not np.all(c["state"] == ref):
                bad += 1
        # persistent tenant shards: bitwise across every rescale. A
        # tenant that ended the run UNPLACED (late adopt failure) or
        # whose digest probe fails IS the finding — record FAIL, do
        # not crash the section out of its own verification
        shard_ok = True
        for t in _TENANTS:
            owner = ctrl.owner_of(t)
            if owner is None:
                shard_ok = False
                continue
            try:
                token, slot = ctrl._new_ack()
                ctrl.channel.send(owner, "shard_digest", tenant=t,
                                  token=token)
                ack = ctrl._wait_ack(token, slot, 20.0,
                                     f"shard digest of {t}")
            except Exception:  # noqa: BLE001 — probe failure = FAIL
                shard_ok = False
                continue
            if ack.get("digest") != _shard_digest(_shard_tiles(t)):
                shard_ok = False

        ws = engine.world_status()
        drain_clean = (engine._peer_failure is None and
                       not ws["dead"] and
                       rt.stats.get("quarantined", 0) == 0)
        migrations = ctrl.migration_pauses_ms[seed_migrations:]
        bitwise_ok = bad == 0 and shard_ok and bool(comps)

        ctrl.shutdown_workers()
        out.update({
            "phases": rows,
            "ramp_tracking_pct": round(min(tracking), 1)
            if tracking else None,
            "requests_completed": len(comps),
            "requests_lost": lost,
            "requests_rerouted": router.rerouted,
            "migrations": len(migrations),
            "migration_pause_p99_ms": round(_pctl(migrations, 0.99), 2)
            if migrations else None,
            "migration_pause_max_ms": round(max(migrations), 2)
            if migrations else None,
            "bitwise": "OK" if bitwise_ok else "FAIL",
            "bitwise_bad": bad,
            "shard_digest_ok": shard_ok,
            "drain_clean": drain_clean,
            # live counts INCLUDE the front end (rank 0), so these are
            # world sizes: the sawtooth target is 2 -> 4 -> 2
            "peak_world": int(peak_world),
            "final_world": int(final_world),
            "world_timeline": _compress_timeline(timeline),
            "failed_joins": ctrl.failed_joins,
            "decisions": [
                {k: d[k] for k in ("from", "to", "reason", "ok")}
                for d in ctrl.decisions if d["acted"]][:16],
            "work_ms": work_ms,
            "capacity_model": ("real-decode" if work_ms <= 0
                               else "modeled-work-ms"),
            "calibrated_rank_capacity_per_sec": (round(cal, 1)
                                                 if cal else None),
            "rates": {"low": low_rate, "high": high_rate},
        })
    finally:
        # mid-bench exceptions must not leave the autoscaler ACTING
        # (spawning workers!) against a context being finalized, nor
        # the sampler thread running — the success path's stop calls
        # above are idempotent re-runs of these
        if ctrl is not None:
            ctrl.stop()
        if stop_sampler is not None:
            stop_sampler.set()
            if st is not None:
                st.join(timeout=3.0)
        try:
            ctx.fini()
        finally:
            for p in workers:
                p.join(timeout=20.0)
                if p.is_alive():
                    p.terminate()
            # comm.elastic changes engine BEHAVIOR (permanent wireup
            # listeners, grow semantics) — it must not leak into later
            # sections measured in this process
            for knob in ("serving.autoscale", "serving.autoscale_poll_s",
                         "comm.elastic"):
                mca_param.unset(knob)
            import shutil
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    return out


def _compress_timeline(timeline: List) -> List:
    """(t, live) samples → change points only (driver-facing size)."""
    out: List = []
    for t, w in timeline:
        if not out or out[-1][1] != w:
            out.append([t, w])
    return out
