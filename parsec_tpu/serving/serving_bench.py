"""Mixed-tenant serving benchmark (``bench.py --section serving``).

Three measurements, matching ISSUE 8's acceptance shape:

1. **clean** — the serving context is rank 0 of a 2-rank socket mesh.
   Two well-behaved decode tenants (A weight 4, B weight 1) drive an
   open-loop load of continuous-batching decode requests for
   ``duration_s`` while a distributed tenant D runs a cross-rank chain
   taskpool spanning both ranks. Recorded per tenant: requests/s,
   p50/p99 end-to-end latency, bitwise check of every completed request
   against the float32 reference replay.
2. **faulty** — same load plus a poison tenant P whose decode bodies
   raise (quarantined on first failure; later submissions refused) and
   a deterministic SIGKILL (``comm.fault_inject=kill``) of rank 1
   mid-load, which aborts ONLY the mesh-scoped tenant D pool
   (rank-local decode pools carry ``rank_scope={0}`` and keep
   serving). The well-behaved tenants' p99 is compared against the
   clean phase: the ≤2× bound is the isolation claim.
3. **overload** — a single-rank context with a tiny shed watermark: a
   high-weight tenant floods the ready queue, then low-weight
   submissions are shed with ``AdmissionRejected`` — the recorded shed
   count proves graceful degradation is rejection, not collapse.
"""

from __future__ import annotations

import multiprocessing as mp
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .decode import DecodeConfig, DecodeEngine
from ..comm.pingpong import _free_port_base
from ..utils.stats import pctl as _pctl

_DECODE_STEPS = 8           # decode steps per request
_CHAIN_TILES = 8            # distributed tenant: tiles per rank round


def _lat_row(lats_ms: List[float], n_submitted: int, n_rejected: int,
             duration_s: float, bitwise_ok: bool) -> Dict:
    return {
        "requests": len(lats_ms),
        "submitted": n_submitted,
        "rejected": n_rejected,
        "requests_per_sec": round(len(lats_ms) / duration_s, 2),
        "p50_ms": (round(_pctl(lats_ms, 0.50) * 1e3, 3)
                   if lats_ms else None),
        "p99_ms": (round(_pctl(lats_ms, 0.99) * 1e3, 3)
                   if lats_ms else None),
        "bitwise": "OK" if bitwise_ok else "FAIL",
    }


# ------------------------------------------------- distributed tenant D
class _DistVec:
    """Round-robin 1-D collection spanning the mesh (tenant D's data)."""

    def __init__(self, name: str, n: int, nb_ranks: int, my_rank: int):
        self.name = name
        self.n = n
        self.nb_ranks = nb_ranks
        self.myrank = my_rank
        self.dc_id = 977
        self.v = {(i,): np.float32(i + 0.5) for i in range(n)
                  if i % nb_ranks == my_rank}

    @staticmethod
    def _k(key):
        return (key[0],) if isinstance(key, (tuple, list)) else (key,)

    def rank_of(self, key) -> int:
        return self._k(key)[0] % self.nb_ranks

    def data_of(self, key):
        return self.v[self._k(key)]

    def write_tile(self, key, value) -> None:
        self.v[self._k(key)] = value

    def keys(self):
        return [(i,) for i in range(self.n)]

    def is_local(self, key) -> bool:
        return self.rank_of(key) == self.myrank


def _build_dist_chain(X, n_tiles: int, rounds: int, delay_s: float):
    """Tenant D's cross-rank pool: per tile a ``rounds``-deep chain
    whose every link hops to the next rank's tile (cross-rank halo
    traffic each step) with a small per-task delay so the pool spans
    the serving window and the injected kill lands mid-load."""
    from ..dsl import ptg

    tp = ptg.Taskpool("dist_chain", X=X, N=n_tiles, T=rounds, D=delay_s)
    C = tp.task_class(
        "C", params=("t", "i"),
        space=lambda g: ((t, i) for t in range(g.T) for i in range(g.N)),
        affinity=lambda g, t, i: (g.X, ((i + t) % g.N,)),
        flows=[ptg.FlowSpec(
            "S", ptg.RW,
            ins=[ptg.In(data=lambda g, t, i: (g.X, (i,)),
                        guard=lambda g, t, i: t == 0),
                 ptg.In(src=("C", lambda g, t, i: (t - 1, i), "S"),
                        guard=lambda g, t, i: t > 0)],
            outs=[ptg.Out(dst=("C", lambda g, t, i: (t + 1, i), "S"),
                          guard=lambda g, t, i: t < g.T - 1),
                  ptg.Out(data=lambda g, t, i: (g.X, (i,)),
                          guard=lambda g, t, i: t == g.T - 1)])])

    @C.body(batchable=False)
    def c_body(task, S):
        time.sleep(tp.g.D)
        return np.float32(S * np.float32(1.0009765625))

    return tp


def _peer_main(rank: int, nb_ranks: int, base_port: int, rounds: int,
               delay_s: float, kill_after: int, q) -> None:
    """Rank 1 of the serving mesh: runs tenant D's distributed pool.
    With ``kill_after`` > 0 this rank SIGKILLs itself
    (``comm.fault_inject=kill`` → os._exit) after that many completed
    tasks — the mid-load rank death of the faulty phase."""
    try:
        from ..comm.socket_engine import SocketCommEngine
        from ..core import context as ctx_mod
        from ..utils import mca_param

        from ..utils.benchenv import pin_wire_bench_env
        pin_wire_bench_env()
        if kill_after > 0:
            mca_param.set("comm.fault_inject", "kill")
            mca_param.set("comm.fault_inject_rank", rank)
            mca_param.set("comm.fault_inject_after", kill_after)
            mca_param.set("comm.fault_inject_unit", "tasks")
        engine = SocketCommEngine(rank, nb_ranks, base_port=base_port)
        ctx = ctx_mod.init(nb_cores=2, comm=engine)
        X = _DistVec("XD", _CHAIN_TILES, nb_ranks, rank)
        tp = _build_dist_chain(X, _CHAIN_TILES, rounds, delay_s)
        ctx.add_taskpool(tp)
        ctx.start()
        ok = ctx.wait(timeout=120)
        vals = {i: float(X.data_of((i,))) for i in range(_CHAIN_TILES)
                if X.rank_of((i,)) == rank}
        engine.sync()
        ctx.fini()
        q.put((rank, "ok", {"terminated": ok, "vals": vals}))
    except BaseException as exc:  # noqa: BLE001 — report to parent
        import traceback
        q.put((rank, "error", f"{exc}\n{traceback.format_exc()}"))


class _OpenLoopTenant:
    """Open-loop request generator for one decode tenant: a new request
    every ``interval_s`` regardless of completions (the arrival process
    does not slow down when the server does — the load shape that makes
    p99 honest)."""

    def __init__(self, engine: DecodeEngine, interval_s: float,
                 n_steps: int, poison_at: Optional[int] = None):
        self.engine = engine
        self.interval_s = interval_s
        self.n_steps = n_steps
        self.poison_at = poison_at
        self.submitted = 0
        self.rejected = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._main, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _main(self):
        rid = 0
        next_t = time.monotonic()
        while not self._stop.is_set():
            try:
                self.engine.request(rid, self.n_steps,
                                    poison_at=self.poison_at)
                self.submitted += 1
            except Exception:  # noqa: BLE001 — admission/quarantine
                self.rejected += 1
            rid += 1
            next_t += self.interval_s
            delay = next_t - time.monotonic()
            if delay > 0:
                self._stop.wait(delay)
            # open-loop: a late server does NOT push arrivals back

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)


def _run_phase(faulty: bool, duration_s: float, nb_ranks: int = 2,
               delay_s: float = 0.002) -> Dict:
    """One serving phase as rank 0 of a fresh mesh (see module doc)."""
    from ..comm.socket_engine import SocketCommEngine
    from ..core import context as ctx_mod
    from ..serving import runtime as srt
    from ..utils import mca_param

    from ..utils.benchenv import pin_wire_bench_env
    pin_wire_bench_env()
    mca_param.set("sched", "wfq")

    rounds = max(8, int(duration_s / max(delay_s, 1e-4)) // _CHAIN_TILES)
    # rank 1 owns every odd tile: it completes ~half of each round's
    # tasks; kill it ~40% through the phase's rounds
    kill_after = (max(4, int(rounds * _CHAIN_TILES * 0.4) // nb_ranks)
                  if faulty else 0)

    mpctx = mp.get_context("spawn")
    q = mpctx.Queue()
    base_port = _free_port_base(nb_ranks)
    peer = mpctx.Process(target=_peer_main,
                         args=(1, nb_ranks, base_port, rounds, delay_s,
                               kill_after, q))
    peer.start()

    out: Dict = {"faulty": faulty}
    engine = SocketCommEngine(0, nb_ranks, base_port=base_port)
    ctx = ctx_mod.init(nb_cores=4, comm=engine)
    try:
        rt = srt.enable(ctx)
        ten_a = rt.tenant("A", weight=4.0)
        ten_b = rt.tenant("B", weight=1.0)
        ctx.start()

        # tenant D: the mesh-scoped distributed pool
        XD = _DistVec("XD", _CHAIN_TILES, nb_ranks, 0)
        dist_tp = _build_dist_chain(XD, _CHAIN_TILES, rounds, delay_s)
        dist_sub = ctx.submit(dist_tp, tenant="D", weight=2.0,
                              rank_scope="all")

        cfg = DecodeConfig()
        eng_a = DecodeEngine(ctx, "tA", cfg=cfg, tenant=ten_a).start()
        eng_b = DecodeEngine(ctx, "tB", cfg=cfg, tenant=ten_b).start()
        gen_a = _OpenLoopTenant(eng_a, 0.030, _DECODE_STEPS).start()
        gen_b = _OpenLoopTenant(eng_b, 0.045, _DECODE_STEPS).start()
        gen_p = None
        if faulty:
            ten_p = rt.tenant("P", weight=0.5)
            eng_p = DecodeEngine(ctx, "tP", cfg=cfg, tenant=ten_p)
            eng_p.start()
            gen_p = _OpenLoopTenant(eng_p, 0.050, _DECODE_STEPS,
                                    poison_at=1).start()

        time.sleep(duration_s)
        for g in (gen_a, gen_b, gen_p):
            if g is not None:
                g.stop()

        rows = {}
        for name, eng, gen in (("A", eng_a, gen_a), ("B", eng_b, gen_b)):
            finished = eng.drain(timeout=60.0)
            lats = [r.latency_s() for r in finished]
            bitwise = all(eng.verify(r) for r in finished)
            rows[name] = _lat_row([x for x in lats if x is not None],
                                  gen.submitted, gen.rejected,
                                  duration_s, bitwise and bool(finished))
        if gen_p is not None:
            rows["P"] = {"submitted": gen_p.submitted,
                         "rejected": gen_p.rejected,
                         "quarantined": rt.tenants()["P"].quarantined
                         is not None}

        # tenant D: completes clean, aborts (quarantining D only) faulty
        d_err = None
        try:
            dist_sub.wait(timeout=120)
        except Exception as exc:  # noqa: BLE001
            d_err = f"{type(exc).__name__}: {exc}"
        rows["D"] = {"completed": dist_sub.error is None,
                     "error": d_err,
                     "quarantined": rt.tenants()["D"].quarantined
                     is not None}
        report = rt.report()
        out["tenants"] = rows
        out["serving_stats"] = report["stats"]
        out["pool_stats"] = {
            k: {kk: v[kk] for kk in ("tenant", "weight", "selected",
                                     "pending")}
            for k, v in (report.get("pools") or {}).items()}
        for eng_ in (eng_a, eng_b):
            eng_.close()
        if not faulty:
            engine.sync()
    finally:
        ctx.fini()
        if faulty:
            peer.join(timeout=15.0)
            if peer.is_alive():
                peer.terminate()
        else:
            try:
                rank, status, payload = q.get(timeout=30.0)
                out["peer"] = {"status": status}
            except Exception:  # noqa: BLE001
                out["peer"] = {"status": "no-report"}
            peer.join(timeout=15.0)
            if peer.is_alive():
                peer.terminate()
    return out


def _overload_probe(n_flood: int = 400, watermark: int = 64,
                    n_attempts: int = 20) -> Dict:
    """Deterministic load-shedding probe (single rank): a high-weight
    tenant floods the ready queue past the watermark, then a low-weight
    tenant's submissions must be shed with AdmissionRejected while the
    flood still completes (degradation = rejection, not collapse)."""
    from ..core import context as ctx_mod
    from ..dsl import dtd
    from ..serving import runtime as srt
    from ..data.collection import LocalCollection
    from ..utils import mca_param

    mca_param.set("serving.shed_watermark", watermark)
    mca_param.set("sched", "wfq")
    ctx = ctx_mod.init(nb_cores=2)
    try:
        rt = srt.enable(ctx)
        hi = rt.tenant("hi", weight=4.0)
        lo = rt.tenant("lo", weight=1.0)
        store = LocalCollection("ov", {(i,): 0.0 for i in range(n_flood)})
        tp = dtd.Taskpool("flood")
        ctx.submit(tp, tenant=hi)
        gate = threading.Event()

        def slow(x):
            gate.wait(10.0)
            return x + 1.0

        # independent tiles: all n_flood tasks are READY immediately —
        # the queue depth is real, not an in-flight chain
        tp.insert_tasks(slow, [[dtd.TileArg(store, (i,), dtd.INOUT)]
                               for i in range(n_flood)])
        depth = ctx.scheduler.pending_tasks()
        shed = 0
        for i in range(n_attempts):
            try:
                ctx.submit(dtd.Taskpool(f"lo{i}"), tenant=lo)
            except srt.AdmissionRejected:
                shed += 1
        gate.set()
        tp.wait()
        return {"flood_tasks": n_flood, "watermark": watermark,
                "queue_depth_at_probe": depth,
                "lo_attempts": n_attempts, "shed": shed,
                "shed_total": rt.stats["shed"]}
    finally:
        mca_param.unset("serving.shed_watermark")
        ctx.fini()


def _native_ab_probe(n_pools: int = 40, rows_per_pool: int = 200) -> Dict:
    """Native-vs-Python serving A/B (ISSUE 10): a single-rank serving
    runtime on a native-capable scheduler (lfq — wfq keeps DTD pools on
    the instrumented Python path by design, so an A/B there measures
    nothing) pushes a stream of admission-controlled submissions through
    both engines. Every task carries the tenant's ``on_retire`` hook, so
    the native engine runs its Python-bodied path: insert, dependency
    countdown, select, steal, and release native; the body + window
    retire in Python — the serving shape of the hot loop.

    Since ISSUE 13 both arms run with the FULL observability plane
    live — the always-on metrics registry AND an installed Trace (the
    request-span path) — because that is the production configuration:
    the native engine keeps running under observation (its in-engine
    event rings record the spans), which this probe asserts with
    ``engine_native`` per arm."""
    import time as _time
    from .. import _native
    from ..core import context as ctx_mod
    from ..dsl import dtd
    from ..profiling.trace import Trace
    from ..serving import runtime as srt
    from ..utils import mca_param

    if not _native.available():
        # degrade instead of raising (forcing native=1 without a
        # toolchain raises by design): record WHY, keep the section
        return {"python": None, "native": None, "native_vs_python": None,
                "note": f"native core unavailable: "
                        f"{_native.build_error()}"}

    def run(native: int) -> Dict:
        ctx = None
        try:
            mca_param.set("runtime.native_dtd", native)
            mca_param.set("sched", "lfq")
            ctx = ctx_mod.init(nb_cores=4)
            Trace().install(ctx)      # metrics + tracing LIVE, both arms
            rt = srt.enable(ctx)
            ctx.start()
            engines = set()
            t0 = _time.perf_counter()
            for i in range(n_pools):
                tp = dtd.Taskpool(f"ab{native}_{i}")
                sub = ctx.submit(tp, tenant="ab")
                tp.insert_tasks(_null_ab_body,
                                [() for _ in range(rows_per_pool)])
                tp.wait()
                sub.wait()
                engines.add(tp._native is not None)
            dt = _time.perf_counter() - t0
            return {"requests_per_sec": round(n_pools / dt, 2),
                    "rows_per_sec": round(n_pools * rows_per_pool / dt, 1),
                    "engine_native": engines == {True},
                    "trace_native_dropped": ctx.trace.native_dropped()}
        finally:
            mca_param.unset("runtime.native_dtd")
            mca_param.unset("sched")
            if ctx is not None:
                ctx.fini()

    run(0)                                     # warm both code paths
    py = run(0)
    nat = run(1)
    ratio = (round(nat["rows_per_sec"] / py["rows_per_sec"], 3)
             if py["rows_per_sec"] else None)
    return {"python": py, "native": nat,
            "native_vs_python": ratio,
            "note": "lfq serving submissions (admission + on_retire per "
                    "task) A/B'd across runtime.native_dtd with metrics "
                    "+ tracing LIVE on both arms (ISSUE 13: the native "
                    "engine keeps running under observation via its "
                    "in-engine event rings); the wfq phase above keeps "
                    "the instrumented Python path per the fallback rule"}


def _null_ab_body(x=None):
    return None


def measure_serving(duration_s: float = 4.0) -> Dict:
    """The full ``--section serving`` measurement (see module doc)."""
    clean = _run_phase(False, duration_s)
    faulty = _run_phase(True, duration_s)
    overload = _overload_probe()
    native_ab = _native_ab_probe()

    def p99(phase, t):
        row = phase["tenants"].get(t) or {}
        return row.get("p99_ms")

    ratios = []
    for t in ("A", "B"):
        c, f = p99(clean, t), p99(faulty, t)
        if isinstance(c, (int, float)) and isinstance(f, (int, float)) \
                and c > 0:
            ratios.append(f / c)
    worst_ratio = round(max(ratios), 3) if ratios else None

    bitwise_ok = all(
        (phase["tenants"][t].get("bitwise") == "OK")
        for phase in (clean, faulty) for t in ("A", "B"))
    isolation_ok = (
        bitwise_ok
        and faulty["tenants"]["P"]["quarantined"]
        and faulty["tenants"]["D"]["quarantined"]
        and not clean["tenants"]["D"]["quarantined"]
        and worst_ratio is not None and worst_ratio <= 2.0)

    reqs = sum(clean["tenants"][t]["requests"] for t in ("A", "B"))
    return {
        "duration_s": duration_s,
        "requests_per_sec": round(reqs / duration_s, 2),
        "p99_ms": p99(faulty, "A"),
        "p99_ratio_worst": worst_ratio,
        "clean": clean,
        "faulty": faulty,
        "overload": overload,
        "native_ab": native_ab,
        "native_vs_python": native_ab.get("native_vs_python"),
        "shed_count": overload["shed"],
        "quarantine_count": faulty["serving_stats"]["quarantined"],
        "isolation_check": "OK" if isolation_ok else "FAIL",
    }
