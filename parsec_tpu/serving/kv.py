"""KV state layer: paged allocation + radix prefix cache (ISSUE 15).

The serving runtime's missing state layer between
:class:`~.decode.DecodeEngine` and the HBM manager (ROADMAP item 3 —
the "millions of users" workload). Production LLM traffic is dominated
by shared prefixes (system prompts, few-shot templates, multi-turn
history); vLLM's PagedAttention (Kwon et al., SOSP 2023) and SGLang's
RadixAttention (Zheng et al., 2024) show that paged, prefix-shared KV
state is the single biggest req/s lever at fixed HBM. This module is
the TPU-runtime-shaped version of that design:

- **Paged allocation** (:class:`KVPagePool`): KV tiles become
  fixed-size pages of ``serving.kv_page_tokens`` (k, v) rows; a request
  holds a page table (ordered pids), pages are refcounted (the
  ``pgraph_consume``-style consumer-countdown pattern of PR 10, held
  under the pool lock since every caller is Python here), and the
  allocation/eviction granularity is a PAGE, not a request. Page
  arrays live in a shared :class:`PagedKVCollection` so DTD decode
  tasks reference them as ordinary tiles, and every page is registered
  with the context's HBM manager under a ``("kvpage", ...)`` key with a
  next-use hint refreshed on each write — page-level Belady eviction,
  and deliberately OUTSIDE the per-collection sweep a cancelled
  tenant's submission triggers (pages are shared across tenants; a
  cancellation releases that request's REFERENCES, never the bytes
  another tenant is reading).
- **Radix prefix tree** (:class:`RadixTree`): a token-prefix trie whose
  nodes own refcounted runs of immutable, PAGE-ALIGNED page ids.
  Requests sharing a prompt prefix share pages; match granularity is a
  whole page (token-level divergence inside a page means that page is
  recomputed — the vLLM block-granularity rule), node runs split at
  page boundaries on divergence, nodes are LRU-evicted leaves-first and
  eviction REFUSES nodes pinned by live requests (``lock_ref``).
  Prefill becomes "match longest prefix, then chunked-prefill only the
  suffix" (the chunk tasks ride the wfq prefill lane — ``sched/
  fair.py`` — so long prompts can't starve decode p99).
- **Copy-on-write** (:meth:`KVPagePool.cow`): writers of a shared page
  (refs > 1) copy at the divergence point — the speculative-decode
  draft branch (``serving/spec.py``) COWs the request's tail page
  before appending draft rows, and releases the copies when the branch
  loses.

Cross-pool safety note (why sharing immutable pages between tenants'
taskpools is race-free without cross-pool dependency tracking): DTD
INPUT flows with no in-flight writer snapshot the tile value at INSERT
time, and the radix tree only publishes a page after the prefill task
that filled it has COMPLETED (publication happens in the prefill-state
task's body, which is RAW-ordered behind every chunk's write-back).
A freed page is only reallocated after every holder dropped its
refcount, i.e. after their readers were inserted (snapshots taken) —
so a later owner's rewrite can never be observed by an earlier
reader. The dfsan sanitizer cannot see this refcount ordering; pools
sharing pages under dfsan would report cross-pool WAW on reused pids
(the tier-1 suites don't enable dfsan on this path).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.collection import LocalCollection
from ..utils import mca_param
from ..utils.debug import debug_verbose

mca_param.register("serving.kv_page_tokens", 16,
                   help="(k, v) rows per KV page — the allocation, "
                        "sharing, and eviction granularity of the KV "
                        "state layer")
mca_param.register("serving.kv_pages", 0,
                   help="page-pool capacity of the KV state layer "
                        "(0 = unbounded); allocation beyond it evicts "
                        "unpinned prefix-cache pages, then raises "
                        "KVPagesExhausted")
mca_param.register("serving.kv_prefix_cache", 1,
                   help="radix prefix cache on/off: requests sharing a "
                        "prompt prefix share immutable KV pages "
                        "(0 = every request prefills its whole prompt)")
mca_param.register("serving.kv_prefill_chunk", 4,
                   help="pages per chunked-prefill task: long prompts "
                        "prefill as independent chunk tasks on the wfq "
                        "prefill lane instead of one monolithic insert")
mca_param.register("serving.kv_spec_draft", 0,
                   help="speculative-decode draft window length (steps "
                        "per verify task; 0 = speculation off). Drafts "
                        "run in a cancellable branch taskpool "
                        "(serving/spec.py)")
mca_param.register("serving.kv_decode_window", 1,
                   help="multi-step decode scheduling: decode steps "
                        "per task (vLLM --num-scheduler-steps shape) — "
                        "the exact per-step kernel sequence runs in "
                        "one body, amortizing per-task runtime "
                        "overhead W-fold; results stay bitwise the "
                        "W=1 chain's by construction")


class KVPagesExhausted(MemoryError):
    """The page pool is at capacity and nothing is evictable — the
    caller (DecodeEngine.request) surfaces this as AdmissionRejected so
    open-loop clients back off instead of crashing."""


class PagedKVCollection(LocalCollection):
    """The shared page store, addressable as DTD tiles keyed ``(pid,)``.

    One collection per :class:`KVStateLayer`, shared by every tenant's
    decode pool on the context — the whole point is that two tenants'
    tasks read the SAME prefix page tile. Runtime write-backs (the
    INOUT flow of prefill-chunk / decode-step tasks) land here; each
    write refreshes the page's HBM entry + next-use hint through the
    owning pool."""

    def __init__(self, name: str, pool: "KVPagePool"):
        super().__init__(name)
        self._pool = pool

    def write_tile(self, key, value) -> None:
        super().write_tile(key, value)
        self._pool._on_page_write(key[0], value)


class KVPagePool:
    """Fixed-size KV page allocator with refcounts, COW, and page-level
    HBM accounting. All bookkeeping under one lock (allocation is off
    the per-step hot path — a request allocates its whole table once)."""

    def __init__(self, name: str, page_tokens: int, d_model: int,
                 capacity: int = 0, hbm=None):
        self.name = name
        self.page_tokens = int(page_tokens)
        self.d_model = int(d_model)
        self.capacity = int(capacity)          # 0 = unbounded
        self.hbm = hbm
        self.dc = PagedKVCollection(f"{name}_pages", self)
        self._lock = threading.RLock()
        self._refs: Dict[int, int] = {}        # pid -> refcount
        self._free: List[int] = []
        self._next_pid = 0
        self._clock = 0                        # next-use hint clock
        # reclaim callback installed by the radix tree: called (n
        # pages wanted) under pressure; returns pages actually freed
        self._reclaim: Optional[Callable[[int], int]] = None
        # trace source installed by KVStateLayer: () -> Trace | None.
        # Page lifecycle ops (alloc/retain/release/free/cow/write) are
        # recorded as "kvpage" events so analysis/conformance.py can
        # replay the run through the kv_lifecycle protocol model
        self._trace_src: Optional[Callable[[], object]] = None
        self.stats = {"allocs": 0, "frees": 0, "cow_copies": 0,
                      "evict_reclaims": 0, "peak_in_use": 0,
                      "exhausted": 0}

    def set_trace_source(self, src: Optional[Callable[[], object]]) -> None:
        """Install the trace lookup (evaluated per op, so a trace
        installed after layer creation is still picked up)."""
        self._trace_src = src

    def _emit(self, op: str, pid: int, refs: Optional[int] = None,
              src: Optional[int] = None) -> None:
        fn = self._trace_src
        if fn is None:
            return
        tr = fn()
        if tr is None:
            return
        info = {"pool": self.name}
        if refs is not None:
            info["refs"] = refs
        if src is not None:
            info["src"] = src
        tr.event("kvpage", op, object_id=pid, info=info)

    # ----------------------------------------------------------- internal
    def _hbm_key(self, pid: int):
        # deliberately NOT (id(dc), key)-shaped: the serving runtime's
        # cancel-time sweep drops every HBM entry of a cancelled pool's
        # collections, and pages are shared across tenants — a page
        # dies only when its refcount does (drop in _free_locked)
        return ("kvpage", id(self), pid)

    def _on_page_write(self, pid: int, value) -> None:
        self._emit("write", pid)
        hbm = self.hbm
        if hbm is None:
            return
        with self._lock:
            if pid not in self._refs:
                return
            self._clock += 1
            nu = self._clock + 1
        # re-register: the HBM entry must hold the CURRENT page bytes
        # (a stale entry would stage superseded data on ensure)
        key = self._hbm_key(pid)
        hbm.drop(key)
        try:
            hbm.register(key, value, next_use=nu)
        except MemoryError:
            pass                   # page larger than the whole budget

    def touch(self, pid: int) -> None:
        """Refresh a page's HBM next-use hint (a cache hit means the
        page is about to be read by a whole request's decode chain) —
        :meth:`HBMManager.hint`, no staging, no eviction."""
        hbm = self.hbm
        if hbm is None:
            return
        with self._lock:
            if pid not in self._refs:
                return
            self._clock += 1
            nu = self._clock + 1
        hbm.hint(self._hbm_key(pid), next_use=nu)

    def _free_locked(self, pid: int) -> None:
        self._refs.pop(pid, None)
        self._free.append(pid)
        self._emit("free", pid, refs=0)
        self.stats["frees"] += 1
        self.dc.drop_tile((pid,))
        if self.hbm is not None:
            self.hbm.drop(self._hbm_key(pid))

    def _fresh_page(self) -> np.ndarray:
        # UNINITIALIZED on purpose: every row a page consumer ever
        # reads is written first (prefill fills its rows, a decode
        # step reads tail[:slot+1], and a page only joins the "prev"
        # set once every slot is written), so a memset per page would
        # be pure allocation-path cost — measured at ~60% of the
        # request-admission critical section under the pool lock
        return np.empty((2, self.page_tokens, self.d_model),
                        dtype=np.float32)

    # ------------------------------------------------------------ public
    def alloc(self, n: int = 1) -> List[int]:
        """Allocate ``n`` UNINITIALIZED pages (refcount 1 each — see
        :meth:`_fresh_page` for the every-row-written-first contract
        that makes a memset dead cost), evicting
        reclaimable prefix-cache pages under capacity pressure. Raises
        :class:`KVPagesExhausted` when the budget cannot hold — the
        request-granularity failure the paged design exists to avoid
        becomes an explicit, page-granular admission signal."""
        with self._lock:
            if self.capacity:
                want = n - (self.capacity - self.pages_in_use())
                if want > 0 and self._reclaim is not None:
                    freed = self._reclaim(want)
                    if freed:
                        self.stats["evict_reclaims"] += freed
                if self.pages_in_use() + n > self.capacity:
                    self.stats["exhausted"] += 1
                    raise KVPagesExhausted(
                        f"KV page pool {self.name}: {n} pages requested,"
                        f" {self.pages_in_use()}/{self.capacity} in use "
                        "and nothing evictable (serving.kv_pages)")
            out = []
            for _ in range(n):
                pid = self._free.pop() if self._free else self._next_pid
                if pid == self._next_pid:
                    self._next_pid += 1
                self._refs[pid] = 1
                self.stats["allocs"] += 1
                out.append(pid)
                self._emit("alloc", pid, refs=1)
                self.dc.write_tile((pid,), self._fresh_page())
            used = self.pages_in_use()
            if used > self.stats["peak_in_use"]:
                self.stats["peak_in_use"] = used
            return out

    def retain(self, pid: int, n: int = 1) -> None:
        with self._lock:
            if pid not in self._refs:
                raise KeyError(f"retain of freed page {pid}")
            self._refs[pid] += n
            self._emit("retain", pid, refs=self._refs[pid])

    def release(self, pid: int) -> None:
        """Drop one reference; the last one returns the page to the
        free list, drops its tile and its HBM entry."""
        with self._lock:
            refs = self._refs.get(pid)
            if refs is None:
                return                # idempotent: already freed
            if refs > 1:
                self._refs[pid] = refs - 1
                self._emit("release", pid, refs=refs - 1)
            else:
                self._emit("release", pid, refs=0)
                self._free_locked(pid)

    def cow(self, pid: int) -> int:
        """Copy-on-write: a private copy of ``pid`` (refcount 1) for a
        writer that must not mutate a shared page — the divergence-
        point copy. The source's refcount is untouched (the caller
        still holds its reference)."""
        src = self.dc.data_of((pid,))
        if src is None:
            raise KeyError(f"cow of unknown page {pid}")
        [new] = self.alloc(1)
        self.dc.write_tile((new,), np.array(src, copy=True))
        with self._lock:
            self.stats["cow_copies"] += 1
        self._emit("cow", new, src=pid)
        return new

    def refs(self, pid: int) -> int:
        with self._lock:
            return self._refs.get(pid, 0)

    def pages_in_use(self) -> int:
        return len(self._refs)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"pages_in_use": len(self._refs),
                    "pages_free": len(self._free),
                    "capacity": self.capacity, **self.stats}


# ---------------------------------------------------------------------------
# radix prefix tree
# ---------------------------------------------------------------------------

class _RadixNode:
    """One trie node: a PAGE-ALIGNED token run backed by the page ids
    that hold its (k, v) rows. Children are keyed by their FIRST PAGE
    of tokens (a pt-tuple) — two continuations that diverge inside a
    page are simply different children, so no split ever has to cut
    through a page. ``lock_ref`` pins the node against eviction while
    a live request references its pages."""

    __slots__ = ("tokens", "pages", "children", "parent", "lock_ref",
                 "last_use")

    def __init__(self, tokens: Tuple[int, ...], pages: Tuple[int, ...],
                 parent: Optional["_RadixNode"]):
        self.tokens = tokens
        self.pages = pages
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.parent = parent
        self.lock_ref = 0
        self.last_use = 0


class MatchHandle:
    """The result of :meth:`RadixTree.match`: the shared page ids (one
    pool reference each, owned by the caller) and the pinned node path.
    ``unlock()`` releases the PINS only — page references are released
    by the request's own release path (uniform with owned pages)."""

    __slots__ = ("pids", "n_tokens", "_nodes", "_tree", "_unlocked")

    def __init__(self, tree: "RadixTree", pids: List[int],
                 n_tokens: int, nodes: List[_RadixNode]):
        self._tree = tree
        self.pids = pids
        self.n_tokens = n_tokens
        self._nodes = nodes
        self._unlocked = False

    def unlock(self) -> None:
        if self._unlocked:
            return
        self._unlocked = True
        with self._tree._lock:
            for node in self._nodes:
                if node.lock_ref > 0:
                    node.lock_ref -= 1


class RadixTree:
    """Token-prefix trie over refcounted, immutable, page-aligned page
    runs (SGLang's RadixAttention shape at vLLM's block granularity).

    The tree owns ONE pool reference per cached page (taken at
    :meth:`insert`, dropped at eviction); matching requests take their
    own references. Node runs are multiples of ``page_tokens``; splits
    happen at page boundaries, so a page id never straddles nodes."""

    def __init__(self, pool: KVPagePool):
        self.pool = pool
        self.pt = pool.page_tokens
        self._root = _RadixNode((), (), None)
        # ONE lock with the pool (re-entrant): alloc-under-pressure
        # calls tree eviction while match/insert call pool retain/
        # release — two locks here would be an ABBA deadlock between a
        # matching thread and an allocating one
        self._lock = pool._lock
        self._clock = 0
        self.stats = {"nodes": 0, "cached_pages": 0, "inserts": 0,
                      "evicted_nodes": 0, "evicted_pages": 0,
                      "splits": 0}
        pool._reclaim = self._reclaim_for_pool

    # ----------------------------------------------------------- helpers
    @staticmethod
    def _common(a: Sequence[int], b: Sequence[int]) -> int:
        n = min(len(a), len(b))
        for i in range(n):
            if a[i] != b[i]:
                return i
        return n

    def _split_locked(self, node: _RadixNode, at_pages: int) -> None:
        """Split ``node`` at the page boundary ``at_pages``: the node
        keeps the head run (identity preserved — live MatchHandles may
        hold it), a new child inherits the tail run and the children.
        The child starts UNPINNED: evicting it under a live request
        only loses cache warmth, never bytes (the request holds page
        refcounts of its own)."""
        cut_t = at_pages * self.pt
        tail = _RadixNode(node.tokens[cut_t:], node.pages[at_pages:],
                          node)
        tail.children = node.children
        for ch in tail.children.values():
            ch.parent = tail
        tail.last_use = node.last_use
        node.tokens = node.tokens[:cut_t]
        node.pages = node.pages[:at_pages]
        node.children = {tail.tokens[:self.pt]: tail}
        self.stats["nodes"] += 1
        self.stats["splits"] += 1

    # ------------------------------------------------------------ public
    def match(self, tokens: Sequence[int]) -> MatchHandle:
        """Longest page-aligned cached prefix of ``tokens``. Returns a
        :class:`MatchHandle` holding one pool reference per matched
        page (caller-owned) and an eviction pin on every node of the
        matched path."""
        tokens = tuple(tokens)
        pids: List[int] = []
        nodes: List[_RadixNode] = []
        with self._lock:
            self._clock += 1
            node, off = self._root, 0
            while True:
                nxt = node.children.get(tokens[off:off + self.pt]) \
                    if off + self.pt <= len(tokens) else None
                if nxt is None:
                    break
                m = self._common(nxt.tokens, tokens[off:])
                m_pages = m // self.pt
                if m_pages == 0:
                    break              # fewer than a page in common
                nxt.last_use = self._clock
                nxt.lock_ref += 1
                nodes.append(nxt)
                take = nxt.pages[:m_pages]
                for pid in take:
                    self.pool.retain(pid)
                    self.pool.touch(pid)
                pids.extend(take)
                if m_pages < len(nxt.pages):
                    break              # diverged inside this node's run
                node, off = nxt, off + m
        return MatchHandle(self, pids, len(pids) * self.pt, nodes)

    def insert(self, tokens: Sequence[int], pids: Sequence[int]) -> int:
        """Publish ``tokens`` (page-aligned: ``len(tokens) == len(pids)
        * page_tokens``) as a cached path backed by ``pids``. The tree
        retains each NEWLY cached page; already-cached prefixes are
        deduplicated (their existing pages stay authoritative). Returns
        the number of pages newly cached. Call only after the pages'
        bytes are final (the prefill-state task body)."""
        tokens = tuple(tokens)
        pids = list(pids)
        if len(tokens) != len(pids) * self.pt:
            raise ValueError(
                f"insert of {len(tokens)} tokens with {len(pids)} pages"
                f" (page_tokens {self.pt}): publication is page-aligned")
        added = 0
        with self._lock:
            self._clock += 1
            self.stats["inserts"] += 1
            node, off, pi = self._root, 0, 0
            while off < len(tokens):
                nxt = node.children.get(tokens[off:off + self.pt])
                if nxt is None:
                    child = _RadixNode(tokens[off:], tuple(pids[pi:]),
                                       node)
                    child.last_use = self._clock
                    node.children[tokens[off:off + self.pt]] = child
                    n_new = len(child.pages)
                    for pid in child.pages:
                        self.pool.retain(pid)
                    self.stats["nodes"] += 1
                    self.stats["cached_pages"] += n_new
                    added += n_new
                    return added
                # the child key is its whole first page, so at least
                # one page is always in common here
                m = self._common(nxt.tokens, tokens[off:])
                m_pages = m // self.pt
                nxt.last_use = self._clock
                if m_pages < len(nxt.pages):
                    self._split_locked(nxt, m_pages)
                node, off, pi = nxt, off + m_pages * self.pt, \
                    pi + m_pages
        return added

    def _evictable_leaves_locked(self) -> List[_RadixNode]:
        out = []
        stack = [self._root]
        while stack:
            n = stack.pop()
            for ch in n.children.values():
                if ch.children:
                    stack.append(ch)
                elif ch.lock_ref == 0:
                    out.append(ch)
        return out

    def evict(self, n_pages: int) -> int:
        """LRU eviction: drop unpinned LEAF nodes (bottom-up — a parent
        becomes a leaf once its children are gone) until ``n_pages``
        page references were released or nothing evictable remains.
        Pinned nodes (``lock_ref > 0``: a live request's matched path)
        are REFUSED. Returns pages released."""
        freed = 0
        with self._lock:
            while freed < n_pages:
                leaves = self._evictable_leaves_locked()
                if not leaves:
                    break
                victim = min(leaves, key=lambda n: n.last_use)
                parent = victim.parent
                del parent.children[victim.tokens[:self.pt]]
                for pid in victim.pages:
                    self.pool.release(pid)
                freed += len(victim.pages)
                self.stats["nodes"] -= 1
                self.stats["cached_pages"] -= len(victim.pages)
                self.stats["evicted_nodes"] += 1
                self.stats["evicted_pages"] += len(victim.pages)
                debug_verbose(3, "kv", "evicted radix node (%d pages)",
                              len(victim.pages))
        return freed

    def _reclaim_for_pool(self, n_pages: int) -> int:
        """Pool-pressure callback: evicted pages whose ONLY reference
        was the tree go straight back to the free list."""
        return self.evict(n_pages)

    def node_count(self) -> int:
        with self._lock:
            return self.stats["nodes"]

    def snapshot(self) -> Dict:
        with self._lock:
            return dict(self.stats)


# ---------------------------------------------------------------------------
# the per-context layer
# ---------------------------------------------------------------------------

class KVStateLayer:
    """The shared KV state plane of one serving context: page pool +
    radix prefix tree + the paged collection, attached as
    ``context.kv_state`` so statusz and the scrape-time metrics
    collectors (``parsec_kv_pages_in_use`` / ``parsec_kv_hit_rate``)
    can read it with zero hot-path cost.

    One layer per context, shared across every tenant's
    :class:`~.decode.DecodeEngine` — cross-tenant sharing of identical
    prefixes is the point (pages are immutable and content-addressed by
    token prefix; no tenant data crosses: only a request that presents
    the SAME tokens reads a cached page)."""

    def __init__(self, ctx, d_model: int, page_tokens: Optional[int] = None,
                 capacity: Optional[int] = None,
                 share: Optional[bool] = None):
        self.ctx = ctx
        self.page_tokens = int(
            page_tokens if page_tokens is not None else
            mca_param.get("serving.kv_page_tokens", 16))
        cap = int(capacity if capacity is not None else
                  mca_param.get("serving.kv_pages", 0))
        self.share = bool(
            share if share is not None else
            str(mca_param.get("serving.kv_prefix_cache", 1)).lower()
            not in ("0", "off", "false"))
        self.pool = KVPagePool(f"kv{id(self) & 0xffff:x}",
                               self.page_tokens, d_model,
                               capacity=cap,
                               hbm=getattr(ctx, "hbm", None))
        self.tree = RadixTree(self.pool)
        self.dc = self.pool.dc
        self._lock = threading.Lock()
        self.stats = {"requests": 0, "requests_hit": 0,
                      "tokens_looked_up": 0, "tokens_hit": 0,
                      "tokens_prefilled": 0,
                      "spec_windows": 0, "spec_accepted_steps": 0,
                      "spec_rejected_windows": 0,
                      "spec_cancelled_branches": 0}
        if ctx is not None:
            ctx.kv_state = self
            # conformance plumbing: page lifecycle events flow into the
            # context trace (when one is installed) for model replay
            self.pool.set_trace_source(
                lambda: getattr(ctx, "trace", None))

    # ------------------------------------------------------------ lookup
    def match(self, tokens: Sequence[int]) -> MatchHandle:
        """Prefix-cache lookup with hit accounting. With sharing off
        (``serving.kv_prefix_cache=0`` — the A/B baseline) this is a
        guaranteed miss at zero tree cost."""
        if not self.share:
            h = MatchHandle(self.tree, [], 0, [])
        else:
            h = self.tree.match(tokens)
        with self._lock:
            self.stats["requests"] += 1
            self.stats["tokens_looked_up"] += len(tokens)
            self.stats["tokens_hit"] += h.n_tokens
            if h.n_tokens:
                self.stats["requests_hit"] += 1
        return h

    def publish(self, tokens: Sequence[int], pids: Sequence[int]) -> int:
        if not self.share or not pids:
            return 0
        return self.tree.insert(tokens, pids)

    def note_prefilled(self, n_tokens: int) -> None:
        with self._lock:
            self.stats["tokens_prefilled"] += n_tokens

    def note_spec(self, windows: int = 0, accepted: int = 0,
                  rejected: int = 0, cancelled: int = 0) -> None:
        with self._lock:
            self.stats["spec_windows"] += windows
            self.stats["spec_accepted_steps"] += accepted
            self.stats["spec_rejected_windows"] += rejected
            self.stats["spec_cancelled_branches"] += cancelled

    def hit_rate(self) -> float:
        with self._lock:
            lk = self.stats["tokens_looked_up"]
            return (self.stats["tokens_hit"] / lk) if lk else 0.0

    # ----------------------------------------------------- observability
    def snapshot(self) -> Dict:
        """The statusz/metrics block — scrape-time only, no hot-path
        accounting beyond the counters already kept."""
        with self._lock:
            stats = dict(self.stats)
        return {"page_tokens": self.page_tokens,
                "share": self.share,
                "hit_rate": round(self.hit_rate(), 6),
                "pool": self.pool.snapshot(),
                "tree": self.tree.snapshot(),
                **stats}


def layer_for(ctx, d_model: int, **kw) -> KVStateLayer:
    """Get-or-create the context's KV state layer (idempotent;
    parameters apply at creation)."""
    layer = getattr(ctx, "kv_state", None)
    if layer is None:
        layer = KVStateLayer(ctx, d_model, **kw)
    return layer
