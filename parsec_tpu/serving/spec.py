"""Speculative decode as a DTD pattern (ISSUE 15, tentpole part 3).

Classic speculative decoding runs a CHEAP draft model ahead of the true
model and verifies a whole window of draft tokens in one true-model
forward pass; accepted positions are provably identical to what the
true model would have produced, and a rejected draft branch is thrown
away. This module maps that onto the task-dataflow runtime:

- **Draft branch** = its own cancellable taskpool per request (the
  cancellation unit ``Taskpool.cancel`` gives us: queued tasks dropped
  at select time, in-flight ones drain). The draft model here is the
  TRUE weights with SLIDING-WINDOW attention (last ``window`` rows
  only) — genuinely cheaper on long contexts, and EXACT while the
  context still fits the window (early drafts accept bitwise; once the
  context outgrows the window, drafts diverge and the branch loses —
  both acceptance and rejection paths are exercised deterministically
  by context length). Draft steps append their (k, v) rows into
  COPY-ON-WRITE pages (:meth:`~.kv.KVPagePool.cow` of the request's
  tail page — the divergence-point copy, the second writer the COW
  design exists for), so the main chain's pages are never touched by
  speculation.
- **Verify tasks** in the MAIN pool replace the per-step decode tasks:
  one verify task replays a whole window of ``serving.kv_spec_draft``
  true steps through the EXACT :func:`~.decode._step_kernel` sequence
  (results are bitwise the non-speculative chain's by construction —
  speculation is invisible to results), compares each true state
  against the draft branch's state for that position (read at
  execution time; a draft that has not produced the position yet
  counts as rejected — acceptance is dynamic, correctness is not),
  and on the first mismatch CANCELS the losing branch.
- **Rejected-branch pages are released back to the pool** once the
  cancelled branch drained (release waits for the branch pool's
  completion event so an in-flight draft's write-back can never race a
  reallocated page).

What speculation buys on THIS runtime: the host-side per-task overhead
dominates a decode step (the bodies are tiny), so folding ``L`` steps
into one verify task cuts the main pool's per-request task count by
``L``× while the draft chain rides a low-weight pool under wfq — the
same economics as verifying L tokens in one forward pass on real
hardware.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

import numpy as np

from ..data.collection import LocalCollection
from ..dsl import dtd
from ..utils import mca_param
from ..utils.debug import debug_verbose

mca_param.register("serving.kv_spec_window", 0,
                   help="sliding-attention window (rows) of the "
                        "speculative draft model; 0 = 2 pages worth")
mca_param.register("serving.kv_spec_weight", 0.25,
                   help="fair-share weight of a request's speculative "
                        "draft branch pool relative to weight 1.0")
mca_param.register("serving.kv_spec_patience_ms", 5.0,
                   help="how long a verify window waits for the draft "
                        "branch's proposed state per position before "
                        "scoring it rejected — verification CONSUMES "
                        "the draft's proposal (real spec decode waits "
                        "for draft tokens by construction), but the "
                        "wait runs on a worker thread so it stays "
                        "SHORT: a slow branch degrades to rejection, "
                        "never to a stalled runtime; a lost or "
                        "cancelled branch is never waited for")


def _sliding_step(x, prevs, tail, slot, window, model):
    """Draft-model decode step: identical to
    :func:`~.decode._step_kernel` except attention only sees the LAST
    ``window`` cached rows. While the context fits the window this is
    bitwise the true step (same arrays, same op order after the
    no-op slice); beyond it, the draft diverges — by design."""
    from .decode import _attend
    k = x @ model.Wk
    v = x @ model.Wv
    tail = tail.copy()
    tail[0, slot] = k
    tail[1, slot] = v
    if prevs:
        K = np.concatenate([p[0] for p in prevs] + [tail[0, :slot + 1]],
                           axis=0)
        V = np.concatenate([p[1] for p in prevs] + [tail[1, :slot + 1]],
                           axis=0)
    else:
        K = tail[0, :slot + 1]
        V = tail[1, :slot + 1]
    return _attend(x, K[-window:], V[-window:], model), tail


def _draft_window_body(*vals):
    """One draft-chain WINDOW in the branch pool (INOUT draft state
    tile, INOUT the window's COW/draft pages, INPUT prior pages):
    ``steps`` sliding-window draft steps in one task body — the draft
    chain advances a whole window per scheduler pass, so it keeps pace
    with the (equally windowed) verify chain. Each position's proposed
    state is published into the side-channel collection AS COMPUTED
    (atomic tile replace; the verify reader tolerates absence)."""
    meta = vals[-1]
    n_rw = meta["n_rw"]
    x = vals[0]
    rw = [v.copy() for v in vals[1:1 + n_rw]]
    dc_read = meta["dc_read"]
    ro = [dc_read((pid,)) for pid in meta["prev_pids"]]
    pages = ro + rw
    pt, model = meta["pt"], meta["model"]
    ddc = meta["ddc"]
    j_base = len(ro)
    for i in range(meta["steps"]):
        t = meta["t0"] + i
        j, slot = divmod(t, pt)
        x, new_tail = _sliding_step(x, pages[:j], pages[j], slot,
                                    meta["window"], model)
        pages[j] = new_tail
        rw[j - j_base] = new_tail
        ddc.write_tile((meta["req"], t), x)
    return (x, *rw)


def verify_exec(vals, meta):
    """Body of one verify window (dispatched from
    :func:`~.decode._paged_body`): replay ``steps`` TRUE decode steps
    in one task — the exact per-step kernel sequence of the
    non-speculative chain — and score the draft branch's states
    against them. ``vals`` = (state, *window INOUT pages, *prior INPUT
    pages, meta)."""
    from .decode import PoisonBody, _step_kernel
    n_rw = meta["n_rw"]
    x = vals[0]
    rw = [v.copy() for v in vals[1:1 + n_rw]]
    dc_read = meta["dc_read"]
    ro = [dc_read((pid,)) for pid in meta["prev_pids"]]
    pages = ro + rw               # absolute page order 0..j1
    pt, model = meta["pt"], meta["model"]
    t0, steps = meta["t0"], meta["steps"]
    j0 = len(ro)
    draft_read = meta["draft_read"]
    accepted, matched = 0, True
    for i in range(steps):
        t = t0 + i
        if meta.get("poison_at") is not None and t == meta["poison_at"]:
            raise PoisonBody(
                f"poison body: request {meta['req']} step {t}")
        j, slot = divmod(t, pt)
        x, new_tail = _step_kernel(x, pages[:j], pages[j], slot, model)
        pages[j] = new_tail
        rw[j - j0] = new_tail
        if matched:
            d = draft_read((meta["req"], t))
            if d is not None and d.shape == x.shape and \
                    np.array_equal(d, x):
                accepted += 1
            else:
                matched = False
    meta["on_verify"](meta["widx"], accepted, steps)
    return (x, *rw)


class SpecController:
    """Per-request speculative-decode coordinator: builds the verify
    windows for the main batch, launches the draft branch once the
    prefill state is final, cancels the branch on the first rejected
    window, and releases the branch's COW pages when the request is
    released."""

    def __init__(self, engine, req, draft_len: int):
        self.engine = engine
        self.req = req
        self.layer = engine.kv_layer
        self.draft_len = max(1, int(draft_len))
        w = int(mca_param.get("serving.kv_spec_window", 0))
        self.window = w if w > 0 else 2 * self.layer.page_tokens
        self._lock = threading.Lock()
        self._cancelled = False
        self._released = False
        self.branch_tp = None
        self.branch_sub = None
        self.draft_pids: List[int] = []
        # draft states keyed (rid, t) — read by verify bodies at
        # execution time (acceptance is dynamic; never correctness)
        self.draft_dc = LocalCollection(
            f"{engine.name}_draft{req.rid}",
            myrank=getattr(engine.ctx, "my_rank", 0))
        self.accepted_steps = 0
        self.rejected = False

    # ------------------------------------------------- main-pool windows
    def verify_rows(self, poison_at: Optional[int]
                    ) -> Tuple[List[list], List[int]]:
        """The request's decode rows as verify windows (for the one
        all-or-nothing ``insert_tasks`` batch)."""
        eng, req = self.engine, self.req
        pt = self.layer.page_tokens
        dc = self.layer.dc
        S = len(req.tokens)
        rows, prios = [], []
        widx = 0
        t = S
        end = S + req.n_steps
        while t < end:
            steps = min(self.draft_len, end - t)
            j0, j1 = t // pt, (t + steps - 1) // pt
            args = [dtd.TileArg(eng.state, (req.rid,), dtd.INOUT)]
            args += [dtd.TileArg(dc, (req.pages[j],), dtd.INOUT)
                     for j in range(j0, j1 + 1)]
            args.append(dtd.ValueArg({
                "kind": "verify", "req": req.rid, "t0": t,
                "steps": steps, "pt": pt, "model": eng.model,
                "n_rw": j1 - j0 + 1, "widx": widx,
                "poison_at": poison_at,
                "prev_pids": tuple(req.pages[:j0]),
                "dc_read": dc.data_of,
                "draft_read": self._draft_read,
                "on_verify": self._on_verify}))
            rows.append(args)
            prios.append(0)
            widx += 1
            t += steps
        return rows, prios

    # ----------------------------------------------------- draft branch
    def start_branch(self) -> None:
        """Launch the draft branch once the prefill state is final.

        The draft chain's INPUT deps on the main pool's pages are
        INSERT-time snapshots (cross-pool reads are untracked), so the
        branch may only be inserted after the prompt pages and the
        prefill state were written back — a tiny bounded watcher
        thread (off every hot path) inserts it at that point."""
        threading.Thread(target=self._launch_when_ready,
                         daemon=True).start()

    def _launch_when_ready(self) -> None:
        """Wait (bounded) for the request's prefill-state write-back —
        detected by OBJECT IDENTITY against the placeholder the engine
        wrote at request time (the runtime's write-back replaces the
        tile object) — then insert the draft chain. A cancelled or
        failed pool simply never launches a branch."""
        eng, req = self.engine, self.req
        import time as _time
        deadline = _time.monotonic() + 30.0
        placeholder = getattr(req, "_spec_x0_ph", None)
        x0 = None
        while _time.monotonic() < deadline:
            with self._lock:
                if self._cancelled or self._released:
                    return
            tp = eng.tp
            if tp is None or tp.cancelled or tp.error is not None:
                return
            x0 = eng.state.data_of((req.rid,))
            if x0 is not None and x0 is not placeholder:
                break
            if req.done_evt.wait(0.002):
                return                # request finished before we drafted
            x0 = None
        if x0 is None:
            return
        try:
            self._insert_branch(np.asarray(x0))
        except Exception as exc:  # noqa: BLE001 — speculation is optional
            debug_verbose(2, "spec", "draft branch of rid %d not "
                          "launched: %s", req.rid, exc)

    def _insert_branch(self, x0: np.ndarray) -> None:
        from .kv import KVPagesExhausted
        eng, req = self.engine, self.req
        layer, pt = self.layer, self.layer.page_tokens
        S = len(req.tokens)
        end = S + req.n_steps
        pool = layer.pool
        # page plan for the draft chain: COW the request's current
        # tail page (the divergence point — the true chain will write
        # the same slots), fresh pages for every later boundary
        j_first = S // pt
        n_draft_pages = (end + pt - 1) // pt - j_first
        try:
            first = pool.cow(req.pages[j_first])
            extra = pool.alloc(max(0, n_draft_pages - 1))
        except (KVPagesExhausted, KeyError) as exc:
            debug_verbose(2, "spec", "no pages for draft branch of "
                          "rid %d: %s", req.rid, exc)
            return
        dpids = [first] + extra
        tp = dtd.Taskpool(f"{eng.name}_spec{req.rid}")
        sub = None
        ctx = eng.ctx
        weight = float(mca_param.get("serving.kv_spec_weight", 0.25))
        try:
            if getattr(ctx, "serving", None) is not None and \
                    eng.submission is not None:
                sub = ctx.submit(tp, tenant=eng.tenant, weight=weight)
            else:
                ctx.add_taskpool(tp)
        except Exception:
            for pid in dpids:
                pool.release(pid)
            raise
        with self._lock:
            if self._cancelled or self._released:
                tp.cancel()
                for pid in dpids:
                    pool.release(pid)
                return
            self.branch_tp = tp
            self.branch_sub = sub
            self.draft_pids = dpids
        dc = layer.dc
        ddc = self.draft_dc
        ddc.write_tile(("s",), x0)
        rows = []
        t = S
        while t < end:
            steps = min(self.draft_len, end - t)
            j0, j1 = t // pt, (t + steps - 1) // pt
            args = [dtd.TileArg(ddc, ("s",), dtd.INOUT)]
            args += [dtd.TileArg(dc, (dpids[j - j_first],), dtd.INOUT)
                     for j in range(j0, j1 + 1)]
            # prior pages by pid: the request's immutable prefix
            # (final at launch time) then the draft's own earlier
            # pages (ordered by the ddc INOUT chain)
            prev_pids = tuple(req.pages[:j_first]) + \
                tuple(dpids[jj - j_first] for jj in range(j_first, j0))
            args.append(dtd.ValueArg({
                "t0": t, "steps": steps, "pt": pt,
                "n_rw": j1 - j0 + 1, "window": self.window,
                "model": eng.model, "req": req.rid, "ddc": ddc,
                "prev_pids": prev_pids, "dc_read": dc.data_of}))
            rows.append(args)
            t += steps
        try:
            tp.insert_tasks(_draft_window_body, rows)
        except Exception as exc:  # noqa: BLE001 — speculation optional
            debug_verbose(2, "spec", "draft insert of rid %d failed: "
                          "%s", req.rid, exc)
            self.cancel_branch(count=False)

    # ------------------------------------------------------ verification
    def _draft_read(self, key):
        """The verify window's view of the draft branch: the proposed
        state for ``key = (rid, t)``, waited for with BOUNDED patience
        (``serving.kv_spec_patience_ms``) — verification consumes the
        draft's proposal, so it grants the branch a grace window; a
        branch that already lost (cancelled/rejected) or a request past
        its drafts is never waited for."""
        import time as _time
        v = self.draft_dc.data_of(key)
        if v is not None:
            return v
        patience = float(mca_param.get("serving.kv_spec_patience_ms",
                                       5.0)) / 1e3
        deadline = _time.monotonic() + patience
        while _time.monotonic() < deadline:
            with self._lock:
                if self._cancelled or self._released:
                    return None
            if self.rejected:
                return None
            tp = self.branch_tp
            if tp is not None and (tp.cancelled or tp.error is not None):
                return None
            v = self.draft_dc.data_of(key)
            if v is not None:
                return v
            _time.sleep(0.0005)
        return v

    def _on_verify(self, widx: int, accepted: int, steps: int) -> None:
        self.accepted_steps += accepted
        self.layer.note_spec(windows=1, accepted=accepted,
                             rejected=1 if accepted < steps else 0)
        if accepted < steps:
            self.rejected = True
            self.cancel_branch()

    def cancel_branch(self, count: bool = True) -> None:
        """Cancel the losing draft branch: queued draft tasks drop at
        select time; the branch's pages return to the pool at
        :meth:`release` (after the branch drained)."""
        with self._lock:
            if self._cancelled:
                return
            self._cancelled = True
            tp, sub = self.branch_tp, self.branch_sub
        if tp is None:
            return
        try:
            if sub is not None:
                sub.cancel()
            else:
                tp.cancel()
        except Exception:  # noqa: BLE001 — already terminated
            pass
        if count:
            self.layer.note_spec(cancelled=1)

    def release(self, timeout: float = 10.0) -> None:
        """Release the branch's resources (idempotent): cancel if still
        running, wait for the branch pool to drain (an in-flight
        draft's write-back must never race a reallocated page), then
        return the COW/draft pages to the pool."""
        with self._lock:
            if self._released:
                return
            self._released = True
        self.cancel_branch(count=False)
        tp = self.branch_tp
        if tp is not None and not tp._complete_evt.wait(timeout):
            # the branch did NOT drain: a still-in-flight draft's
            # write-back would corrupt a reallocated page — LEAK the
            # pids (loudly) rather than release them for reuse
            from ..utils.debug import warning
            warning("spec", "draft branch of rid %d not drained in "
                    "%.1fs; leaking %d draft pages instead of "
                    "releasing them for reuse", self.req.rid, timeout,
                    len(self.draft_pids))
            self.draft_pids = []
            return
        for pid in self.draft_pids:
            self.layer.pool.release(pid)
        self.draft_pids = []
        for key in self.draft_dc.keys():
            self.draft_dc.drop_tile(key)
