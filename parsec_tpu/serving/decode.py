"""Continuous-batching transformer decode over DTD insertions.

The workload the north star implies (ROADMAP item 4, Orca-style
iteration-level scheduling): each live request advances one decode step
per iteration; steps are DTD task insertions whose INOUT chain on the
request's state tile serializes its own steps while steps of DIFFERENT
requests (and different tenants' pools) interleave freely under the
weighted-fair scheduler — the runtime's dataflow tracking IS the
continuous batcher.

- **KV cache as a tiled collection**: per (request, tile-index) tiles of
  ``(2, kv_tile, D)`` packed keys+values in a
  :class:`KVCacheCollection`; device-resident tiles are registered with
  the context's HBM budget manager (``device.hbm_budget_mb``) with
  next-use hints, so under memory pressure the plan-informed (Belady)
  ranking evicts the coldest cache tiles and a finished request's tiles
  are dropped outright.
- **Decode steps as DTD insertions**: step *t* reads the full prior
  cache (INPUT tiles), appends its (k, v) into the tail tile (INOUT)
  and rewrites the state vector (INOUT); the shared step kernel
  (:func:`_step_kernel`) is also what the bitwise reference replays, so
  "bitwise-correct under faults" is checked against the exact float32
  op sequence, not a tolerance.
- **Long contexts**: prompt prefill builds the whole prompt's KV cache
  and first state with ONE compiled attention call —
  :func:`~parsec_tpu.compiled.ring_attention.ring_attention` over a
  mesh when one is given (sequence-sharded ppermute ring), the dense
  jnp fold otherwise.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..data.collection import LocalCollection
from ..dsl import dtd


class PoisonBody(ValueError):
    """Deliberate task-body failure injected by a misbehaving tenant
    (the serving bench's poison traffic)."""


@dataclass
class DecodeConfig:
    d_model: int = 32
    n_heads: int = 2
    kv_tile: int = 8          # (k, v) pairs per cache tile
    seed: int = 7

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


class DecodeModel:
    """Deterministic float32 decode-step weights."""

    def __init__(self, cfg: DecodeConfig):
        rng = np.random.default_rng(cfg.seed)
        D = cfg.d_model

        def w(shape):
            return (rng.standard_normal(shape) * 0.25 /
                    math.sqrt(shape[0])).astype(np.float32)

        self.cfg = cfg
        self.Wq, self.Wk, self.Wv, self.Wo = (w((D, D)) for _ in range(4))
        self.W1 = w((D, 2 * D))
        self.W2 = w((2 * D, D))

    def init_state(self, rid: int) -> np.ndarray:
        rng = np.random.default_rng(self.cfg.seed * 1_000_003 + rid)
        return rng.standard_normal(self.cfg.d_model).astype(np.float32)


def _ffn_tail(x: np.ndarray, ctx_vec: np.ndarray,
              model: DecodeModel) -> np.ndarray:
    """Output projection + residual FFN + tanh over one position's
    attention context — shared by the stepwise decode kernel and the
    compiled prompt prefill so both land on the same float32 tail."""
    o = ctx_vec @ model.Wo
    h1 = x + o
    h2 = h1 + np.maximum(h1 @ model.W1, np.float32(0.0)) @ model.W2
    return np.tanh(h2)


def _attend(x: np.ndarray, K: np.ndarray, V: np.ndarray,
            model: DecodeModel) -> np.ndarray:
    """One decode attention + FFN step over the cached (K, V) rows —
    float32 throughout, fixed op order (the bitwise contract both the
    task body and the reference replay)."""
    cfg = model.cfg
    H, dh = cfg.n_heads, cfg.d_head
    q = (x @ model.Wq).reshape(H, dh)
    Kh = K.reshape(K.shape[0], H, dh)
    Vh = V.reshape(V.shape[0], H, dh)
    ctx = np.empty((H, dh), dtype=np.float32)
    scale = np.float32(1.0 / math.sqrt(dh))
    for h in range(H):
        s = (Kh[:, h, :] @ q[h]) * scale
        m = np.float32(s.max())
        e = np.exp(s - m, dtype=np.float32)
        w = e / np.float32(e.sum())
        ctx[h] = w @ Vh[:, h, :]
    return _ffn_tail(x, ctx.reshape(H * dh), model)


def _step_kernel(x: np.ndarray, prevs: List[np.ndarray],
                 tail: np.ndarray, slot: int, model: DecodeModel):
    """Shared decode-step kernel: append (k, v) of ``x`` at ``slot`` of
    the tail tile, attend over the full cache, return (new state, new
    tail). Functional: the tail is copied, never mutated in place
    (snapshot readers of the prior version stay valid — the DTD
    functional-body contract)."""
    k = x @ model.Wk
    v = x @ model.Wv
    tail = tail.copy()
    tail[0, slot] = k
    tail[1, slot] = v
    if prevs:
        K = np.concatenate([p[0] for p in prevs] + [tail[0, :slot + 1]],
                           axis=0)
        V = np.concatenate([p[1] for p in prevs] + [tail[1, :slot + 1]],
                           axis=0)
    else:
        K = tail[0, :slot + 1]
        V = tail[1, :slot + 1]
    return _attend(x, K, V, model), tail


def _decode_body(state, tail, *rest):
    """DTD task body of one decode step. ``rest`` = the request's prior
    (full) KV tiles, then the per-step meta dict (ValueArg)."""
    prevs, meta = list(rest[:-1]), rest[-1]
    t = meta["t"]
    if meta.get("poison_at") is not None and t == meta["poison_at"]:
        raise PoisonBody(
            f"poison body: request {meta['req']} step {t}")
    return _step_kernel(state, prevs, tail, meta["slot"], meta["model"])


def _done_body(state, meta):
    """Completion sentinel: an INPUT-only reader of the request's state
    tile, RAW-chained behind the final decode step — so it runs
    strictly AFTER the runtime wrote the final step's outputs back to
    the collections. Recording completion from the final step's own
    body would fire BEFORE its write-back, racing any cleanup."""
    done = meta.get("on_done")
    if done is not None:
        done(meta["req"], state)


def _prompt_of(model: DecodeModel, rid: int, prompt_len: int) -> np.ndarray:
    rng = np.random.default_rng(model.cfg.seed * 7_919 + rid)
    return rng.standard_normal(
        (prompt_len, model.cfg.d_model)).astype(np.float32)


def _prefill_request(model: DecodeModel, rid: int, prompt_len: int,
                     mesh=None):
    """Prompt prefill for one request: K/V of every prompt position
    (packed into whole leading KV tiles by the caller) and the initial
    decode state — the LAST position's attention context from ONE
    compiled attention call (:func:`prefill_attention`: ring over a
    mesh, dense otherwise) folded through the shared FFN tail. Returns
    ``(x0, K, V)`` as float32 numpy; deterministic per (model, rid,
    backend), so the reference replay reproduces it bitwise."""
    cfg = model.cfg
    if prompt_len % cfg.kv_tile:
        raise ValueError(
            f"prompt_len {prompt_len} must be a multiple of kv_tile "
            f"{cfg.kv_tile} (whole prefilled cache tiles)")
    prompt = _prompt_of(model, rid, prompt_len)
    K = prompt @ model.Wk
    V = prompt @ model.Wv
    ctx_rows = prefill_attention(model, prompt, mesh=mesh, causal=True)
    x0 = _ffn_tail(prompt[-1], ctx_rows[-1], model)
    return x0, K, V


def _packed_tiles(model: DecodeModel, K: np.ndarray,
                  V: np.ndarray) -> List[np.ndarray]:
    cfg = model.cfg
    kt = cfg.kv_tile
    tiles = []
    for j in range(K.shape[0] // kt):
        tile = np.zeros((2, kt, cfg.d_model), dtype=np.float32)
        tile[0] = K[j * kt:(j + 1) * kt]
        tile[1] = V[j * kt:(j + 1) * kt]
        tiles.append(tile)
    return tiles


def reference_decode(model: DecodeModel, rid: int, n_steps: int,
                     prompt_len: int = 0, mesh=None) -> np.ndarray:
    """Single-threaded replay of ``n_steps`` decode steps for request
    ``rid`` (after an optional prompt prefill) through the SAME kernels
    the engine runs — the bitwise oracle."""
    cfg = model.cfg
    if prompt_len:
        x, K, V = _prefill_request(model, rid, prompt_len, mesh=mesh)
        tiles = _packed_tiles(model, K, V)
    else:
        x = model.init_state(rid)
        tiles: List[np.ndarray] = []
    for t in range(prompt_len, prompt_len + n_steps):
        j, slot = divmod(t, cfg.kv_tile)
        if slot == 0:
            tiles.append(np.zeros((2, cfg.kv_tile, cfg.d_model),
                                  dtype=np.float32))
        x, tiles[j] = _step_kernel(x, tiles[:j], tiles[j], slot, model)
    return x


# ------------------------------------------------------- paged (ISSUE 15)
def token_embedding(model: DecodeModel, tok: int) -> np.ndarray:
    """Deterministic float32 embedding of one token id (cached on the
    model). Token-identified prompts are what make prefixes SHAREABLE:
    two requests presenting the same token ids mean the same bytes."""
    cache = getattr(model, "_emb_cache", None)
    if cache is None:
        cache = model._emb_cache = {}
    e = cache.get(tok)
    if e is None:
        rng = np.random.default_rng(model.cfg.seed * 524_287 + int(tok))
        e = rng.standard_normal(model.cfg.d_model).astype(np.float32)
        e.setflags(write=False)
        cache[tok] = e
    return e


def page_rows(model: DecodeModel, toks) -> np.ndarray:
    """(k, v) rows for ``toks`` as ``(2, len(toks), D)`` — computed
    per-ROW (vector @ matrix), so a row's bytes depend ONLY on its own
    token: prefill chunking, partial-page fills, and prefix sharing can
    never change results bitwise (a row reused from the cache is
    byte-identical to the row the no-sharing replay computes)."""
    out = np.empty((2, len(toks), model.cfg.d_model), dtype=np.float32)
    for i, tok in enumerate(toks):
        e = token_embedding(model, tok)
        out[0, i] = e @ model.Wk
        out[1, i] = e @ model.Wv
    return out


def paged_prefill_state(model: DecodeModel, tokens, pages) -> np.ndarray:
    """Initial decode state after a token prompt: the LAST position's
    attention over every prompt row (assembled from the page run) folded
    through the shared FFN tail — the exact numpy kernel
    :func:`reference_decode_paged` replays, so sharing stays bitwise-
    invisible. ``pages`` must cover ``len(tokens)`` rows."""
    S = len(tokens)
    K = np.concatenate([p[0] for p in pages], axis=0)[:S]
    V = np.concatenate([p[1] for p in pages], axis=0)[:S]
    return _attend(token_embedding(model, tokens[-1]), K, V, model)


def reference_decode_paged(model: DecodeModel, tokens, n_steps: int,
                           page_tokens: int) -> np.ndarray:
    """Single-threaded no-sharing replay of a token-prompted paged
    request through the SAME kernels the engine runs (per-row prefill,
    last-position attention, per-step :func:`_step_kernel`) — the
    bitwise oracle proving prefix sharing, chunked prefill, and
    speculative decode are invisible to results."""
    pt = page_tokens
    tokens = tuple(tokens)
    S = len(tokens)
    if S < 1:
        raise ValueError("paged decode requires a non-empty prompt")
    n_pages = (S + n_steps + pt - 1) // pt
    pages = [np.zeros((2, pt, model.cfg.d_model), dtype=np.float32)
             for _ in range(n_pages)]
    for j in range((S + pt - 1) // pt):
        toks = tokens[j * pt:min((j + 1) * pt, S)]
        rows = page_rows(model, toks)
        pages[j][:, :len(toks)] = rows
    x = paged_prefill_state(model, tokens,
                            pages[:(S + pt - 1) // pt])
    for t in range(S, S + n_steps):
        j, slot = divmod(t, pt)
        x, pages[j] = _step_kernel(x, pages[:j], pages[j], slot, model)
    return x


def _paged_body(*vals):
    """Single DTD body for every row of a paged request's task graph —
    ONE ``insert_tasks`` batch per request means ONE admission check:
    the graph is admitted all-or-nothing (a mid-graph rejection cannot
    leave a half-inserted request leaking pages). The trailing ValueArg
    meta dict selects the role:

    - ``prefill``: fill this chunk's pages' (k, v) rows (INOUT pages;
      functional — copies, never mutates, so snapshot readers stay
      valid). Rides the wfq prefill lane (priority < 0).
    - ``state``: last-position attention over the prompt pages (INPUT)
      into the request's state tile (INOUT); publishes the full prompt
      pages to the radix tree — the pages are final HERE (this task is
      RAW-ordered behind every chunk's write-back), which is what makes
      cross-pool sharing race-free.
    - ``step``: one decode step (exactly :func:`_decode_body`).
    - ``verify``: one speculative-decode window (serving/spec.py).
    - ``done``: the completion sentinel (:func:`_done_body`).
    """
    meta = vals[-1]
    kind = meta["kind"]
    if kind == "step":
        # the page TABLE is the argument, not the pages (the
        # PagedAttention shape): prior pages are read by pid at
        # EXECUTION time. Correct without per-page dataflow edges
        # because (a) the request's INOUT state chain serializes its
        # steps, (b) the state task INPUT-fences every prefill write,
        # (c) write-backs precede successor release, and (d) the
        # request's page refcounts keep every pid immutable-in-place
        # until release — so the 40+ INPUT TileArgs a long-context
        # step would otherwise carry (and their insert/dep-count cost)
        # collapse into one tuple of ints.
        t = meta["t"]
        if meta.get("poison_at") is not None and t == meta["poison_at"]:
            raise PoisonBody(
                f"poison body: request {meta['req']} step {t}")
        dc_read = meta["dc_read"]
        prevs = [dc_read((pid,)) for pid in meta["prev_pids"]]
        return _step_kernel(vals[0], prevs, vals[1], meta["slot"],
                            meta["model"])
    if kind == "steps":
        # multi-step decode window (serving.kv_decode_window > 1): the
        # EXACT per-step kernel sequence run W steps per task — same
        # floats, W× fewer scheduler passes per request
        n_rw = meta["n_rw"]
        x = vals[0]
        rw = [v.copy() for v in vals[1:1 + n_rw]]
        dc_read = meta["dc_read"]
        pages = [dc_read((pid,)) for pid in meta["prev_pids"]] + rw
        pt, model = meta["pt"], meta["model"]
        j_base = len(pages) - n_rw
        for i in range(meta["steps"]):
            t = meta["t0"] + i
            if meta.get("poison_at") is not None and \
                    t == meta["poison_at"]:
                raise PoisonBody(
                    f"poison body: request {meta['req']} step {t}")
            j, slot = divmod(t, pt)
            x, new_tail = _step_kernel(x, pages[:j], pages[j], slot,
                                       model)
            pages[j] = new_tail
            rw[j - j_base] = new_tail
        return (x, *rw)
    if kind == "done":
        return _done_body(vals[0], meta)
    model = meta["model"]
    if kind == "prefill":
        pages = vals[:-1]
        out = []
        for page, toks in zip(pages, meta["toks"]):
            page = page.copy()
            page[:, :len(toks)] = page_rows(model, toks)
            out.append(page)
        return out[0] if len(out) == 1 else tuple(out)
    if kind == "state":
        # cached-prefix pages are final and refcount-held, so they are
        # read by pid (no dataflow edge); only the request's OWN
        # suffix-prefill pages arrive as INPUT flows — the fence that
        # orders this task behind its chunk tasks' write-backs
        dc_read = meta["dc_read"]
        pages = [dc_read((pid,)) for pid in meta["prev_pids"]]
        pages += list(vals[1:-1])
        x0 = paged_prefill_state(model, meta["tokens"], pages)
        publish = meta.get("publish")
        if publish is not None:
            publish()
        return x0
    if kind == "verify":
        from .spec import verify_exec
        return verify_exec(vals, meta)
    raise ValueError(f"unknown paged row kind {kind!r}")


# --------------------------------------------------------------- prefill
def prefill_attention(model: DecodeModel, prompt: np.ndarray,
                      mesh=None, causal: bool = True) -> np.ndarray:
    """Long-context prompt prefill: one compiled attention call over the
    whole prompt ``(S, D)`` — ring attention (sequence-sharded ppermute
    ring, ``compiled/ring_attention.py``) when a mesh is given, the
    dense jnp fold otherwise. Returns the attention output ``(S, D)``
    as float32 numpy."""
    import jax.numpy as jnp
    from ..compiled.ring_attention import dense_attention, ring_attention
    cfg = model.cfg
    S = prompt.shape[0]
    H, dh = cfg.n_heads, cfg.d_head
    Q = (prompt @ model.Wq).reshape(S, H, dh)
    K = (prompt @ model.Wk).reshape(S, H, dh)
    V = (prompt @ model.Wv).reshape(S, H, dh)
    if mesh is not None:
        out = ring_attention(jnp.asarray(Q), jnp.asarray(K),
                             jnp.asarray(V), mesh, causal=causal)
    else:
        out = dense_attention(jnp.asarray(Q), jnp.asarray(K),
                              jnp.asarray(V), causal=causal)
    return np.asarray(out, dtype=np.float32).reshape(S, H * dh)


# ------------------------------------------------------------ collections
class KVCacheCollection(LocalCollection):
    """Dict-backed KV cache whose device-resident tiles are registered
    with the HBM budget manager: every write refreshes the tile's
    next-use hint (a live request touches its whole cache again next
    step), so the Belady ranking evicts the longest-idle cache tiles
    first and :meth:`drop_request` releases a finished request's tiles
    outright. Host (numpy) tiles pass through untracked."""

    def __init__(self, name: str, hbm=None, myrank: int = 0):
        super().__init__(name, myrank=myrank)
        self.hbm = hbm
        self._clock = 0

    def _mkey(self, key):
        return (id(self), tuple(key))

    def write_tile(self, key, value) -> None:
        super().write_tile(key, value)
        hbm = self.hbm
        if hbm is None or not isinstance(value, hbm.jax.Array):
            return
        self._clock += 1

        def _spill(_k, host, dc=self, key=key):
            LocalCollection.write_tile(dc, key, host)

        try:
            hbm.put(self._mkey(key), value, next_use=self._clock + 1,
                    spill=_spill)
        except MemoryError:
            pass                      # tile bigger than the whole budget

    def drop_request(self, rid: int) -> None:
        """Release a finished request's cache: HBM-manager entries AND
        the host tiles (a persistent serving engine would otherwise
        grow by one request's KV forever)."""
        for key in self.keys():
            if key[0] == rid:
                if self.hbm is not None:
                    self.hbm.drop(self._mkey(key))
                self.drop_tile(key)


# ---------------------------------------------------------------- engine
@dataclass
class PendingRequest:
    rid: int
    n_steps: int
    submitted_t: float
    prompt_len: int = 0
    mesh: object = None
    done_evt: threading.Event = field(default_factory=threading.Event)
    finished_t: Optional[float] = None
    result: Optional[np.ndarray] = None
    # paged (KV state layer) requests — ISSUE 15
    tokens: Optional[tuple] = None      # token prompt (None = classic)
    pages: Optional[list] = None        # page table: ordered pids
    match: object = None                # radix MatchHandle (node pins)
    n_cached: int = 0                   # prefix tokens served from cache
    spec: object = None                 # speculative-decode controller

    def latency_s(self) -> Optional[float]:
        return (self.finished_t - self.submitted_t
                if self.finished_t is not None else None)


class DecodeEngine:
    """Continuous-batching decode front end for ONE tenant.

    ``start()`` submits a persistent DTD pool through the serving
    runtime; ``request()`` inserts a request's decode steps (admission
    control applies per insert — :class:`~.runtime.AdmissionRejected`
    propagates to the caller); completion is detected per request by
    the final step's body callback, so per-request latency is
    end-to-end through the runtime, not a wrapper around wait()."""

    def __init__(self, ctx, name: str, cfg: Optional[DecodeConfig] = None,
                 tenant=None, model: Optional[DecodeModel] = None,
                 kv_layer=None, **submit_kwargs):
        self.ctx = ctx
        self.name = name
        self.cfg = cfg or DecodeConfig()
        self.model = model or DecodeModel(self.cfg)
        self.tenant = tenant
        # KV state layer (serving/kv.py): when attached, token-prompted
        # requests take the paged path — radix prefix match, paged
        # allocation, chunked prefill on the wfq prefill lane, optional
        # speculative decode
        self.kv_layer = kv_layer
        self.submit_kwargs = submit_kwargs
        # collections OWNED by this context's rank: a decode engine on
        # a worker rank of an elastic mesh must place its steps locally
        # (rank_of = 0 would ship every task to the front-end rank)
        self.state = LocalCollection(f"{name}_state",
                                     myrank=ctx.my_rank)
        self.kv = KVCacheCollection(f"{name}_kv", hbm=ctx.hbm,
                                    myrank=ctx.my_rank)
        self.tp = None
        self.submission = None
        self.pending: Dict[int, PendingRequest] = {}
        self._lock = threading.Lock()

    def start(self) -> "DecodeEngine":
        self.tp = dtd.Taskpool(f"{self.name}_decode")
        self.submission = self.ctx.submit(self.tp, tenant=self.tenant,
                                          **self.submit_kwargs)
        return self

    def _on_done(self, rid: int, h: np.ndarray) -> None:
        # record only — tile cleanup happens in release(): this runs
        # INSIDE the final step's body, before the runtime writes the
        # step's outputs back, so dropping tiles here would race the
        # completion write-back
        with self._lock:
            req = self.pending.get(rid)
        if req is not None:
            req.finished_t = time.monotonic()
            req.result = h
            req.done_evt.set()

    def request(self, rid: int, n_steps: int,
                poison_at: Optional[int] = None,
                prompt_len: int = 0, mesh=None,
                tokens=None) -> PendingRequest:
        """Admit one request and insert its decode steps. With
        ``prompt_len`` (a multiple of ``kv_tile``) the prompt's
        attention runs as ONE compiled prefill call (ring attention
        over ``mesh`` when given, dense otherwise) that SEEDS the
        request's KV cache tiles and initial state; the stepwise decode
        then attends over prompt + generated positions.

        With ``tokens`` (a sequence of token ids; requires a
        ``kv_layer``) the request takes the PAGED path instead: longest
        cached prefix served from the radix tree, only the suffix
        chunk-prefilled (wfq prefill lane), optional speculative decode
        (``serving.kv_spec_draft``)."""
        if tokens is not None:
            if self.kv_layer is None:
                raise ValueError(
                    "token-prompted requests need a KV state layer "
                    "(DecodeEngine(kv_layer=...))")
            return self._request_paged(rid, tuple(int(t) for t in tokens),
                                       n_steps, poison_at)
        cfg, model = self.cfg, self.model
        req = PendingRequest(rid, n_steps, time.monotonic(),
                             prompt_len=prompt_len, mesh=mesh)
        with self._lock:
            self.pending[rid] = req
        if prompt_len:
            x0, K, V = _prefill_request(model, rid, prompt_len,
                                        mesh=mesh)
            prefilled = _packed_tiles(model, K, V)
        else:
            x0, prefilled = model.init_state(rid), []
        self.state.write_tile((rid,), x0)
        for j, tile in enumerate(prefilled):
            self.kv.write_tile((rid, j), tile)
        t0 = prompt_len
        n_tiles = (t0 + n_steps + cfg.kv_tile - 1) // cfg.kv_tile
        for j in range(len(prefilled), n_tiles):
            self.kv.write_tile((rid, j), np.zeros(
                (2, cfg.kv_tile, cfg.d_model), dtype=np.float32))
        rows = []
        for t in range(t0, t0 + n_steps):
            j, slot = divmod(t, cfg.kv_tile)
            args = [dtd.TileArg(self.state, (rid,), dtd.INOUT),
                    dtd.TileArg(self.kv, (rid, j), dtd.INOUT)]
            args += [dtd.TileArg(self.kv, (rid, jj), dtd.INPUT)
                     for jj in range(j)]
            args.append(dtd.ValueArg({
                "req": rid, "t": t, "slot": slot,
                "model": model, "poison_at": poison_at}))
            rows.append(args)
        try:
            self.tp.insert_tasks(_decode_body, rows)
            # completion sentinel (see _done_body): post-write-back
            self.tp.insert_task(
                _done_body, dtd.TileArg(self.state, (rid,), dtd.INPUT),
                dtd.ValueArg({"req": rid, "on_done": self._on_done}))
        except Exception:
            # rejected insert (admission window, quarantine, aborted
            # pool): release the tiles written above too, or every
            # rejected rid of an open-loop stream leaks one state +
            # n_tiles KV tiles into the persistent collections
            with self._lock:
                self.pending.pop(rid, None)
            self.kv.drop_request(rid)
            self.state.drop_tile((rid,))
            raise
        return req

    # ------------------------------------------------ paged path (ISSUE 15)
    def _request_paged(self, rid: int, tokens: tuple, n_steps: int,
                       poison_at: Optional[int]) -> PendingRequest:
        """Token-prompted request through the KV state layer: match the
        longest cached prefix, allocate the rest of the page table,
        then insert the request's WHOLE task graph (prefill chunks on
        the wfq prefill lane, state, decode steps or speculative verify
        windows, completion sentinel) as ONE batch — one admission
        check, admitted all-or-nothing."""
        from ..utils import mca_param
        from .kv import KVPagesExhausted
        from .runtime import AdmissionRejected
        layer, model = self.kv_layer, self.model
        pt = layer.page_tokens
        S = len(tokens)
        if S < 1:
            raise ValueError("paged decode requires a non-empty prompt")
        total = S + n_steps
        n_pages = (total + pt - 1) // pt
        req = PendingRequest(rid, n_steps, time.monotonic(),
                             prompt_len=S, tokens=tokens)
        handle = layer.match(tokens)
        c_pages = len(handle.pids)
        req.match = handle
        req.n_cached = handle.n_tokens
        try:
            own = layer.pool.alloc(n_pages - c_pages)
        except KVPagesExhausted as exc:
            self._release_paged_refs(handle.pids, handle)
            raise AdmissionRejected(str(exc)) from exc
        pages = list(handle.pids) + own
        req.pages = pages
        with self._lock:
            self.pending[rid] = req
        placeholder = np.zeros(model.cfg.d_model, dtype=np.float32)
        req._spec_x0_ph = placeholder   # spec watcher: write-back is
        #                                 detected by object identity
        self.state.write_tile((rid,), placeholder)
        dc = layer.dc
        n_prompt_pages = (S + pt - 1) // pt

        rows, prios = [], []
        # chunked prefill of the UNCACHED suffix pages only
        chunk = max(1, int(mca_param.get("serving.kv_prefill_chunk", 4)))
        j = c_pages
        while j < n_prompt_pages:
            span = list(range(j, min(j + chunk, n_prompt_pages)))
            rows.append(
                [dtd.TileArg(dc, (pages[p],), dtd.INOUT) for p in span]
                + [dtd.ValueArg({
                    "kind": "prefill", "model": model, "req": rid,
                    "toks": [tokens[p * pt:min((p + 1) * pt, S)]
                             for p in span]})])
            prios.append(-1)
            j += chunk
        layer.note_prefilled(S - handle.n_tokens)
        # prefill-state task: INPUT every prompt page; publishes the
        # FULL prompt pages to the radix tree (bytes final here)
        full_prompt_pages = S // pt

        def _publish(_layer=layer, _tokens=tokens[:full_prompt_pages * pt],
                     _pids=tuple(pages[:full_prompt_pages])):
            _layer.publish(_tokens, _pids)

        rows.append(
            [dtd.TileArg(self.state, (rid,), dtd.INOUT)]
            + [dtd.TileArg(dc, (pages[p],), dtd.INPUT)
               for p in range(c_pages, n_prompt_pages)]
            + [dtd.ValueArg({"kind": "state", "model": model,
                             "req": rid, "tokens": tokens,
                             "prev_pids": tuple(pages[:c_pages]),
                             "dc_read": dc.data_of,
                             "publish": _publish})])
        prios.append(-1)
        # decode rows: plain per-step tasks, or speculative windows
        draft = int(mca_param.get("serving.kv_spec_draft", 0))
        if draft > 0 and n_steps > 0:
            from . import spec
            req.spec = spec.SpecController(self, req, draft)
            rows_v, prios_v = req.spec.verify_rows(poison_at)
            rows.extend(rows_v)
            prios.extend(prios_v)
        elif int(mca_param.get("serving.kv_decode_window", 1)) > 1:
            win = int(mca_param.get("serving.kv_decode_window", 1))
            t = S
            while t < S + n_steps:
                steps = min(win, S + n_steps - t)
                j0, j1 = t // pt, (t + steps - 1) // pt
                args = [dtd.TileArg(self.state, (rid,), dtd.INOUT)]
                args += [dtd.TileArg(dc, (pages[j],), dtd.INOUT)
                         for j in range(j0, j1 + 1)]
                args.append(dtd.ValueArg({
                    "kind": "steps", "req": rid, "t0": t,
                    "steps": steps, "pt": pt, "n_rw": j1 - j0 + 1,
                    "model": model, "poison_at": poison_at,
                    "prev_pids": tuple(pages[:j0]),
                    "dc_read": dc.data_of}))
                rows.append(args)
                prios.append(0)
                t += steps
        else:
            for t in range(S, S + n_steps):
                pj, slot = divmod(t, pt)
                rows.append([
                    dtd.TileArg(self.state, (rid,), dtd.INOUT),
                    dtd.TileArg(dc, (pages[pj],), dtd.INOUT),
                    dtd.ValueArg({
                        "kind": "step", "req": rid, "t": t,
                        "slot": slot, "model": model,
                        "poison_at": poison_at,
                        "prev_pids": tuple(pages[:pj]),
                        "dc_read": dc.data_of})])
                prios.append(0)
        rows.append([dtd.TileArg(self.state, (rid,), dtd.INPUT),
                     dtd.ValueArg({"kind": "done", "req": rid,
                                   "on_done": self._on_done})])
        prios.append(0)
        try:
            self.tp.insert_tasks(_paged_body, rows, priorities=prios)
        except Exception:
            if self.tp.error is None and not self.tp.cancelled:
                # rejected by admission: the batch's single admission
                # check ran BEFORE any row was inserted — release now
                with self._lock:
                    self.pending.pop(rid, None)
                self._release_paged(req)
            # else: the pool aborted mid-batch — some rows may be in
            # flight, so the request stays pending and drain()'s
            # dead-pool sweep releases it after the drain completes
            raise
        if req.spec is not None:
            req.spec.start_branch()
        return req

    def _release_paged_refs(self, pids, handle) -> None:
        layer = self.kv_layer
        for pid in pids:
            layer.pool.release(pid)
        if handle is not None:
            handle.unlock()

    def _release_paged(self, req: PendingRequest) -> None:
        """Release one paged request's resources: the branch pool's
        pages (speculation), every page-table reference (the last one
        frees the page, its tile, and its HBM entry), the radix node
        pins, and the state tile."""
        if req.spec is not None:
            req.spec.release()
            req.spec = None
        if req.pages is not None:
            self._release_paged_refs(req.pages, req.match)
            req.pages = None
            req.match = None
        self.state.drop_tile((req.rid,))

    def drain(self, timeout: float = 60.0,
              prune: bool = True) -> List[PendingRequest]:
        """Wait for every pending request; returns the finished ones
        (requests of an aborted/cancelled pool stay unfinished). With
        ``prune`` (default) the finished requests are released — their
        state/KV tiles and bookkeeping are reclaimed, which is what
        keeps a persistent engine's footprint bounded under an
        open-loop stream; results stay on the returned handles for
        verification.

        DEAD-POOL sweep (ISSUE 15 leak audit): when the engine's pool
        was cancelled (deadline reaper, explicit cancel) or aborted
        (poison body, quarantine), its unfinished requests can never
        finish — after the pool's in-flight tasks drain
        (``_complete_evt``; dropped-at-select tasks never touch tiles,
        in-flight ones have written back by then), their tiles, pages,
        and HBM entries are released too. Without this, every
        deadline-cancelled or quarantine-aborted request leaked its
        state tile + KV tiles/pages into the persistent collections."""
        deadline = time.monotonic() + timeout
        with self._lock:
            reqs = list(self.pending.values())
        for req in reqs:
            left = max(0.0, deadline - time.monotonic())
            req.done_evt.wait(left)
            if self.tp is not None and self.tp.error is not None:
                break
        finished = [r for r in reqs if r.done_evt.is_set()]
        if prune:
            for r in finished:
                self.release(r)
            tp = self.tp
            if tp is not None and (tp.cancelled or tp.error is not None):
                # releasing BEFORE the pool fully terminated could race
                # an in-flight task's write-back against page reuse
                tp._complete_evt.wait(max(0.0,
                                          deadline - time.monotonic()))
                if tp._complete_evt.is_set():
                    with self._lock:
                        dead = [r for r in self.pending.values()
                                if not r.done_evt.is_set()]
                    for r in dead:
                        self.release(r)
        return finished

    def release(self, req: PendingRequest) -> None:
        """Reclaim one collected request: pending-table entry, state
        tile, KV cache tiles (host + HBM-manager entries) or — paged —
        page-table references, radix pins, and the speculative branch.
        ``req.result`` survives for verification."""
        with self._lock:
            self.pending.pop(req.rid, None)
        if req.pages is not None or req.spec is not None:
            self._release_paged(req)
            return
        self.kv.drop_request(req.rid)
        self.state.drop_tile((req.rid,))

    def verify(self, req: PendingRequest) -> bool:
        """Bitwise check of a finished request against the reference
        replay (same float32 kernels — prefill included — same op
        order). Paged requests replay the NO-SHARING paged oracle, so
        prefix sharing and speculation must be invisible to pass."""
        if req.tokens is not None:
            ref = reference_decode_paged(self.model, req.tokens,
                                         req.n_steps,
                                         self.kv_layer.page_tokens)
        else:
            ref = reference_decode(self.model, req.rid, req.n_steps,
                                   prompt_len=req.prompt_len,
                                   mesh=req.mesh)
        return req.result is not None and \
            req.result.shape == ref.shape and \
            bool(np.all(req.result == ref))

    def close(self) -> None:
        """Drain and retire the engine's pool (aborted pools count as
        already drained), then release every remaining request — a
        closed engine holds no tiles, pages, or HBM entries."""
        tp = self.tp
        if tp is not None and not tp.completed:
            try:
                tp.wait()
            except RuntimeError:
                pass                  # aborted/cancelled pools: done
        with self._lock:
            left = list(self.pending.values())
        for req in left:
            self.release(req)
