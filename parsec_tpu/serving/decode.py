"""Continuous-batching transformer decode over DTD insertions.

The workload the north star implies (ROADMAP item 4, Orca-style
iteration-level scheduling): each live request advances one decode step
per iteration; steps are DTD task insertions whose INOUT chain on the
request's state tile serializes its own steps while steps of DIFFERENT
requests (and different tenants' pools) interleave freely under the
weighted-fair scheduler — the runtime's dataflow tracking IS the
continuous batcher.

- **KV cache as a tiled collection**: per (request, tile-index) tiles of
  ``(2, kv_tile, D)`` packed keys+values in a
  :class:`KVCacheCollection`; device-resident tiles are registered with
  the context's HBM budget manager (``device.hbm_budget_mb``) with
  next-use hints, so under memory pressure the plan-informed (Belady)
  ranking evicts the coldest cache tiles and a finished request's tiles
  are dropped outright.
- **Decode steps as DTD insertions**: step *t* reads the full prior
  cache (INPUT tiles), appends its (k, v) into the tail tile (INOUT)
  and rewrites the state vector (INOUT); the shared step kernel
  (:func:`_step_kernel`) is also what the bitwise reference replays, so
  "bitwise-correct under faults" is checked against the exact float32
  op sequence, not a tolerance.
- **Long contexts**: prompt prefill builds the whole prompt's KV cache
  and first state with ONE compiled attention call —
  :func:`~parsec_tpu.compiled.ring_attention.ring_attention` over a
  mesh when one is given (sequence-sharded ppermute ring), the dense
  jnp fold otherwise.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..data.collection import LocalCollection
from ..dsl import dtd


class PoisonBody(ValueError):
    """Deliberate task-body failure injected by a misbehaving tenant
    (the serving bench's poison traffic)."""


@dataclass
class DecodeConfig:
    d_model: int = 32
    n_heads: int = 2
    kv_tile: int = 8          # (k, v) pairs per cache tile
    seed: int = 7

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


class DecodeModel:
    """Deterministic float32 decode-step weights."""

    def __init__(self, cfg: DecodeConfig):
        rng = np.random.default_rng(cfg.seed)
        D = cfg.d_model

        def w(shape):
            return (rng.standard_normal(shape) * 0.25 /
                    math.sqrt(shape[0])).astype(np.float32)

        self.cfg = cfg
        self.Wq, self.Wk, self.Wv, self.Wo = (w((D, D)) for _ in range(4))
        self.W1 = w((D, 2 * D))
        self.W2 = w((2 * D, D))

    def init_state(self, rid: int) -> np.ndarray:
        rng = np.random.default_rng(self.cfg.seed * 1_000_003 + rid)
        return rng.standard_normal(self.cfg.d_model).astype(np.float32)


def _ffn_tail(x: np.ndarray, ctx_vec: np.ndarray,
              model: DecodeModel) -> np.ndarray:
    """Output projection + residual FFN + tanh over one position's
    attention context — shared by the stepwise decode kernel and the
    compiled prompt prefill so both land on the same float32 tail."""
    o = ctx_vec @ model.Wo
    h1 = x + o
    h2 = h1 + np.maximum(h1 @ model.W1, np.float32(0.0)) @ model.W2
    return np.tanh(h2)


def _attend(x: np.ndarray, K: np.ndarray, V: np.ndarray,
            model: DecodeModel) -> np.ndarray:
    """One decode attention + FFN step over the cached (K, V) rows —
    float32 throughout, fixed op order (the bitwise contract both the
    task body and the reference replay)."""
    cfg = model.cfg
    H, dh = cfg.n_heads, cfg.d_head
    q = (x @ model.Wq).reshape(H, dh)
    Kh = K.reshape(K.shape[0], H, dh)
    Vh = V.reshape(V.shape[0], H, dh)
    ctx = np.empty((H, dh), dtype=np.float32)
    scale = np.float32(1.0 / math.sqrt(dh))
    for h in range(H):
        s = (Kh[:, h, :] @ q[h]) * scale
        m = np.float32(s.max())
        e = np.exp(s - m, dtype=np.float32)
        w = e / np.float32(e.sum())
        ctx[h] = w @ Vh[:, h, :]
    return _ffn_tail(x, ctx.reshape(H * dh), model)


def _step_kernel(x: np.ndarray, prevs: List[np.ndarray],
                 tail: np.ndarray, slot: int, model: DecodeModel):
    """Shared decode-step kernel: append (k, v) of ``x`` at ``slot`` of
    the tail tile, attend over the full cache, return (new state, new
    tail). Functional: the tail is copied, never mutated in place
    (snapshot readers of the prior version stay valid — the DTD
    functional-body contract)."""
    k = x @ model.Wk
    v = x @ model.Wv
    tail = tail.copy()
    tail[0, slot] = k
    tail[1, slot] = v
    if prevs:
        K = np.concatenate([p[0] for p in prevs] + [tail[0, :slot + 1]],
                           axis=0)
        V = np.concatenate([p[1] for p in prevs] + [tail[1, :slot + 1]],
                           axis=0)
    else:
        K = tail[0, :slot + 1]
        V = tail[1, :slot + 1]
    return _attend(x, K, V, model), tail


def _decode_body(state, tail, *rest):
    """DTD task body of one decode step. ``rest`` = the request's prior
    (full) KV tiles, then the per-step meta dict (ValueArg)."""
    prevs, meta = list(rest[:-1]), rest[-1]
    t = meta["t"]
    if meta.get("poison_at") is not None and t == meta["poison_at"]:
        raise PoisonBody(
            f"poison body: request {meta['req']} step {t}")
    return _step_kernel(state, prevs, tail, meta["slot"], meta["model"])


def _done_body(state, meta):
    """Completion sentinel: an INPUT-only reader of the request's state
    tile, RAW-chained behind the final decode step — so it runs
    strictly AFTER the runtime wrote the final step's outputs back to
    the collections. Recording completion from the final step's own
    body would fire BEFORE its write-back, racing any cleanup."""
    done = meta.get("on_done")
    if done is not None:
        done(meta["req"], state)


def _prompt_of(model: DecodeModel, rid: int, prompt_len: int) -> np.ndarray:
    rng = np.random.default_rng(model.cfg.seed * 7_919 + rid)
    return rng.standard_normal(
        (prompt_len, model.cfg.d_model)).astype(np.float32)


def _prefill_request(model: DecodeModel, rid: int, prompt_len: int,
                     mesh=None):
    """Prompt prefill for one request: K/V of every prompt position
    (packed into whole leading KV tiles by the caller) and the initial
    decode state — the LAST position's attention context from ONE
    compiled attention call (:func:`prefill_attention`: ring over a
    mesh, dense otherwise) folded through the shared FFN tail. Returns
    ``(x0, K, V)`` as float32 numpy; deterministic per (model, rid,
    backend), so the reference replay reproduces it bitwise."""
    cfg = model.cfg
    if prompt_len % cfg.kv_tile:
        raise ValueError(
            f"prompt_len {prompt_len} must be a multiple of kv_tile "
            f"{cfg.kv_tile} (whole prefilled cache tiles)")
    prompt = _prompt_of(model, rid, prompt_len)
    K = prompt @ model.Wk
    V = prompt @ model.Wv
    ctx_rows = prefill_attention(model, prompt, mesh=mesh, causal=True)
    x0 = _ffn_tail(prompt[-1], ctx_rows[-1], model)
    return x0, K, V


def _packed_tiles(model: DecodeModel, K: np.ndarray,
                  V: np.ndarray) -> List[np.ndarray]:
    cfg = model.cfg
    kt = cfg.kv_tile
    tiles = []
    for j in range(K.shape[0] // kt):
        tile = np.zeros((2, kt, cfg.d_model), dtype=np.float32)
        tile[0] = K[j * kt:(j + 1) * kt]
        tile[1] = V[j * kt:(j + 1) * kt]
        tiles.append(tile)
    return tiles


def reference_decode(model: DecodeModel, rid: int, n_steps: int,
                     prompt_len: int = 0, mesh=None) -> np.ndarray:
    """Single-threaded replay of ``n_steps`` decode steps for request
    ``rid`` (after an optional prompt prefill) through the SAME kernels
    the engine runs — the bitwise oracle."""
    cfg = model.cfg
    if prompt_len:
        x, K, V = _prefill_request(model, rid, prompt_len, mesh=mesh)
        tiles = _packed_tiles(model, K, V)
    else:
        x = model.init_state(rid)
        tiles: List[np.ndarray] = []
    for t in range(prompt_len, prompt_len + n_steps):
        j, slot = divmod(t, cfg.kv_tile)
        if slot == 0:
            tiles.append(np.zeros((2, cfg.kv_tile, cfg.d_model),
                                  dtype=np.float32))
        x, tiles[j] = _step_kernel(x, tiles[:j], tiles[j], slot, model)
    return x


# --------------------------------------------------------------- prefill
def prefill_attention(model: DecodeModel, prompt: np.ndarray,
                      mesh=None, causal: bool = True) -> np.ndarray:
    """Long-context prompt prefill: one compiled attention call over the
    whole prompt ``(S, D)`` — ring attention (sequence-sharded ppermute
    ring, ``compiled/ring_attention.py``) when a mesh is given, the
    dense jnp fold otherwise. Returns the attention output ``(S, D)``
    as float32 numpy."""
    import jax.numpy as jnp
    from ..compiled.ring_attention import dense_attention, ring_attention
    cfg = model.cfg
    S = prompt.shape[0]
    H, dh = cfg.n_heads, cfg.d_head
    Q = (prompt @ model.Wq).reshape(S, H, dh)
    K = (prompt @ model.Wk).reshape(S, H, dh)
    V = (prompt @ model.Wv).reshape(S, H, dh)
    if mesh is not None:
        out = ring_attention(jnp.asarray(Q), jnp.asarray(K),
                             jnp.asarray(V), mesh, causal=causal)
    else:
        out = dense_attention(jnp.asarray(Q), jnp.asarray(K),
                              jnp.asarray(V), causal=causal)
    return np.asarray(out, dtype=np.float32).reshape(S, H * dh)


# ------------------------------------------------------------ collections
class KVCacheCollection(LocalCollection):
    """Dict-backed KV cache whose device-resident tiles are registered
    with the HBM budget manager: every write refreshes the tile's
    next-use hint (a live request touches its whole cache again next
    step), so the Belady ranking evicts the longest-idle cache tiles
    first and :meth:`drop_request` releases a finished request's tiles
    outright. Host (numpy) tiles pass through untracked."""

    def __init__(self, name: str, hbm=None, myrank: int = 0):
        super().__init__(name, myrank=myrank)
        self.hbm = hbm
        self._clock = 0

    def _mkey(self, key):
        return (id(self), tuple(key))

    def write_tile(self, key, value) -> None:
        super().write_tile(key, value)
        hbm = self.hbm
        if hbm is None or not isinstance(value, hbm.jax.Array):
            return
        self._clock += 1

        def _spill(_k, host, dc=self, key=key):
            LocalCollection.write_tile(dc, key, host)

        try:
            hbm.put(self._mkey(key), value, next_use=self._clock + 1,
                    spill=_spill)
        except MemoryError:
            pass                      # tile bigger than the whole budget

    def drop_request(self, rid: int) -> None:
        """Release a finished request's cache: HBM-manager entries AND
        the host tiles (a persistent serving engine would otherwise
        grow by one request's KV forever)."""
        for key in self.keys():
            if key[0] == rid:
                if self.hbm is not None:
                    self.hbm.drop(self._mkey(key))
                self.drop_tile(key)


# ---------------------------------------------------------------- engine
@dataclass
class PendingRequest:
    rid: int
    n_steps: int
    submitted_t: float
    prompt_len: int = 0
    mesh: object = None
    done_evt: threading.Event = field(default_factory=threading.Event)
    finished_t: Optional[float] = None
    result: Optional[np.ndarray] = None

    def latency_s(self) -> Optional[float]:
        return (self.finished_t - self.submitted_t
                if self.finished_t is not None else None)


class DecodeEngine:
    """Continuous-batching decode front end for ONE tenant.

    ``start()`` submits a persistent DTD pool through the serving
    runtime; ``request()`` inserts a request's decode steps (admission
    control applies per insert — :class:`~.runtime.AdmissionRejected`
    propagates to the caller); completion is detected per request by
    the final step's body callback, so per-request latency is
    end-to-end through the runtime, not a wrapper around wait()."""

    def __init__(self, ctx, name: str, cfg: Optional[DecodeConfig] = None,
                 tenant=None, model: Optional[DecodeModel] = None,
                 **submit_kwargs):
        self.ctx = ctx
        self.name = name
        self.cfg = cfg or DecodeConfig()
        self.model = model or DecodeModel(self.cfg)
        self.tenant = tenant
        self.submit_kwargs = submit_kwargs
        # collections OWNED by this context's rank: a decode engine on
        # a worker rank of an elastic mesh must place its steps locally
        # (rank_of = 0 would ship every task to the front-end rank)
        self.state = LocalCollection(f"{name}_state",
                                     myrank=ctx.my_rank)
        self.kv = KVCacheCollection(f"{name}_kv", hbm=ctx.hbm,
                                    myrank=ctx.my_rank)
        self.tp = None
        self.submission = None
        self.pending: Dict[int, PendingRequest] = {}
        self._lock = threading.Lock()

    def start(self) -> "DecodeEngine":
        self.tp = dtd.Taskpool(f"{self.name}_decode")
        self.submission = self.ctx.submit(self.tp, tenant=self.tenant,
                                          **self.submit_kwargs)
        return self

    def _on_done(self, rid: int, h: np.ndarray) -> None:
        # record only — tile cleanup happens in release(): this runs
        # INSIDE the final step's body, before the runtime writes the
        # step's outputs back, so dropping tiles here would race the
        # completion write-back
        with self._lock:
            req = self.pending.get(rid)
        if req is not None:
            req.finished_t = time.monotonic()
            req.result = h
            req.done_evt.set()

    def request(self, rid: int, n_steps: int,
                poison_at: Optional[int] = None,
                prompt_len: int = 0, mesh=None) -> PendingRequest:
        """Admit one request and insert its decode steps. With
        ``prompt_len`` (a multiple of ``kv_tile``) the prompt's
        attention runs as ONE compiled prefill call (ring attention
        over ``mesh`` when given, dense otherwise) that SEEDS the
        request's KV cache tiles and initial state; the stepwise decode
        then attends over prompt + generated positions."""
        cfg, model = self.cfg, self.model
        req = PendingRequest(rid, n_steps, time.monotonic(),
                             prompt_len=prompt_len, mesh=mesh)
        with self._lock:
            self.pending[rid] = req
        if prompt_len:
            x0, K, V = _prefill_request(model, rid, prompt_len,
                                        mesh=mesh)
            prefilled = _packed_tiles(model, K, V)
        else:
            x0, prefilled = model.init_state(rid), []
        self.state.write_tile((rid,), x0)
        for j, tile in enumerate(prefilled):
            self.kv.write_tile((rid, j), tile)
        t0 = prompt_len
        n_tiles = (t0 + n_steps + cfg.kv_tile - 1) // cfg.kv_tile
        for j in range(len(prefilled), n_tiles):
            self.kv.write_tile((rid, j), np.zeros(
                (2, cfg.kv_tile, cfg.d_model), dtype=np.float32))
        rows = []
        for t in range(t0, t0 + n_steps):
            j, slot = divmod(t, cfg.kv_tile)
            args = [dtd.TileArg(self.state, (rid,), dtd.INOUT),
                    dtd.TileArg(self.kv, (rid, j), dtd.INOUT)]
            args += [dtd.TileArg(self.kv, (rid, jj), dtd.INPUT)
                     for jj in range(j)]
            args.append(dtd.ValueArg({
                "req": rid, "t": t, "slot": slot,
                "model": model, "poison_at": poison_at}))
            rows.append(args)
        try:
            self.tp.insert_tasks(_decode_body, rows)
            # completion sentinel (see _done_body): post-write-back
            self.tp.insert_task(
                _done_body, dtd.TileArg(self.state, (rid,), dtd.INPUT),
                dtd.ValueArg({"req": rid, "on_done": self._on_done}))
        except Exception:
            # rejected insert (admission window, quarantine, aborted
            # pool): release the tiles written above too, or every
            # rejected rid of an open-loop stream leaks one state +
            # n_tiles KV tiles into the persistent collections
            with self._lock:
                self.pending.pop(rid, None)
            self.kv.drop_request(rid)
            self.state.drop_tile((rid,))
            raise
        return req

    def drain(self, timeout: float = 60.0,
              prune: bool = True) -> List[PendingRequest]:
        """Wait for every pending request; returns the finished ones
        (requests of an aborted/cancelled pool stay unfinished). With
        ``prune`` (default) the finished requests are released — their
        state/KV tiles and bookkeeping are reclaimed, which is what
        keeps a persistent engine's footprint bounded under an
        open-loop stream; results stay on the returned handles for
        verification."""
        deadline = time.monotonic() + timeout
        with self._lock:
            reqs = list(self.pending.values())
        for req in reqs:
            left = max(0.0, deadline - time.monotonic())
            req.done_evt.wait(left)
            if self.tp is not None and self.tp.error is not None:
                break
        finished = [r for r in reqs if r.done_evt.is_set()]
        if prune:
            for r in finished:
                self.release(r)
        return finished

    def release(self, req: PendingRequest) -> None:
        """Reclaim one collected request: pending-table entry, state
        tile, and KV cache tiles (host + HBM-manager entries).
        ``req.result`` survives for verification."""
        with self._lock:
            self.pending.pop(req.rid, None)
        self.kv.drop_request(req.rid)
        self.state.drop_tile((req.rid,))

    def verify(self, req: PendingRequest) -> bool:
        """Bitwise check of a finished request against the reference
        replay (same float32 kernels — prefill included — same op
        order)."""
        ref = reference_decode(self.model, req.rid, req.n_steps,
                               prompt_len=req.prompt_len, mesh=req.mesh)
        return req.result is not None and \
            req.result.shape == ref.shape and \
            bool(np.all(req.result == ref))

    def close(self) -> None:
        """Drain and retire the engine's pool (aborted pools count as
        already drained)."""
        tp = self.tp
        if tp is None or tp.completed:
            return
        try:
            tp.wait()
        except RuntimeError:
            pass                      # aborted/cancelled pools: done
