"""Serving runtime: tenants, admission control, deadlines, quarantine.

Design (ROADMAP item 4; reference divergence documented in PARITY —
PaRSEC's context is single-application, so everything here is
beyond-reference):

- **Tenants** are the isolation and accounting unit. Every submission
  binds a taskpool to a tenant; the taskpool carries the tenant's
  weight (``fair_weight``, read by the wfq scheduler), its name (read
  by the ``tenant`` PINS module) and a ``rank_scope`` so a peer death
  only fails pools whose scope contains the dead rank.
- **Admission** is a two-level window grown from the PR 3 DTD insertion
  throttle: inserts past the tenant's *soft* threshold park briefly
  (backpressure, event-driven wakeup on retire), and past the *hard*
  window — or past the backpressure timeout, or past the tenant's HBM
  reservation cap — raise :class:`AdmissionRejected` instead of parking
  unboundedly. Rejection is explicit so an open-loop client learns to
  back off; parking forever would just move the queue into the clients.
- **Deadlines**: ``submit(tp, deadline_s=...)`` registers the pool with
  a reaper thread; on expiry the pool is *cancelled* — queued tasks are
  dropped at select time, in-flight ones drain, the tenant's window and
  HBM reservations are released, and device-resident tiles of the
  pool's collections are swept from the HBM manager. Termination is
  idempotent (PR 6), so the cancelled pool's draining tasks cannot
  poison any other pool's termdet.
- **Quarantine**: a pool that fails for any non-cancellation reason
  (poison body, lint-gate :class:`~parsec_tpu.analysis.lint.
  HazardError` at registration, rank death aborting a scoped pool)
  quarantines its tenant — later submissions raise
  :class:`TenantQuarantined` until ``release_quarantine``. The failed
  pool's error is *owned* here (``Taskpool.error_owned``) so it never
  poisons an unrelated caller's ``Context.wait``.
- **Load shedding**: when the ready-queue depth or the measured
  per-task runtime overhead (PR 3 stage timers) crosses its watermark,
  new submissions from every tenant below the top live weight are
  rejected with ``AdmissionRejected("overload shed ...")`` — degrading
  by dropping the cheapest traffic instead of collapsing throughput for
  everyone.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Union

from ..core.taskpool import CancelledError, Taskpool
from ..utils import mca_param
from ..utils.debug import debug_verbose, warning

mca_param.register("serving.tenant_window", 4096,
                   help="per-tenant HARD cap of in-flight inserted DTD "
                        "rows across the tenant's pools; inserts beyond "
                        "it raise AdmissionRejected")
mca_param.register("serving.tenant_backpressure", 0.5,
                   help="soft fraction of serving.tenant_window at which "
                        "inserts park (backpressure) before rejecting")
mca_param.register("serving.backpressure_timeout_s", 5.0,
                   help="max seconds an insert may park in tenant "
                        "backpressure before AdmissionRejected")
mca_param.register("serving.tenant_max_pools", 64,
                   help="per-tenant cap of concurrently live submissions")
mca_param.register("serving.tenant_hbm_mb", 0,
                   help="per-tenant HBM reservation cap for submissions "
                        "declaring hbm_bytes (0 = unlimited)")
mca_param.register("serving.shed_watermark", 0,
                   help="ready-queue depth above which new submissions "
                        "from below-top-weight tenants are shed "
                        "(0 = shedding off)")
mca_param.register("serving.shed_overhead_us", 0.0,
                   help="measured per-task runtime overhead (stage "
                        "timers: select+dispatch+release µs/task) above "
                        "which shedding also triggers (0 = off)")
mca_param.register("serving.deadline_poll_s", 0.02,
                   help="deadline reaper poll interval")
mca_param.register("serving.strict_fair", 1,
                   help="serving mode disables the bypass-slot chain so "
                        "every ready task goes through the weighted-fair "
                        "scheduler (0 keeps the throughput-path bypass)")


class AdmissionRejected(RuntimeError):
    """A submission or insert was refused by admission control (tenant
    window / HBM reservation / overload shed) — the caller should back
    off and retry, not treat this as a crash."""


class TenantQuarantined(AdmissionRejected):
    """The tenant is quarantined after a failure (poison body, lint
    gate, rank death); submissions are refused until
    ``ServingRuntime.release_quarantine``."""


class DeadlineExceeded(CancelledError):
    """A submission's deadline passed: its not-yet-running tasks were
    dropped, in-flight ones drained, and its reservations released."""


class Tenant:
    """One isolation/accounting unit sharing the persistent context."""

    def __init__(self, name: str, weight: float, window: int,
                 soft: int, max_pools: int, hbm_bytes: int):
        self.name = name
        self.weight = float(weight)
        self.window = int(window)          # hard in-flight row cap
        self.soft = int(soft)              # backpressure threshold
        self.max_pools = int(max_pools)
        self.hbm_bytes = int(hbm_bytes)    # reservation cap (0 = unlimited)
        self.cv = threading.Condition()
        self.inflight = 0                  # admitted-not-retired rows
        self.hbm_reserved = 0
        self.quarantined: Optional[BaseException] = None
        self.active: Dict[Taskpool, "Submission"] = {}
        self._waiters = 0
        self.stats = {"submitted": 0, "completed": 0, "failed": 0,
                      "rejected": 0, "shed": 0, "cancelled": 0,
                      "rows_admitted": 0, "rows_retired": 0,
                      "parked": 0}

    def __repr__(self) -> str:
        return (f"<Tenant {self.name} w={self.weight} "
                f"inflight={self.inflight}"
                f"{' QUARANTINED' if self.quarantined else ''}>")


class _PoolAdmission:
    """Per-(tenant, taskpool) window bookkeeping installed as the DTD
    pool's ``admission``/``on_retire`` hooks. ``close()`` releases the
    residue of admitted-but-never-retired rows when the pool ends (a
    cancelled pool's dropped tasks never retire), after which late
    retires from draining tasks are ignored — the window can neither
    leak nor double-release."""

    __slots__ = ("runtime", "tenant", "admitted", "retired", "closed")

    def __init__(self, runtime: "ServingRuntime", tenant: Tenant):
        self.runtime = runtime
        self.tenant = tenant
        self.admitted = 0
        self.retired = 0
        self.closed = False

    def admit(self, tp: Taskpool, n: int) -> None:
        ten = self.tenant
        timeout = float(mca_param.get("serving.backpressure_timeout_s",
                                      5.0))
        deadline = time.monotonic() + timeout
        park_t0 = None        # perf_counter stamp of the first park
        with ten.cv:
            while True:
                if ten.quarantined is not None:
                    ten.stats["rejected"] += 1
                    raise TenantQuarantined(
                        f"tenant {ten.name} is quarantined: "
                        f"{ten.quarantined}")
                if tp.error is not None:
                    raise RuntimeError(
                        f"taskpool {tp.name} aborted: {tp.error}") \
                        from tp.error
                if ten.inflight + n > ten.window:
                    # hard window: explicit rejection, never unbounded
                    # parking (the client is open-loop — parking forever
                    # just moves its queue into this thread)
                    ten.stats["rejected"] += 1
                    raise AdmissionRejected(
                        f"tenant {ten.name}: queue depth "
                        f"{ten.inflight}+{n} exceeds window "
                        f"{ten.window} (serving.tenant_window)")
                if ten.inflight <= ten.soft:
                    # backpressure keys on the EXISTING depth: a batch
                    # that fits the hard window admits even when it
                    # alone exceeds the soft threshold — an idle tenant
                    # has nothing in flight to retire, so parking such a
                    # batch could only ever exit via the timeout
                    break
                # soft window: backpressure park, bounded
                left = deadline - time.monotonic()
                if left <= 0:
                    ten.stats["rejected"] += 1
                    raise AdmissionRejected(
                        f"tenant {ten.name}: backpressure park exceeded "
                        f"{timeout:.1f}s "
                        f"(serving.backpressure_timeout_s) at depth "
                        f"{ten.inflight}")
                if park_t0 is None:
                    park_t0 = time.perf_counter()
                ten._waiters += 1
                try:
                    ten.cv.wait(min(left, 0.25))
                finally:
                    ten._waiters -= 1
            ten.inflight += n
            self.admitted += n
            ten.stats["rows_admitted"] += n
            inflight_now = ten.inflight
            if park_t0 is not None:
                # admission-park counter: one of the autoscaler's
                # scale-up signals (serving/elastic.py) — parks piling
                # up mean the tenant windows are the bottleneck
                ten.stats["parked"] += 1
        tr = self.runtime.ctx.trace
        if tr is not None:
            # admission protocol event (analysis/conformance.py replays
            # these through the admission_budget model): rows admitted,
            # depth after, and the window the decision was made against
            tr.event("admission", "admit", object_id=tp.name,
                     info={"tenant": ten.name, "rows": n,
                           "inflight": inflight_now,
                           "window": ten.window, "soft": ten.soft})
        if park_t0 is not None:
            self.runtime._bump("parked")
            self._record_park(tp, ten, park_t0, n)

    def _record_park(self, tp: Taskpool, ten: Tenant,
                     park_t0: float, n: int) -> None:
        """Record a backpressure park as an ``admission`` span of the
        request's trace (only actual waits — an unthrottled admit adds
        zero events). Recorded after the fact with explicit times."""
        tr = self.runtime.ctx.trace
        rid = getattr(tp, "trace_rid", None)
        if tr is None or rid is None:
            return
        from ..profiling import spans as spans_mod
        sid = spans_mod.next_span_id(self.runtime.ctx.my_rank)
        now = time.perf_counter()
        info = {"rid": rid, "span": sid,
                "parent": getattr(tp, "root_span", None),
                "tenant": ten.name, "rows": n}
        tr.event("admission", "begin", t=park_t0 - tr.t0,
                 object_id=tp.name, info=info)
        tr.event("admission", "end", t=now - tr.t0,
                 object_id=tp.name, info=info)

    def on_retire(self, _tp: Taskpool) -> None:
        ten = self.tenant
        with ten.cv:
            if self.closed:
                return          # residue already reconciled by close()
            self.retired += 1
            ten.inflight -= 1
            ten.stats["rows_retired"] += 1
            inflight_now = ten.inflight
            if ten._waiters:
                ten.cv.notify_all()
        tr = self.runtime.ctx.trace
        if tr is not None:
            tr.event("admission", "retire", object_id=_tp.name,
                     info={"tenant": ten.name, "rows": 1,
                           "inflight": inflight_now})

    def close(self) -> None:
        ten = self.tenant
        with ten.cv:
            if self.closed:
                return
            self.closed = True
            residue = self.admitted - self.retired
            if residue > 0:
                ten.inflight -= residue
            inflight_now = ten.inflight
            ten.cv.notify_all()
        if residue > 0:
            tr = self.runtime.ctx.trace
            if tr is not None:
                # end-of-pool residue reconciliation (cancelled pools'
                # dropped tasks never retire) — replayed as a bulk
                # retire by the conformance pass
                tr.event("admission", "reconcile", object_id="close",
                         info={"tenant": ten.name, "rows": residue,
                               "inflight": inflight_now})


class Submission:
    """Handle for one submitted taskpool (returned by Context.submit)."""

    def __init__(self, runtime: "ServingRuntime", tp: Taskpool,
                 tenant: Tenant, deadline_s: Optional[float],
                 hbm_bytes: int):
        self.runtime = runtime
        self.tp = tp
        self.tenant = tenant
        self.submitted_t = time.monotonic()
        self.deadline_t = (self.submitted_t + deadline_s
                           if deadline_s is not None else None)
        self.finished_t: Optional[float] = None
        self.hbm_bytes = int(hbm_bytes)

    @property
    def done(self) -> bool:
        return self.tp.completed

    @property
    def error(self) -> Optional[BaseException]:
        return self.tp.error

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the pool terminates. Raises the pool's error —
        :class:`DeadlineExceeded`/:class:`~parsec_tpu.core.taskpool.
        CancelledError` for cancellations, the original failure
        otherwise. Returns False on wait timeout."""
        ok = self.tp._complete_evt.wait(timeout)
        err = self.tp.error
        if err is not None:
            if isinstance(err, (CancelledError, AdmissionRejected)):
                raise err
            raise RuntimeError(
                f"taskpool {self.tp.name} aborted: {err}") from err
        return ok

    def cancel(self, exc: Optional[BaseException] = None) -> bool:
        """Cancel this submission (idempotent): drop queued tasks, drain
        in-flight ones, release the tenant's window/HBM reservations and
        sweep its device-resident tiles. True when this call performed
        the cancellation."""
        return self.runtime._cancel(self, exc)

    def latency_s(self) -> Optional[float]:
        return (self.finished_t - self.submitted_t
                if self.finished_t is not None else None)


class ServingRuntime:
    """Multi-tenant serving supervisor attached to one Context."""

    def __init__(self, context, strict_fair: Optional[bool] = None):
        self.ctx = context
        context.serving = self
        self._lock = threading.RLock()
        self._tenants: Dict[str, Tenant] = {}
        self._deadlines: List[Submission] = []
        self._reaper: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.stats = {"submitted": 0, "completed": 0, "failed": 0,
                      "rejected": 0, "shed": 0, "quarantined": 0,
                      "cancelled": 0, "deadline_cancelled": 0,
                      "parked": 0}
        # elastic-capacity controller (serving/elastic.py) — attached
        # by ElasticController so statusz/metrics can surface the
        # autoscaler's state next to the tenant report
        self.elastic = None
        self._stats_lock = threading.Lock()
        if strict_fair is None:
            strict_fair = str(mca_param.get(
                "serving.strict_fair", 1)).lower() not in ("0", "off",
                                                           "false")
        if strict_fair:
            # every ready task goes through the scheduler so wfq's
            # weighted-fair arbitration actually sees it (the bypass
            # slot would hand a tenant's successor straight to the
            # worker, starving the arbitration)
            context._bypass_chain = False
        # always-on per-tenant request-latency distribution
        # (profiling/metrics.py): observed once per finished
        # submission, exported as a log2-bucket Prometheus histogram
        from ..profiling import metrics as metrics_mod
        self._m_latency = metrics_mod.registry().histogram(
            "parsec_request_latency_seconds",
            "submission latency (submit -> pool termination) per "
            "tenant", ("tenant",)) if metrics_mod.enabled() else None

    # ------------------------------------------------------------ tenants
    def tenant(self, name: str, weight: float = 1.0,
               window: Optional[int] = None,
               max_pools: Optional[int] = None,
               hbm_bytes: Optional[int] = None) -> Tenant:
        """Get-or-create the named tenant (idempotent; parameters only
        apply at creation)."""
        with self._lock:
            ten = self._tenants.get(name)
            if ten is None:
                window = int(window if window is not None else
                             mca_param.get("serving.tenant_window", 4096))
                frac = float(mca_param.get("serving.tenant_backpressure",
                                           0.5))
                soft = max(1, int(window * min(max(frac, 0.0), 1.0)))
                ten = Tenant(
                    name, weight, window, soft,
                    max_pools if max_pools is not None else
                    int(mca_param.get("serving.tenant_max_pools", 64)),
                    hbm_bytes if hbm_bytes is not None else
                    int(mca_param.get("serving.tenant_hbm_mb", 0))
                    * (1 << 20))
                self._tenants[name] = ten
            return ten

    def tenants(self) -> Dict[str, Tenant]:
        with self._lock:
            return dict(self._tenants)

    def release_quarantine(self, tenant: Union[str, Tenant]) -> None:
        ten = self.tenant(tenant) if isinstance(tenant, str) else tenant
        with ten.cv:
            ten.quarantined = None
            ten.cv.notify_all()

    def _bump(self, key: str) -> None:
        """Locked runtime-counter increment: submit paths run on many
        client threads, and a bare dict += is a read-modify-write that
        drops counts under preemption — these totals are the shedding/
        quarantine evidence the bench and PARITY report."""
        with self._stats_lock:
            self.stats[key] += 1

    def _quarantine(self, ten: Tenant, exc: BaseException) -> None:
        with ten.cv:
            first = ten.quarantined is None
            if first:
                ten.quarantined = exc
            ten.cv.notify_all()
        if first:
            self._bump("quarantined")
            warning("serving", "tenant %s quarantined: %s", ten.name, exc)

    # ----------------------------------------------------------- overload
    def _overload_reason(self) -> Optional[str]:
        wm = int(mca_param.get("serving.shed_watermark", 0))
        if wm > 0:
            depth = self.ctx.scheduler.pending_tasks()
            if depth > wm:
                return (f"ready-queue depth {depth} > watermark {wm} "
                        "(serving.shed_watermark)")
        ov = float(mca_param.get("serving.shed_overhead_us", 0.0))
        if ov > 0 and self.ctx.stage_timers:
            total_s = executed = 0
            for es in self.ctx.streams:
                total_s += (es.stats.get("select_s", 0.0) +
                            es.stats.get("dispatch_s", 0.0) +
                            es.stats.get("release_s", 0.0))
                executed += es.stats.get("executed", 0)
            if executed:
                per_us = total_s / executed * 1e6
                if per_us > ov:
                    return (f"runtime overhead {per_us:.1f} µs/task > "
                            f"budget {ov:.1f} (serving.shed_overhead_us)")
        return None

    def _top_live_weight(self) -> float:
        with self._lock:
            live = [t.weight for t in self._tenants.values()
                    if t.quarantined is None]
        return max(live) if live else 0.0

    # ------------------------------------------------------------- submit
    def submit(self, tp: Taskpool, tenant=None,
               deadline_s: Optional[float] = None,
               weight: Optional[float] = None,
               rank_scope=None, hbm_bytes: int = 0) -> Submission:
        ten = tenant if isinstance(tenant, Tenant) else \
            self.tenant(tenant or "default",
                        weight=weight if weight is not None else 1.0)
        if ten.quarantined is not None:
            ten.stats["rejected"] += 1
            self._bump("rejected")
            raise TenantQuarantined(
                f"tenant {ten.name} is quarantined: {ten.quarantined}")
        reason = self._overload_reason()
        if reason is not None and ten.weight < self._top_live_weight():
            # graceful degradation: shed the lowest-weight NEW traffic
            # instead of letting queue growth collapse everyone's p99
            ten.stats["shed"] += 1
            self._bump("shed")
            raise AdmissionRejected(
                f"overload shed (tenant {ten.name}, weight "
                f"{ten.weight:g} < top {self._top_live_weight():g}): "
                f"{reason}")
        scope = self._resolve_scope(rank_scope)   # may raise: validate
        #                                           BEFORE reserving
        sub = Submission(self, tp, ten, deadline_s, hbm_bytes)
        with ten.cv:
            # check AND reserve in ONE critical section: concurrent
            # client threads racing this cap must not both observe the
            # pre-reservation count (the many-callers shape is the
            # whole point of the runtime)
            if len(ten.active) >= ten.max_pools:
                ten.stats["rejected"] += 1
                self._bump("rejected")
                raise AdmissionRejected(
                    f"tenant {ten.name}: {len(ten.active)} live "
                    f"submissions >= cap {ten.max_pools} "
                    "(serving.tenant_max_pools)")
            if ten.hbm_bytes and \
                    ten.hbm_reserved + hbm_bytes > ten.hbm_bytes:
                ten.stats["rejected"] += 1
                self._bump("rejected")
                raise AdmissionRejected(
                    f"tenant {ten.name}: HBM reservation "
                    f"{ten.hbm_reserved + hbm_bytes} exceeds cap "
                    f"{ten.hbm_bytes} (serving.tenant_hbm_mb)")
            ten.hbm_reserved += hbm_bytes
            ten.active[tp] = sub

        # pool attributes are written only AFTER every admission check
        # passed: a rejected taskpool leaves submit() untouched, so a
        # caller falling back to plain add_taskpool doesn't inherit a
        # serving-scoped rank_scope or an error_owned flag that would
        # hide its failures from Context.wait
        tp.tenant_name = ten.name
        tp.fair_weight = weight if weight is not None else ten.weight
        tp.rank_scope = scope
        tp.error_owned = True
        # request-scoped distributed tracing (profiling/spans.py): the
        # rid derives from the taskpool NAME (the cross-rank registry
        # identity), so every rank of a distributed submission mints
        # the SAME rid without any exchange — one span tree spans the
        # mesh; the root span parents startup tasks and admission parks
        from ..profiling import spans as spans_mod
        if getattr(tp, "trace_rid", None) is None:
            tp.trace_rid = spans_mod.mint_rid(tp.name)
        tp.root_span = f"{tp.trace_rid}#root{self.ctx.my_rank}"
        tr = self.ctx.trace
        if tr is not None:
            tr.event("req", "begin", object_id=tp.trace_rid,
                     info={"rid": tp.trace_rid, "span": tp.root_span,
                           "parent": None, "tenant": ten.name})
        adm = None
        if hasattr(tp, "insert_task") and hasattr(tp, "admission"):
            adm = _PoolAdmission(self, ten)
            tp.admission = adm
            tp.on_retire = adm.on_retire
        prev_on_complete = tp.on_complete
        tp.on_complete = lambda pool, _sub=sub, _prev=prev_on_complete: \
            self._pool_finished(_sub, _prev)
        try:
            self.ctx.add_taskpool(tp)
        except Exception as exc:
            # the registration-time lint gate fired (analysis.lint=error
            # HazardError) or registration failed outright: charge the
            # TENANT, release what we reserved, leave everyone else
            # untouched
            with ten.cv:
                ten.active.pop(tp, None)
                ten.hbm_reserved -= hbm_bytes
            if adm is not None:
                adm.close()
            ten.stats["failed"] += 1
            self._bump("failed")
            self._quarantine(ten, exc)
            raise
        with ten.cv:
            ten.stats["submitted"] += 1
        self._bump("submitted")
        if sub.deadline_t is not None:
            with self._lock:
                self._deadlines.append(sub)
                self._ensure_reaper()
        debug_verbose(3, "serving", "submitted %s for tenant %s "
                      "(weight %g, deadline %s)", tp.name, ten.name,
                      tp.fair_weight, deadline_s)
        return sub

    def _resolve_scope(self, rank_scope) -> Optional[frozenset]:
        """Serving submissions default to a LOCAL failure scope: only
        this rank's death can fail them, so one tenant's dead rank
        cannot cascade into every tenant's pools. Pass ``"all"`` (or
        None explicitly via a distributed submission's iterable of
        ranks) for pools that genuinely span the mesh."""
        if rank_scope == "all":
            return None
        if rank_scope is None:
            return frozenset({self.ctx.my_rank})
        if isinstance(rank_scope, Iterable):
            return frozenset(int(r) for r in rank_scope)
        raise ValueError(f"rank_scope {rank_scope!r}: expected 'all', "
                         "None, or an iterable of ranks")

    # ----------------------------------------------------- pool lifecycle
    def _pool_finished(self, sub: Submission, prev_on_complete) -> None:
        """Taskpool on_complete hook (fires inside _on_terminated,
        before the context removes the pool): reconcile accounting,
        quarantine on failure, hand off to any user hook."""
        tp = sub.tp
        ten = sub.tenant
        sub.finished_t = time.monotonic()
        if self._m_latency is not None:
            self._m_latency.labels(tenant=ten.name).observe(
                sub.finished_t - sub.submitted_t)
        tr = self.ctx.trace
        rid = getattr(tp, "trace_rid", None)
        if tr is not None and rid is not None:
            tr.event("req", "end", object_id=rid,
                     info={"rid": rid, "span": tp.root_span,
                           "error": (str(tp.error)[:120]
                                     if tp.error else None)})
        adm = getattr(tp, "admission", None)
        if isinstance(adm, _PoolAdmission):
            adm.close()
        with ten.cv:
            ten.active.pop(tp, None)
            ten.hbm_reserved -= sub.hbm_bytes
        err = tp.error
        if err is None:
            ten.stats["completed"] += 1
            self._bump("completed")
        elif isinstance(err, CancelledError):
            ten.stats["cancelled"] += 1
            self._bump("cancelled")
            if isinstance(err, DeadlineExceeded):
                self._bump("deadline_cancelled")
        else:
            # poison body / rank death: per-taskpool failure unit — the
            # tenant is quarantined, survivors keep serving
            ten.stats["failed"] += 1
            self._bump("failed")
            self._quarantine(ten, err)
        with self._lock:
            if sub in self._deadlines:
                self._deadlines.remove(sub)
        if prev_on_complete is not None:
            prev_on_complete(tp)

    def _release_tiles(self, tp: Taskpool) -> int:
        """Sweep the HBM manager's entries for the pool's collections —
        a cancelled tenant's device-resident KV/working tiles must not
        squat in the budget."""
        hbm = self.ctx.hbm
        if hbm is None:
            return 0
        dc_ids = set()
        tiles = getattr(tp, "tiles", None)       # DTD tile bank
        if tiles is not None:
            for t in tiles.all():
                dc_ids.add(id(t.collection))
        g = getattr(tp, "g", None)               # PTG globals
        for obj in vars(g).values() if g is not None else ():
            if hasattr(obj, "data_of") and hasattr(obj, "write_tile"):
                dc_ids.add(id(obj))
        if not dc_ids:
            return 0
        return hbm.sweep(lambda k, e: isinstance(k, tuple) and k
                         and k[0] in dc_ids)

    def _cancel(self, sub: Submission,
                exc: Optional[BaseException] = None) -> bool:
        tp = sub.tp
        if tp.completed or tp.cancelled:
            return False
        tp.cancel(exc if exc is not None else CancelledError(
            f"submission {tp.name} cancelled"))
        self._release_tiles(tp)
        return True

    # ------------------------------------------------------------- reaper
    def _ensure_reaper(self) -> None:
        if self._reaper is None or not self._reaper.is_alive():
            t = threading.Thread(target=self._reaper_main,
                                 name="parsec-serving-reaper",
                                 daemon=True)
            self._reaper = t
            t.start()

    def _reaper_main(self) -> None:
        poll = float(mca_param.get("serving.deadline_poll_s", 0.02))
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                due = [s for s in self._deadlines
                       if s.deadline_t is not None and s.deadline_t <= now]
            for sub in due:
                age = now - sub.submitted_t
                self._cancel(sub, DeadlineExceeded(
                    f"submission {sub.tp.name} (tenant "
                    f"{sub.tenant.name}) exceeded its deadline "
                    f"({age:.3f}s elapsed)"))
                with self._lock:
                    if sub in self._deadlines:
                        self._deadlines.remove(sub)
            self._stop.wait(poll)

    def shutdown(self) -> None:
        self._stop.set()
        t = self._reaper
        if t is not None:
            t.join(timeout=2.0)

    # ------------------------------------------------------ observability
    def report(self) -> Dict:
        """Aggregate serving stats + per-tenant rows + (when wfq is
        installed) the scheduler's per-pool service accounting."""
        out = {"stats": dict(self.stats), "tenants": {}}
        for name, ten in self.tenants().items():
            out["tenants"][name] = {
                "weight": ten.weight, "inflight": ten.inflight,
                "hbm_reserved": ten.hbm_reserved,
                "quarantined": (str(ten.quarantined)
                                if ten.quarantined else None),
                **ten.stats}
        sched = self.ctx.scheduler
        if hasattr(sched, "pool_stats"):
            out["pools"] = sched.pool_stats()
        if self.elastic is not None:
            out["elastic"] = self.elastic.status()
        kvl = getattr(self.ctx, "kv_state", None)
        if kvl is not None:
            out["kv"] = kvl.snapshot()
        return out


def enable(context, strict_fair: Optional[bool] = None) -> ServingRuntime:
    """Attach a serving runtime to ``context`` (idempotent) and return
    it. For weighted-fair arbitration build the context with
    ``scheduler="wfq"`` (or ``--mca sched wfq``)."""
    if context.serving is not None:
        return context.serving
    return ServingRuntime(context, strict_fair=strict_fair)
