"""Multi-tenant serving runtime (ROADMAP item 4).

PaRSEC assumes one application driving one context; this package turns a
persistent :class:`~parsec_tpu.core.context.Context` into a shared
service: many client threads submit taskpools concurrently through
``Context.submit`` while the runtime enforces per-tenant admission
windows with backpressure (grown from the PR 3 DTD insertion throttle),
weighted-fair selection across live taskpools (``sched=wfq``),
per-submission deadlines with cancellation, tenant quarantine on
failure (poison bodies, lint-gate refusals, rank death), and open-loop
load shedding under overload — so no tenant can wedge, starve, or crash
another.

The proving workload is the Orca-style continuous-batching transformer
decode loop in :mod:`.decode` (KV cache as a tiled collection under the
HBM budget manager, per-request decode steps as DTD insertions), benched
by ``bench.py --section serving`` via :mod:`.serving_bench`.
"""

from .runtime import (AdmissionRejected, DeadlineExceeded, ServingRuntime,
                      Submission, Tenant, TenantQuarantined, enable)
from .elastic import (AutoscalePolicy, ElasticController, ElasticWorker,
                      Signals)

__all__ = ["AdmissionRejected", "DeadlineExceeded", "ServingRuntime",
           "Submission", "Tenant", "TenantQuarantined", "enable",
           "AutoscalePolicy", "ElasticController", "ElasticWorker",
           "Signals"]
