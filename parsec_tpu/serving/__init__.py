"""Multi-tenant serving runtime (ROADMAP item 4).

PaRSEC assumes one application driving one context; this package turns a
persistent :class:`~parsec_tpu.core.context.Context` into a shared
service: many client threads submit taskpools concurrently through
``Context.submit`` while the runtime enforces per-tenant admission
windows with backpressure (grown from the PR 3 DTD insertion throttle),
weighted-fair selection across live taskpools (``sched=wfq``),
per-submission deadlines with cancellation, tenant quarantine on
failure (poison bodies, lint-gate refusals, rank death), and open-loop
load shedding under overload — so no tenant can wedge, starve, or crash
another.

The proving workload is the Orca-style continuous-batching transformer
decode loop in :mod:`.decode` (KV cache as a tiled collection under the
HBM budget manager, per-request decode steps as DTD insertions), benched
by ``bench.py --section serving`` via :mod:`.serving_bench`.

The KV state layer (:mod:`.kv`, ROADMAP item 3 / ISSUE 15) adds the
cross-request state plane: paged KV allocation (page-granular
refcounts, COW, eviction), a radix prefix cache so requests sharing a
prompt prefix share immutable pages, chunked prefill on the wfq
prefill lane, and speculative decode as a cancellable draft-branch DTD
pattern (:mod:`.spec`) — benched by ``bench.py --section serving_kv``
via :mod:`.kv_bench`.
"""

from .runtime import (AdmissionRejected, DeadlineExceeded, ServingRuntime,
                      Submission, Tenant, TenantQuarantined, enable)
from .elastic import (AutoscalePolicy, ElasticController, ElasticWorker,
                      Signals)
from .kv import (KVPagePool, KVPagesExhausted, KVStateLayer, RadixTree,
                 layer_for)

__all__ = ["AdmissionRejected", "DeadlineExceeded", "ServingRuntime",
           "Submission", "Tenant", "TenantQuarantined", "enable",
           "AutoscalePolicy", "ElasticController", "ElasticWorker",
           "Signals", "KVPagePool", "KVPagesExhausted", "KVStateLayer",
           "RadixTree", "layer_for"]
