"""Shared-prefix KV-layer benchmark (``bench.py --section serving_kv``).

ISSUE 15's acceptance surface — the "millions of users" workload shape:
an open-loop trace of ``n_tenants`` tenants whose prompts share a
global SYSTEM prompt plus a per-tenant few-shot template, differing
only in a short per-request suffix (the production distribution both
PagedAttention and RadixAttention report: long shared head, short
unique tail).

Two arms at the SAME page budget (``serving.kv_pages``), same trace,
same context shape:

- **noshare** — ``serving.kv_prefix_cache=0``: every request chunk-
  prefills its whole prompt into its own pages (paged allocation still
  on — this is the no-SHARING baseline, not the no-paging one).
- **share** — the radix prefix cache on: after a prefix is first
  prefilled, later requests match it and prefill only the suffix.

Arrivals are open-loop with bounded retry on ``AdmissionRejected``
(page-budget exhaustion = explicit backpressure, not a crash). Two
load shapes: a BURST phase (whole trace offered at once) whose
sustained completed req/s per arm gives ``speedup_vs_nosharing``
(target ≥ 3×), and an ISO-LOAD phase (both arms paced at 75% of the
no-sharing arm's measured capacity) where "fixed p99" is checked —
the share arm's p99 at identical offered load must not exceed the
no-sharing arm's. Every completed request of every phase is checked
bitwise against the no-sharing float32 reference replay
(:func:`~.decode.reference_decode_paged`) — sharing must be invisible
to results.

A third phase exercises SPECULATIVE decode (short prompts so the
sliding-window draft model is exact early — acceptances — then
deterministically diverges — rejection + branch cancellation), A/B'd
against the same trace with speculation off for a latency ratio.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

from ..utils.stats import pctl as _pctl

_PAGE_TOKENS = 16
_SYS_PAGES = 56            # global system prompt: 896 tokens
_TENANT_PAGES = 4          # per-tenant few-shot template: 64 tokens
_UNIQUE_TOKENS = 16        # per-request unique suffix: 1 page
_DECODE_STEPS = 4
_PREFILL_CHUNK = 1         # pages per chunked-prefill task
_DECODE_WINDOW = 4         # multi-step decode scheduling, BOTH arms
_PAGE_BUDGET = 3000        # pages — identical in BOTH arms


def _sys_tokens() -> tuple:
    return tuple(10_000 + i for i in range(_SYS_PAGES * _PAGE_TOKENS))


def _tenant_tokens(ti: int) -> tuple:
    return tuple(20_000 + ti * 1_000 + i
                 for i in range(_TENANT_PAGES * _PAGE_TOKENS))


def _request_tokens(ti: int, ri: int) -> tuple:
    uniq = tuple(40_000 + ti * 10_000 + ri * 100 + i
                 for i in range(_UNIQUE_TOKENS))
    return _sys_tokens() + _tenant_tokens(ti) + uniq


def _run_arm(share: bool, n_tenants: int, reqs_per_tenant: int,
             spec_draft: int = 0, prompt_fn=None, n_steps: int =
             _DECODE_STEPS, submit_threads: int = 4,
             rate_per_sec: float = 0.0,
             decode_window: int = _DECODE_WINDOW) -> Dict:
    """One arm: fresh context + KV layer, submit the whole trace
    open-loop (bounded retry on admission rejection), drain, verify
    bitwise, report sustained rates."""
    import parsec_tpu as parsec
    from .. import serving as srv
    from ..serving.decode import DecodeConfig, DecodeEngine
    from ..serving.kv import KVStateLayer
    from ..utils import mca_param

    mca_param.set("sched", "wfq")
    mca_param.set("serving.kv_prefill_chunk", _PREFILL_CHUNK)
    mca_param.set("serving.kv_decode_window", decode_window)
    if spec_draft:
        mca_param.set("serving.kv_spec_draft", spec_draft)
    ctx = parsec.init(nb_cores=4)
    prompt_fn = prompt_fn or _request_tokens
    try:
        srv.enable(ctx)
        ctx.start()
        cfg = DecodeConfig()
        layer = KVStateLayer(ctx, cfg.d_model,
                             page_tokens=_PAGE_TOKENS,
                             capacity=_PAGE_BUDGET, share=share)
        engines = [DecodeEngine(ctx, f"kt{ti}", cfg=cfg,
                                tenant=f"kt{ti}", kv_layer=layer).start()
                   for ti in range(n_tenants)]

        reqs: List = []
        reqs_lock = threading.Lock()
        retries = [0]

        def submit_one(ti: int, rid: int, toks, steps: int,
                       record: bool = True) -> None:
            # a rejected submission retries with a short backoff (the
            # page budget IS the admission signal) instead of being
            # silently dropped from the offered load
            arrival = time.monotonic()
            deadline = arrival + 120.0
            while True:
                try:
                    r = engines[ti].request(rid, steps, tokens=toks)
                    # latency clocks from ARRIVAL, not admission: the
                    # noshare arm queues in this retry loop, the share
                    # arm queues in-engine — p99 must charge both the
                    # same way or the budget-constrained arm's queueing
                    # would be invisible
                    r.submitted_t = arrival
                    if record:
                        with reqs_lock:
                            reqs.append((ti, r))
                    return
                except srv.AdmissionRejected:
                    if record:
                        with reqs_lock:
                            retries[0] += 1
                    if time.monotonic() > deadline:
                        return
                    time.sleep(0.005)

        # warm phase (excluded from the measurement): one request per
        # tenant populates the prefix cache — the measured window is
        # the STEADY-STATE of a long-running service (sessions arriving
        # against an established cache), identical in both arms so the
        # noshare baseline pays the same warmup (incl. page-budget
        # backpressure: warming 100 unshared 46-page prompts does not
        # fit 3000 pages at once)
        for ti in range(n_tenants):
            submit_one(ti, ti * 1_000 + 999, prompt_fn(ti, 999), 1,
                       record=False)
            if ti % 25 == 24:
                for eng in engines:
                    eng.drain(timeout=120.0)
        for eng in engines:
            eng.drain(timeout=120.0)
        warm_hit = layer.stats["tokens_hit"]
        warm_lk = layer.stats["tokens_looked_up"]

        def submit_range(tis) -> None:
            # open-loop per submitter: sweep rounds over its tenants.
            # With ``rate_per_sec`` the sweep is PACED (each submitter
            # carries its share of the global arrival rate, a late
            # server never slows arrivals) — the iso-load latency
            # phase; 0 = burst (the capacity phase).
            interval = (len(shards) / rate_per_sec
                        if rate_per_sec else 0.0)
            next_t = time.monotonic()
            for ri in range(reqs_per_tenant):
                for ti in tis:
                    if interval:
                        delay = next_t - time.monotonic()
                        if delay > 0:
                            time.sleep(delay)
                        next_t += interval
                    submit_one(ti, ti * 1_000 + ri, prompt_fn(ti, ri),
                               n_steps)

        # completion-driven release (the elastic bench's completer
        # shape): a finished request's pages go back to the pool AS IT
        # COMPLETES — under a saturated page budget the submitters'
        # admission retries are fed by these releases; releasing only
        # at end-of-run would deadlock the open loop against the
        # budget. ``req.result``/``latency_s`` survive release for the
        # bitwise check below.
        finished: List = []
        stop = threading.Event()

        def completer() -> None:
            while True:
                moved = 0
                for ti, eng in enumerate(engines):
                    with eng._lock:
                        done = [r for r in eng.pending.values()
                                if r.done_evt.is_set()]
                    for r in done:
                        eng.release(r)
                        finished.append((ti, r))
                        moved += 1
                if not moved:
                    if stop.is_set():
                        return
                    time.sleep(0.003)

        t0 = time.monotonic()
        ct = threading.Thread(target=completer, daemon=True)
        ct.start()
        shards = [list(range(ti, n_tenants, submit_threads))
                  for ti in range(submit_threads)]
        threads = [threading.Thread(target=submit_range, args=(s,),
                                    daemon=True) for s in shards if s]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300.0)
        deadline = time.monotonic() + 120.0
        while any(eng.pending for eng in engines) and \
                time.monotonic() < deadline:
            time.sleep(0.01)            # completer empties pending
        stop.set()
        ct.join(timeout=10.0)
        t_total = time.monotonic() - t0

        bad = sum(1 for ti, r in finished if not engines[ti].verify(r))
        lats = sorted(r.latency_s() * 1e3 for _ti, r in finished
                      if r.latency_s() is not None)
        n = len(finished)
        snap = layer.snapshot()
        pool_snap = snap["pool"]
        prompt_tokens = sum(len(r.tokens) for _ti, r in finished)
        out = {
            "share": share,
            "requests": n,
            "offered": n_tenants * reqs_per_tenant,
            "admission_retries": retries[0],
            "wall_s": round(t_total, 3),
            "requests_per_sec": round(n / t_total, 2) if t_total else 0,
            # EFFECTIVE prompt ingest rate: tokens of completed
            # requests' prompts per second (cached or computed — the
            # user-visible prefill bandwidth)
            "prefill_tokens_per_sec":
                round(prompt_tokens / t_total, 1) if t_total else 0,
            "prefill_tokens_computed": snap["tokens_prefilled"],
            "p50_ms": round(_pctl(lats, 0.50), 2) if lats else None,
            "p99_ms": round(_pctl(lats, 0.99), 2) if lats else None,
            "bitwise": "OK" if (bad == 0 and n > 0) else "FAIL",
            "bitwise_bad": bad,
            # hit rate over the MEASURED window only (warmup excluded)
            "kv_hit_rate": round(
                (layer.stats["tokens_hit"] - warm_hit)
                / max(1, layer.stats["tokens_looked_up"] - warm_lk), 4),
            "pages_in_use_peak": pool_snap["peak_in_use"],
            "pages_budget": pool_snap["capacity"],
            "pool_exhausted_events": pool_snap["exhausted"],
            "cow_copies": pool_snap["cow_copies"],
            "evict_reclaims": pool_snap["evict_reclaims"],
            "spec": {k: snap[k] for k in
                     ("spec_windows", "spec_accepted_steps",
                      "spec_rejected_windows",
                      "spec_cancelled_branches")},
        }
        for eng in engines:
            eng.close()
        out["pages_in_use_final"] = layer.pool.pages_in_use()
        out["pages_cached_final"] = layer.tree.snapshot()["cached_pages"]
        return out
    finally:
        for knob in ("sched", "serving.kv_prefill_chunk",
                     "serving.kv_decode_window", "serving.kv_spec_draft"):
            mca_param.unset(knob)
        parsec.fini(ctx)


def _spec_phase(n_tenants: int = 8, reqs_per_tenant: int = 2) -> Dict:
    """Speculative-decode A/B on a short-prompt trace: one page of
    prompt keeps early contexts inside the draft's sliding window
    (exact ⇒ accepted), 24 steps pushes past it (diverges ⇒ branch
    cancelled) — both paths exercised, results bitwise either way."""

    def prompts(ti: int, ri: int) -> tuple:
        return tuple(60_000 + ti * 100 + ri * 7 + i
                     for i in range(_PAGE_TOKENS))

    # window=1 in BOTH arms: the classic speculative-decode A/B is
    # draft+batched-verify vs the plain per-step chain (the multi-step
    # window row is measured separately by the capacity arms)
    base = _run_arm(True, n_tenants, reqs_per_tenant, spec_draft=0,
                    prompt_fn=prompts, n_steps=24, submit_threads=2,
                    decode_window=1)
    spec = _run_arm(True, n_tenants, reqs_per_tenant, spec_draft=6,
                    prompt_fn=prompts, n_steps=24, submit_threads=2,
                    decode_window=1)
    ratio = (round(base["p50_ms"] / spec["p50_ms"], 3)
             if base.get("p50_ms") and spec.get("p50_ms") else None)
    return {
        "baseline_p50_ms": base.get("p50_ms"),
        "spec_p50_ms": spec.get("p50_ms"),
        "spec_latency_speedup": ratio,
        "bitwise": "OK" if (base["bitwise"] == "OK"
                            and spec["bitwise"] == "OK") else "FAIL",
        **spec["spec"],
        "draft_pages_released": spec["pages_in_use_final"]
        == spec["pages_cached_final"],
    }


def _measure_child(q, n_tenants: int, reqs_per_tenant: int) -> None:
    """Spawn-child entry: the measurement in a fresh process whose BLAS
    pools were pinned to ONE thread by the parent's env (read at
    library load — see :func:`measure_serving_kv_pinned`). The GIL
    switch interval is pinned low too (both arms): decode bodies are
    dozens of tiny GIL-dropping numpy calls, and the default 5 ms
    interval turns every re-acquire into a convoy stall — the same
    class of cost PR 3/PR 10 batched completions to avoid."""
    try:
        import sys
        sys.setswitchinterval(0.0002)
        import jax
        jax.config.update("jax_platforms", "cpu")
        q.put(("ok", measure_serving_kv(n_tenants, reqs_per_tenant)))
    except BaseException as exc:  # noqa: BLE001 — report to parent
        import traceback
        q.put(("error", f"{exc}\n{traceback.format_exc()}"))


def measure_serving_kv_pinned(n_tenants: int = 100,
                              reqs_per_tenant: int = 4) -> Dict:
    """Run :func:`measure_serving_kv` in a spawn child with BLAS thread
    pools pinned to 1 (OPENBLAS/OMP/MKL env, read at import time).
    Unpinned, each of the 4 workers' tiny-matrix numpy calls opens a
    multi-thread BLAS parallel region — 16+ spinning threads inflate a
    0.1 ms decode body ~100x and the measurement stops being about the
    runtime at all."""
    import multiprocessing as mp
    import os
    pins = {"OPENBLAS_NUM_THREADS": "1", "OMP_NUM_THREADS": "1",
            "MKL_NUM_THREADS": "1"}
    old = {k: os.environ.get(k) for k in pins}
    os.environ.update(pins)
    try:
        mpctx = mp.get_context("spawn")
        q = mpctx.Queue()
        p = mpctx.Process(target=_measure_child,
                          args=(q, n_tenants, reqs_per_tenant))
        p.start()
        try:
            status, payload = q.get(timeout=1800)
        finally:
            p.join(timeout=30.0)
            if p.is_alive():
                p.terminate()
        if status != "ok":
            raise RuntimeError(f"serving_kv child failed: {payload}")
        return payload
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def measure_serving_kv(n_tenants: int = 100,
                       reqs_per_tenant: int = 4) -> Dict:
    """The full ``--section serving_kv`` measurement (see module doc).

    Two load shapes per the acceptance criterion ("≥3× sustained req/s
    ... at fixed p99"):

    - **capacity** (burst): the whole trace offered open-loop with
      bounded admission retry; sustained completed req/s per arm —
      ``speedup_vs_nosharing`` is their ratio. Cross-arm p99 is NOT
      comparable here (the budget-constrained arm's queueing hides in
      admission backoff).
    - **iso-load** (paced): both arms at the SAME offered rate (75% of
      the no-sharing arm's measured capacity — both sustain it);
      "fixed p99" = the share arm's p99 must not exceed the no-sharing
      arm's at identical load.
    """
    noshare = _run_arm(False, n_tenants, reqs_per_tenant)
    share = _run_arm(True, n_tenants, reqs_per_tenant)
    iso_rate = max(2.0, 0.75 * noshare["requests_per_sec"])
    iso_n = _run_arm(False, n_tenants, 2, rate_per_sec=iso_rate)
    iso_s = _run_arm(True, n_tenants, 2, rate_per_sec=iso_rate)
    spec = _spec_phase()

    speedup = (round(share["requests_per_sec"]
                     / noshare["requests_per_sec"], 3)
               if noshare["requests_per_sec"] else None)
    p99_ok = (isinstance(iso_s.get("p99_ms"), (int, float)) and
              isinstance(iso_n.get("p99_ms"), (int, float)) and
              iso_s["p99_ms"] <= iso_n["p99_ms"])
    accept = (speedup is not None and speedup >= 3.0
              and share["kv_hit_rate"] > 0
              and share["bitwise"] == "OK"
              and noshare["bitwise"] == "OK"
              and iso_s["bitwise"] == "OK"
              and iso_n["bitwise"] == "OK"
              and spec["bitwise"] == "OK"
              and p99_ok)
    return {
        "n_tenants": n_tenants,
        "reqs_per_tenant": reqs_per_tenant,
        "page_tokens": _PAGE_TOKENS,
        "prompt_tokens": (_SYS_PAGES + _TENANT_PAGES) * _PAGE_TOKENS
        + _UNIQUE_TOKENS,
        "decode_steps": _DECODE_STEPS,
        "pages_budget": _PAGE_BUDGET,
        "requests_per_sec": share["requests_per_sec"],
        "requests_per_sec_nosharing": noshare["requests_per_sec"],
        "speedup_vs_nosharing": speedup,
        "kv_hit_rate": share["kv_hit_rate"],
        "prefill_tokens_per_sec": share["prefill_tokens_per_sec"],
        # the guarded p99 row: the share arm at the iso-load rate (a
        # stable sub-saturation point; burst p99 is backlog-shaped)
        "p99_ms": iso_s.get("p99_ms"),
        "p99_ms_nosharing_iso": iso_n.get("p99_ms"),
        "iso_rate_per_sec": round(iso_rate, 2),
        "p99_fixed_ok": p99_ok,
        "bitwise": "OK" if (share["bitwise"] == "OK"
                            and noshare["bitwise"] == "OK"
                            and iso_s["bitwise"] == "OK"
                            and iso_n["bitwise"] == "OK") else "FAIL",
        "share": share,
        "noshare": noshare,
        "iso_share": iso_s,
        "iso_noshare": iso_n,
        "spec": spec,
        "spec_accepted_steps": spec.get("spec_accepted_steps"),
        "spec_cancelled_branches": spec.get("spec_cancelled_branches"),
        "acceptance": "OK" if accept else "FAIL",
    }
