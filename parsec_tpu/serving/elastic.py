"""Elastic capacity: autoscale, drain, and rebalance the serving mesh.

PaRSEC treats the rank set as fixed for the life of the context
(parsec_init → parsec_fini over one MPI world); this reproduction's
PR 6 already rejoins a dead rank and PR 8 sheds load, but nothing
closed the control loop. This module is the policy-driven elasticity
subsystem a production serving runtime needs (ROADMAP item 4) — wired
from parts that already exist:

- **Signals** come from the PR 9 metrics plane and the serving runtime:
  ready-queue/backlog depth (per-rank heartbeats over a dedicated
  ``AMTag.ELASTIC`` channel), admission parks/rejections and the shed
  counter (``ServingRuntime.stats``), and p99-vs-deadline headroom
  (a rolling completion-latency window).
- **Policy** (:class:`AutoscalePolicy`): signals → desired serving-rank
  count, with hysteresis (separate up/down thresholds + consecutive
  idle rounds before a shrink) and a cooldown between acts so the
  controller cannot flap. ``serving.autoscale = off | advise | act``:
  ``advise`` computes and records decisions without executing them.
- **Scale-up** rides the PR 6 rejoin path extended to FRESH ranks:
  the controller picks the next slot (reusing drained slots first so
  the world stays dense), asks the harness to spawn it
  (``spawn_rank`` callback), and the socket engine admits it beyond
  the original world size (``comm.elastic``) — peer tables, termdet
  waves, barriers and recovery allgathers all run over the enlarged
  live set. A joiner stalled past ``comm.rejoin_timeout`` (e.g. the
  ``slowjoin`` fault injection) is ABANDONED cleanly: the decision is
  recorded failed and the loop keeps running.
- **Scale-down** is quiesce → checkpoint-cut → drain: the victim's
  tenants are migrated off first (each shard travels through the PR 6
  checkpoint vehicle: owner saves a single-rank step, adopter
  restores it), then the victim receives ``drain``, finishes its
  in-flight work, acks, and leaves with an orderly BYE — peers record
  it DEPARTED, never dead: no failure path, no quarantine, no abort
  sweep.
- **Tenant migration** (:meth:`ElasticController.migrate_tenant`) is
  also exposed directly for hot-spot isolation: routing for the tenant
  pauses, the shard moves, routing resumes — the pause window is the
  ``migration_pause`` the bench reports p99 over.

The module is workload-agnostic: the request/serving integration
(what a "tenant" actually runs — e.g. the continuous-batching decode
engine) plugs in through :class:`ElasticWorker` callbacks and the
controller's routing-pause hooks. ``serving/elastic_bench.py`` is the
proving harness (``bench.py --section elastic``).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..comm.engine import AMTag
from ..utils import mca_param
from ..utils.debug import debug_verbose, warning
from ..utils.stats import pctl as _pctl

mca_param.register("serving.autoscale", "off",
                   help="elastic-capacity autoscaler mode: off | "
                        "advise (compute + record decisions, never "
                        "act) | act (execute scale-up/down/rebalance)",
                   choices=("off", "advise", "act"))
mca_param.register("serving.autoscale_poll_s", 0.25,
                   help="autoscaler control-loop poll interval")
mca_param.register("serving.autoscale_cooldown_s", 2.0,
                   help="minimum seconds between autoscaler ACTS (a "
                        "decision inside the cooldown is recorded but "
                        "holds the current count — anti-flap)")
mca_param.register("serving.autoscale_min_ranks", 1,
                   help="lower bound of the serving-rank count (the "
                        "controller rank is not a serving rank)")
mca_param.register("serving.autoscale_max_ranks", 0,
                   help="upper bound of the serving-rank count "
                        "(0 = unbounded; the spawn callback may still "
                        "refuse)")
mca_param.register("serving.autoscale_up_backlog", 8.0,
                   help="scale up when the per-serving-rank backlog "
                        "(queued + in-flight requests) exceeds this")
mca_param.register("serving.autoscale_down_backlog", 1.0,
                   help="a poll with per-rank backlog below this "
                        "counts toward the idle-rounds shrink trigger")
mca_param.register("serving.autoscale_idle_rounds", 4,
                   help="consecutive below-down-backlog polls before "
                        "the policy proposes a scale-down (hysteresis)")
mca_param.register("serving.autoscale_headroom", 0.8,
                   help="scale up when the rolling p99 latency exceeds "
                        "this fraction of the request deadline (only "
                        "when a deadline is configured)")
mca_param.register("serving.drain_timeout_s", 30.0,
                   help="seconds the controller waits for a victim "
                        "rank's drained ack before recording the "
                        "scale-down failed")
mca_param.register("serving.migrate_timeout_s", 30.0,
                   help="seconds the controller waits for each tenant "
                        "migration leg (drop / adopt ack)")


# ---------------------------------------------------------------------------
# signals + policy
# ---------------------------------------------------------------------------

@dataclass
class Signals:
    """One control-loop observation (everything the policy reads)."""
    serving_ranks: int = 0
    backlog: float = 0.0             # queued + in-flight requests, mesh-wide
    per_rank: Dict[int, float] = field(default_factory=dict)
    parks: int = 0                   # cumulative admission parks
    rejections: int = 0              # cumulative admission rejections
    shed: int = 0                    # cumulative overload sheds
    p99_s: Optional[float] = None    # rolling completion p99
    deadline_s: Optional[float] = None


class AutoscalePolicy:
    """Signals → desired serving-rank count, with hysteresis + cooldown.

    Scale-up fires on ANY pressure signal: per-rank backlog over
    ``serving.autoscale_up_backlog``, new admission parks/rejections or
    sheds since the last poll, or rolling p99 past
    ``serving.autoscale_headroom`` × the deadline. Scale-down needs
    ``serving.autoscale_idle_rounds`` CONSECUTIVE polls under
    ``serving.autoscale_down_backlog`` per rank — one busy poll resets
    the streak. Acts are separated by ``serving.autoscale_cooldown_s``;
    a decision landing inside the cooldown holds the current count with
    reason ``"cooldown"`` (recorded, not acted)."""

    def __init__(self, min_ranks: Optional[int] = None,
                 max_ranks: Optional[int] = None,
                 up_backlog: Optional[float] = None,
                 down_backlog: Optional[float] = None,
                 idle_rounds: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 headroom: Optional[float] = None):
        g = mca_param.get
        self.min_ranks = int(min_ranks if min_ranks is not None
                             else g("serving.autoscale_min_ranks", 1))
        self.max_ranks = int(max_ranks if max_ranks is not None
                             else g("serving.autoscale_max_ranks", 0))
        self.up_backlog = float(
            up_backlog if up_backlog is not None
            else g("serving.autoscale_up_backlog", 8.0))
        self.down_backlog = float(
            down_backlog if down_backlog is not None
            else g("serving.autoscale_down_backlog", 1.0))
        self.idle_rounds = int(
            idle_rounds if idle_rounds is not None
            else g("serving.autoscale_idle_rounds", 4))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else g("serving.autoscale_cooldown_s", 2.0))
        self.headroom = float(headroom if headroom is not None
                              else g("serving.autoscale_headroom", 0.8))
        self._idle_streak = 0
        self._last_act_t: Optional[float] = None
        # None until the first observation: the runtime's counters are
        # cumulative since process start, so the first poll must
        # BASELINE them, not read the historical total as a one-poll
        # delta (which would fire a spurious scale-up on attach)
        self._last_parks: Optional[int] = None
        self._last_rejections: Optional[int] = None
        self._last_shed: Optional[int] = None

    def note_act(self, now: float) -> None:
        """The controller EXECUTED a decision — start the cooldown."""
        self._last_act_t = now
        self._idle_streak = 0

    def cooldown_remaining(self, now: float) -> float:
        if self._last_act_t is None:
            return 0.0
        return max(0.0, self._last_act_t + self.cooldown_s - now)

    def _up_reason(self, sig: Signals) -> Optional[str]:
        n = max(sig.serving_ranks, 1)
        per = sig.backlog / n
        if per > self.up_backlog:
            return (f"backlog {per:.1f}/rank > "
                    f"{self.up_backlog:g} (serving.autoscale_up_backlog)")
        if self._last_parks is not None:
            d_park = sig.parks - self._last_parks
            d_rej = sig.rejections - self._last_rejections
            d_shed = sig.shed - self._last_shed
            if d_park > 0 or d_rej > 0:
                return (f"admission pressure (+{d_park} parks, "
                        f"+{d_rej} rejections since last poll)")
            if d_shed > 0:
                return f"load shedding fired (+{d_shed})"
        if sig.p99_s is not None and sig.deadline_s:
            if sig.p99_s > self.headroom * sig.deadline_s:
                return (f"p99 {sig.p99_s * 1e3:.1f}ms > "
                        f"{self.headroom:g}x deadline "
                        f"{sig.deadline_s * 1e3:.0f}ms "
                        "(serving.autoscale_headroom)")
        return None

    def decide(self, sig: Signals, now: float) -> Tuple[int, str]:
        """Returns ``(desired_serving_ranks, reason)``. Counter deltas
        (parks/rejections/shed) are consumed even during cooldown so a
        burst inside the cooldown doesn't double-fire after it."""
        n = sig.serving_ranks
        up = self._up_reason(sig)
        self._last_parks = sig.parks
        self._last_rejections = sig.rejections
        self._last_shed = sig.shed
        if self.cooldown_remaining(now) > 0:
            # hysteresis state still advances during cooldown, so an
            # idle mesh doesn't need idle_rounds MORE polls after it
            if up is None and n > 0 and \
                    sig.backlog / max(n, 1) < self.down_backlog:
                self._idle_streak += 1
            return n, "cooldown"
        if up is not None:
            cap = self.max_ranks if self.max_ranks > 0 else n + 1
            if n < cap:
                self._idle_streak = 0
                return n + 1, up
            self._idle_streak = 0
            return n, f"at max_ranks {cap}: {up}"
        if n > 0 and sig.backlog / max(n, 1) < self.down_backlog:
            self._idle_streak += 1
            if self._idle_streak >= self.idle_rounds and \
                    n > self.min_ranks:
                self._idle_streak = 0
                return n - 1, (f"idle {self.idle_rounds} rounds "
                               f"(backlog {sig.backlog:g} < "
                               f"{self.down_backlog:g}/rank)")
        else:
            self._idle_streak = 0
        return n, "steady"


# ---------------------------------------------------------------------------
# AM channel (AMTag.ELASTIC): op-keyed dispatch shared by both roles
# ---------------------------------------------------------------------------

class _ElasticChannel:
    """Op-dispatching wrapper of ``AMTag.ELASTIC``. ONE handler per
    engine (controller and worker roles register their ops into it);
    handlers run on the comm thread and must not block — both roles
    only enqueue/flag and do the real work on their own threads."""

    def __init__(self, comm):
        self.comm = comm
        self._handlers: Dict[str, Callable[[int, Dict], None]] = {}
        existing = getattr(comm, "_elastic_channel", None)
        if existing is not None:
            # same-process controller+worker (loopback tests): share
            self._handlers = existing._handlers
        else:
            comm.tag_register(AMTag.ELASTIC, self._dispatch)
            comm._elastic_channel = self

    def on(self, op: str, fn: Callable[[int, Dict], None]) -> None:
        self._handlers[op] = fn

    def send(self, dst: int, op: str, **kw) -> None:
        msg = {"op": op}
        msg.update(kw)
        self.comm.send_am(AMTag.ELASTIC, dst, msg)

    def _dispatch(self, src: int, msg: Dict) -> None:
        fn = self._handlers.get(msg.get("op"))
        if fn is None:
            warning("elastic", "no handler for elastic op %r from %d",
                    msg.get("op"), src)
            return
        fn(src, msg)


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class ElasticController:
    """The autoscaler control loop (runs on the front-end rank).

    ``spawn_rank(rank, world, live_peers)`` is the harness-provided
    launcher of a fresh rank process; ``tenants`` seeds the placement
    (tenant → serving rank, round-robin). Attach routing hooks with
    :meth:`set_router` so migrations can pause/resume a tenant's
    traffic, and feed completions through :meth:`record_latency` for
    the p99-headroom signal. ``runtime`` (a ``ServingRuntime``) is
    optional — when given, its park/reject/shed counters become policy
    signals and ``statusz``/``report`` surface :meth:`status`."""

    def __init__(self, ctx, runtime=None,
                 spawn_rank: Optional[Callable] = None,
                 tenants=(), policy: Optional[AutoscalePolicy] = None,
                 mode: Optional[str] = None,
                 deadline_s: Optional[float] = None):
        self.ctx = ctx
        self.comm = ctx.comm
        if self.comm is None:
            raise ValueError("ElasticController needs a comm engine "
                             "(the mesh it scales)")
        self.runtime = runtime
        if runtime is not None:
            runtime.elastic = self
        self.spawn_rank = spawn_rank
        self.policy = policy or AutoscalePolicy()
        self.mode = (mode if mode is not None else
                     str(mca_param.get("serving.autoscale",
                                       "off"))).lower()
        self.deadline_s = deadline_s
        live = [r for r in self.comm.world_status()["live"]
                if r != ctx.my_rank]
        self.serving_ranks: List[int] = sorted(live)
        self.placement: Dict[str, int] = {}
        # last checkpoint step holding each tenant's shard: the adopt
        # source for a tenant whose placement is None (either never
        # placed, or a migration's drop leg succeeded and its adopt
        # leg failed — the shard sits durable in the step, not lost)
        self.shard_steps: Dict[str, Optional[int]] = {}
        self._place(tenants)
        self.draining: set = set()
        self.desired = len(self.serving_ranks)
        self.last_decision: Optional[Dict] = None
        self.decisions: List[Dict] = []      # ACTED scale ops (full log)
        self.advisories: List[Dict] = []     # notable non-acted (last 32)
        self.failed_joins = 0
        self.migration_pauses_ms: List[float] = []
        self._hb: Dict[int, Dict] = {}
        self._hb_lock = threading.Lock()
        self._lat: deque = deque(maxlen=512)
        self._outstanding_fn: Optional[Callable[[], Dict[int, float]]] \
            = None
        self._pause_fn: Optional[Callable[[str], None]] = None
        self._resume_fn: Optional[Callable[[str], None]] = None
        self._acks: Dict[int, List] = {}       # token -> [Event, payload]
        self._ack_lock = threading.Lock()
        self._token = itertools.count(1)
        self._step = itertools.count(1)        # migration ckpt steps
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.channel = _ElasticChannel(self.comm)
        self.channel.on("stats", self._on_stats)
        self.channel.on("ack", self._on_ack)

    # ------------------------------------------------------------ wiring
    def _place(self, tenants) -> None:
        for i, t in enumerate(sorted(tenants)):
            if self.serving_ranks:
                self.placement[t] = self.serving_ranks[
                    i % len(self.serving_ranks)]

    def set_router(self, outstanding_fn: Callable[[], Dict[int, float]],
                   pause_fn: Callable[[str], None],
                   resume_fn: Callable[[str], None]) -> None:
        """Routing integration: ``outstanding_fn() -> {rank: backlog}``
        (requests routed but not yet completed, per serving rank);
        ``pause_fn(tenant)`` / ``resume_fn(tenant)`` bracket a tenant
        migration — paused traffic queues at the router and flushes to
        the new owner on resume (the measured migration pause)."""
        self._outstanding_fn = outstanding_fn
        self._pause_fn = pause_fn
        self._resume_fn = resume_fn

    def record_latency(self, latency_s: float) -> None:
        self._lat.append(float(latency_s))

    def owner_of(self, tenant: str) -> Optional[int]:
        return self.placement.get(tenant)

    def draining_ranks(self) -> List[int]:
        return sorted(self.draining)

    # ------------------------------------------------------- AM handlers
    def _on_stats(self, src: int, msg: Dict) -> None:
        with self._hb_lock:
            self._hb[src] = {"t": time.monotonic(),
                             "backlog": float(msg.get("backlog", 0.0)),
                             "tenants": msg.get("tenants", [])}

    def _on_ack(self, src: int, msg: Dict) -> None:
        with self._ack_lock:
            slot = self._acks.get(msg.get("token"))
        if slot is not None:
            slot[1] = msg
            slot[0].set()

    def _new_ack(self) -> Tuple[int, List]:
        token = next(self._token)
        slot = [threading.Event(), None]
        with self._ack_lock:
            self._acks[token] = slot
        return token, slot

    def _wait_ack(self, token: int, slot: List, timeout: float,
                  what: str) -> Dict:
        try:
            if not slot[0].wait(timeout):
                raise TimeoutError(f"elastic: no ack for {what} within "
                                   f"{timeout:.1f}s")
            msg = slot[1]
            if msg.get("error"):
                raise RuntimeError(f"elastic: {what} failed on the "
                                   f"remote rank: {msg['error']}")
            return msg
        finally:
            with self._ack_lock:
                self._acks.pop(token, None)

    # ----------------------------------------------------------- signals
    def signals(self) -> Signals:
        sig = Signals(serving_ranks=len(self.serving_ranks))
        per: Dict[int, float] = {r: 0.0 for r in self.serving_ranks}
        with self._hb_lock:
            for r, hb in self._hb.items():
                if r in per:
                    per[r] = hb["backlog"]
        if self._outstanding_fn is not None:
            for r, v in (self._outstanding_fn() or {}).items():
                # router-side view dominates: it also counts requests
                # a saturated worker has not even received yet
                per[r] = max(per.get(r, 0.0), float(v))
        sig.per_rank = per
        sig.backlog = sum(per.values())
        rt = self.runtime
        if rt is not None:
            st = rt.stats
            sig.parks = int(st.get("parked", 0))
            sig.rejections = int(st.get("rejected", 0))
            sig.shed = int(st.get("shed", 0))
        lats = list(self._lat)
        sig.p99_s = _pctl(lats, 0.99)
        sig.deadline_s = self.deadline_s
        return sig

    # ------------------------------------------------------ control loop
    def start(self) -> "ElasticController":
        if self.mode == "off" or self._thread is not None:
            return self
        t = threading.Thread(target=self._loop,
                             name="parsec-autoscaler", daemon=True)
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        poll = float(mca_param.get("serving.autoscale_poll_s", 0.25))
        while not self._stop.wait(poll):
            try:
                self.step()
            except Exception as exc:  # noqa: BLE001 — loop must survive
                warning("elastic", "autoscaler step raised: %s", exc)
                import traceback
                traceback.print_exc()

    def step(self) -> Dict:
        """One control iteration (also callable directly — tests and
        deterministic harnesses drive it without the thread)."""
        now = time.monotonic()
        sig = self.signals()
        if self.mode == "act":
            # repair pass: a tenant left UNPLACED (a migration's adopt
            # leg failed, or a drain carried leftovers) is re-placed
            # from its durable shard step — without this, only the
            # next scale-UP would ever restore its traffic. Reuses
            # this poll's signal set (signals() walks the router's
            # outstanding map under its lock — no second pass).
            self.repair_placement(sig)
        desired, reason = self.policy.decide(sig, now)
        self.desired = desired
        current = len(self.serving_ranks)
        decision = {"t": now, "from": current, "to": desired,
                    "reason": reason, "mode": self.mode,
                    "backlog": round(sig.backlog, 1), "acted": False,
                    "ok": None}
        if desired != current:
            if self.mode == "act":
                decision["acted"] = True
                try:
                    if desired > current:
                        self.grow_one()
                    else:
                        self.shrink_one(sig)
                    decision["ok"] = True
                except Exception as exc:  # noqa: BLE001 — abandoned op
                    decision["ok"] = False
                    decision["error"] = str(exc)[:200]
                    warning("elastic", "scale %d -> %d abandoned: %s",
                            current, desired, exc)
                self.policy.note_act(time.monotonic())
            else:
                debug_verbose(2, "elastic",
                              "advise: would scale %d -> %d (%s)",
                              current, desired, reason)
        if decision["acted"]:
            self.decisions.append(decision)
            del self.decisions[:-256]
        elif reason not in ("steady", "cooldown"):
            # advise-mode would-acts and at-cap pressure: a separate
            # bounded log, so chatter can never push the (rare, load-
            # bearing) acted entries out of the operator's view
            self.advisories.append(decision)
            del self.advisories[:-32]
        self.last_decision = decision
        return decision

    # --------------------------------------------------------- scale up
    def _next_slot(self) -> int:
        """Reuse the lowest drained/dead slot first (keeps the world
        dense — a joiner wires up to every live in-range peer), else
        extend the world by one."""
        ws = self.comm.world_status()
        gone = sorted(set(ws["departed"]) | set(ws["dead"]))
        for r in gone:
            if r != self.ctx.my_rank:
                return r
        return int(ws["world"])

    def grow_one(self) -> int:
        """Admit one fresh serving rank: spawn → wait for the socket
        engine's admission → rebalance tenants onto it. A joiner
        stalled past ``comm.rejoin_timeout`` is abandoned (raises
        TimeoutError; the loop records the failure and continues)."""
        if self.spawn_rank is None:
            raise RuntimeError("scale-up needs a spawn_rank callback")
        new_rank = self._next_slot()
        ws = self.comm.world_status()
        world = max(int(ws["world"]), new_rank + 1)
        # controller FIRST in the joiner's wireup order: an abandoned
        # joiner is denied here before it can touch any other peer
        me = self.ctx.my_rank
        live = [me] + [r for r in ws["live"] if r != me]
        self._allow_join_everywhere(new_rank, live)
        self.spawn_rank(new_rank, world, live)
        try:
            self.comm.wait_rejoin(new_rank)
        except TimeoutError:
            admitted_late = False
            if hasattr(self.comm, "abandon_join"):
                # two-sided abandonment: a late arrival of the stalled
                # joiner is DENIED at the handshake — it must not be
                # silently admitted into quorums the controller will
                # never route work to. Propagated to every live peer
                # too (the joiner wires to the controller first, but
                # belt-and-braces against reordered transports). The
                # joiner may have squeaked in between our timeout and
                # the abandon mark — re-check once; an admitted rank
                # is a SUCCESS, not a zombie.
                self.comm.abandon_join(new_rank)
                for r in self.comm.world_status()["live"]:
                    if r != self.ctx.my_rank:
                        self.channel.send(r, "abandon_join",
                                          rank=new_rank)
                try:
                    self.comm.wait_rejoin(new_rank, timeout=0.05)
                    admitted_late = True
                    self._allow_join_everywhere(new_rank)
                except TimeoutError:
                    pass
            if not admitted_late:
                self.failed_joins += 1
                raise
        # readiness handshake: socket admission happens in the
        # joiner's engine constructor, BEFORE its ElasticWorker (and
        # hence its AMTag.ELASTIC handler) exists — migrating tenants
        # into that window would silently drop the adopt op and park
        # the tenant's routing for the whole migrate timeout. The
        # worker heartbeats immediately on construction; wait for it.
        self._wait_agent(new_rank)
        self.serving_ranks = sorted(set(self.serving_ranks) |
                                    {new_rank})
        self.rebalance()
        return new_rank

    def _allow_join_everywhere(self, rank: int, live=None) -> None:
        """Re-arm a joiner id on THIS engine and every live peer — an
        earlier abandonment was broadcast, so re-arming only locally
        would leave the fresh joiner denied by every worker it wires
        to after the controller."""
        if not hasattr(self.comm, "allow_join"):
            return
        self.comm.allow_join(rank)
        if live is None:
            live = self.comm.world_status()["live"]
        for r in live:
            if r != self.ctx.my_rank:
                self.channel.send(r, "allow_join", rank=rank)

    def _wait_agent(self, rank: int,
                    timeout: Optional[float] = None) -> None:
        """Block until ``rank``'s worker agent has heartbeat (its
        control-plane handler is registered); raises TimeoutError so a
        joined-but-agentless rank is a recorded failed decision, not a
        silent 30 s routing outage per migrated tenant."""
        if timeout is None:
            timeout = float(mca_param.get("serving.migrate_timeout_s",
                                          30.0))
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._hb_lock:
                if rank in self._hb:
                    return
            time.sleep(0.01)
        raise TimeoutError(
            f"rank {rank} joined the mesh but its elastic worker "
            f"agent sent no heartbeat within {timeout:.1f}s")

    def repair_placement(self, sig: Optional[Signals] = None) -> int:
        """Re-place every unplaced tenant (adopt-leg failure / drain
        leftovers) onto the least-loaded serving rank, adopting from
        its last durable shard step. Returns tenants re-placed; a
        still-failing adopt is logged and retried next step."""
        unplaced = sorted(t for t, r in self.placement.items()
                          if r is None)
        if not unplaced:
            return 0
        ranks = sorted(set(self.serving_ranks) - self.draining)
        if not ranks:
            return 0
        per = (sig if sig is not None else self.signals()).per_rank
        n = 0
        for t in unplaced:
            dst = min(ranks, key=lambda r: (per.get(r, 0.0), r))
            try:
                self.migrate_tenant(t, dst)
                n += 1
            except Exception as exc:  # noqa: BLE001 — retry next step
                warning("elastic", "re-placing tenant %s on rank %d "
                        "failed (will retry): %s", t, dst, exc)
        return n

    def rebalance(self) -> int:
        """Recompute the tenant → rank placement round-robin over the
        CURRENT serving ranks and migrate every tenant whose owner
        changed (the newcomer-onboarding path after a grow; also the
        repair path after a shrink). Returns migrations performed."""
        ranks = sorted(set(self.serving_ranks) - self.draining)
        if not ranks:
            return 0
        n = 0
        for i, t in enumerate(sorted(self.placement)):
            dst = ranks[i % len(ranks)]
            if self.placement[t] != dst:
                self.migrate_tenant(t, dst)
                n += 1
        return n

    # ------------------------------------------------------- scale down
    def shrink_one(self, sig: Optional[Signals] = None) -> int:
        """Quiesce → checkpoint-cut → drain one victim rank: migrate
        its tenants off, then send ``drain`` and wait for the ack; the
        victim leaves with an orderly BYE (peers record DEPARTED — the
        whole point is that a drained rank is never a failure)."""
        candidates = [r for r in self.serving_ranks
                      if r not in self.draining]
        if len(candidates) <= self.policy.min_ranks:
            raise RuntimeError("shrink refused: at min_ranks")
        per = (sig.per_rank if sig is not None else
               self.signals().per_rank)
        # least-loaded victim; highest id on ties (drained high slots
        # are reused first on the next grow, keeping the world dense)
        victim = max(candidates,
                     key=lambda r: (-per.get(r, 0.0), r))
        self.draining.add(victim)
        try:
            remaining = [r for r in self.serving_ranks
                         if r != victim and r not in self.draining]
            owned = sorted(t for t, r in self.placement.items()
                           if r == victim)
            if owned and not remaining:
                # scale-to-zero with live tenants: refuse with a clear
                # error instead of crashing the control loop every
                # poll (min_ranks=0 is a registered knob value)
                raise RuntimeError(
                    f"shrink refused: rank {victim} hosts tenants "
                    f"{owned} and no serving rank remains to adopt "
                    "them (raise serving.autoscale_min_ranks)")
            for i, t in enumerate(owned):
                self.migrate_tenant(t, remaining[i % len(remaining)])
            token, slot = self._new_ack()
            # the drain carries a checkpoint step so any LEFTOVER
            # tenant (normally all migrated off above) still exits
            # through the checkpoint vehicle, never lost
            step = next(self._step)
            self.channel.send(victim, "drain", token=token, step=step)
            ack = self._wait_ack(
                token, slot,
                float(mca_param.get("serving.drain_timeout_s", 30.0)),
                f"drain of rank {victim}")
            for t, s in (ack.get("steps") or {}).items():
                self.placement[t] = None
                self.shard_steps[t] = s
            self.serving_ranks = [r for r in self.serving_ranks
                                  if r != victim]
        finally:
            self.draining.discard(victim)
        return victim

    # -------------------------------------------------- tenant migration
    def migrate_tenant(self, tenant: str, dst: int) -> float:
        """Move one tenant's serving state from its current owner to
        ``dst`` through the checkpoint vehicle: pause routing → owner
        drains the tenant's in-flight work and saves its shard as a
        single-rank checkpoint step → ``dst`` restores the step and
        starts serving → resume routing. Returns the pause in ms (the
        bench's ``migration_pause`` sample). Also the hot-spot
        isolation primitive — callable directly, not only from
        scale events."""
        src = self.placement.get(tenant)
        if src == dst:
            return 0.0
        timeout = float(mca_param.get("serving.migrate_timeout_s", 30.0))
        t0 = time.perf_counter()
        if self._pause_fn is not None:
            self._pause_fn(tenant)
        try:
            step = next(self._step)
            if src is not None:
                token, slot = self._new_ack()
                self.channel.send(src, "drop_tenant", tenant=tenant,
                                  step=step, token=token)
                ack = self._wait_ack(token, slot, timeout,
                                     f"drop of tenant {tenant} on "
                                     f"rank {src}")
                step = ack.get("step", step)
                # the drop leg committed: src no longer serves the
                # tenant, the shard lives in checkpoint ``step``. From
                # here the tenant is UNPLACED until an adopt succeeds —
                # a failed adopt must not leave routing pointed at src
                # (whose worker would bounce forever) nor a later
                # retry re-dropping a shard src no longer holds.
                self.placement[tenant] = None
                self.shard_steps[tenant] = step
            else:
                # unplaced tenant: adopt from its last durable shard
                # step (None = genuinely fresh)
                step = self.shard_steps.get(tenant)
            token, slot = self._new_ack()
            self.channel.send(dst, "adopt_tenant", tenant=tenant,
                              step=step, token=token)
            self._wait_ack(token, slot, timeout,
                           f"adopt of tenant {tenant} on rank {dst}")
            self.placement[tenant] = dst
        finally:
            if self._resume_fn is not None:
                self._resume_fn(tenant)
        pause_ms = (time.perf_counter() - t0) * 1e3
        self.migration_pauses_ms.append(pause_ms)
        debug_verbose(2, "elastic", "tenant %s: rank %s -> %d in %.1fms",
                      tenant, src, dst, pause_ms)
        return pause_ms

    def shutdown_workers(self) -> None:
        """Orderly end-of-life: every serving rank exits WITHOUT
        migration (the harness is tearing the whole mesh down)."""
        for r in list(self.serving_ranks):
            try:
                self.channel.send(r, "shutdown")
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass

    # ------------------------------------------------------------ status
    def status(self) -> Dict:
        """The statusz/report ``autoscaler`` block."""
        now = time.monotonic()
        last = None
        if self.last_decision is not None:
            d = self.last_decision
            last = {"age_s": round(now - d["t"], 2),
                    "from": d["from"], "to": d["to"],
                    "reason": d["reason"], "acted": d["acted"],
                    "ok": d["ok"]}
        return {"mode": self.mode,
                "desired": self.desired,
                "serving_ranks": list(self.serving_ranks),
                "draining": self.draining_ranks(),
                "placement": dict(self.placement),
                "cooldown_remaining_s": round(
                    self.policy.cooldown_remaining(now), 3),
                "last_decision": last,
                "decisions": len(self.decisions),
                "advisories": len(self.advisories),
                "failed_joins": self.failed_joins,
                "migrations": len(self.migration_pauses_ms),
                "migration_pause_p99_ms": (
                    round(_pctl(self.migration_pauses_ms, 0.99), 3)
                    if self.migration_pauses_ms else None)}


# ---------------------------------------------------------------------------
# worker agent
# ---------------------------------------------------------------------------

class ElasticWorker:
    """Serving-rank agent: heartbeats + the drain/migrate protocol.

    The workload plugs in through three callbacks:

    - ``on_adopt(tenant, step)`` — start serving ``tenant``; ``step``
      is the migration checkpoint to restore its shard from (None for
      a fresh tenant).
    - ``on_drop(tenant, step)`` — stop serving ``tenant``: drain its
      in-flight work, save its shard as checkpoint ``step``, release
      its resources.
    - ``on_request(src, msg)`` — serve one routed request (runs on the
      dedicated request thread, so a blocking admission park never
      delays the control plane).

    ``backlog_fn()`` feeds the heartbeat (queued + in-flight requests
    on this rank). AM handlers only enqueue; the service thread does
    the blocking work — a drain mid-checkpoint cannot stall the comm
    thread."""

    def __init__(self, ctx, controller_rank: int = 0,
                 on_adopt: Optional[Callable] = None,
                 on_drop: Optional[Callable] = None,
                 on_request: Optional[Callable] = None,
                 backlog_fn: Optional[Callable[[], float]] = None):
        self.ctx = ctx
        self.comm = ctx.comm
        self.controller_rank = controller_rank
        self.on_adopt = on_adopt
        self.on_drop = on_drop
        self.on_request = on_request
        self.backlog_fn = backlog_fn
        self.tenants: List[str] = []
        self._ops: "queue.Queue[Tuple[int, Dict]]" = queue.Queue()
        self._reqs: "queue.Queue[Tuple[int, Dict]]" = queue.Queue()
        self.drained = threading.Event()
        self._stop = threading.Event()
        self.channel = _ElasticChannel(self.comm)
        for op in ("adopt_tenant", "drop_tenant", "drain", "shutdown"):
            self.channel.on(op, self._enqueue_op)
        self.channel.on("req", self._enqueue_req)
        self.channel.on("abandon_join", self._on_abandon_join)
        self.channel.on("allow_join", self._on_allow_join)
        self._svc = threading.Thread(target=self._service_main,
                                     name="parsec-elastic-worker",
                                     daemon=True)
        self._req_thread = threading.Thread(
            target=self._request_main, name="parsec-elastic-req",
            daemon=True)
        self._svc.start()
        self._req_thread.start()

    # ---------------------------------------------------------- plumbing
    def _enqueue_op(self, src: int, msg: Dict) -> None:
        self._ops.put((src, msg))

    def _enqueue_req(self, src: int, msg: Dict) -> None:
        self._reqs.put((src, msg))

    def _on_abandon_join(self, src: int, msg: Dict) -> None:
        # comm-thread handler: a set add is GIL-atomic, no enqueue
        # needed — the controller abandoned a stalled joiner and every
        # peer must deny its late arrival
        if hasattr(self.comm, "abandon_join"):
            self.comm.abandon_join(msg["rank"])

    def _on_allow_join(self, src: int, msg: Dict) -> None:
        # the controller is reusing a previously-abandoned slot for a
        # FRESH spawn: re-arm it here too (set discard, GIL-atomic)
        if hasattr(self.comm, "allow_join"):
            self.comm.allow_join(msg["rank"])

    def _ack(self, src: int, msg: Dict, **kw) -> None:
        token = msg.get("token")
        if token is not None:
            self.channel.send(src, "ack", token=token, **kw)

    def send_controller(self, op: str, **kw) -> None:
        self.channel.send(self.controller_rank, op, **kw)

    # ------------------------------------------------------------ threads
    def _request_main(self) -> None:
        while not self._stop.is_set():
            try:
                src, msg = self._reqs.get(timeout=0.1)
            except queue.Empty:
                continue
            if self.on_request is None:
                continue
            try:
                self.on_request(src, msg)
            except Exception as exc:  # noqa: BLE001 — keep serving
                warning("elastic", "request handler raised: %s", exc)

    def _service_main(self) -> None:
        poll = float(mca_param.get("serving.autoscale_poll_s", 0.25))
        last_hb = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last_hb >= poll:
                last_hb = now
                backlog = 0.0
                if self.backlog_fn is not None:
                    try:
                        backlog = float(self.backlog_fn())
                    except Exception:  # noqa: BLE001 — heartbeat only
                        pass
                try:
                    self.send_controller("stats", rank=self.comm.rank,
                                         backlog=backlog,
                                         tenants=list(self.tenants))
                except Exception:  # noqa: BLE001 — mesh tearing down
                    pass
            try:
                src, msg = self._ops.get(timeout=poll)
            except queue.Empty:
                continue
            op = msg.get("op")
            try:
                if op == "adopt_tenant":
                    t = msg["tenant"]
                    if self.on_adopt is not None:
                        self.on_adopt(t, msg.get("step"))
                    if t not in self.tenants:
                        self.tenants.append(t)
                    self._ack(src, msg)
                elif op == "drop_tenant":
                    t = msg["tenant"]
                    step = msg.get("step")
                    if self.on_drop is not None:
                        step = self.on_drop(t, step)
                    if t in self.tenants:
                        self.tenants.remove(t)
                    self._ack(src, msg, step=step)
                elif op == "drain":
                    # quiesce → checkpoint-cut → leave: leftover
                    # tenants (normally migrated off already) are
                    # dropped through the same checkpoint vehicle so
                    # nothing is lost even on a direct drain (they all
                    # share the drain's step — one step dir holds one
                    # file per collection)
                    steps = {}
                    for t in list(self.tenants):
                        if self.on_drop is not None:
                            steps[t] = self.on_drop(t, msg.get("step"))
                        self.tenants.remove(t)
                    self._ack(src, msg, steps=steps)
                    self.drained.set()
                elif op == "shutdown":
                    self._ack(src, msg)
                    self.drained.set()
            except Exception as exc:  # noqa: BLE001 — ack the failure
                warning("elastic", "worker op %r raised: %s", op, exc)
                import traceback
                traceback.print_exc()
                self._ack(src, msg, error=str(exc)[:200])

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until this rank was told to drain/shutdown. The
        caller then finalizes its context — the engine's orderly BYE
        is what moves this rank to DEPARTED on every peer."""
        return self.drained.wait(timeout)

    def stop(self) -> None:
        self._stop.set()
        self._svc.join(timeout=5.0)
        self._req_thread.join(timeout=5.0)
