"""Profiling, instrumentation and DAG capture.

Reference systems (SURVEY §2.7/§2.13):
- PINS callback chains on runtime events (parsec/mca/pins/pins.h:26-53).
- Binary trace with a dictionary of paired begin/end keys (profiling.c),
  converted offline to pandas tables — here :mod:`trace` records events
  in-memory and exports to pandas/JSON directly.
- DOT grapher of the executed DAG (parsec_prof_grapher.c).
"""

from . import pins
from .pins import PinsManager, PinsEvent
from . import pins_modules
from .pins_modules import TaskProfiler, PrintSteals, Alperf, \
    Counters, IteratorsChecker, StragglerWatchdog, new_module, \
    install_selected
from . import metrics
from .metrics import MetricsRegistry, registry as metrics_registry
from . import spans
from .trace import Trace
from .grapher import Grapher
from .ptg_to_dtd import replay_ptg_through_dtd
from .dictionary import PropertiesDictionary, install_runtime_properties
from .sde import SDERegistry, global_registry, install_runtime_counters
from .sim import SimReport, simulate
