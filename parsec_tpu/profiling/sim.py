"""Simulation mode: critical-path dating of a PTG taskpool.

Reference: the ``PARSEC_SIM`` build option (CMakeLists.txt:203) dates
every task with ``sim_exec_date`` — the earliest completion time given
its predecessors' dates plus a per-class ``sim_cost_fct``
(parsec_internal.h:407-409, 511-513) — yielding the DAG's critical path
without executing bodies.

Here the dating runs analytically over the closed-form PTG structure:
``simulate`` walks the task space in topological order and computes
``date(t) = max(date(pred)) + cost(t)``. Costs come from, in order:
an explicit ``cost`` callable ``(task_class, locals) -> float``, the
class's ``time_estimate`` (reference sim_cost_fct slot), or 1.0.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ..core.task import Task
from ..core.taskpool import DataRef
from ..dsl import ptg as ptg_mod


class SimReport:
    """Critical-path dating result."""

    def __init__(self, dates: Dict, length: float, n_tasks: int,
                 total_work: float = 0.0):
        self.dates = dates          # (class_name, locals) -> completion date
        self.critical_path = length
        self.n_tasks = n_tasks
        self._total_work = total_work

    def date_of(self, class_name: str, locals: Tuple[int, ...]) -> float:
        return self.dates[(class_name, tuple(locals))]

    def parallelism(self) -> float:
        """Average parallelism = total work / critical path."""
        return self._total_work / self.critical_path \
            if self.critical_path else 0.0


def simulate(tp: ptg_mod.Taskpool,
             cost: Optional[Callable] = None) -> SimReport:
    """Date every task of ``tp`` and return the critical-path report."""
    from .ptg_to_dtd import topo_order   # dataflow+WAR order reusable here

    def cost_of(tc, locals) -> float:
        if cost is not None:
            return float(cost(tc, locals))
        if tc.time_estimate is not None:
            probe = Task(tp, tc, locals)
            return float(tc.time_estimate(probe))
        return 1.0

    dates: Dict[Tuple[str, Tuple], float] = {}
    ready_at: Dict[Tuple[str, Tuple], float] = {}
    total_work = 0.0
    for tc, p in topo_order(tp):
        key = (tc.name, tuple(p))
        c = cost_of(tc, p)
        total_work += c
        start = ready_at.get(key, 0.0)
        done = start + c
        dates[key] = done
        probe = Task(tp, tc, p)
        for f in tc.flows:
            probe.data[f.name] = 0
            probe.output[f.name] = 0
        for ref in tc.iterate_successors(probe):
            if isinstance(ref, DataRef):
                continue
            skey = (ref.task_class.name, tuple(ref.locals))
            ready_at[skey] = max(ready_at.get(skey, 0.0), done)
    return SimReport(dates, max(dates.values(), default=0.0), len(dates),
                     total_work=total_work)
