"""Software-defined-event counters (reference parsec/papi_sde.c, 264 LoC
+ the per-scheduler pending-task gauges each sched component registers).

The reference exports runtime counters through PAPI-SDE so external PAPI
consumers can read them. Here :class:`SDERegistry` holds *counters*
(monotonic, incremented by the runtime) and *gauges* (sampled provider
functions); ``read()`` returns the merged live view. A process-global
registry mirrors PAPI-SDE's global handle; contexts register their
standard gauges at :func:`install_runtime_counters`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict


class SDERegistry:
    def __init__(self) -> None:
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()

    # -- counters (monotonic, runtime-incremented) ------------------------
    def register_counter(self, name: str, initial: float = 0) -> None:
        with self._lock:
            self._counters.setdefault(name, initial)

    def add(self, name: str, delta: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + delta

    # -- gauges (sampled) -------------------------------------------------
    def register_gauge(self, name: str, provider: Callable[[], Any]) -> None:
        with self._lock:
            self._gauges[name] = provider

    def unregister(self, name: str) -> None:
        with self._lock:
            self._counters.pop(name, None)
            self._gauges.pop(name, None)

    # -- reads ------------------------------------------------------------
    def read(self, name: str) -> Any:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            provider = self._gauges.get(name)
        if provider is None:
            raise KeyError(name)
        return provider()

    def read_all(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self._counters)
            gauges = dict(self._gauges)
        for name, provider in gauges.items():
            try:
                out[name] = provider()
            except Exception as exc:
                out[name] = f"<error: {exc}>"
        return out


_global = SDERegistry()


def global_registry() -> SDERegistry:
    return _global


def install_runtime_counters(context,
                             registry: SDERegistry = None) -> SDERegistry:
    """Register the standard gauges the reference's components export:
    scheduler queue depth (SCHEDULER::PENDING_TASKS in the reference's
    sched components), per-stream exec counts, comm message counters."""
    reg = registry or _global
    prefix = f"parsec::rank{context.my_rank}"
    reg.register_gauge(f"{prefix}::SCHEDULER::PENDING_TASKS",
                       lambda: context.scheduler.pending_tasks())
    reg.register_gauge(f"{prefix}::TASKS_EXECUTED",
                       lambda: sum(es.stats["executed"]
                                   for es in context.streams) +
                       context.stats.get("device_completed", 0))
    reg.register_gauge(f"{prefix}::TASKS_STOLEN",
                       lambda: sum(es.stats["stolen"]
                                   for es in context.streams))
    if context.comm is not None:
        reg.register_gauge(f"{prefix}::COMM::ACTIVATIONS_SENT",
                           lambda: context.comm.stats["activations_sent"])
        reg.register_gauge(f"{prefix}::COMM::BYTES_SENT",
                           lambda: context.comm.stats["bytes_sent"])
    return reg
