"""Always-on metrics plane: counters, gauges, log₂ histograms.

Reference role: parsec/mca/pins + the SDE software counters expose
runtime state, but only as offline traces or pull-by-hand dicts
(PINS: Danalis et al., VPA/SC 2014). A serving runtime needs the same
signals LIVE and cheap enough to leave enabled, so this module is a
small Prometheus-style registry:

- **Counters** shard per recording thread (one plain dict slot per
  thread — no locks, no CAS on the hot path; the GIL makes the
  single-writer-per-shard increment safe) and aggregate at read time.
- **Gauges** are either set directly or computed at scrape time by
  registered *collectors* (closures reading live runtime state:
  scheduler queue depth, wfq ``pool_stats``, tenant windows, HBM
  residency, compile-cache hits). Nothing is paid until someone
  scrapes.
- **Histograms** bucket by log₂ (one ``math.frexp`` per observation) —
  the per-tenant request-latency distribution ships as a standard
  Prometheus histogram.

Export: :func:`to_prometheus_text` (text exposition format 0.0.4) and
:func:`to_dict` (JSON), both served by the optional stdlib HTTP
listener (``serving.metrics_port``: ``/metrics`` + ``/statusz``) and by
``Context.statusz()``.

The registry is process-global (like the Prometheus client default
registry): comm engines, contexts, and serving runtimes all register
into ONE export surface instead of keeping parallel ad-hoc dicts.
``profiling.metrics = 0`` disables the runtime's hot-path increments
and collectors — the A/B switch the observability bench measures the
always-on cost with; the registry object itself always exists.
"""

from __future__ import annotations

import itertools
import json
import math
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import mca_param

mca_param.register("profiling.metrics", 1,
                   help="always-on metrics plane: hot-path counters + "
                        "scrape-time collectors (0 = off; the A/B "
                        "baseline of bench.py --section observability)")
mca_param.register("serving.metrics_port", 0,
                   help="serve /metrics (Prometheus text) and /statusz "
                        "(JSON) on this localhost port via a stdlib "
                        "HTTP listener (0 = off)")


def enabled() -> bool:
    return str(mca_param.get("profiling.metrics", 1)).lower() not in (
        "0", "off", "false")


def _label_key(labelnames: Tuple[str, ...], kv: Dict[str, Any]) -> Tuple:
    try:
        return tuple(str(kv[n]) for n in labelnames)
    except KeyError as exc:
        raise ValueError(
            f"metric labels {labelnames} require {exc.args[0]!r}") from exc


class _Counter:
    """One labeled counter child: per-thread shards, summed at read."""

    __slots__ = ("_shards",)

    def __init__(self) -> None:
        self._shards: Dict[int, float] = {}

    def inc(self, n: float = 1.0) -> None:
        # single writer per shard key (the recording thread), so the
        # read-modify-write below cannot interleave with another
        # writer; readers only ever sum a snapshot
        s = self._shards
        tid = threading.get_ident()
        s[tid] = s.get(tid, 0.0) + n

    def value(self) -> float:
        return sum(self._shards.values())


class _Gauge:
    """One labeled gauge child: last-set value or a callable source."""

    __slots__ = ("_value", "_fn")

    def __init__(self) -> None:
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v: float) -> None:
        self._value = float(v)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a scrape must not raise
                return float("nan")
        return self._value


class _Histogram:
    """One labeled log₂-bucket histogram child (per-thread shards).

    Bucket *i* counts observations with ``value <= 2**i`` (and above the
    next-lower power of two); the exposition renders the standard
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``."""

    __slots__ = ("_shards",)

    def __init__(self) -> None:
        # tid -> [bucket-counts dict, sum, count]
        self._shards: Dict[int, List] = {}

    def observe(self, v: float) -> None:
        v = float(v)
        if v <= 0.0:
            exp = -64                      # underflow bucket
        else:
            m, exp = math.frexp(v)         # v = m * 2**exp, 0.5 <= m < 1
            if m == 0.5:                   # exact power of two: le=2**(exp-1)
                exp -= 1
        s = self._shards
        tid = threading.get_ident()
        shard = s.get(tid)
        if shard is None:
            shard = s[tid] = [{}, 0.0, 0]
        b = shard[0]
        b[exp] = b.get(exp, 0) + 1
        shard[1] += v
        shard[2] += 1

    def snapshot(self) -> Tuple[Dict[int, int], float, int]:
        buckets: Dict[int, int] = {}
        total, count = 0.0, 0
        for b, s, c in list(self._shards.values()):
            # list(items) snapshots the bucket dict (GIL-atomic): a
            # concurrent observe() may insert a NEW log2 bucket while a
            # scrape iterates — live iteration would raise "dictionary
            # changed size during iteration" out of the HTTP handler
            for exp, n in list(b.items()):
                buckets[exp] = buckets.get(exp, 0) + n
            total += s
            count += c
        return buckets, total, count


class _Family:
    """A named metric family holding one child per label-value tuple."""

    def __init__(self, name: str, help_: str, kind: str,
                 labelnames: Tuple[str, ...], child_cls):
        self.name = name
        self.help = help_
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self._child_cls = child_cls
        self._children: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()

    def labels(self, **kv):
        key = _label_key(self.labelnames, kv)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = self._child_cls()
        return child

    def clear(self) -> None:
        """Drop every child."""
        with self._lock:
            self._children.clear()

    def remove(self, **kv) -> None:
        """Unexport one child (a caller-held reference keeps working —
        removal only stops the registry from exporting it). Collectors
        prune dead pools/tenants with this so a persistent serving
        Context's registry stays bounded."""
        self.remove_key(_label_key(self.labelnames, kv))

    def remove_key(self, key: Tuple) -> None:
        with self._lock:
            self._children.pop(key, None)

    def samples(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]


class MetricsRegistry:
    """Process-global metric registry (Prometheus-client shaped)."""

    def __init__(self) -> None:
        self._families: Dict[str, _Family] = {}
        self._lock = threading.Lock()
        self._collectors: List[Callable[[], None]] = []
        self.collector_errors = 0

    # ------------------------------------------------------- registration
    def _family(self, name: str, help_: str, kind: str,
                labelnames: Tuple[str, ...], child_cls) -> _Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind or fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} re-registered as {kind}"
                    f"{labelnames} but exists as {fam.kind}"
                    f"{fam.labelnames}")
            return fam
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(
                    name, help_, kind, labelnames, child_cls)
            return fam

    def counter(self, name: str, help_: str = "",
                labelnames: Tuple[str, ...] = ()) -> _Family:
        return self._family(name, help_, "counter", labelnames, _Counter)

    def gauge(self, name: str, help_: str = "",
              labelnames: Tuple[str, ...] = ()) -> _Family:
        return self._family(name, help_, "gauge", labelnames, _Gauge)

    def histogram(self, name: str, help_: str = "",
                  labelnames: Tuple[str, ...] = ()) -> _Family:
        return self._family(name, help_, "histogram", labelnames,
                            _Histogram)

    def prune_ranks(self, gone_ranks, label: str = "rank") -> int:
        """Unexport every child whose ``label`` value names a rank in
        ``gone_ranks`` — the elastic-capacity pruning pass: when the
        live set shrinks (a rank drained or died), its rank-labeled
        children (wire counters of a loopback fabric, per-rank capacity
        gauges, pool/tenant rows of a departed rank) must not linger in
        ``/metrics`` forever. Caller-held references keep working
        (``_Family.remove`` semantics). Returns the number of children
        pruned; a rank re-admitted later simply re-creates its children
        on the next record/scrape."""
        gone = {str(int(r)) for r in gone_ranks}
        if not gone:
            return 0
        n = 0
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            if label not in fam.labelnames:
                continue
            idx = fam.labelnames.index(label)
            for labels, _child in fam.samples():
                key = tuple(labels[name] for name in fam.labelnames)
                if key[idx] in gone:
                    fam.remove_key(key)
                    n += 1
        return n

    def register_collector(self, fn: Callable[[], None]) -> None:
        """``fn`` runs at every scrape and sets gauges from live state."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    # ------------------------------------------------------------- export
    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — one bad collector must not
                self.collector_errors += 1     # sink the whole scrape

    @staticmethod
    def _esc(v: str) -> str:
        return str(v).replace("\\", r"\\").replace('"', r'\"') \
            .replace("\n", r"\n")

    @classmethod
    def _labelstr(cls, labels: Dict[str, str],
                  extra: Tuple[Tuple[str, str], ...] = ()) -> str:
        parts = [f'{k}="{cls._esc(v)}"' for k, v in labels.items()]
        parts += [f'{k}="{cls._esc(v)}"' for k, v in extra]
        return "{" + ",".join(parts) + "}" if parts else ""

    def to_prometheus_text(self) -> str:
        """Text exposition format 0.0.4 (the /metrics payload)."""
        self._run_collectors()
        out: List[str] = []
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            out.append(f"# HELP {fam.name} {fam.help}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for labels, child in fam.samples():
                ls = self._labelstr(labels)
                if fam.kind == "histogram":
                    buckets, total, count = child.snapshot()
                    cum = 0
                    for exp in sorted(buckets):
                        cum += buckets[exp]
                        le = self._labelstr(
                            labels, (("le", repr(float(2.0 ** exp))),))
                        out.append(f"{fam.name}_bucket{le} {cum}")
                    inf = self._labelstr(labels, (("le", "+Inf"),))
                    out.append(f"{fam.name}_bucket{inf} {count}")
                    out.append(f"{fam.name}_sum{ls} {total}")
                    out.append(f"{fam.name}_count{ls} {count}")
                else:
                    out.append(f"{fam.name}{ls} {child.value()}")
        return "\n".join(out) + "\n"

    def to_dict(self) -> Dict[str, Any]:
        """JSON view of every family (the /statusz metrics block)."""
        self._run_collectors()
        out: Dict[str, Any] = {}
        with self._lock:
            families = list(self._families.values())
        for fam in families:
            rows = []
            for labels, child in fam.samples():
                if fam.kind == "histogram":
                    buckets, total, count = child.snapshot()
                    rows.append({"labels": labels, "count": count,
                                 "sum": total,
                                 "buckets": {repr(float(2.0 ** e)): n
                                             for e, n in
                                             sorted(buckets.items())}})
                else:
                    rows.append({"labels": labels,
                                 "value": child.value()})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": rows}
        return out


_REGISTRY = MetricsRegistry()
_ENGINE_IDS = itertools.count(1)


def registry() -> MetricsRegistry:
    """The process-global registry every runtime layer exports into."""
    return _REGISTRY


def next_engine_id() -> int:
    """Unique per-process comm-engine id (the ``engine`` label that
    keeps two in-process loopback engines' wire counters separable)."""
    return next(_ENGINE_IDS)


# ---------------------------------------------------------------------------
# Context collectors: live runtime state -> gauges at scrape time
# ---------------------------------------------------------------------------

def install_context_collectors(context) -> Callable[[], None]:
    """Register one scrape-time collector for ``context`` (weakly held)
    covering scheduler depth/steal rates, wfq ``pool_stats``, tenant
    admission windows, HBM residency/evictions, and compile-cache hits.
    Returns the uninstall closure (called from ``Context.fini``).

    Bounded by construction: every gauge child this collector sets is
    tracked, children for pools/tenants that disappeared are pruned at
    the next scrape, and the uninstall closure removes them all — a
    persistent serving Context minting one pool per request cannot grow
    the registry without bound."""
    import weakref
    reg = registry()
    ref = weakref.ref(context)
    rank = str(context.my_rank)
    owned: Dict[Any, set] = {}        # family -> label keys set by us

    g_done = reg.gauge("parsec_tasks_completed_total",
                       "tasks completed by the host runtime (sum of "
                       "the per-stream executed counters + device "
                       "completions + native-DTD engine completions; "
                       "computed at scrape time — the hot path pays "
                       "nothing)", ("rank",))
    g_native = reg.gauge("parsec_native_dtd",
                         "native DTD engine counters (inserted/"
                         "ready_pushed/stolen/released_edges/"
                         "completed_native/completed_python/"
                         "ring_highwater/inflight/ready plus the "
                         "observability-plane rows obs_recorded/"
                         "obs_dropped/obs_ring_depth of the in-engine "
                         "event rings, read from the engine's C++ "
                         "atomics at scrape time)",
                         ("rank", "key"))
    g_ready = reg.gauge("parsec_sched_ready_tasks",
                        "tasks queued in the scheduler", ("rank",))
    g_pools = reg.gauge("parsec_active_taskpools",
                        "live taskpools in the context", ("rank",))
    g_stream = reg.gauge("parsec_stream_events",
                         "per-context stream totals (selected/stolen/"
                         "starved/executed)", ("rank", "event"))
    g_pool = reg.gauge("parsec_pool_tasks",
                       "wfq per-pool service accounting "
                       "(enqueued/selected/pending)",
                       ("rank", "pool", "tenant", "event"))
    g_tenant = reg.gauge("parsec_tenant_state",
                         "serving tenant admission state (inflight/"
                         "window/hbm_reserved/quarantined and the "
                         "runtime stats rows)", ("rank", "tenant", "key"))
    g_hbm = reg.gauge("parsec_hbm_stats",
                      "HBM residency manager counters "
                      "(resident_tiles/stage_in/spills/bytes_staged/"
                      "bytes_spilled/peak_bytes/evict_belady/evict_lru)",
                      ("rank", "key"))
    g_cc = reg.gauge("parsec_compile_cache",
                     "compile-cache hit/miss counters "
                     "(utils.compile_cache.cache_stats)", ("key",))
    g_cap = reg.gauge("parsec_capacity",
                      "elastic-capacity state (configured/world/live/"
                      "departed/dead rank counts from the comm "
                      "engine's world_status, plus the autoscaler's "
                      "desired count when a controller is attached)",
                      ("rank", "key"))
    g_kv_pages = reg.gauge("parsec_kv_pages_in_use",
                           "KV state layer: pages currently allocated "
                           "(prefix cache + live requests + draft "
                           "branches) — the autoscaler's KV-pressure "
                           "signal", ("rank",))
    g_kv_hit = reg.gauge("parsec_kv_hit_rate",
                         "KV prefix-cache hit rate (prompt tokens "
                         "served from cached pages / prompt tokens "
                         "looked up, cumulative)", ("rank",))
    g_kv = reg.gauge("parsec_kv_state",
                     "KV state layer counters (pages_free/capacity/"
                     "cow_copies/evict_reclaims/peak_in_use/exhausted/"
                     "tokens_prefilled/requests/requests_hit/"
                     "spec_windows/spec_accepted_steps/"
                     "spec_rejected_windows/spec_cancelled_branches "
                     "plus the radix-tree nodes/cached_pages/"
                     "evicted_* rows), read at scrape time",
                     ("rank", "key"))

    pruned_ranks: set = set()         # gone ranks already swept

    def _prune() -> None:
        for fam, keys in owned.items():
            for key in keys:
                fam.remove_key(key)
        owned.clear()

    def collect() -> None:
        ctx = ref()
        if ctx is None:
            reg.unregister_collector(collect)
            _prune()
            return
        seen: Dict[Any, set] = {}

        def setg(fam, value, **labels) -> None:
            key = _label_key(fam.labelnames, labels)
            fam.labels(**labels).set(value)
            seen.setdefault(fam, set()).add(key)

        setg(g_ready, ctx.scheduler.pending_tasks(), rank=rank)
        with ctx._lock:
            setg(g_pools, len(ctx._active_taskpools), rank=rank)
        agg = {"selected": 0, "stolen": 0, "starved": 0, "executed": 0}
        for es in ctx.streams:
            for k in agg:
                agg[k] += es.stats.get(k, 0)
        for k, v in agg.items():
            setg(g_stream, v, rank=rank, event=k)
        # native DTD engines complete tasks outside the stream counters
        # (the whole point of the native loop) — fold them in so the
        # completed-total stays correct whichever engine ran the pool
        nstats = ctx.native_dtd_stats()
        for k, v in nstats.items():
            setg(g_native, v, rank=rank, key=k)
        setg(g_done, agg["executed"] +
             ctx.stats.get("device_completed", 0) +
             nstats.get("completed_native", 0) +
             nstats.get("completed_python", 0), rank=rank)
        sched = ctx.scheduler
        if hasattr(sched, "pool_stats"):
            for pool, row in sched.pool_stats().items():
                ten = row.get("tenant") or ""
                for k in ("enqueued", "selected", "pending"):
                    setg(g_pool, row[k], rank=rank, pool=pool,
                         tenant=ten, event=k)
        srv = ctx.serving
        if srv is not None:
            for name, ten in srv.tenants().items():
                rows = {"inflight": ten.inflight, "window": ten.window,
                        "hbm_reserved": ten.hbm_reserved,
                        "quarantined": 1 if ten.quarantined else 0,
                        **ten.stats}
                for k, v in rows.items():
                    setg(g_tenant, v, rank=rank, tenant=name, key=k)
        # native-engine completions per tenant (ISSUE 13): native pools
        # bypass the per-task tenant hooks — the engine atomics carry
        # the truth, folded here at scrape time
        for ten, n in ctx.native_tenant_stats().items():
            if n:
                setg(g_tenant, n, rank=rank, tenant=ten,
                     key="native_tasks")
        kvl = getattr(ctx, "kv_state", None)
        if kvl is not None:
            # scrape-time collectors ONLY (ISSUE 15 contract: the KV
            # hot path pays nothing for observability) — the layer's
            # snapshot is a lock-guarded dict copy
            snap = kvl.snapshot()
            pool_snap = snap.pop("pool", {})
            tree_snap = snap.pop("tree", {})
            setg(g_kv_pages, pool_snap.get("pages_in_use", 0),
                 rank=rank)
            setg(g_kv_hit, snap.get("hit_rate", 0.0), rank=rank)
            for k in ("pages_free", "capacity", "cow_copies",
                      "evict_reclaims", "peak_in_use", "exhausted"):
                setg(g_kv, pool_snap.get(k, 0), rank=rank, key=k)
            for k in ("nodes", "cached_pages", "evicted_nodes",
                      "evicted_pages"):
                setg(g_kv, tree_snap.get(k, 0), rank=rank,
                     key=f"tree_{k}")
            for k in ("tokens_prefilled", "requests", "requests_hit",
                      "spec_windows", "spec_accepted_steps",
                      "spec_rejected_windows",
                      "spec_cancelled_branches"):
                setg(g_kv, snap.get(k, 0), rank=rank, key=k)
        hbm = ctx.hbm
        if hbm is not None:
            with hbm._lock:
                resident = sum(1 for e in hbm._entries.values()
                               if e.get("offset") is not None)
                stats = dict(hbm.stats)
            setg(g_hbm, resident, rank=rank, key="resident_tiles")
            for k, v in stats.items():
                setg(g_hbm, v, rank=rank, key=k)
        try:
            from ..utils import compile_cache
            for k, v in compile_cache.cache_stats().items():
                setg(g_cc, v, key=k)
        except Exception:  # noqa: BLE001 — optional surface
            pass
        comm = ctx.comm
        if comm is not None and hasattr(comm, "world_status"):
            ws = comm.world_status()
            for k in ("configured", "world"):
                setg(g_cap, ws.get(k, 0), rank=rank, key=k)
            for k in ("live", "departed", "dead"):
                setg(g_cap, len(ws.get(k) or ()), rank=rank, key=k)
            el = getattr(srv, "elastic", None) if srv is not None \
                else None
            if el is not None:
                setg(g_cap, el.desired, rank=rank, key="desired")
            # elastic-capacity pruning (the live set shrank): children
            # labeled with a drained/dead rank — wire counters of an
            # in-process loopback fabric, stale pool/tenant rows, a
            # departed rank's capacity gauges — must not linger in
            # /metrics forever. Own-rank children are never pruned,
            # and each gone rank is swept ONCE (the scrape after the
            # shrink), not re-scanned on every later scrape of a
            # long-lived context; a re-admitted rank drops out of the
            # swept set so a later departure prunes it again.
            gone = (set(ws.get("departed") or ()) |
                    set(ws.get("dead") or ())) - {ctx.my_rank}
            pruned_ranks.intersection_update(gone)
            fresh = gone - pruned_ranks
            if fresh:
                reg.prune_ranks(fresh)
                pruned_ranks.update(fresh)
        # prune children for pools/tenants that disappeared since the
        # last scrape — the per-request pool gauges would otherwise
        # accumulate one frozen child-set per finished submission
        for fam, keys in list(owned.items()):
            for key in keys - seen.get(fam, set()):
                fam.remove_key(key)
        owned.clear()
        owned.update(seen)

    def uninstall() -> None:
        reg.unregister_collector(collect)
        _prune()

    reg.register_collector(collect)
    return uninstall


# ---------------------------------------------------------------------------
# HTTP listener (serving.metrics_port): /metrics + /statusz
# ---------------------------------------------------------------------------

class MetricsServer:
    """Stdlib HTTP listener serving the registry (daemon thread)."""

    def __init__(self, port: int, statusz_fn: Optional[Callable] = None,
                 host: str = "127.0.0.1"):
        import http.server

        reg = registry()
        statusz = statusz_fn or (lambda: {"metrics": reg.to_dict()})

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                if self.path.startswith("/metrics"):
                    body = reg.to_prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.startswith("/statusz"):
                    try:
                        body = json.dumps(statusz()).encode()
                    except Exception as exc:  # noqa: BLE001
                        body = json.dumps(
                            {"error": str(exc)[:200]}).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # noqa: D102 — silence stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="parsec-metrics-http",
                                        daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)


def serve_http(port: int, statusz_fn: Optional[Callable] = None
               ) -> MetricsServer:
    """Start the /metrics + /statusz listener on ``port`` (0 = pick a
    free port; read it back from ``server.port``)."""
    return MetricsServer(port, statusz_fn)
