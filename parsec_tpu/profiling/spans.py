"""Request-scoped distributed tracing: span context + reconstruction.

Dapper-shaped (Sigelman et al., Google TR 2010) over the existing
:class:`~parsec_tpu.profiling.trace.Trace` event stream: every serving
``Submission`` mints a trace id (*rid*), and the runtime records
causally-parented spans as ordinary trace events whose ``info`` carries
``{rid, span, parent}``:

- ``req``        — the submission root (serving/runtime.py, begin at
                   submit, end at pool termination);
- ``admission``  — a backpressure park in the tenant window (recorded
                   only when the insert actually waited);
- ``task``       — one task execution; the begin event also carries
                   ``q_us`` (ready→select queue wait) so the queue
                   share costs no extra event;
- ``wire``       — one tree-edge/wire hop: the SENDER records phase
                   ``sent`` (minting the hop's span id, parented to the
                   sending task), every receiver records ``recv`` with
                   the same span id; tasks released by the payload are
                   parented to the hop.

Span ids are INTEGERS — ``(rank << 44) | n`` with a per-process
monotonic counter — so ids from different ranks never collide and the
merged multi-rank tree needs no coordination; the mint is one shift+or
(it runs once per task on the null-task hot path, where a formatted
string measurably moved the obs_overhead_pct guard). The only
non-integer ids are submission ROOT spans
(``"req:<pool>#root<rank>"`` strings, serving/runtime.py) — the
reconstruction treats ids as opaque keys either way.

Cross-rank timestamp alignment: each rank's dumped trace carries
``meta = {rank, t0, clock_offset_s}`` where ``clock_offset_s`` is the
wire-measured offset of this process's ``perf_counter`` domain to rank
0's (pingpong handshake, ``SocketCommEngine.clock_offset_to``); a span
at local time ``t`` aligns to ``t + t0 + clock_offset_s`` in rank-0's
clock. :func:`align_shift` returns that shift per trace.

Reconstruction (:func:`build_spans`, :func:`critpath`) powers the
``tools critpath`` CLI: the request's span tree, its latency breakdown
(admission / queue / exec / wire), and the critical path walked over
executed dependency edges (the parent links ARE dep edges).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence

_counter = itertools.count(1)
_rid_counter = itertools.count(1)
_lock = threading.Lock()

#: rank field width of an integer span id (ids are ints, not strings:
#: the mint runs once per task on the null-task hot path, where the
#: f-string version measurably moved the obs_overhead_pct guard)
_RANK_SHIFT = 44

#: bit set in every NATIVELY-minted span id (the pdtd event rings mint
#: ids in C++ from their own process-global counter — ISSUE 13): it
#: partitions the sub-rank id space so a native id can never collide
#: with this module's Python counter on the same rank, with zero
#: cross-engine coordination
_NATIVE_BIT = 43


def next_span_id(rank: int = 0) -> int:
    """Mint a process-unique span id; the rank rides the high bits so
    ids from different ranks never collide in a merged trace."""
    return (rank << _RANK_SHIFT) | next(_counter)


def native_span_base(rank: int = 0) -> int:
    """Base ORed into every span id the native pdtd event rings mint
    (``pdtd_obs_enable``): rank in the high bits like
    :func:`next_span_id`, plus the native marker bit so the two mint
    domains stay disjoint within a rank."""
    return (rank << _RANK_SHIFT) | (1 << _NATIVE_BIT)


def mint_rid(name: str) -> str:
    """Deterministic request/trace id for a submission: derived from
    the taskpool NAME (the cross-rank registry identity), so every rank
    of a distributed submission mints the SAME rid without any wire
    exchange — one span tree spans the mesh."""
    return f"req:{name}"


def local_rid(rank: int = 0) -> str:
    """A rank-local rid for untenanted/ad-hoc tracing."""
    with _lock:
        return f"req:r{rank}-{next(_rid_counter)}"


# ---------------------------------------------------------------------------
# reconstruction over dumped traces
# ---------------------------------------------------------------------------

def align_shift(trace: Dict[str, Any]) -> float:
    """Seconds to ADD to a trace's event times to land in the root
    rank's perf_counter domain (0.0 for metadata-less traces — the
    pre-span single-process format stays byte-compatible)."""
    meta = trace.get("meta") or {}
    return float(meta.get("t0", 0.0)) + float(
        meta.get("clock_offset_s", 0.0))


def _rank_of(trace: Dict[str, Any], fallback: int) -> int:
    meta = trace.get("meta") or {}
    return int(meta.get("rank", fallback))


def build_spans(traces: Sequence[Dict[str, Any]],
                rid: Optional[str] = None) -> Dict[str, Dict]:
    """Reconstruct the span graph from dumped rank traces.

    Returns ``{span_id: node}`` with nodes shaped::

        {"kind": "req"|"admission"|"task"|"wire", "rid", "rank",
         "t0", "t1",            # aligned seconds (root-rank clock)
         "parent": span_id|None,
         "name", "q_us",        # task nodes
         "src", "dst", "nbytes",  # wire nodes (per-edge children in
                                   "edges": [{src, dst, t_sent, t_recv}])
        }

    ``rid=None`` keeps every request; pass a rid to filter."""
    nodes: Dict[str, Dict] = {}
    wire_sent: Dict[tuple, Dict] = {}     # (span, dst) -> sent record
    wire_recv: List[Dict] = []
    open_begins: Dict[str, Dict] = {}
    for fallback_rank, tr in enumerate(traces):
        shift = align_shift(tr)
        rank = _rank_of(tr, fallback_rank)
        for ev in tr["events"]:
            info = ev.get("info") or {}
            sid = info.get("span")
            if sid is None or (rid is not None and
                               info.get("rid") != rid):
                continue
            t = ev["t"] + shift
            key, phase = ev["key"], ev["phase"]
            if key == "wire":
                if phase == "sent":
                    wire_sent[(sid, info.get("dst"))] = {
                        "t": t, "rank": rank, "info": info}
                elif phase == "recv":
                    wire_recv.append({"t": t, "rank": rank,
                                      "info": info})
                continue
            if phase == "begin":
                node = nodes.get(sid)
                if node is None:
                    node = nodes[sid] = {
                        "kind": key, "rid": info.get("rid"),
                        "rank": rank, "t0": t, "t1": t,
                        "parent": info.get("parent"),
                        "name": str(ev.get("object") or key)}
                    if "q_us" in info:
                        node["q_us"] = info["q_us"]
                open_begins[sid] = node
            elif phase == "end":
                node = open_begins.pop(sid, None) or nodes.get(sid)
                if node is not None:
                    node["t1"] = max(node["t1"], t)
    # wire hops: one node per span id, one edge per (src, dst) pair;
    # the node's [t0, t1] covers send-of-first-edge .. recv-of-last
    for rec in wire_recv:
        info = rec["info"]
        sid = info["span"]
        sent = wire_sent.get((sid, rec["rank"]))
        t_sent = sent["t"] if sent is not None else rec["t"]
        node = nodes.get(sid)
        if node is None:
            node = nodes[sid] = {
                "kind": "wire", "rid": info.get("rid"),
                "rank": info.get("src", -1), "t0": t_sent,
                "t1": rec["t"], "parent": info.get("parent"),
                "name": f"wire:{sid}", "nbytes": info.get("nbytes", 0),
                "edges": []}
        node["t0"] = min(node["t0"], t_sent)
        node["t1"] = max(node["t1"], rec["t"])
        node.setdefault("edges", []).append(
            {"src": info.get("src"), "dst": rec["rank"],
             "t_sent": t_sent, "t_recv": rec["t"]})
    # a sent hop whose recv trace is missing still shows up (dur 0)
    for (sid, dst), sent in wire_sent.items():
        if sid not in nodes:
            info = sent["info"]
            nodes[sid] = {"kind": "wire", "rid": info.get("rid"),
                          "rank": sent["rank"], "t0": sent["t"],
                          "t1": sent["t"], "parent": info.get("parent"),
                          "name": f"wire:{sid}",
                          "nbytes": info.get("nbytes", 0), "edges": []}
    return nodes


def rids(traces: Sequence[Dict[str, Any]]) -> List[str]:
    """Every rid present in the traces, in first-seen order."""
    seen: List[str] = []
    for tr in traces:
        for ev in tr["events"]:
            r = (ev.get("info") or {}).get("rid")
            if r is not None and r not in seen:
                seen.append(r)
    return seen


def breakdown(nodes: Dict[str, Dict]) -> Dict[str, float]:
    """Latency shares in milliseconds: admission (backpressure parks),
    queue (ready→select waits), exec (task bodies), wire (send→recv
    per hop edge)."""
    out = {"admission_ms": 0.0, "queue_ms": 0.0, "exec_ms": 0.0,
           "wire_ms": 0.0, "spans": len(nodes)}
    for node in nodes.values():
        kind = node["kind"]
        dur_ms = (node["t1"] - node["t0"]) * 1e3
        if kind == "admission":
            out["admission_ms"] += dur_ms
        elif kind == "task":
            out["exec_ms"] += dur_ms
            out["queue_ms"] += node.get("q_us", 0.0) / 1e3
        elif kind == "wire":
            for e in node.get("edges", ()):
                out["wire_ms"] += max(e["t_recv"] - e["t_sent"], 0.0) \
                    * 1e3
    for k in ("admission_ms", "queue_ms", "exec_ms", "wire_ms"):
        out[k] = round(out[k], 4)
    return out


def critpath(traces: Sequence[Dict[str, Any]], rid: str) -> Dict:
    """Reconstruct ``rid``'s span tree and report its latency breakdown
    plus the critical path over executed dep edges: starting from the
    last-finishing task span, walk parent links (task → wire hop →
    producing task → ... → submission root)."""
    nodes = build_spans(traces, rid=rid)
    if not nodes:
        raise ValueError(f"rid {rid!r}: no spans found "
                         f"(have {rids(traces)[:8]})")
    bd = breakdown(nodes)
    tasks = [n for n in nodes.values() if n["kind"] == "task"]
    tail = max(tasks or nodes.values(), key=lambda n: n["t1"])
    t_base = min(n["t0"] for n in nodes.values())
    path: List[Dict] = []
    cur: Optional[Dict] = tail
    seen: set = set()
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        path.append({
            "kind": cur["kind"], "name": cur["name"],
            "rank": cur["rank"],
            "start_ms": round((cur["t0"] - t_base) * 1e3, 4),
            "dur_ms": round((cur["t1"] - cur["t0"]) * 1e3, 4),
            "queue_us": cur.get("q_us")})
        cur = nodes.get(cur.get("parent"))
    path.reverse()
    ranks = sorted({n["rank"] for n in nodes.values()})
    return {
        "rid": rid,
        "ranks": ranks,
        "n_spans": len(nodes),
        "n_tasks": len(tasks),
        "request_ms": round((tail["t1"] - t_base) * 1e3, 4),
        "breakdown": bd,
        "critical_path": path,
        # the root "req" span covers the whole request; only the work
        # spans along the walk sum into the path length
        "critical_path_ms": round(sum(p["dur_ms"] for p in path
                                      if p["kind"] != "req"), 4),
    }


def render_critpath(rep: Dict) -> str:
    """Human-readable critical-path report (the CLI output)."""
    bd = rep["breakdown"]
    lines = [
        f"request {rep['rid']}: {rep['request_ms']:.3f} ms across "
        f"ranks {rep['ranks']} ({rep['n_spans']} spans, "
        f"{rep['n_tasks']} tasks)",
        f"  breakdown: admission {bd['admission_ms']:.3f} ms | "
        f"queue {bd['queue_ms']:.3f} ms | exec {bd['exec_ms']:.3f} ms "
        f"| wire {bd['wire_ms']:.3f} ms",
        f"  critical path ({len(rep['critical_path'])} spans, "
        f"{rep['critical_path_ms']:.3f} ms):",
    ]
    for p in rep["critical_path"]:
        q = f" q={p['queue_us']:.0f}us" if p.get("queue_us") else ""
        lines.append(f"    [{p['kind']:9s}] r{p['rank']} "
                     f"+{p['start_ms']:9.3f} ms  {p['dur_ms']:9.3f} ms"
                     f"{q}  {p['name']}")
    return "\n".join(lines)
