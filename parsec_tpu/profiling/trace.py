"""Event tracing.

Reference: parsec/profiling.c (PBT binary traces — per-stream buffers,
dictionary of paired begin/end keys with typed info payloads,
profiling.h:44-80) + tools/profiling/python/pbt2ptt.pyx (conversion to
pandas HDF5 tables).

Events are recorded in per-recording-thread RING buffers (the
reference's per-execution-stream buffer model: one writer per buffer, so
recording takes no lock — a previous build appended to one global list
under one global lock, which both contended the workers and grew without
bound in a persistent serving Context). Each ring holds at most
``profiling.trace_max_events`` events; when it wraps, the oldest event
is dropped and the per-ring ``dropped`` counter advances — bounded
memory is the contract, and ``Trace.dropped()`` is the honesty counter
(a wrapped serving trace says HOW MANY events it lost, never silently).

Export goes directly to pandas (``to_pandas``) or JSON — the offline
converter collapses into the runtime since the host side is already
Python. Dumped traces carry a ``meta`` block ({rank, t0,
clock_offset_s, dropped}) so the multi-rank merge in
:mod:`~parsec_tpu.profiling.tools` can align ranks onto one clock (the
offset is measured by the comm engine's pingpong handshake at dump
time — see ``SocketCommEngine.clock_offset_to``).

Request-scoped spans (profiling/spans.py) ride the same stream: the
task hooks attach ``{rid, span, parent, q_us}`` info to the begin/end
events of tasks whose taskpool carries a ``trace_rid``.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional

from .pins import PinsEvent
from ..utils import mca_param

mca_param.register("profiling.trace_max_events", 100000,
                   help="per-recording-thread ring-buffer capacity of "
                        "the trace: a persistent serving Context stays "
                        "bounded; when a ring wraps the oldest events "
                        "are dropped and Trace.dropped() counts them")
mca_param.register("profiling.native_ring_events", 16384,
                   help="per-worker capacity (records) of the NATIVE "
                        "DTD engine's in-engine event rings "
                        "(pdtd_obs_enable — ISSUE 13): rings grow x4 "
                        "up to this cap, then drop-oldest with the "
                        "drop counter advancing (folded into "
                        "Trace.dropped() and the trace meta block)")
mca_param.register("profiling.trace_max_native_sources", 256,
                   help="native ring snapshots a Trace retains (one "
                        "per natively-executed pool): a persistent "
                        "serving context stays bounded — evicted "
                        "snapshots count into Trace.dropped()")


#: first slot of a combined request-span ring record (one entry per
#: rid'd task, expanded into the begin/end event pair at read time)
_SPAN_REC = 0


class _Ring:
    """One recording thread's event ring (single writer, no lock)."""

    __slots__ = ("dq", "dropped")

    def __init__(self, maxlen: int):
        self.dq: deque = deque(maxlen=maxlen)
        self.dropped = 0


class NativeRingAdapter:
    """Scrape-time bridge from ONE native DTD engine's in-engine event
    rings (``pdtd_obs_*`` in _native/core.cpp — ISSUE 13) into this
    trace: ``to_records`` drains the rings at dump/scrape time and
    expands each fixed-stride 48-byte record into the PR 9 trace-record
    shape byte-compatibly (same keys, span parenting via the completion
    dep edges the engine tracked, ``q_us`` from the native ready→select
    stamps), so chrome/critpath/spans/counts work unchanged on
    natively-executed pools. While the pool is live the drain is a
    non-consuming snapshot; at pool retirement :meth:`snapshot` pulls
    the raw arrays ONCE (one memcpy per ring, zero per-event Python
    cost) and releases the engine so its C rings can be freed."""

    def __init__(self, engine) -> None:
        self._lock = threading.Lock()
        self._engine = engine          # dsl.dtd_native.NativeDTD while live
        self.tp = engine.tp            # rid/root_span read late-bound
        self.pool_name = engine.tp.name
        self.class_names = engine.class_names   # shared, insert-grown
        self.offset_s = engine.obs_offset_s
        self._frozen: Optional[List] = None
        self._frozen_dropped = 0

    def _arrays(self) -> List:
        with self._lock:
            if self._frozen is not None:
                return self._frozen
            eng = self._engine
        return eng.obs_drain() if eng is not None else []

    def dropped(self) -> int:
        """Records lost to native ring wraps (the honesty counter)."""
        with self._lock:
            if self._frozen is not None:
                return self._frozen_dropped
            eng = self._engine
        return eng.obs_dropped() if eng is not None else 0

    def event_count(self) -> int:
        return sum(len(a) for a in self._arrays())

    def raw_arrays(self) -> List:
        """The structured record arrays themselves (ring-fed consumers
        like the straggler watchdog's native path)."""
        return self._arrays()

    def snapshot(self) -> None:
        """Freeze at pool retirement: drain the rings into owned arrays
        and drop the engine reference (idempotent)."""
        with self._lock:
            if self._frozen is not None:
                return
            eng = self._engine
            self._engine = None
            if eng is None:
                self._frozen = []
                return
            self._frozen = eng.obs_drain()
            self._frozen_dropped = eng.obs_dropped()

    def to_records(self, t0: float) -> List[Dict[str, Any]]:
        """Expand the binary records into PR 9-format event dicts with
        times relative to the owning trace's ``t0``."""
        from .. import _native
        arrays = self._arrays()
        if not arrays:
            return []
        tp = self.tp
        rid = getattr(tp, "trace_rid", None)
        root = getattr(tp, "root_span", None)
        names = self.class_names
        shift = self.offset_s - t0
        nonep = _native.OBS_PARENT_NONE
        span_of: Dict[int, int] = {}
        for a in arrays:
            for s, sp in zip(a["seq"].tolist(), a["span"].tolist()):
                span_of[s] = sp
        events: List[Dict[str, Any]] = []
        for a in arrays:
            t0s = (a["t0_ns"] * 1e-9 + shift).tolist()
            t1s = (a["t1_ns"] * 1e-9 + shift).tolist()
            qs = a["q_ns"].tolist()
            sps = a["span"].tolist()
            sqs = a["seq"].tolist()
            pss = a["parent_seq"].tolist()
            cls = a["cls"].tolist()
            wks = a["worker"].tolist()
            for i, seq in enumerate(sqs):
                sid = sps[i]
                name = names[cls[i]] if cls[i] < len(names) else "dtd_task"
                if rid is None:
                    # profiler shape (no request context): the classic
                    # begin/end pair, keyed by the unique span id
                    events.append({"key": "task", "phase": "begin",
                                   "t": t0s[i], "stream": wks[i],
                                   "object": sid, "info": {}})
                    events.append({"key": "task", "phase": "end",
                                   "t": t1s[i], "stream": -1,
                                   "object": sid,
                                   "info": {"class": name,
                                            "locals": [seq]}})
                    continue
                ps = pss[i]
                parent = root if ps == nonep else span_of.get(ps, root)
                binfo: Dict[str, Any] = {"rid": rid, "span": sid,
                                         "parent": parent}
                if ps != nonep:
                    binfo["q_us"] = round(qs[i] / 1e3, 1)
                events.append({"key": "task", "phase": "begin",
                               "t": t0s[i], "stream": wks[i],
                               "object": sid, "info": binfo})
                events.append({"key": "task", "phase": "end",
                               "t": t1s[i], "stream": -1, "object": sid,
                               "info": {"class": name, "locals": [seq],
                                        "span": sid, "rid": rid}})
        return events


class Trace:
    """In-memory trace with a key dictionary (parsec_profiling API analog:
    dictionary entries = add_dictionary_keyword, events = trace_flags)."""

    def __init__(self, max_events: Optional[int] = None) -> None:
        self._dict: Dict[str, Dict[str, Any]] = {}
        self._max_events = int(
            max_events if max_events is not None else
            mca_param.get("profiling.trace_max_events", 100000)) or 1
        self._rings: Dict[int, _Ring] = {}     # recording thread -> ring
        self._ring_lock = threading.Lock()     # ring creation only
        # native DTD engines' ring adapters (ISSUE 13): bounded, evicted
        # snapshots fold into dropped() so a truncated capture is loud
        self._native_sources: deque = deque()
        self._native_evicted = 0
        self._max_native = max(1, int(mca_param.get(
            "profiling.trace_max_native_sources", 256)))
        self.t0 = time.perf_counter()
        self.rank = 0
        self._comm = None                      # set by install()
        # hot-path span-id mint, bound once: rank bits | shared counter
        from . import spans as _spans
        self._span_base = 0
        self._span_next = _spans._counter.__next__

    # -- dictionary (profiling.h:44-80 analog) ----------------------------
    def add_keyword(self, name: str, attributes: str = "",
                    info_schema: Optional[Dict[str, str]] = None) -> str:
        self._dict[name] = {"attributes": attributes,
                            "info": info_schema or {}}
        return name

    # -- event recording --------------------------------------------------
    def _ring(self) -> _Ring:
        tid = threading.get_ident()
        ring = self._rings.get(tid)        # GIL-atomic read: hit is free
        if ring is None:
            with self._ring_lock:
                ring = self._rings.get(tid)
                if ring is None:
                    ring = self._rings[tid] = _Ring(self._max_events)
        return ring

    def _append(self, key: str, phase: str, t: float, stream_id: int,
                object_id: Any, info: Optional[Dict]) -> None:
        """Hot recording path: one TUPLE into this thread's ring (a
        dict per event measured ~3x the allocation cost on the
        null-task rate; to_records materializes dicts at READ time)."""
        ring = self._ring()
        dq = ring.dq
        if len(dq) == dq.maxlen:
            ring.dropped += 1              # ring wrap: honesty counter
        dq.append((key, phase, t, stream_id, object_id, info))

    def event(self, key: str, phase: str, stream_id: int = -1,
              object_id: Any = None, info: Optional[Dict] = None,
              t: Optional[float] = None) -> None:
        """Record one event. ``t`` (seconds relative to this trace's
        ``t0``) may be passed explicitly for after-the-fact spans (e.g.
        an admission park recorded once the wait resolves)."""
        self._append(key, phase,
                     (time.perf_counter() - self.t0) if t is None else t,
                     stream_id, object_id, info)

    def begin(self, key: str, **kw) -> None:
        self.event(key, "begin", **kw)

    def end(self, key: str, **kw) -> None:
        self.event(key, "end", **kw)

    def dropped(self) -> int:
        """Events lost to ring wraps across every recording thread,
        INCLUDING the native engines' in-engine rings and any evicted
        native snapshots (a truncated native capture must be loud)."""
        with self._ring_lock:
            n = sum(r.dropped for r in self._rings.values())
        return n + self.native_dropped()

    def native_dropped(self) -> int:
        """The native-ring share of :meth:`dropped` (meta/statusz row)."""
        with self._ring_lock:
            n = self._native_evicted
            sources = list(self._native_sources)
        return n + sum(src.dropped() for src in sources)

    # -- native DTD engine rings (ISSUE 13) -------------------------------
    def add_native_source(self, src: "NativeRingAdapter") -> None:
        """Attach one native engine's ring adapter: its records join
        ``to_records`` (expanded lazily at dump/scrape time) and its
        drop counter joins ``dropped()``. Bounded by
        ``profiling.trace_max_native_sources`` — the oldest snapshot is
        evicted with its event+drop counts folded into the drop total,
        so a persistent serving context cannot grow without bound."""
        with self._ring_lock:
            self._native_sources.append(src)
            while len(self._native_sources) > self._max_native:
                old = self._native_sources.popleft()
                self._native_evicted += old.event_count() + old.dropped()

    # hooks wired by install(). Paired by task.uid (an int — repr()
    # per event measured 2x the whole append cost); the human-readable
    # class/locals ride the end event's info. These two run once per
    # task on the null-task hot path, where every allocation is
    # visible in the obs_overhead_pct bench guard, so:
    # - ring appends are inlined (no _append call);
    # - a REQUEST-SCOPED task records ONE combined ring entry at
    #   completion (begin stamps parked in task.prof, all dict/info
    #   formatting deferred to to_records) — the begin/end event PAIR
    #   is materialized at read time, byte-identical to the classic
    #   shape. Tradeoff: a rid'd task that crashes mid-body leaves no
    #   event (the rid-less profiler pair still covers crash forensics).
    def task_begin(self, es, task) -> None:
        tp = task.taskpool
        if tp.trace_rid is not None:
            # ONE fused prof store: (span id, begin stamp, stream) —
            # the combined span record picks it up at completion
            task.prof["b"] = (self._span_base | self._span_next(),
                              time.perf_counter(),
                              es.th_id if es is not None else -1)
            return
        ring = self._ring()
        dq = ring.dq
        if len(dq) == dq.maxlen:
            ring.dropped += 1
        dq.append(("task", "begin", time.perf_counter() - self.t0,
                   es.th_id if es is not None else -1, task.uid, None))

    def task_complete(self, task) -> None:
        prof = task.prof
        ring = self._rings.get(threading.get_ident())
        if ring is None:
            ring = self._ring()
        dq = ring.dq
        if len(dq) == dq.maxlen:
            ring.dropped += 1
        b = prof.get("b")
        if b is not None:
            tp = task.taskpool
            # combined span record (expanded by to_records); absolute
            # perf_counter stamps, converted at read time
            dq.append((_SPAN_REC, b[1], time.perf_counter(), b[2],
                       task.uid, task.task_class.name, task.locals,
                       b[0], prof.get("rid") or tp.trace_rid,
                       prof.get("parent_span", tp.root_span),
                       prof.get("q_t0")))
            return
        dq.append(("task", "end", time.perf_counter() - self.t0, -1,
                   task.uid, {"class": task.task_class.name,
                              "locals": task.locals}))

    def install(self, context) -> "Trace":
        """Subscribe to the context's PINS chains (task_profiler module
        analog, mca/pins/task_profiler) and, when a comm engine is
        attached, its per-message instrumentation (msg_size events)."""
        self.add_keyword("task", info_schema={"class": "str",
                                              "locals": "list"})
        self.add_keyword("wire", info_schema={"rid": "str", "span": "str",
                                              "nbytes": "int"})
        self.add_keyword("admission", info_schema={"rid": "str"})
        self.add_keyword("req", info_schema={"rid": "str"})
        # KV page lifecycle (alloc/retain/release/free/cow/write) —
        # consumed by analysis/conformance.py for model replay
        self.add_keyword("kvpage", info_schema={"pool": "str",
                                                "refs": "int"})
        context.trace = self
        self.rank = context.my_rank
        from .spans import _RANK_SHIFT
        self._span_base = self.rank << _RANK_SHIFT
        # native_ok: pools on the native DTD engine record the same
        # begin/end spans into the in-engine rings (ISSUE 13), so a
        # live trace no longer forces the instrumented Python path
        context.pins.register(PinsEvent.EXEC_BEGIN, self.task_begin,
                              native_ok=True)
        if context.comm is not None:
            self._comm = context.comm
            context.comm.install_trace(self)
        return self

    # -- export -----------------------------------------------------------
    def to_records(self) -> List[Dict[str, Any]]:
        with self._ring_lock:
            rings = list(self._rings.values())
            native = list(self._native_sources)
        t0 = self.t0
        events: List[Dict[str, Any]] = []
        for r in rings:
            # list(deque) is a C-level snapshot (GIL-atomic): recording
            # threads may append concurrently with a live dump — a
            # Python-level iteration over the live deque would raise
            # "deque mutated during iteration"
            for ev in list(r.dq):
                if ev[0] == _SPAN_REC:
                    # combined request-span record -> begin/end pair
                    (_k, tb, te, stream, uid, cls, locs, sid, rid,
                     parent, q_t0) = ev
                    binfo = {"rid": rid, "span": sid, "parent": parent}
                    if q_t0 is not None:
                        binfo["q_us"] = round((tb - q_t0) * 1e6, 1)
                    events.append({"key": "task", "phase": "begin",
                                   "t": tb - t0, "stream": stream,
                                   "object": uid, "info": binfo})
                    events.append({"key": "task", "phase": "end",
                                   "t": te - t0, "stream": -1,
                                   "object": uid,
                                   "info": {"class": cls,
                                            "locals": locs,
                                            "span": sid, "rid": rid}})
                    continue
                k, p, t, s, o, i = ev
                events.append({"key": k, "phase": p, "t": t,
                               "stream": s, "object": o,
                               "info": i or {}})
        for src in native:
            # natively-executed pools: the in-engine ring records,
            # expanded here to the byte-compatible event shape
            events.extend(src.to_records(t0))
        events.sort(key=lambda ev: ev["t"])
        return events

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.to_records())

    def meta(self) -> Dict[str, Any]:
        """Per-rank trace metadata: rank, the local perf_counter origin
        (t0), the drop counter, and — when a multi-rank comm engine is
        attached — the wire-measured clock offset to rank 0 that makes
        the Perfetto merge align (tools.merge_chrome / spans)."""
        nd = self.native_dropped()
        with self._ring_lock:
            py_dropped = sum(r.dropped for r in self._rings.values())
        out: Dict[str, Any] = {"rank": self.rank, "t0": self.t0,
                               "dropped": py_dropped + nd,
                               "native_dropped": nd}
        comm = self._comm
        if comm is not None:
            try:
                out.update(comm.clock_meta())
            except Exception as exc:  # noqa: BLE001 — meta is best-effort
                out["clock_error"] = str(exc)[:120]
        return out

    def dump_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump({"dictionary": self._dict,
                       "meta": self.meta(),
                       "events": self.to_records()}, fh)

    def dump_chrome_trace(self, path: str) -> None:
        """Second trace backend (the reference's OTF2 drop-in,
        profiling_otf2.c): Chrome trace-event JSON — loadable by
        chrome://tracing / Perfetto. begin/end pairs become duration
        events per stream; unpaired events become instants."""
        out = []
        # pair on (key, object) — ends may be recorded by a different
        # stream than the begin (e.g. task completion on another worker),
        # so the stream id is display info (tid from the begin), not key
        open_begins: Dict[tuple, Dict] = {}
        for ev in self.to_records():
            us = ev["t"] * 1e6
            key = (ev["key"], ev["object"])
            if ev["phase"] == "begin":
                open_begins[key] = ev
            elif ev["phase"] == "end" and key in open_begins:
                b = open_begins.pop(key)
                out.append({"name": ev["key"], "ph": "X", "pid": 0,
                            "tid": b["stream"], "ts": b["t"] * 1e6,
                            "dur": us - b["t"] * 1e6,
                            "args": ev["info"] or {}})
            else:
                out.append({"name": f"{ev['key']}:{ev['phase']}",
                            "ph": "i", "pid": 0, "tid": ev["stream"],
                            "ts": us, "s": "t",
                            "args": ev["info"] or {}})
        for b in open_begins.values():      # still-open begins → instants
            out.append({"name": f"{b['key']}:begin", "ph": "i", "pid": 0,
                        "tid": b["stream"], "ts": b["t"] * 1e6, "s": "t",
                        "args": b["info"] or {}})
        with open(path, "w") as fh:
            json.dump({"traceEvents": out}, fh)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = defaultdict(int)
        for ev in self.to_records():
            out[f"{ev['key']}:{ev['phase']}"] += 1
        return dict(out)
