"""Event tracing.

Reference: parsec/profiling.c (PBT binary traces — per-stream buffers,
dictionary of paired begin/end keys with typed info payloads,
profiling.h:44-80) + tools/profiling/python/pbt2ptt.pyx (conversion to
pandas HDF5 tables).

Here events are recorded in per-stream in-memory buffers with the same
dictionary structure and exported directly to pandas (``to_pandas``) or
JSON — the offline converter collapses into the runtime since the host side
is already Python.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from .pins import PinsEvent


class Trace:
    """In-memory trace with a key dictionary (parsec_profiling API analog:
    dictionary entries = add_dictionary_keyword, events = trace_flags)."""

    def __init__(self) -> None:
        self._dict: Dict[str, Dict[str, Any]] = {}
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self.t0 = time.perf_counter()

    # -- dictionary (profiling.h:44-80 analog) ----------------------------
    def add_keyword(self, name: str, attributes: str = "",
                    info_schema: Optional[Dict[str, str]] = None) -> str:
        self._dict[name] = {"attributes": attributes,
                            "info": info_schema or {}}
        return name

    # -- event recording --------------------------------------------------
    def event(self, key: str, phase: str, stream_id: int = -1,
              object_id: Any = None, info: Optional[Dict] = None) -> None:
        ev = {"key": key, "phase": phase, "t": time.perf_counter() - self.t0,
              "stream": stream_id, "object": object_id, "info": info or {}}
        with self._lock:
            self._events.append(ev)

    def begin(self, key: str, **kw) -> None:
        self.event(key, "begin", **kw)

    def end(self, key: str, **kw) -> None:
        self.event(key, "end", **kw)

    # hooks wired by install()
    def task_begin(self, es, task) -> None:
        self.event("task", "begin",
                   stream_id=es.th_id if es is not None else -1,
                   object_id=repr(task))

    def task_complete(self, task) -> None:
        self.event("task", "end", object_id=repr(task),
                   info={"class": task.task_class.name,
                         "locals": list(task.locals)})

    def install(self, context) -> "Trace":
        """Subscribe to the context's PINS chains (task_profiler module
        analog, mca/pins/task_profiler) and, when a comm engine is
        attached, its per-message instrumentation (msg_size events)."""
        self.add_keyword("task", info_schema={"class": "str", "locals": "list"})
        context.trace = self
        context.pins.register(PinsEvent.EXEC_BEGIN, self.task_begin)
        if context.comm is not None:
            context.comm.install_trace(self)
        return self

    # -- export -----------------------------------------------------------
    def to_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.to_records())

    def dump_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump({"dictionary": self._dict,
                       "events": self.to_records()}, fh)

    def dump_chrome_trace(self, path: str) -> None:
        """Second trace backend (the reference's OTF2 drop-in,
        profiling_otf2.c): Chrome trace-event JSON — loadable by
        chrome://tracing / Perfetto. begin/end pairs become duration
        events per stream; unpaired events become instants."""
        out = []
        # pair on (key, object) — ends may be recorded by a different
        # stream than the begin (e.g. task completion on another worker),
        # so the stream id is display info (tid from the begin), not key
        open_begins: Dict[tuple, Dict] = {}
        for ev in self.to_records():
            us = ev["t"] * 1e6
            key = (ev["key"], ev["object"])
            if ev["phase"] == "begin":
                open_begins[key] = ev
            elif ev["phase"] == "end" and key in open_begins:
                b = open_begins.pop(key)
                out.append({"name": ev["key"], "ph": "X", "pid": 0,
                            "tid": b["stream"], "ts": b["t"] * 1e6,
                            "dur": us - b["t"] * 1e6,
                            "args": ev["info"] or {}})
            else:
                out.append({"name": f"{ev['key']}:{ev['phase']}",
                            "ph": "i", "pid": 0, "tid": ev["stream"],
                            "ts": us, "s": "t",
                            "args": ev["info"] or {}})
        for b in open_begins.values():      # still-open begins → instants
            out.append({"name": f"{b['key']}:begin", "ph": "i", "pid": 0,
                        "tid": b["stream"], "ts": b["t"] * 1e6, "s": "t",
                        "args": b["info"] or {}})
        with open(path, "w") as fh:
            json.dump({"traceEvents": out}, fh)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = defaultdict(int)
        for ev in self.to_records():
            out[f"{ev['key']}:{ev['phase']}"] += 1
        return dict(out)
