"""Event tracing.

Reference: parsec/profiling.c (PBT binary traces — per-stream buffers,
dictionary of paired begin/end keys with typed info payloads,
profiling.h:44-80) + tools/profiling/python/pbt2ptt.pyx (conversion to
pandas HDF5 tables).

Here events are recorded in per-stream in-memory buffers with the same
dictionary structure and exported directly to pandas (``to_pandas``) or
JSON — the offline converter collapses into the runtime since the host side
is already Python.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from .pins import PinsEvent


class Trace:
    """In-memory trace with a key dictionary (parsec_profiling API analog:
    dictionary entries = add_dictionary_keyword, events = trace_flags)."""

    def __init__(self) -> None:
        self._dict: Dict[str, Dict[str, Any]] = {}
        self._events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self.t0 = time.perf_counter()

    # -- dictionary (profiling.h:44-80 analog) ----------------------------
    def add_keyword(self, name: str, attributes: str = "",
                    info_schema: Optional[Dict[str, str]] = None) -> str:
        self._dict[name] = {"attributes": attributes,
                            "info": info_schema or {}}
        return name

    # -- event recording --------------------------------------------------
    def event(self, key: str, phase: str, stream_id: int = -1,
              object_id: Any = None, info: Optional[Dict] = None) -> None:
        ev = {"key": key, "phase": phase, "t": time.perf_counter() - self.t0,
              "stream": stream_id, "object": object_id, "info": info or {}}
        with self._lock:
            self._events.append(ev)

    def begin(self, key: str, **kw) -> None:
        self.event(key, "begin", **kw)

    def end(self, key: str, **kw) -> None:
        self.event(key, "end", **kw)

    # hooks wired by install()
    def task_begin(self, es, task) -> None:
        self.event("task", "begin",
                   stream_id=es.th_id if es is not None else -1,
                   object_id=repr(task))

    def task_complete(self, task) -> None:
        self.event("task", "end", object_id=repr(task),
                   info={"class": task.task_class.name,
                         "locals": list(task.locals)})

    def install(self, context) -> "Trace":
        """Subscribe to the context's PINS chains (task_profiler module
        analog, mca/pins/task_profiler)."""
        self.add_keyword("task", info_schema={"class": "str", "locals": "list"})
        context.trace = self
        context.pins.register(PinsEvent.EXEC_BEGIN, self.task_begin)
        return self

    # -- export -----------------------------------------------------------
    def to_records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.to_records())

    def dump_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump({"dictionary": self._dict,
                       "events": self.to_records()}, fh)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = defaultdict(int)
        for ev in self.to_records():
            out[f"{ev['key']}:{ev['phase']}"] += 1
        return dict(out)
