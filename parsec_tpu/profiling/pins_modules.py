"""PINS instrumentation modules.

Reference: parsec/mca/pins/ — modules hook the runtime's callback chains
(pins.h:26-53) per execution stream. The reference ships task_profiler
(writes task begin/end to the trace), print_steals (per-stream steal
counters), alperf (per-class activity/performance), iterators_checker
(runtime sanity of successor iterators) and papi (hardware counters —
analog here: the ``counters`` module below, rusage-backed since this
environment has no PAPI and no portable TPU hardware counters; the
SDE-style software counters live in profiling.sde). Modules are
selected MCA-style via the ``pins`` param (comma-separated names) and
installed at context init.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from .pins import PinsEvent
from ..utils import mca_param
from ..utils.debug import debug_verbose

mca_param.register("pins", "",
                   help="comma-separated PINS modules to install at init "
                        "(task_profiler, print_steals, alperf, "
                        "iterators_checker, counters, overhead, tenant, "
                        "straggler, dfsan)")
mca_param.register("profiling.straggler_factor", 3.0,
                   help="straggler watchdog: flag a task instance whose "
                        "body time exceeds its class's rolling p99 "
                        "times this factor")
mca_param.register("profiling.straggler_window", 256,
                   help="straggler watchdog: rolling per-class sample "
                        "window the p99 is estimated over")
mca_param.register("profiling.straggler_min_samples", 20,
                   help="straggler watchdog: observations of a class "
                        "before flagging starts (a cold p99 estimate "
                        "flags compile warmup, not stragglers)")


class PinsModule:
    """Base module: ``install(context)`` subscribes to the PINS chains,
    ``uninstall()`` removes the subscriptions, ``report()`` returns the
    collected data (reference modules print at component close).

    ``native_ok`` (per subscription) is the ISSUE 13 classification:
    ``True`` = the observer has a native-engine equivalent or only
    reads state at scrape time, so it does not force DTD pools onto the
    instrumented Python path; ``"trace"`` = native-ok only while a live
    Trace snapshots the engine rings for it (``observe_native_rings``
    is then fed at pool retirement); ``False`` (default) = a per-task
    Python observer — pools stay on the Python engine."""

    name = "module"

    def __init__(self) -> None:
        self.context = None
        self._subs: List = []    # (event, cb) pairs for uninstall

    def _sub(self, event: PinsEvent, cb, native_ok: object = False) -> None:
        self.context.pins.register(event, cb, native_ok=native_ok)
        self._subs.append((event, cb))

    def install(self, context) -> "PinsModule":
        self.context = context
        return self

    def uninstall(self) -> None:
        for event, cb in self._subs:
            self.context.pins.unregister(event, cb)
        self._subs.clear()

    def report(self) -> Dict[str, Any]:
        return {}


class TaskProfiler(PinsModule):
    """mca/pins/task_profiler analog: records task begin/end into the
    context trace (creating one if absent)."""

    name = "task_profiler"

    def install(self, context) -> "TaskProfiler":
        super().install(context)
        from .trace import Trace
        self._installed_trace = context.trace is None
        if context.trace is None:
            Trace().install(context)
        self.trace = context.trace
        if self._installed_trace:
            # Trace.install registered this outside our bookkeeping — adopt
            # it so uninstall() stops the event flow; a user-installed
            # trace keeps its own subscription
            self._subs.append((PinsEvent.EXEC_BEGIN, self.trace.task_begin))
        return self

    def uninstall(self) -> None:
        super().uninstall()
        if self._installed_trace and self.context.trace is self.trace:
            self.context.trace = None   # stop task_complete recording too
            # Trace.install also hooked the comm engine's msg-size
            # instrumentation — detach it, or the engine keeps recording
            # into the dead trace after fini
            if (self.context.comm is not None and
                    getattr(self.context.comm, "_trace", None) is self.trace):
                self.context.comm.install_trace(None)

    def report(self) -> Dict[str, Any]:
        return self.trace.counts()


class PrintSteals(PinsModule):
    """mca/pins/print_steals analog: per-stream counts of tasks obtained
    by stealing (from a VP peer or the system overflow queue). The
    counters themselves are maintained by the local-queue schedulers in
    ``es.stats["stolen"]``; this module snapshots and reports them."""

    name = "print_steals"

    def report(self) -> Dict[int, Dict[str, int]]:
        return {es.th_id: {"selected": es.stats.get("selected", 0),
                           "stolen": es.stats.get("stolen", 0)}
                for es in self.context.streams}

    def print(self) -> None:
        for th_id, row in sorted(self.report().items()):
            debug_verbose(0, "pins", "stream %d: %d selected, %d stolen",
                          th_id, row["selected"], row["stolen"])


class Alperf(PinsModule):
    """mca/pins/alperf analog: per-task-class activity counters —
    executions and cumulative body time."""

    name = "alperf"

    def install(self, context) -> "Alperf":
        super().install(context)
        self._lock = threading.Lock()
        self._stats: Dict[str, Dict[str, float]] = defaultdict(
            lambda: {"count": 0, "time_s": 0.0})
        self._sub(PinsEvent.EXEC_BEGIN, self._begin)
        self._sub(PinsEvent.EXEC_END, self._end)
        return self

    def _begin(self, es, task) -> None:
        task.prof["alperf_t0"] = time.perf_counter()

    def _end(self, es, task) -> None:
        t0 = task.prof.pop("alperf_t0", None)
        dt = 0.0 if t0 is None else time.perf_counter() - t0
        with self._lock:
            row = self._stats[task.task_class.name]
            row["count"] += 1
            row["time_s"] += dt

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._stats.items()}


class IteratorsChecker(PinsModule):
    """mca/pins/iterators_checker analog: at release time, re-runs the
    completed task's ``iterate_successors`` and validates every ref —
    target class belongs to the same taskpool, the named flow exists,
    the dep bit matches the flow, and (for PTG classes, where the task
    space is closed-form) the target instance exists. Violations raise,
    failing the run loudly the way the reference module aborts."""

    name = "iterators_checker"

    def install(self, context) -> "IteratorsChecker":
        super().install(context)
        self.checked = 0
        self._lock = threading.Lock()
        self._space_cache: Dict[Any, set] = {}
        self._sub(PinsEvent.RELEASE_DEPS_BEGIN, self._check)
        return self

    def _space_of(self, tc) -> Optional[set]:
        if not hasattr(tc, "enumerate_space"):
            return None
        with self._lock:
            space = self._space_cache.get(tc)
            if space is None:
                space = self._space_cache[tc] = set(tc.enumerate_space())
        return space

    def _check(self, es, task) -> None:
        from ..core.taskpool import DataRef, SuccessorRef
        tc = task.task_class
        # DTD successor lists are consumed-once runtime state, not a pure
        # closed-form iterator — only PTG-style classes can be re-iterated
        if not hasattr(tc, "enumerate_space"):
            return
        tp = task.taskpool
        for ref in tc.iterate_successors(task):
            if isinstance(ref, DataRef):
                if ref.collection is None:
                    raise AssertionError(
                        f"{task!r}: DataRef with no collection")
                continue
            assert isinstance(ref, SuccessorRef)
            dst = ref.task_class
            if dst not in tp.task_classes:
                raise AssertionError(
                    f"{task!r} -> {dst.name}: class not in taskpool")
            flow = dst.flow_by_name.get(ref.flow_name)
            if flow is None:
                raise AssertionError(
                    f"{task!r} -> {dst.name}.{ref.flow_name}: no such flow")
            if ref.dep_index != flow.index:
                raise AssertionError(
                    f"{task!r} -> {dst.name}.{ref.flow_name}: dep bit "
                    f"{ref.dep_index} != flow index {flow.index}")
            if len(ref.locals) != len(dst.params):
                raise AssertionError(
                    f"{task!r} -> {dst.name}{ref.locals}: arity "
                    f"{len(ref.locals)} != {len(dst.params)} params")
            space = self._space_of(dst)
            if space is not None and tuple(ref.locals) not in space:
                raise AssertionError(
                    f"{task!r} -> {dst.name}{tuple(ref.locals)}: target "
                    f"instance outside the task space")
        with self._lock:
            self.checked += 1

    def report(self) -> Dict[str, int]:
        return {"tasks_checked": self.checked}


class Counters(PinsModule):
    """mca/pins/papi analog (pins_papi.c:1-592): read a counter set at
    EXEC begin/end per execution stream and accumulate the deltas per
    task class. This environment exposes no PAPI and no portable TPU
    hardware counters (PARITY.md N/A table), so the counter source is
    ``resource.getrusage(RUSAGE_THREAD)`` — per-thread CPU time, page
    faults and context switches — plus the monotonic clock. The
    frame structure matches the reference module: sample at begin,
    delta at end, aggregate per (class, counter)."""

    name = "counters"

    #: counter name -> rusage attribute
    _FIELDS = {
        "utime_s": "ru_utime",
        "stime_s": "ru_stime",
        "minflt": "ru_minflt",
        "majflt": "ru_majflt",
        "nvcsw": "ru_nvcsw",
        "nivcsw": "ru_nivcsw",
    }

    def __init__(self) -> None:
        super().__init__()
        self._begin: Dict[int, tuple] = {}      # task id -> sample
        self.totals: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))
        self._lock = threading.Lock()

    @staticmethod
    def _sample():
        import resource
        who = getattr(resource, "RUSAGE_THREAD", resource.RUSAGE_SELF)
        return (resource.getrusage(who), time.perf_counter(),
                threading.get_ident())

    def install(self, context) -> "Counters":
        super().install(context)
        self._sub(PinsEvent.EXEC_BEGIN, self._exec_begin)
        self._sub(PinsEvent.EXEC_END, self._exec_end)
        return self

    def _exec_begin(self, es, task) -> None:
        self._begin[id(task)] = self._sample()

    def _exec_end(self, es, task) -> None:
        b = self._begin.pop(id(task), None)
        if b is None:
            return
        (ru0, t0, tid0), (ru1, t1, tid1) = b, self._sample()
        key = task.task_class.name
        with self._lock:
            tot = self.totals[key]
            tot["tasks"] += 1
            tot["wall_s"] += t1 - t0
            if tid0 != tid1:
                # ASYNC completion (e.g. the batching manager): END
                # fires on a different thread, so a RUSAGE_THREAD delta
                # would subtract one thread's counters from another's.
                # Only wall time is cross-thread meaningful.
                tot["async_tasks"] += 1
                return
            for cname, attr in self._FIELDS.items():
                # ru_utime/ru_stime are float seconds in Python's
                # resource module; the rest are ints
                tot[cname] += float(getattr(ru1, attr) -
                                    getattr(ru0, attr))

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self.totals.items()}


class OverheadProfiler(PinsModule):
    """Per-stage runtime-overhead timers: insert (DTD insertion, on the
    inserting thread), select (scheduler select), dispatch
    (prepare_input + incarnation walk + hook call) and release
    (release-deps: successor iteration, dependency countdown,
    scheduling). The timers themselves live in the runtime hot loops
    behind ``context.stage_timers`` (one attribute test when off —
    ``runtime.stage_timers`` MCA param); this module flips the flag on
    install and aggregates the collected stream/taskpool counters into
    the per-task overhead budget the taskrate bench reports.

    Reported seconds are THREAD seconds (summed across workers): with W
    busy workers, per-task wall overhead is roughly the per-task thread
    time / W."""

    name = "overhead"

    def install(self, context) -> "OverheadProfiler":
        super().install(context)
        self._prev_flag = context.stage_timers
        context.stage_timers = True
        return self

    def uninstall(self) -> None:
        super().uninstall()
        self.context.stage_timers = self._prev_flag

    def report(self) -> Dict[str, Any]:
        agg = {"select_s": 0.0, "select_calls": 0, "dispatch_s": 0.0,
               "release_s": 0.0, "executed": 0}
        for es in self.context.streams:
            for k in agg:
                agg[k] += es.stats.get(k, 0)
        agg["insert_s"] = 0.0
        agg["insert_calls"] = 0
        with self.context._lock:
            pools = list(self.context._taskpools_by_name.values())
        for tp in pools:
            agg["insert_s"] += getattr(tp, "insert_s", 0.0)
            agg["insert_calls"] += getattr(tp, "insert_calls", 0)
        n = max(agg["executed"], 1)
        agg["per_task_us"] = {
            "insert": round(agg["insert_s"] / max(agg["insert_calls"], 1)
                            * 1e6, 3),
            "select": round(agg["select_s"] / n * 1e6, 3),
            "dispatch": round(agg["dispatch_s"] / n * 1e6, 3),
            "release": round(agg["release_s"] / n * 1e6, 3),
        }
        return agg


class StragglerWatchdog(PinsModule):
    """Online straggler detection (the PINS-shaped watchdog the serving
    plane runs LIVE instead of post-mortem): per task class, body times
    feed a rolling window whose p99 is re-estimated every window/4
    observations; an instance exceeding ``p99 × profiling.
    straggler_factor`` (after ``profiling.straggler_min_samples``
    observations) is flagged — into the report, the always-on metrics
    registry (``parsec_stragglers_total{class}``), and a warning log.
    A uniform slowdown moves the p99 WITH the tasks, so the watchdog
    flags outliers (one wedged worker, one pathological input), not
    load."""

    name = "straggler"

    def install(self, context) -> "StragglerWatchdog":
        super().install(context)
        from collections import deque
        from . import metrics as metrics_mod
        self._deque = deque
        self._factor = float(mca_param.get(
            "profiling.straggler_factor", 3.0))
        self._window = max(int(mca_param.get(
            "profiling.straggler_window", 256)), 8)
        self._min = max(int(mca_param.get(
            "profiling.straggler_min_samples", 20)), 2)
        self._lock = threading.Lock()
        # class -> [window deque, seen count, cached p99 (None = stale)]
        self._rows: Dict[str, list] = {}
        self.flagged: List[Dict[str, Any]] = []
        self._m_flagged = metrics_mod.registry().counter(
            "parsec_stragglers_total",
            "task instances flagged by the straggler watchdog "
            "(body time > rolling p99 x profiling.straggler_factor)",
            ("class",)) if metrics_mod.enabled() else None
        # native_ok="trace": with a live Trace the watchdog is fed the
        # native engine's ring records at pool retirement
        # (observe_native_rings) — near-live for the one-pool-per-
        # request serving shape; without a trace there is no native
        # data source, so the pool stays on the Python path
        self._sub(PinsEvent.EXEC_BEGIN, self._begin, native_ok="trace")
        self._sub(PinsEvent.EXEC_END, self._end, native_ok="trace")
        return self

    def _begin(self, es, task) -> None:
        task.prof["straggler_t0"] = time.perf_counter()

    @staticmethod
    def _p99(samples) -> float:
        s = sorted(samples)
        return s[min(int(len(s) * 0.99), len(s) - 1)]

    def _end(self, es, task) -> None:
        t0 = task.prof.pop("straggler_t0", None)
        if t0 is None:
            return
        self._observe(task.task_class.name, time.perf_counter() - t0,
                      list(task.locals))

    def _observe(self, cls: str, dt: float, locals_: List) -> None:
        """ONE detection rule for both paths (live EXEC hooks and the
        native ring feed): min-samples gate, window//4 p99
        re-estimation, flag shape, counter, log — a one-sided tuning
        edit cannot diverge the engines' straggler behavior."""
        flag = None
        with self._lock:
            row = self._rows.get(cls)
            if row is None:
                row = self._rows[cls] = [
                    self._deque(maxlen=self._window), 0, None]
            win, seen, p99 = row
            if seen >= self._min:
                if p99 is None or seen % max(self._window // 4, 1) == 0:
                    p99 = row[2] = self._p99(win)
                if dt > p99 * self._factor:
                    flag = {"class": cls,
                            "locals": locals_,
                            "body_s": round(dt, 6),
                            "p99_s": round(p99, 6),
                            "factor": round(dt / max(p99, 1e-12), 2)}
                    self.flagged.append(flag)
            win.append(dt)
            row[1] = seen + 1
        if flag is not None:
            if self._m_flagged is not None:
                self._m_flagged.labels(**{"class": cls}).inc()
            debug_verbose(1, "pins",
                          "straggler: %s%r body %.3f ms > p99 %.3f ms "
                          "x %.1f", cls, tuple(locals_),
                          flag["body_s"] * 1e3, flag["p99_s"] * 1e3,
                          self._factor)

    def observe_native_rings(self, arrays, class_names) -> None:
        """Ring-fed native path (ISSUE 13): a natively-executed pool's
        body durations (select→completion from the in-engine event
        rings) arrive in bulk when the rings are snapshotted at pool
        retirement — near-live for the one-pool-per-request serving
        shape. Each record goes through the SAME per-observation rule
        as the live path (_observe), so an outlier inside the first
        fold is still flagged. The per-record Python cost is paid only
        at FOLD time and only with this module installed."""
        import numpy as np
        for a in arrays:
            durs = (a["t1_ns"].astype(np.int64) -
                    a["t0_ns"].astype(np.int64)) / 1e9
            cls_ids = a["cls"]
            seqs = a["seq"]
            for cid in np.unique(cls_ids):
                name = class_names[cid] if cid < len(class_names) \
                    else "dtd_task"
                mask = cls_ids == cid
                for d, s in zip(durs[mask].tolist(),
                                seqs[mask].tolist()):
                    self._observe(name, d, [s])

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "flagged": list(self.flagged),
                "classes": {cls: {"seen": row[1],
                                  "p99_s": (round(self._p99(row[0]), 6)
                                            if row[0] else None)}
                            for cls, row in self._rows.items()}}


class TenantAccounting(PinsModule):
    """Per-tenant service accounting for the multi-tenant serving
    runtime (ROADMAP item 4): executed tasks and cumulative body
    seconds attributed to each taskpool's ``tenant_name`` (pools
    outside the serving runtime land under ``(untenanted)``), merged
    with the wfq scheduler's per-pool selection counters when that
    scheduler is installed — the evidence that makes starvation
    measurable rather than anecdotal."""

    name = "tenant"

    def install(self, context) -> "TenantAccounting":
        super().install(context)
        from . import metrics as metrics_mod
        self._lock = threading.Lock()
        self._rows: Dict[str, Dict[str, float]] = defaultdict(
            lambda: {"tasks": 0, "body_s": 0.0})
        # unified counter surface: the rows ALSO land in the shared
        # metrics registry (live /metrics export); the per-instance
        # dict remains the isolated per-context view report() serves
        self._m_tasks = self._m_body = None
        if metrics_mod.enabled():
            self._m_tasks = metrics_mod.registry().counter(
                "parsec_tenant_tasks_total",
                "tasks executed per tenant", ("rank", "tenant"))
            self._m_body = metrics_mod.registry().counter(
                "parsec_tenant_body_seconds_total",
                "cumulative task-body seconds per tenant",
                ("rank", "tenant"))
        self._rank = str(context.my_rank)
        # native_ok: pools on the native DTD engine never fire these
        # hooks — their completions are folded per tenant at scrape
        # time from the engine's C++ atomics (report() / the context
        # metrics collector read Context.native_tenant_stats), so the
        # accounting module must not force the 12k/s Python path
        self._sub(PinsEvent.EXEC_BEGIN, self._begin, native_ok=True)
        self._sub(PinsEvent.EXEC_END, self._end, native_ok=True)
        return self

    @staticmethod
    def _tenant_of(task) -> str:
        return getattr(task.taskpool, "tenant_name", None) or \
            "(untenanted)"

    def _begin(self, es, task) -> None:
        task.prof["tenant_t0"] = time.perf_counter()

    def _end(self, es, task) -> None:
        t0 = task.prof.pop("tenant_t0", None)
        dt = 0.0 if t0 is None else time.perf_counter() - t0
        ten = self._tenant_of(task)
        with self._lock:
            row = self._rows[ten]
            row["tasks"] += 1
            row["body_s"] += dt
        if self._m_tasks is not None:
            self._m_tasks.labels(rank=self._rank, tenant=ten).inc()
            self._m_body.labels(rank=self._rank, tenant=ten).inc(dt)

    def report(self) -> Dict[str, Any]:
        with self._lock:
            out = {"tenants": {k: dict(v) for k, v in self._rows.items()}}
        # fold native-engine completions per tenant (ISSUE 13: native
        # pools bypass the EXEC hooks; the engine's atomics are the
        # truth — body_s stays Python-measured, native bodies may
        # never enter Python at all)
        for ten, n in self.context.native_tenant_stats().items():
            t = out["tenants"].setdefault(ten, {"tasks": 0,
                                                "body_s": 0.0})
            t["native_tasks"] = t.get("native_tasks", 0) + n
        sched = self.context.scheduler
        if hasattr(sched, "pool_stats"):
            # fold wfq's selection/backlog view in per tenant
            for row in sched.pool_stats().values():
                ten = row.get("tenant") or "(untenanted)"
                t = out["tenants"].setdefault(ten, {"tasks": 0,
                                                    "body_s": 0.0})
                t["selected"] = t.get("selected", 0) + row["selected"]
                t["pending"] = t.get("pending", 0) + row["pending"]
        return out


_MODULES = {
    "task_profiler": TaskProfiler,
    "print_steals": PrintSteals,
    "alperf": Alperf,
    "iterators_checker": IteratorsChecker,
    "counters": Counters,
    "overhead": OverheadProfiler,
    "tenant": TenantAccounting,
    "straggler": StragglerWatchdog,
}


def available() -> List[str]:
    return sorted(_MODULES) + ["dfsan"]


def new_module(name: str) -> PinsModule:
    if name == "dfsan":
        # the runtime race sanitizer lives in analysis/ (it is half of
        # the hazard-checker package, not a profiling concern); lazy
        # import also keeps pins_modules free of an import cycle
        from ..analysis.dfsan import DataflowSanitizer
        return DataflowSanitizer()
    try:
        return _MODULES[name]()
    except KeyError:
        raise ValueError(f"unknown PINS module {name!r}; have {available()}")


def install_selected(context) -> List[PinsModule]:
    """Install the modules named by the ``pins`` MCA param
    (mca/pins/pins_init.c analog)."""
    spec = str(mca_param.get("pins", "") or "")
    mods = []
    for name in filter(None, (s.strip() for s in spec.split(","))):
        mods.append(new_module(name).install(context))
    return mods
