"""Replay a PTG taskpool through the DTD front end.

Reference: parsec/mca/pins/ptg_to_dtd (601 LoC) — a correctness
cross-check that takes a compiled PTG DAG and re-executes it through the
dynamic-task-discovery interface, validating that both front ends drive
the runtime to the same result.

Here the replay uses the PTG class's closed-form structure directly:

1. enumerate every task instance of every class and topologically order
   them (Kahn) over ``iterate_successors`` edges **plus write-after-read
   edges**: PTG values travel with activations, so a reader and the
   tile's next writer are unordered in the dataflow DAG — but DTD
   discovers dependencies from *insertion order* over tiles, so a reader
   inserted after the next writer would see the wrong version. Each
   reader is therefore ordered before the tile's first writer that
   follows the reader's producer;
2. for each task, form one :class:`~parsec_tpu.dsl.dtd.TileArg` per flow
   from the flow's ``tile`` placement (``FlowSpec.tile`` — the JDF data
   annotation) with the flow's access mode, and insert the class's body.

DTD's tile tracking then rebuilds the same RAW/WAW dependency structure
the PTG expressions encode, and ``flush()`` writes the tiles back —
running the identical bodies through a completely different discovery
path. Tests compare the resulting collection contents against a PTG run.

Requirements on the PTG taskpool: every flow declares ``tile``, no CTL
flows (DTD has no control-only arguments), no NEW inputs and no
reshapes — i.e. the same class of taskpools the compiled wavefront
executor accepts. Bodies receive a :class:`_ReplayTask` shim as their
``task`` argument carrying ``task_class`` and ``locals`` (the identity
fields bodies legitimately read); runtime-private Task state is absent.
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Callable, Dict, List, Optional, Tuple

from ..core.task import FlowAccess, Task
from ..core.taskpool import DataRef
from ..dsl import dtd as dtd_mod
from ..dsl import ptg as ptg_mod

_Key = Tuple[str, Tuple[int, ...]]


class _ReplayTask:
    """Stand-in for the Task handed to PTG bodies during DTD replay."""

    __slots__ = ("task_class", "locals")

    def __init__(self, task_class, locals: Tuple[int, ...]):
        self.task_class = task_class
        self.locals = tuple(locals)

    def __repr__(self) -> str:
        return f"{self.task_class.name}{self.locals}"


def _kahn(keys, succs, indeg) -> List[_Key]:
    indeg = dict(indeg)
    queue = deque(k for k in keys if indeg[k] == 0)
    order = []
    while queue:
        k = queue.popleft()
        order.append(k)
        for dst in succs.get(k, ()):
            indeg[dst] -= 1
            if indeg[dst] == 0:
                queue.append(dst)
    return order


def topo_order(tp: ptg_mod.Taskpool) -> List[Tuple[ptg_mod.PTGTaskClass,
                                                   Tuple[int, ...]]]:
    """Topologically order the full task space over dataflow edges plus
    per-tile WAR edges, producing a valid sequential *program order* for
    tile-granular discovery (DTD insertion)."""
    g = tp.g
    by_key: Dict[_Key, Tuple] = {}
    succs: Dict[_Key, List[_Key]] = defaultdict(list)
    indeg: Dict[_Key, int] = {}
    for tc in tp.task_classes:
        for p in tc.enumerate_space():
            by_key[(tc.name, p)] = (tc, p)
            indeg[(tc.name, p)] = 0

    def add_edge(a: _Key, b: _Key) -> None:
        if a != b:
            succs[a].append(b)
            indeg[b] += 1

    for tc, p in by_key.values():
        probe = Task(tp, tc, p)
        for f in tc.flows:
            probe.data[f.name] = 0
            probe.output[f.name] = 0
        for ref in tc.iterate_successors(probe):
            if isinstance(ref, DataRef):
                continue
            add_edge((tc.name, p), (ref.task_class.name, tuple(ref.locals)))

    base = _kahn(by_key, succs, indeg)
    if len(base) != len(by_key):
        raise RuntimeError("PTG taskpool has a dependency cycle")
    pos = {k: i for i, k in enumerate(base)}

    # Per-tile access lists → WAR edges. A reader of version v (produced
    # by task P, or the initial collection value when its In is a data
    # read, treated as position -1) must precede the first writer of the
    # tile positioned after P.
    writers: Dict[Tuple, List[_Key]] = defaultdict(list)
    readers: Dict[Tuple, List[Tuple[_Key, int]]] = defaultdict(list)
    for tc, p in by_key.values():
        for f, spec in zip(tc.flows, tc.spec_list):
            if f.is_ctl or spec.tile is None:
                continue
            dc, key = spec.tile(g, *p)
            key = tuple(key) if isinstance(key, (tuple, list)) else (key,)
            tile = (id(dc), key)
            if f.access & FlowAccess.WRITE:
                writers[tile].append((tc.name, p))
            dep = tc._active_in(g, spec, p)
            if dep is None:
                continue
            producer_pos = -1
            if dep.src is not None:
                src_cls, src_params_fn, _sf = dep.src
                sp = src_params_fn(g, *p)
                sp = tuple(sp) if isinstance(sp, (tuple, list)) else (sp,)
                producer_pos = pos[(src_cls, tuple(sp))]
            readers[tile].append(((tc.name, p), producer_pos))

    war_added = False
    for tile, rlist in readers.items():
        wchain = sorted(writers.get(tile, ()), key=pos.__getitem__)
        wpos = [pos[w] for w in wchain]
        for rkey, producer_pos in rlist:
            # first writer strictly after the version's producer
            for w, wp in zip(wchain, wpos):
                if wp > producer_pos:
                    if w != rkey:
                        add_edge(rkey, w)
                        war_added = True
                    break

    if war_added:
        base = _kahn(by_key, succs, indeg)
        if len(base) != len(by_key):
            raise RuntimeError(
                "PTG taskpool has no valid sequential order: WAR edges "
                "close a cycle (conflicting writers of one tile?)")
    return [by_key[k] for k in base]


def replay_ptg_through_dtd(tp: ptg_mod.Taskpool, context,
                           name: Optional[str] = None) -> dtd_mod.Taskpool:
    """Execute PTG taskpool ``tp``'s DAG through the DTD interface on
    ``context``; returns the drained DTD taskpool (tiles flushed back to
    their collections). ``tp`` itself is never enqueued."""
    if ptg_mod.taskpool_uses_reshape(tp):
        raise ValueError("ptg_to_dtd replay cannot carry reshape specs")
    for tc in tp.task_classes:
        for f, spec in zip(tc.flows, tc.spec_list):
            if f.is_ctl:
                raise ValueError(
                    f"{tc.name}.{f.name}: CTL flows cannot replay via DTD")
            if spec.tile is None:
                raise ValueError(
                    f"{tc.name}.{f.name}: flow needs a tile placement")
            if any(d.new is not None for d in spec.ins):
                raise ValueError(
                    f"{tc.name}.{f.name}: NEW inputs cannot replay via DTD")

    dtd_tp = dtd_mod.Taskpool(name or f"{tp.name}-via-dtd")
    context.add_taskpool(dtd_tp)

    # one wrapper per class so DTD's lazy (fn, shape) class cache reuses
    # classes instead of minting one per insert; the task's locals arrive
    # as a leading ValueArg and are rewrapped into a _ReplayTask shim
    bodies: Dict[str, Callable] = {}
    for tc in tp.task_classes:
        hook = tc.incarnations[0].hook

        def fn(locals_, *tiles, _h=hook, _tc=tc):
            return _h(_ReplayTask(_tc, locals_), *tiles)

        fn.__name__ = f"{tc.name}_dtd"
        bodies[tc.name] = fn

    g = tp.g
    for tc, p in topo_order(tp):
        args = [dtd_mod.TileArg(*spec.tile(g, *p), access=f.access)
                for f, spec in zip(tc.flows, tc.spec_list)]
        dtd_tp.insert_task(bodies[tc.name], dtd_mod.ValueArg(tuple(p)),
                           *args, priority=tc.priority_fn(p))
    dtd_tp.flush()
    dtd_tp.wait()
    return dtd_tp
