"""PINS: performance instrumentation callback chains.

Reference: parsec/mca/pins/pins.h — callback chains on runtime events
(SELECT/PREPARE_INPUT/EXEC/COMPLETE_EXEC/RELEASE_DEPS begin+end, ...),
registered per execution stream and invoked via PARSEC_PINS macros.

Here a :class:`PinsManager` per context holds ordered callback lists per
event; modules register with :meth:`register`. The built-in
``task_profiler`` equivalent is profiling.trace.Trace, which subscribes to
EXEC events.
"""

from __future__ import annotations

import enum
from collections import defaultdict
from typing import Callable, Dict, List


class PinsEvent(enum.IntEnum):
    SELECT_BEGIN = 0
    SELECT_END = 1
    PREPARE_INPUT_BEGIN = 2
    PREPARE_INPUT_END = 3
    EXEC_BEGIN = 4
    EXEC_END = 5
    COMPLETE_EXEC_BEGIN = 6
    COMPLETE_EXEC_END = 7
    RELEASE_DEPS_BEGIN = 8
    RELEASE_DEPS_END = 9
    ACTIVATE_CB_BEGIN = 10
    ACTIVATE_CB_END = 11
    DATA_FLUSH_BEGIN = 12
    DATA_FLUSH_END = 13
    TASKPOOL_INIT = 14
    # collection-tile accesses observed by the dfsan race sanitizer
    # (analysis/dfsan.py re-broadcasts every access it stamps, so other
    # modules/tests can chain on tile reads/writes without their own
    # runtime hooks); carries (task, collection, key)
    DATA_READ = 15
    DATA_WRITE = 16
    # one tree-edge forward of a data-plane broadcast (comm thread):
    # carries (taskpool_name, src_rank, children, payload_nbytes) —
    # the collective-propagation visibility check-comms.py asserts on
    BCAST_FWD = 17


class PinsManager:
    def __init__(self, context) -> None:
        self.context = context
        self._chains: Dict[PinsEvent, List[Callable]] = defaultdict(list)
        # native-engine classification per registered callback (ISSUE
        # 13): True = the observer has a native equivalent (the pdtd
        # event rings) or only reads state at scrape time, so it does
        # NOT disqualify the native DTD engine; "trace" = native-ok
        # only while a live Trace feeds it ring records at pool
        # retirement (the straggler watchdog); False (default) = a
        # per-task Python observer the native hot loop cannot fire —
        # pools stay on the instrumented Python path.
        self._native_ok: Dict[Callable, object] = {}

    def register(self, event: PinsEvent, cb: Callable,
                 native_ok: object = False) -> None:
        self._chains[event].append(cb)
        self._native_ok[cb] = native_ok

    def active(self) -> bool:
        """True when ANY callback chain is populated (regardless of
        native classification) — kept for report/diagnostic callers;
        the native-engine gate is :meth:`needs_python_engine`."""
        return any(self._chains.values())

    def needs_python_engine(self, trace_live: bool = False) -> bool:
        """True when a registered callback requires the per-task Python
        hook chain — the instrumented-fallback gate the native DTD
        engine checks (``dsl/dtd_native.engine_for``). Callbacks
        registered ``native_ok=True`` never disqualify; ``"trace"``
        ones disqualify only when no live Trace will snapshot the
        native rings for them."""
        for chain in self._chains.values():
            for cb in chain:
                ok = self._native_ok.get(cb, False)
                if ok is True:
                    continue
                if ok == "trace" and trace_live:
                    continue
                return True
        return False

    def unregister(self, event: PinsEvent, cb: Callable) -> None:
        try:
            self._chains[event].remove(cb)
        except ValueError:
            pass
        if not any(cb in chain for chain in self._chains.values()):
            self._native_ok.pop(cb, None)

    def _fire(self, event: PinsEvent, *args) -> None:
        for cb in self._chains.get(event, ()):
            cb(*args)

    # convenience hooks used by the core
    def taskpool_init(self, tp) -> None:
        self._fire(PinsEvent.TASKPOOL_INIT, tp)

    def select_begin(self, es, tasks) -> None:
        self._fire(PinsEvent.SELECT_BEGIN, es, tasks)

    def prepare_input_begin(self, es, task) -> None:
        self._fire(PinsEvent.PREPARE_INPUT_BEGIN, es, task)

    def prepare_input_end(self, es, task) -> None:
        self._fire(PinsEvent.PREPARE_INPUT_END, es, task)

    def exec_begin(self, es, task) -> None:
        self._fire(PinsEvent.EXEC_BEGIN, es, task)

    def exec_end(self, es, task) -> None:
        self._fire(PinsEvent.EXEC_END, es, task)

    def release_deps_begin(self, es, task) -> None:
        self._fire(PinsEvent.RELEASE_DEPS_BEGIN, es, task)

    def release_deps_end(self, es, task) -> None:
        self._fire(PinsEvent.RELEASE_DEPS_END, es, task)

    def complete_exec_begin(self, es, task) -> None:
        self._fire(PinsEvent.COMPLETE_EXEC_BEGIN, es, task)

    def complete_exec_end(self, es, task) -> None:
        self._fire(PinsEvent.COMPLETE_EXEC_END, es, task)

    def data_read(self, task, collection, key) -> None:
        self._fire(PinsEvent.DATA_READ, task, collection, key)

    def data_write(self, task, collection, key) -> None:
        self._fire(PinsEvent.DATA_WRITE, task, collection, key)

    def bcast_fwd(self, taskpool_name, src_rank, children, nbytes) -> None:
        self._fire(PinsEvent.BCAST_FWD, taskpool_name, src_rank,
                   children, nbytes)
