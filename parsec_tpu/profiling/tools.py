"""Standalone trace-reader tools (tools/profiling analog).

The reference ships a reader suite around its binary traces:
``dbpreader.c`` (C reader), ``dbp2xml``, ``dbp-dot2png``, ``dbp2mem``,
and the Python/Cython ``pbt2ptt.pyx`` + ``profile2h5.py`` converters
producing pandas HDF5 tables (SURVEY §2.13, 11 kLoC of tools/). This
module is the TPU build's equivalent over the JSON traces written by
:meth:`profiling.trace.Trace.dump_json` — usable as a library AND as a
CLI::

    python -m parsec_tpu.profiling.tools summary  rank0.json rank1.json
    python -m parsec_tpu.profiling.tools chrome   out.json rank*.json
    python -m parsec_tpu.profiling.tools csv      out.csv  rank*.json
    python -m parsec_tpu.profiling.tools comms    rank*.json
    python -m parsec_tpu.profiling.tools critpath <rid> rank*.json

``summary`` = dbpreader's per-key statistics; ``chrome`` merges ranks
into one Chrome/Perfetto timeline (pid = rank) ALIGNED onto rank 0's
clock via each trace's ``meta.clock_offset_s`` (the pingpong handshake
recorded at dump time — without it, per-process ``perf_counter``
origins are arbitrary and a multi-rank merge is fiction); ``csv`` is
the profile2h5 pandas-table analog; ``comms`` reproduces
check-comms.py's message-count/byte-sum report from the comm msg_size
events; ``critpath`` reconstructs one request's span tree
(profiling/spans.py) and prints its admission/queue/exec/wire latency
breakdown plus the critical path over executed dep edges (pass ``-``
as the rid to list the requests present).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Sequence, Tuple


def load(path: str) -> Dict[str, Any]:
    """One rank's dumped trace: {"dictionary": ..., "events": [...]}."""
    with open(path) as fh:
        d = json.load(fh)
    if "events" not in d:
        raise ValueError(f"{path}: not a parsec_tpu trace dump")
    return d


def load_ranks(paths: Sequence[str]) -> List[Dict[str, Any]]:
    return [load(p) for p in paths]


def _pair_durations(events: List[Dict]) -> Dict[str, List[float]]:
    """Match begin/end pairs per (key, object) → seconds per key."""
    open_begins: Dict[Tuple, float] = {}
    durs: Dict[str, List[float]] = defaultdict(list)
    for ev in events:
        k = (ev["key"], ev.get("object"))
        if ev["phase"] == "begin":
            open_begins[k] = ev["t"]
        elif ev["phase"] == "end" and k in open_begins:
            durs[ev["key"]].append(ev["t"] - open_begins.pop(k))
    return durs


def summary(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-key event counts and paired-duration statistics across ranks
    (dbpreader-style). Per-rank drop counters from the meta block ride
    along — a truncated capture (Python ring wrap or native in-engine
    ring wrap, ISSUE 13) must be visible from the CLI, not silent."""
    out: Dict[str, Any] = {"ranks": len(traces), "keys": {}}
    for rank, tr in enumerate(traces):
        events = tr["events"]
        meta = tr.get("meta") or {}
        if meta.get("dropped") or meta.get("native_dropped"):
            out.setdefault("dropped", []).append(
                {"rank": meta.get("rank", rank),
                 "dropped": meta.get("dropped", 0),
                 "native_dropped": meta.get("native_dropped", 0)})
        counts: Dict[str, int] = defaultdict(int)
        for ev in events:
            counts[f"{ev['key']}:{ev['phase']}"] += 1
        durs = _pair_durations(events)
        for key, lst in durs.items():
            row = out["keys"].setdefault(
                key, {"pairs": 0, "total_s": 0.0, "max_s": 0.0})
            row["pairs"] += len(lst)
            row["total_s"] += sum(lst)
            row["max_s"] = max(row["max_s"], max(lst))
        out.setdefault("counts", []).append(dict(counts))
    for row in out["keys"].values():
        row["avg_s"] = row["total_s"] / max(row["pairs"], 1)
        for f in ("total_s", "max_s", "avg_s"):
            row[f] = round(row[f], 6)
    return out


def comms(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """check-comms.py analog: activation counts and payload byte sums
    from the comm msg_size events (reference asserts e.g. 100 activates
    / 209,715,200 bytes for bw_test). Only ACTIVATION-class events
    (comm_activate / comm_bcast) feed the headline counters — segment
    and rendezvous-leg events (comm_seg/comm_put/comm_get) carry bytes
    of an already-counted activation and would double-count every
    large payload; they get their own per-kind breakdown instead
    (mirrors ``CommEngine.stats_by_kind``)."""
    out: Dict[str, Any] = {}
    by_kind: Dict[str, Dict[str, int]] = {}
    for rank, tr in enumerate(traces):
        sent = recv = bytes_sent = bytes_recv = 0
        for ev in tr["events"]:
            key = str(ev["key"])
            if not key.startswith("comm_"):
                continue
            n = int(ev.get("info", {}).get("msg_size", 0))
            kind = key[len("comm_"):]
            bk = by_kind.setdefault(kind, {
                "sent_msgs": 0, "sent_bytes": 0,
                "recv_msgs": 0, "recv_bytes": 0})
            if ev["phase"] == "sent":
                bk["sent_msgs"] += 1
                bk["sent_bytes"] += n
                if kind in ("activate", "bcast"):
                    sent += 1
                    bytes_sent += n
            elif ev["phase"] == "recv":
                bk["recv_msgs"] += 1
                bk["recv_bytes"] += n
                if kind in ("activate", "bcast"):
                    recv += 1
                    bytes_recv += n
        out[f"rank{rank}"] = {
            "activations_sent": sent, "activations_recv": recv,
            "bytes_sent": bytes_sent, "bytes_recv": bytes_recv}
    out["total"] = {
        k: sum(r[k] for r in out.values() if isinstance(r, dict))
        for k in ("activations_sent", "activations_recv",
                  "bytes_sent", "bytes_recv")}
    out["by_kind"] = by_kind
    return out


def _align_shifts(traces: List[Dict[str, Any]]) -> List[float]:
    """Per-trace shift (seconds) landing every rank's events on one
    clock: ``t0 + clock_offset_s`` from the trace meta, normalized so
    the earliest trace starts at 0. Metadata-less traces (the
    single-process format) shift by 0 — byte-compatible."""
    from .spans import align_shift
    raw = [align_shift(tr) for tr in traces]
    if not any(raw):
        return raw
    base = min(s for s in raw if s) if any(raw) else 0.0
    return [s - base if s else 0.0 for s in raw]


def merge_chrome(traces: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Multi-rank Chrome/Perfetto timeline: pid = rank, tid = stream;
    ranks aligned onto one clock via the dump-time offset handshake
    (``meta.clock_offset_s``)."""
    out = []
    shifts = _align_shifts(traces)
    for rank, tr in enumerate(traces):
        shift = shifts[rank]
        open_begins: Dict[Tuple, Dict] = {}
        for ev in tr["events"]:
            us = (ev["t"] + shift) * 1e6
            k = (ev["key"], ev.get("object"))
            if ev["phase"] == "begin":
                open_begins[k] = ev
            elif ev["phase"] == "end" and k in open_begins:
                b = open_begins.pop(k)
                b_us = (b["t"] + shift) * 1e6
                out.append({"name": ev["key"], "ph": "X", "pid": rank,
                            "tid": b["stream"], "ts": b_us,
                            "dur": us - b_us,
                            "args": ev.get("info") or {}})
            else:
                out.append({"name": f"{ev['key']}:{ev['phase']}",
                            "ph": "i", "pid": rank, "tid": ev["stream"],
                            "ts": us, "s": "t",
                            "args": ev.get("info") or {}})
    return {"traceEvents": out}


def to_rows(traces: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Flat table rows (profile2h5 pandas analog): one row per event
    with the info dict splatted into ``info_*`` columns."""
    rows = []
    for rank, tr in enumerate(traces):
        for ev in tr["events"]:
            row = {"rank": rank, "key": ev["key"], "phase": ev["phase"],
                   "t": ev["t"], "stream": ev["stream"],
                   "object": str(ev.get("object"))}
            for ik, iv in (ev.get("info") or {}).items():
                row[f"info_{ik}"] = iv
            rows.append(row)
    return rows


def write_csv(path: str, rows: List[Dict[str, Any]]) -> None:
    import csv
    cols: List[str] = []
    for r in rows:
        for c in r:
            if c not in cols:
                cols.append(c)
    with open(path, "w", newline="") as fh:
        w = csv.DictWriter(fh, fieldnames=cols)
        w.writeheader()
        w.writerows(rows)


def main(argv: Sequence[str] = None) -> int:
    p = argparse.ArgumentParser(
        prog="parsec_tpu.profiling.tools",
        description="trace reader suite (tools/profiling analog)")
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summary", help="per-key stats (dbpreader)")
    s.add_argument("traces", nargs="+")
    c = sub.add_parser("chrome", help="merged Chrome/Perfetto timeline")
    c.add_argument("out")
    c.add_argument("traces", nargs="+")
    v = sub.add_parser("csv", help="flat event table (profile2h5)")
    v.add_argument("out")
    v.add_argument("traces", nargs="+")
    m = sub.add_parser("comms", help="comm volume report (check-comms)")
    m.add_argument("traces", nargs="+")
    k = sub.add_parser("critpath", help="one request's span tree: "
                       "latency breakdown + critical path ('-' lists "
                       "the rids present)")
    k.add_argument("rid")
    k.add_argument("traces", nargs="+")
    k.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of text")
    args = p.parse_args(argv)

    traces = load_ranks(args.traces)
    if args.cmd == "summary":
        json.dump(summary(traces), sys.stdout, indent=1)
        print()
    elif args.cmd == "chrome":
        with open(args.out, "w") as fh:
            json.dump(merge_chrome(traces), fh)
        print(f"wrote {args.out}")
    elif args.cmd == "csv":
        write_csv(args.out, to_rows(traces))
        print(f"wrote {args.out}")
    elif args.cmd == "comms":
        json.dump(comms(traces), sys.stdout, indent=1)
        print()
    elif args.cmd == "critpath":
        from . import spans
        if args.rid == "-":
            for r in spans.rids(traces):
                print(r)
            return 0
        rep = spans.critpath(traces, args.rid)
        if args.json:
            json.dump(rep, sys.stdout, indent=1)
            print()
        else:
            print(spans.render_critpath(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
