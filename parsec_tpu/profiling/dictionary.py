"""Runtime properties dictionary (reference parsec/dictionary.c, 943 LoC).

The reference exports a tree of namespaces/task-class properties backed by
live provider functions, published to shared memory so external monitors
can sample the runtime online. Here the dictionary is an in-process
registry of ``namespace → property → provider()``; :meth:`snapshot`
samples everything, and :func:`install_runtime_properties` wires the
standard namespaces (context, scheduler, devices, comm, taskpools) the
reference registers at init.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional


class PropertiesDictionary:
    """Namespaced registry of live runtime properties."""

    def __init__(self) -> None:
        self._ns: Dict[str, Dict[str, Callable[[], Any]]] = {}
        self._lock = threading.Lock()

    def register(self, namespace: str, name: str,
                 provider: Callable[[], Any]) -> None:
        with self._lock:
            self._ns.setdefault(namespace, {})[name] = provider

    def unregister(self, namespace: str, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._ns.pop(namespace, None)
            else:
                self._ns.get(namespace, {}).pop(name, None)

    def namespaces(self):
        with self._lock:
            return sorted(self._ns)

    def properties(self, namespace: str):
        with self._lock:
            return sorted(self._ns.get(namespace, {}))

    def query(self, namespace: str, name: str) -> Any:
        with self._lock:
            provider = self._ns[namespace][name]
        return provider()

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Sample every property once (the online-monitoring read)."""
        with self._lock:
            items = {ns: dict(props) for ns, props in self._ns.items()}
        out: Dict[str, Dict[str, Any]] = {}
        for ns, props in items.items():
            out[ns] = {}
            for name, provider in props.items():
                try:
                    out[ns][name] = provider()
                except Exception as exc:  # provider died — report, not raise
                    out[ns][name] = f"<error: {exc}>"
        return out


def install_runtime_properties(context) -> PropertiesDictionary:
    """Register the standard namespaces over a live context (the set the
    reference's dictionary.c publishes at parsec_init)."""
    d = PropertiesDictionary()
    d.register("context", "nb_cores", lambda: context.nb_cores)
    d.register("context", "nb_ranks", lambda: context.nb_ranks)
    d.register("context", "my_rank", lambda: context.my_rank)
    d.register("context", "active_taskpools",
               lambda: len(context._active_taskpools))
    d.register("sched", "name", lambda: context.scheduler.name)
    d.register("sched", "pending_tasks",
               lambda: context.scheduler.pending_tasks())
    for es in context.streams:
        d.register("streams", f"es{es.th_id}", lambda es=es: dict(es.stats))
    for dev in context.devices.devices:
        d.register("device", dev.name,
                   lambda dev=dev: dev.dump_statistics())
    if context.comm is not None:
        d.register("comm", "stats", lambda: dict(context.comm.stats))
    context.properties = d
    return d
