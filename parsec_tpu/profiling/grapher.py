"""DOT grapher: capture the executed DAG.

Reference: parsec/parsec_prof_grapher.c (266 LoC), enabled by the --dot
flag (parsec.c:589-607) — emits one .dot file per rank with a node per
executed task and an edge per satisfied dependency.

Edges are colored by the *consumer flow's* :class:`~parsec_tpu.core.
task.FlowAccess` (READ/WRITE/RW solid, CTL dashed grey), and hazard
edges reported by the static lint (analysis/lint.py ``LintReport.
to_dot``) are drawn red/bold/dotted with the rule name — the same DOT
output doubles as the lint's visual report and the runtime's executed
DAG capture (``profiling.dot`` MCA param).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..core.task import FlowAccess

#: edge color per consumer-flow access mode (colorblind-safe hues)
ACCESS_COLORS = {
    FlowAccess.READ: "#1b7837",     # green  — value consumed
    FlowAccess.WRITE: "#d95f0e",    # orange — value produced
    FlowAccess.RW: "#2166ac",       # blue   — consumed and produced
    FlowAccess.CTL: "#878787",      # grey   — control only (dashed)
}
HAZARD_COLOR = "#b2182b"


def _access_attrs(access: Optional[FlowAccess]) -> str:
    if access is None:
        return ""
    if access & FlowAccess.CTL:
        return f' color="{ACCESS_COLORS[FlowAccess.CTL]}" style=dashed'
    color = ACCESS_COLORS.get(FlowAccess(access & FlowAccess.RW))
    return f' color="{color}"' if color else ""


class Grapher:
    def __init__(self) -> None:
        self._nodes: Dict[str, Dict] = {}
        self._edges: List[Tuple[str, str, str, Optional[FlowAccess]]] = []
        # hazard overlay: (src, dst, flow, rule) — rendered red/bold;
        # these are NOT dependency edges (their absence is the hazard)
        self._hazards: List[Tuple[str, str, str, str]] = []
        self._lock = threading.Lock()

    def install(self, context) -> "Grapher":
        context.grapher = self
        return self

    # -- runtime capture (Context.complete_task) ---------------------------
    def task_executed(self, task) -> None:
        with self._lock:
            self._nodes[repr(task)] = {"class": task.task_class.name}

    def dep_edge(self, src_task, dst_class, dst_locals, flow: str) -> None:
        """One satisfied dependency src_task → dst_class(dst_locals).flow
        (called by the release path); colored by the consumer flow's
        access mode."""
        dst = f"{dst_class.name}({', '.join(map(str, dst_locals))})"
        dst_flow = dst_class.flow_by_name.get(flow)
        access = dst_flow.access if dst_flow is not None else None
        with self._lock:
            self._edges.append((repr(src_task), dst, flow, access))

    # -- static capture (analysis/lint.py visual report) -------------------
    def add_node(self, label: str, task_class: str) -> None:
        with self._lock:
            self._nodes[label] = {"class": task_class}

    def add_edge(self, src: str, dst: str, flow: str,
                 access: Optional[FlowAccess] = None) -> None:
        with self._lock:
            self._edges.append((src, dst, flow, access))

    def mark_hazard(self, src: str, dst: str, flow: str, rule: str) -> None:
        """Overlay a hazard reported by the lint: src and dst are the
        unordered pair (or consecutive cycle members); drawn red."""
        with self._lock:
            self._hazards.append((src, dst, flow, rule))
            # hazard endpoints may not be executed/enumerated nodes yet
            self._nodes.setdefault(src, {"class": src.split("(")[0]})
            self._nodes.setdefault(dst, {"class": dst.split("(")[0]})

    # -- rendering ---------------------------------------------------------
    def to_dot(self) -> str:
        palette = ["#66c2a5", "#fc8d62", "#8da0cb", "#e78ac3", "#a6d854",
                   "#ffd92f", "#e5c494", "#b3b3b3"]
        with self._lock:
            classes = sorted({n["class"] for n in self._nodes.values()})
            color = {c: palette[i % len(palette)]
                     for i, c in enumerate(classes)}
            lines = ["digraph G {", "  node [style=filled];"]
            hazard_nodes = {h[0] for h in self._hazards} | \
                           {h[1] for h in self._hazards}
            for name, attr in self._nodes.items():
                extra = (f' color="{HAZARD_COLOR}" penwidth=2'
                         if name in hazard_nodes else "")
                lines.append(
                    f'  "{name}" [fillcolor="{color[attr["class"]]}"'
                    f'{extra}];')
            for src, dst, flow, access in self._edges:
                lines.append(f'  "{src}" -> "{dst}" [label="{flow}"'
                             f'{_access_attrs(access)}];')
            for src, dst, flow, rule in self._hazards:
                label = f"{rule}:{flow}" if flow else rule
                lines.append(
                    f'  "{src}" -> "{dst}" [label="{label}" '
                    f'color="{HAZARD_COLOR}" fontcolor="{HAZARD_COLOR}" '
                    f'style=dotted penwidth=2 dir=both constraint=false];')
        lines.append("}")
        return "\n".join(lines)

    def write(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_dot())
