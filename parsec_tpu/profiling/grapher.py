"""DOT grapher: capture the executed DAG.

Reference: parsec/parsec_prof_grapher.c (266 LoC), enabled by the --dot
flag (parsec.c:589-607) — emits one .dot file per rank with a node per
executed task and an edge per satisfied dependency.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple


class Grapher:
    def __init__(self) -> None:
        self._nodes: Dict[str, Dict] = {}
        self._edges: List[Tuple[str, str, str]] = []
        self._lock = threading.Lock()

    def install(self, context) -> "Grapher":
        context.grapher = self
        return self

    def task_executed(self, task) -> None:
        with self._lock:
            self._nodes[repr(task)] = {"class": task.task_class.name}

    def dep_edge(self, src_task, dst_repr: str, flow: str) -> None:
        with self._lock:
            self._edges.append((repr(src_task), dst_repr, flow))

    def to_dot(self) -> str:
        palette = ["#66c2a5", "#fc8d62", "#8da0cb", "#e78ac3", "#a6d854",
                   "#ffd92f", "#e5c494", "#b3b3b3"]
        classes = sorted({n["class"] for n in self._nodes.values()})
        color = {c: palette[i % len(palette)] for i, c in enumerate(classes)}
        lines = ["digraph G {", "  node [style=filled];"]
        with self._lock:
            for name, attr in self._nodes.items():
                lines.append(
                    f'  "{name}" [fillcolor="{color[attr["class"]]}"];')
            for src, dst, flow in self._edges:
                lines.append(f'  "{src}" -> "{dst}" [label="{flow}"];')
        lines.append("}")
        return "\n".join(lines)

    def write(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_dot())
