"""Sanitizer lane driver (ISSUE 14): build + run the native core under
TSan/ASan/UBSan, with zero-report as the enforceable contract.

Two lanes, both seeded and reproducible:

- **Native stress lane** (:func:`run_stress`): ``sanstress.cpp`` is
  compiled TOGETHER with ``core.cpp`` into a standalone executable,
  entirely under one sanitizer — no Python in the process, so every
  reported frame is our code and zero-report needs no suppressions.
  Scenarios drive insert/steal/cancel/abort/obs-ring-drain/concurrent-
  scrape schedules (the PR 13 ``pdtd_stats``-vs-ring-growth race is a
  pinned scenario); the ``PARSEC_SAN_YIELD`` injection points compiled
  into the variant widen the interleaving space per seed.
- **Python lane** (:func:`run_python_lane`): a fresh interpreter with
  ``PARSEC_NATIVE_SAN=<variant>`` and the gcc sanitizer runtime
  LD_PRELOADed runs a real workload on the sanitized ``.so`` — this is
  the "reproducible via ``native.sanitize=tsan``" surface an operator
  uses against a suspicious serving binary.

Skips are CLEAN and explicit: :func:`capable` probes the toolchain
once per variant (compile + link + run of a trivial program) so CI on
a container without sanitizer runtimes skips instead of failing.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Tuple

from . import SAN_FLAGS, build_flags, sanitizer_runtime

_HERE = os.path.dirname(os.path.abspath(__file__))
_CORE = os.path.join(_HERE, "core.cpp")
_STRESS = os.path.join(_HERE, "sanstress.cpp")
_BUILD_DIR = os.path.join(_HERE, "build")

#: markers a sanitizer prints per finding — the zero-report scan
REPORT_MARKERS = (
    "WARNING: ThreadSanitizer",
    "ERROR: AddressSanitizer",
    "ERROR: LeakSanitizer",
    "runtime error:",               # UBSan
    "SUMMARY: UndefinedBehaviorSanitizer",
)

#: every stress scenario the driver knows (sanstress.cpp main)
SCENARIOS = ("pdtd", "plifo", "phash", "pmempool", "pgraph")

_lock = threading.Lock()
_capable: Dict[str, Optional[str]] = {}     # variant -> None | reason


def sanitizer_env(var: str, preload: bool = True) -> Dict[str, str]:
    """Environment for running variant ``var``: report-to-exit-code
    options plus (``preload=True``, the Python lane) the LD_PRELOAD of
    the gcc runtime. ``detect_leaks=0`` for ASan under CPython — the
    interpreter intentionally leaks at exit and those frames are
    third-party by definition (the native stress lane runs WITH leak
    detection, where every frame is ours)."""
    env = {
        "TSAN_OPTIONS": "exitcode=66 " +
                        os.environ.get("TSAN_OPTIONS", ""),
        "UBSAN_OPTIONS": "print_stacktrace=1 " +
                         os.environ.get("UBSAN_OPTIONS", ""),
    }
    if preload:
        env["ASAN_OPTIONS"] = ("detect_leaks=0 exitcode=66 " +
                               os.environ.get("ASAN_OPTIONS", ""))
        rt = sanitizer_runtime(var)
        if rt:
            prior = os.environ.get("LD_PRELOAD", "")
            env["LD_PRELOAD"] = rt + (":" + prior if prior else "")
    else:
        env["ASAN_OPTIONS"] = ("exitcode=66 " +
                               os.environ.get("ASAN_OPTIONS", ""))
    return env


def capable(var: str) -> Optional[str]:
    """None when variant ``var`` can compile, link AND run in this
    container; otherwise the human-readable reason to skip."""
    if var not in SAN_FLAGS:
        return f"unknown variant {var!r}"
    with _lock:
        if var in _capable:
            return _capable[var]
    import tempfile
    reason: Optional[str] = None
    with tempfile.TemporaryDirectory(prefix="parsec_san_") as td:
        src = os.path.join(td, "probe.cpp")
        exe = os.path.join(td, "probe")
        with open(src, "w") as f:
            f.write("#include <thread>\n"
                    "int main(){int x=0;std::thread t([&]{x=1;});"
                    "t.join();return x-1;}\n")
        try:
            proc = subprocess.run(
                ["g++", *build_flags(var), "-pthread", "-o", exe, src],
                capture_output=True, text=True, timeout=120)
            if proc.returncode != 0:
                reason = (f"{var} probe compile failed: "
                          f"{proc.stderr[-200:]}")
            else:
                run = subprocess.run(
                    [exe], capture_output=True, text=True, timeout=60,
                    env={**os.environ, **sanitizer_env(var,
                                                       preload=False)})
                if run.returncode != 0:
                    reason = (f"{var} probe run failed rc="
                              f"{run.returncode}: {run.stderr[-200:]}")
        except FileNotFoundError:
            reason = "g++ not found on PATH"
        except (OSError, subprocess.SubprocessError) as exc:
            reason = f"{var} probe errored: {exc}"
    with _lock:
        _capable[var] = reason
    return reason


def count_reports(text: str) -> int:
    return sum(text.count(m) for m in REPORT_MARKERS)


def _stress_stamp(var: str) -> str:
    h = hashlib.sha256()
    for p in (_CORE, _STRESS):
        with open(p, "rb") as f:
            h.update(f.read())
    h.update(" ".join(build_flags(var)).encode())
    return h.hexdigest()[:16]


def build_stress(var: str) -> str:
    """Compile the stress driver for variant ``var`` (cached under
    ``_native/build/`` keyed by source hashes + flags). Raises
    RuntimeError with the compiler tail on failure."""
    os.makedirs(_BUILD_DIR, exist_ok=True)
    exe = os.path.join(_BUILD_DIR, f"sanstress_{var}")
    stamp = exe + ".stamp"
    want = _stress_stamp(var)
    if os.path.exists(exe):
        try:
            with open(stamp) as f:
                if f.read().strip() == want:
                    return exe
        except OSError:
            pass
    cmd = ["g++", *build_flags(var), "-Wall", "-Wextra", "-Werror",
           "-pthread", "-o", exe + ".tmp", _CORE, _STRESS]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(f"sanstress {var} build failed: "
                           f"{proc.stderr[-500:]}")
    os.replace(exe + ".tmp", exe)
    with open(stamp, "w") as f:
        f.write(want)
    return exe


def run_stress(var: str, scenario: str = "all", seed: int = 42,
               iters: int = 2, timeout: int = 300) -> dict:
    """One stress run; returns {rc, reports, output} — the zero-report
    contract is ``rc == 0 and reports == 0``."""
    exe = build_stress(var)
    env = {**os.environ, **sanitizer_env(var, preload=False)}
    proc = subprocess.run([exe, scenario, str(seed), str(iters)],
                          capture_output=True, text=True,
                          timeout=timeout, env=env)
    out = (proc.stdout or "") + (proc.stderr or "")
    return {"rc": proc.returncode, "reports": count_reports(out),
            "output": out[-4000:]}


def py_lane_script(var: str, n_tasks: int = 400,
                   marker: str = "SANLANE_OK") -> str:
    """The canonical Python-lane workload: a real DTD pool on the
    sanitized variant, asserting the sanitized engine actually engaged
    (variant selected, yield points compiled in, native pool live)
    before printing ``marker``. ONE builder serves the test and bench
    lanes so the two cannot drift apart."""
    return f'''
import parsec_tpu as parsec
from parsec_tpu import _native
from parsec_tpu.data import LocalCollection
from parsec_tpu.dsl import dtd

assert _native.variant() == {var!r}, _native.variant()
assert _native.available(), _native.build_error()
assert _native.load().psan_yield_enabled() == 1   # sanitized variant
ctx = parsec.init(nb_cores=4)
ctx.start()
C = LocalCollection("C", {{(i,): 0 for i in range(8)}})
tp = dtd.Taskpool("sanlane")
ctx.add_taskpool(tp)
def bump(x):
    return x + 1
tp.insert_tasks(bump, [(dtd.TileArg(C, (i % 8,), dtd.INOUT),)
                       for i in range({n_tasks})])
assert tp._native is not None, "sanitized engine must engage"
tp.wait()
assert sum(C.data_of((i,)) for i in range(8)) == {n_tasks}
parsec.fini(ctx)
print({marker!r})
'''


def run_python_lane(var: str, script: str,
                    timeout: int = 600) -> Tuple[int, str]:
    """Run ``script`` in a fresh interpreter on the sanitized variant:
    ``PARSEC_NATIVE_SAN=<var>`` selects the build, the sanitizer
    runtime rides LD_PRELOAD. Returns (rc, combined output). The repo
    root is prepended to PYTHONPATH so the subprocess imports THIS
    checkout."""
    from . import _build
    # build the variant HERE (no preload in this process): the lane
    # subprocess must only dlopen — compiling under LD_PRELOAD would
    # run the compiler itself through the sanitizer
    _build(var)
    repo = os.path.dirname(os.path.dirname(_HERE))
    env = {**os.environ, **sanitizer_env(var, preload=True)}
    env["PARSEC_NATIVE_SAN"] = var
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True,
                          timeout=timeout, env=env)
    return proc.returncode, (proc.stdout or "") + (proc.stderr or "")


def clang_tidy_available() -> bool:
    import shutil
    return shutil.which("clang-tidy") is not None


def run_clang_tidy(checks: str = "concurrency-*,bugprone-*") -> dict:
    """clang-tidy's concurrency/bugprone checks over core.cpp (the
    tier-1 gate runs this only when the binary exists)."""
    proc = subprocess.run(
        ["clang-tidy", f"-checks=-*,{checks}", _CORE, "--",
         "-std=c++17", "-pthread"],
        capture_output=True, text=True, timeout=600)
    out = (proc.stdout or "") + (proc.stderr or "")
    return {"rc": proc.returncode,
            "warnings": out.count(" warning: "),
            "output": out[-4000:]}


def stress_matrix(variants=None, seeds=(42, 7), iters: int = 2,
                  scenarios: Optional[List[str]] = None) -> dict:
    """The bench/CI sweep: every capable variant x seed over the full
    scenario set. Returns per-variant rows with total report counts;
    incapable variants record their skip reason."""
    rows = {}
    for var in (variants or tuple(SAN_FLAGS)):
        reason = capable(var)
        if reason is not None:
            rows[var] = {"skipped": reason}
            continue
        total_reports, worst_rc, runs = 0, 0, []
        for seed in seeds:
            for sc in (scenarios or ["all"]):
                r = run_stress(var, sc, seed=seed, iters=iters)
                total_reports += r["reports"]
                worst_rc = worst_rc or r["rc"]
                runs.append({"scenario": sc, "seed": seed,
                             "rc": r["rc"], "reports": r["reports"]})
                if r["rc"] != 0 or r["reports"]:
                    runs[-1]["output"] = r["output"][-1500:]
        rows[var] = {"reports": total_reports, "rc": worst_rc,
                     "clean": worst_rc == 0 and total_reports == 0,
                     "runs": runs}
    return rows
