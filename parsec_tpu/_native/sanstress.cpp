// sanstress — seeded interleaving-stress driver for the native core
// (ISSUE 14). Compiled TOGETHER with core.cpp into a standalone
// executable, entirely under one sanitizer (tsan/asan/ubsan), with NO
// Python in the process: every frame a sanitizer reports is OUR code,
// so zero-report is an enforceable contract (no suppressions needed —
// the whole point of the lane). The PARSEC_SAN_YIELD injection points
// compiled into core.cpp widen the interleaving space per run; the
// seed argument moves the explored neighborhood.
//
// Scenarios (runnable individually or as "all"):
//   pdtd    — the full dynamic-task engine under contention: an
//             inserter thread staging chained + independent batches
//             through the two-phase insert, W pump threads (native
//             bodies complete inside pdtd_pump; "Python-bodied" tasks
//             are drained through pdtd_pump_batch and completed via
//             pdtd_complete/pdtd_complete_batch), the observability
//             rings enabled with a SMALL initial capacity so growth
//             AND the wrapped drop-oldest regime both run, and a
//             scraper thread hammering pdtd_stats + pdtd_obs_drain
//             CONCURRENT with ring growth — the exact PR 13
//             pdtd_stats-vs-growth data race, pinned here forever.
//             Odd repetitions cancel mid-flight (drop-at-select +
//             cv wakeup), even ones drain cleanly via pdtd_wait_below.
//   plifo   — N threads hammering the lock-free LIFO push/pop (the
//             ABA-tag CAS windows are where PSAN_YIELD digs in).
//   phash   — concurrent insert/find/remove across resize thresholds.
//   pmempool— cross-thread alloc/release (thread-owned freelists).
//   pgraph  — the static-DAG executor on a random layered DAG with a
//             native body, plus pgraph_consume countdown from bodies.
//
// Exit code 0 = scenario invariants held; the sanitizer runtime turns
// any report into a nonzero exit (TSAN_OPTIONS=exitcode=66, ASan
// aborts, UBSan is compiled -fno-sanitize-recover). The invariant
// checks make this double as a correctness stress even unsanitized.

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// the pdtd observability record layout (mirrors core.cpp PdtdObsRec)
struct ObsRec {
  uint64_t t0_ns, t1_ns, q_ns, span;
  uint32_t seq, parent_seq, cls;
  int32_t worker;
};

extern "C" {
void psan_seed(uint64_t seed);
int psan_yield_enabled(void);
// pdtd
void* pdtd_new(int nworkers, uint32_t queue_capacity);
void pdtd_free(void* e);
int64_t pdtd_insert(void* e, uint32_t n, const int32_t* prio,
                    const uint8_t* flags, const uint32_t* npreds,
                    const uint32_t* preds, uint8_t* linked_out,
                    uint32_t cls);
void pdtd_arm(void* e, uint32_t first, uint32_t n);
int pdtd_pump(void* e, int worker, uint32_t* out_tid);
int pdtd_pump_batch(void* e, int worker, uint32_t* out_tids, int cap,
                    int* ran_native);
int pdtd_complete(void* e, int worker, uint32_t tid, uint32_t* drops_out,
                  int32_t drops_cap, int32_t* info_out, uint64_t t0,
                  uint64_t t1);
int pdtd_complete_batch(void* e, int worker, const uint32_t* tids, int n,
                        const uint64_t* t01);
uint32_t pdtd_inflight(void* e);
uint32_t pdtd_ready(void* e);
uint32_t pdtd_wait_below(void* e, uint32_t threshold, int timeout_ms);
void pdtd_cancel(void* e);
void pdtd_stats(void* e, uint64_t* out20);
int pdtd_obs_enable(void* e, uint64_t span_base, uint32_t cap_max);
void pdtd_obs_disable(void* e);
int pdtd_obs_drain(void* e, int worker, ObsRec* out, uint32_t cap_out);
void pdtd_lockdbg_enable(void* e);
// foundation classes
void* plifo_new(uint32_t capacity);
void plifo_free(void* l);
int plifo_push(void* l, uint64_t item);
int plifo_pop(void* l, uint64_t* out);
uint32_t plifo_size(void* l);
void* phash_new(uint32_t nbuckets_hint);
void phash_free(void* h);
int phash_insert(void* h, uint64_t key, uint64_t val);
int phash_find(void* h, uint64_t key, uint64_t* out);
int phash_remove(void* h, uint64_t key, uint64_t* out);
uint64_t phash_size(void* h);
void* pmempool_new(uint32_t elt_size, int nthreads);
void pmempool_free(void* p);
void* pmempool_alloc(void* p, int thread);
void pmempool_release(void* p, int thread, void* elt);
uint64_t pmempool_outstanding(void* p);
typedef int (*pgraph_body_fn)(uint32_t task_id, int32_t worker);
void* pgraph_new(uint32_t n, const int32_t* ndeps, const int32_t* priority,
                 uint64_t m, const uint32_t* esrc, const uint32_t* edst,
                 pgraph_body_fn body, int nworkers);
void pgraph_free(void* g);
int pgraph_run(void* g);
uint32_t pgraph_remaining(void* g);
int pgraph_consume(void* g, uint32_t tid);
}

namespace {

int g_failures = 0;

#define CHECK(cond, ...)                                          \
  do {                                                            \
    if (!(cond)) {                                                \
      std::fprintf(stderr, "CHECK FAILED %s:%d: ", __FILE__,      \
                   __LINE__);                                     \
      std::fprintf(stderr, __VA_ARGS__);                          \
      std::fprintf(stderr, "\n");                                 \
      g_failures++;                                               \
    }                                                             \
  } while (0)

// small deterministic PRNG (seed-reproducible schedules)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed | 1) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
  uint32_t below(uint32_t n) { return (uint32_t)(next() % n); }
};

// ------------------------------------------------------------------ pdtd
// One repetition: insert n_batches of batch sz tasks (mixed native/
// "python"-bodied, chained to random earlier tasks), pump from W
// threads, scrape stats + drain rings concurrently, cancel on odd reps.
void pdtd_rep(uint64_t seed, int rep, int nworkers, int n_batches,
              int batch) {
  void* e = pdtd_new(nworkers, 64);  // tiny plifo: exercise overflow
  CHECK(e != nullptr, "pdtd_new");
  pdtd_lockdbg_enable(e);
  // small cap_max: growth (1024 -> cap) AND drop-oldest both engage
  CHECK(pdtd_obs_enable(e, (1ull << 43), 2048) == 0, "obs_enable");
  const bool cancel_rep = (rep & 1) != 0;
  std::atomic<bool> done{false};
  std::atomic<uint64_t> native_done{0}, python_done{0};

  std::vector<std::thread> pumps;
  for (int w = 0; w < nworkers; ++w) {
    pumps.emplace_back([&, w] {
      Rng r(seed + 1000 + w);
      std::vector<uint32_t> tids(32);
      std::vector<uint64_t> t01(64, 0);
      int32_t info[2];
      uint32_t drops[8];
      while (!done.load(std::memory_order_acquire) ||
             pdtd_inflight(e) > 0) {
        int ran = 0;
        int n = pdtd_pump_batch(e, w, tids.data(), 32, &ran);
        if (ran) native_done.fetch_add(1, std::memory_order_relaxed);
        if (n == 0 && !ran) {
          std::this_thread::yield();
          continue;
        }
        // "python bodies": complete half one-by-one (drop reporting
        // path), half through the batched call
        int half = n / 2;
        for (int i = 0; i < half; ++i) {
          int rc = pdtd_complete(e, w, tids[i], drops, 8, info,
                                 r.next() | 1, r.next() | 1);
          CHECK(rc >= 0, "pdtd_complete rc=%d", rc);
          python_done.fetch_add(1, std::memory_order_relaxed);
        }
        if (n > half) {
          int rc = pdtd_complete_batch(e, w, tids.data() + half,
                                       n - half, t01.data());
          CHECK(rc >= 0, "pdtd_complete_batch rc=%d", rc);
          python_done.fetch_add(n - half, std::memory_order_relaxed);
        }
      }
    });
  }
  // concurrent scraper: pdtd_stats + ring drains DURING growth (the
  // PR 13 pdtd_stats-vs-ring-growth race regression — satellite 1)
  std::thread scraper([&] {
    uint64_t st[20];
    std::vector<ObsRec> buf(2048);
    while (!done.load(std::memory_order_acquire) ||
           pdtd_inflight(e) > 0) {
      pdtd_stats(e, st);
      CHECK(st[18] == 0, "lock-order pair recorded: mask=%llu",
            (unsigned long long)st[18]);
      for (int w = 0; w < nworkers; ++w) {
        int n = pdtd_obs_drain(e, w, buf.data(), 2048);
        CHECK(n >= 0, "obs_drain rc=%d", n);
      }
      std::this_thread::yield();
    }
  });

  Rng r(seed + rep);
  std::vector<int32_t> prio(batch);
  std::vector<uint8_t> flags(batch);
  std::vector<uint32_t> npreds(batch);
  std::vector<uint32_t> preds;
  std::vector<uint8_t> linked;
  uint32_t inserted = 0;
  for (int b = 0; b < n_batches; ++b) {
    preds.clear();
    for (int i = 0; i < batch; ++i) {
      prio[i] = (int32_t)r.below(7);
      flags[i] = (uint8_t)(r.below(2));  // mix native / python bodies
      uint32_t np = inserted ? r.below(3) : 0;
      npreds[i] = np;
      for (uint32_t k = 0; k < np; ++k)
        preds.push_back(r.below(inserted));  // any earlier task
    }
    linked.assign(preds.size() ? preds.size() : 1, 0);
    int64_t first = pdtd_insert(e, batch, prio.data(), flags.data(),
                                npreds.data(), preds.data(),
                                linked.data(), 0);
    CHECK(first == (int64_t)inserted, "insert first=%lld want %u",
          (long long)first, inserted);
    pdtd_arm(e, (uint32_t)first, batch);
    inserted += batch;
    if (cancel_rep && b == n_batches / 2) pdtd_cancel(e);
    if ((b & 3) == 0) pdtd_wait_below(e, batch * 4, 50);
  }
  // drain: every inserted task must leave flight (completed or
  // cancel-dropped) — a stuck countdown would hang here, so bound it
  auto t0 = std::chrono::steady_clock::now();
  while (pdtd_inflight(e) > 0) {
    pdtd_wait_below(e, 0, 100);
    if (std::chrono::steady_clock::now() - t0 >
        std::chrono::seconds(60)) {
      CHECK(false, "drain timed out: inflight=%u ready=%u",
            pdtd_inflight(e), pdtd_ready(e));
      break;
    }
  }
  done.store(true, std::memory_order_release);
  for (auto& t : pumps) t.join();
  scraper.join();
  uint64_t st[20];
  pdtd_stats(e, st);
  CHECK(st[0] == inserted, "inserted=%llu want %u",
        (unsigned long long)st[0], inserted);
  // completed + cancel-dropped account for every inserted task
  uint64_t accounted = st[6] + st[7] + st[10];
  CHECK(accounted == inserted, "accounted=%llu want %u (cancel=%d)",
        (unsigned long long)accounted, inserted, (int)cancel_rep);
  CHECK(st[18] == 0, "lock pairs must stay 0, got mask=%llu",
        (unsigned long long)st[18]);
  if (!cancel_rep)
    CHECK(st[15] + st[16] >= inserted,
          "obs recorded+dropped=%llu < inserted=%u",
          (unsigned long long)(st[15] + st[16]), inserted);
  pdtd_obs_disable(e);
  pdtd_free(e);
}

void scenario_pdtd(uint64_t seed, int iters) {
  for (int rep = 0; rep < iters; ++rep)
    pdtd_rep(seed, rep, 4, 40, 128);
}

// ----------------------------------------------------------------- plifo
void scenario_plifo(uint64_t seed, int iters) {
  void* l = plifo_new(512);
  CHECK(l != nullptr, "plifo_new");
  const int T = 6;
  std::atomic<uint64_t> pushed{0}, popped{0};
  std::vector<std::thread> ths;
  for (int t = 0; t < T; ++t) {
    ths.emplace_back([&, t] {
      Rng r(seed + t);
      uint64_t v;
      for (int i = 0; i < iters * 4000; ++i) {
        if (r.below(2)) {
          if (plifo_push(l, r.next()) == 0)
            pushed.fetch_add(1, std::memory_order_relaxed);
        } else if (plifo_pop(l, &v)) {
          popped.fetch_add(1, std::memory_order_relaxed);
        }
      }
    });
  }
  for (auto& t : ths) t.join();
  uint64_t v;
  uint64_t drained = 0;
  while (plifo_pop(l, &v)) drained++;
  CHECK(pushed.load() == popped.load() + drained,
        "plifo conservation: pushed=%llu popped=%llu drained=%llu",
        (unsigned long long)pushed.load(),
        (unsigned long long)popped.load(), (unsigned long long)drained);
  CHECK(plifo_size(l) == 0, "plifo size after drain");
  plifo_free(l);
}

// ----------------------------------------------------------------- phash
void scenario_phash(uint64_t seed, int iters) {
  void* h = phash_new(16);  // tiny: force resizes under load
  CHECK(h != nullptr, "phash_new");
  const int T = 4;
  std::vector<std::thread> ths;
  for (int t = 0; t < T; ++t) {
    ths.emplace_back([&, t] {
      Rng r(seed + 31 * t);
      uint64_t out;
      for (int i = 0; i < iters * 2500; ++i) {
        // per-thread key range + a shared overlapping range
        uint64_t key = r.below(2) ? (uint64_t)t << 32 | r.below(512)
                                  : r.below(256);
        switch (r.below(3)) {
          case 0: phash_insert(h, key, key * 3); break;
          case 1:
            if (phash_find(h, key, &out))
              CHECK(out == key * 3, "phash value for %llu",
                    (unsigned long long)key);
            break;
          default: phash_remove(h, key, nullptr); break;
        }
      }
    });
  }
  for (auto& t : ths) t.join();
  phash_free(h);
}

// -------------------------------------------------------------- pmempool
void scenario_pmempool(uint64_t seed, int iters) {
  const int T = 4;
  void* p = pmempool_new(96, T);
  CHECK(p != nullptr, "pmempool_new");
  std::vector<std::thread> ths;
  for (int t = 0; t < T; ++t) {
    ths.emplace_back([&, t] {
      Rng r(seed + 7 * t);
      std::vector<void*> mine;
      for (int i = 0; i < iters * 2000; ++i) {
        if (mine.size() < 16 && r.below(2)) {
          void* e = pmempool_alloc(p, t);
          CHECK(e != nullptr, "pmempool_alloc");
          std::memset(e, t, 96);  // touch: ASan would catch overlap
          mine.push_back(e);
        } else if (!mine.empty()) {
          // cross-thread release path half the time
          pmempool_release(p, r.below(2) ? t : (t + 1) % T,
                           mine.back());
          mine.pop_back();
        }
      }
      for (void* e : mine) pmempool_release(p, t, e);
    });
  }
  for (auto& t : ths) t.join();
  CHECK(pmempool_outstanding(p) == 0, "pmempool outstanding=%llu",
        (unsigned long long)pmempool_outstanding(p));
  pmempool_free(p);
}

// ---------------------------------------------------------------- pgraph
std::atomic<uint64_t> g_body_runs{0};
void* g_graph = nullptr;

int graph_body(uint32_t tid, int32_t worker) {
  (void)worker;
  g_body_runs.fetch_add(1, std::memory_order_relaxed);
  // consume this task's own output consumers' view of a PRED: model
  // the Python executor's read-then-consume on every incoming edge is
  // driven from Python; here just hammer the atomic countdown path
  pgraph_consume(g_graph, tid);
  return 0;
}

void scenario_pgraph(uint64_t seed, int iters) {
  for (int rep = 0; rep < iters; ++rep) {
    Rng r(seed + rep);
    const uint32_t layers = 6, width = 32, n = layers * width;
    std::vector<uint32_t> esrc, edst;
    for (uint32_t L = 1; L < layers; ++L)
      for (uint32_t i = 0; i < width; ++i)
        for (int k = 0; k < 3; ++k) {
          esrc.push_back((L - 1) * width + r.below(width));
          edst.push_back(L * width + i);
        }
    std::vector<int32_t> ndeps(n, 0), prio(n);
    for (uint32_t d : edst) ndeps[d]++;
    for (uint32_t i = 0; i < n; ++i) prio[i] = (int32_t)r.below(5);
    g_body_runs.store(0);
    void* g = pgraph_new(n, ndeps.data(), prio.data(), esrc.size(),
                         esrc.data(), edst.data(), graph_body, 4);
    CHECK(g != nullptr, "pgraph_new");
    g_graph = g;
    CHECK(pgraph_run(g) == 0, "pgraph_run");
    CHECK(pgraph_remaining(g) == 0, "pgraph remaining");
    CHECK(g_body_runs.load() == n, "bodies ran %llu want %u",
          (unsigned long long)g_body_runs.load(), n);
    pgraph_free(g);
  }
}

}  // namespace

int main(int argc, char** argv) {
  std::string scenario = argc > 1 ? argv[1] : "all";
  uint64_t seed = argc > 2 ? std::strtoull(argv[2], nullptr, 10) : 42;
  int iters = argc > 3 ? std::atoi(argv[3]) : 2;
  psan_seed(seed);
  std::printf("sanstress scenario=%s seed=%llu iters=%d yield=%d\n",
              scenario.c_str(), (unsigned long long)seed, iters,
              psan_yield_enabled());
  bool all = scenario == "all";
  bool known = all;
  if (all || scenario == "pdtd") { scenario_pdtd(seed, iters); known = true; }
  if (all || scenario == "plifo") { scenario_plifo(seed, iters); known = true; }
  if (all || scenario == "phash") { scenario_phash(seed, iters); known = true; }
  if (all || scenario == "pmempool") {
    scenario_pmempool(seed, iters);
    known = true;
  }
  if (all || scenario == "pgraph") { scenario_pgraph(seed, iters); known = true; }
  if (!known) {
    std::fprintf(stderr, "unknown scenario %s\n", scenario.c_str());
    return 2;
  }
  std::printf("sanstress %s: %s\n", scenario.c_str(),
              g_failures ? "FAILED" : "OK");
  return g_failures ? 1 : 0;
}
