"""Native C++ core loader.

Builds ``core.cpp`` into a shared library with g++ on first use (cached
next to the source, keyed by a hash of the source — an edited core.cpp
rebuilds instead of silently loading the stale binary) and exposes it
through ctypes. The Python runtime falls back to its pure-Python
implementations when the toolchain is unavailable (``load() -> None``),
so the package works everywhere; ``build_error()`` reports WHY the
library is missing so callers that require it (``runtime.native_dtd=1``)
can fail loudly instead of silently degrading. On a real deployment the
native engine carries the dependency-tracking, dynamic-task (DTD), and
static-DAG execution hot paths, mirroring the reference where those
layers are native C (parsec/parsec.c, parsec/scheduling.c,
parsec/interfaces/dtd/insert_function.c, parsec/class/*).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "core.cpp")
_SO = os.path.join(_HERE, "libparsec_core.so")
_STAMP = _SO + ".srchash"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_build_error: Optional[str] = None

BODY_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32, ctypes.c_int32)

#: pdtd_stats slot names, in the C ABI's out[20] order. The obs_* rows
#: are the native observability plane (ISSUE 13): records written to /
#: dropped from the per-worker event rings, plus the current ring depth
#: (a gauge — excluded from the context's retired-pool folding, like
#: inflight/ready).
PDTD_STAT_KEYS = (
    "inserted", "linked_deps", "ready_pushed", "popped", "stolen",
    "overflow_pushed", "completed_native", "completed_python",
    "released_edges", "output_drops", "dropped_cancelled",
    "ring_highwater", "inflight", "ready", "pump_calls",
    "obs_recorded", "obs_dropped", "obs_ring_depth",
    "reserved", "reserved")

#: numpy dtype mirroring the C PdtdObsRec (48-byte fixed stride): one
#: binary record per completed native-engine task, expanded to the
#: PR 9 trace-record format at scrape time (profiling/trace.py)
OBS_REC_FIELDS = [("t0_ns", "<u8"), ("t1_ns", "<u8"), ("q_ns", "<u8"),
                  ("span", "<u8"), ("seq", "<u4"), ("parent_seq", "<u4"),
                  ("cls", "<u4"), ("worker", "<i4")]
OBS_PARENT_NONE = 0xFFFFFFFF


def obs_dtype():
    import numpy as np
    dt = np.dtype(OBS_REC_FIELDS)
    assert dt.itemsize == 48, dt.itemsize   # must match the C struct
    return dt


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def _build() -> bool:
    global _build_error
    try:
        want = _src_hash()
    except OSError as exc:
        _build_error = f"cannot read {_SRC}: {exc}"
        return False
    if os.path.exists(_SO):
        try:
            with open(_STAMP) as f:
                have = f.read().strip()
        except OSError:
            have = ""               # pre-hash .so (or stamp lost): rebuild
        if have == want:
            return True
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _SO + ".tmp", _SRC]
    try:
        proc = subprocess.run(cmd, check=True, capture_output=True,
                              timeout=120)
        del proc
        os.replace(_SO + ".tmp", _SO)
        with open(_STAMP, "w") as f:
            f.write(want)
        return True
    except FileNotFoundError:
        _build_error = "g++ not found on PATH"
    except subprocess.CalledProcessError as exc:
        tail = (exc.stderr or b"").decode(errors="replace")[-500:]
        _build_error = f"g++ failed (rc={exc.returncode}): {tail}"
    except (OSError, subprocess.SubprocessError) as exc:
        _build_error = f"build failed: {exc}"
    # rebuild impossible but a (prebuilt / stampless) .so exists: try
    # it — a deployment shipping the binary without the toolchain must
    # not lose the native engine; a STALE binary missing newly-added
    # symbols fails the bind cleanly (load()'s AttributeError guard)
    return os.path.exists(_SO)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, i32, p = (ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int32,
                        ctypes.c_void_p)
    lib.pdep_new.restype = p
    lib.pdep_free.argtypes = [p]
    lib.pdep_size.argtypes = [p]
    lib.pdep_size.restype = u64
    lib.pdep_update.argtypes = [p, u64, u64, u32, ctypes.c_int, i32,
                                ctypes.POINTER(i32)]
    lib.pdep_update.restype = ctypes.c_int
    lib.pdep_finalize.argtypes = [p, u64, u64, ctypes.c_int,
                                  ctypes.POINTER(i32)]
    lib.pdep_finalize.restype = ctypes.c_int
    lib.plevel_kahn.argtypes = [u64, u64, ctypes.POINTER(u32),
                                ctypes.POINTER(u32), ctypes.POINTER(i32)]
    lib.plevel_kahn.restype = ctypes.c_int
    lib.pgraph_new.argtypes = [u32, ctypes.POINTER(i32), ctypes.POINTER(i32),
                               u64, ctypes.POINTER(u32), ctypes.POINTER(u32),
                               BODY_FN, ctypes.c_int]
    lib.pgraph_new.restype = p
    lib.pgraph_free.argtypes = [p]
    lib.pgraph_run.argtypes = [p]
    lib.pgraph_run.restype = ctypes.c_int
    lib.pgraph_remaining.argtypes = [p]
    lib.pgraph_remaining.restype = u32
    lib.pgraph_consume.argtypes = [p, u32]
    lib.pgraph_consume.restype = ctypes.c_int
    # pdtd: dynamic-task engine (DTD insert→release hot loop)
    lib.pdtd_new.argtypes = [ctypes.c_int, u32]
    lib.pdtd_new.restype = p
    lib.pdtd_free.argtypes = [p]
    lib.pdtd_insert.argtypes = [p, u32, ctypes.POINTER(i32),
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.POINTER(u32), ctypes.POINTER(u32),
                                ctypes.POINTER(ctypes.c_uint8), u32]
    lib.pdtd_insert.restype = ctypes.c_int64
    lib.pdtd_arm.argtypes = [p, u32, u32]
    lib.pdtd_pump.argtypes = [p, ctypes.c_int, ctypes.POINTER(u32)]
    lib.pdtd_pump.restype = ctypes.c_int
    lib.pdtd_pump_batch.argtypes = [p, ctypes.c_int, ctypes.POINTER(u32),
                                    ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.pdtd_pump_batch.restype = ctypes.c_int
    lib.pdtd_complete.argtypes = [p, ctypes.c_int, u32,
                                  ctypes.POINTER(u32), i32,
                                  ctypes.POINTER(i32), u64, u64]
    lib.pdtd_complete.restype = ctypes.c_int
    lib.pdtd_complete_batch.argtypes = [p, ctypes.c_int,
                                        ctypes.POINTER(u32), ctypes.c_int,
                                        ctypes.POINTER(u64)]
    lib.pdtd_complete_batch.restype = ctypes.c_int
    lib.pdtd_inflight.argtypes = [p]
    lib.pdtd_inflight.restype = u32
    lib.pdtd_ready.argtypes = [p]
    lib.pdtd_ready.restype = u32
    lib.pdtd_wait_below.argtypes = [p, u32, ctypes.c_int]
    lib.pdtd_wait_below.restype = u32
    lib.pdtd_cancel.argtypes = [p]
    lib.pdtd_stats.argtypes = [p, ctypes.POINTER(u64)]
    # pdtd observability plane (ISSUE 13): per-worker event rings
    lib.pdtd_obs_now.argtypes = []
    lib.pdtd_obs_now.restype = u64
    lib.pdtd_obs_enable.argtypes = [p, u64, u32]
    lib.pdtd_obs_enable.restype = ctypes.c_int
    lib.pdtd_obs_disable.argtypes = [p]
    lib.pdtd_obs_drain.argtypes = [p, ctypes.c_int, p, u32]
    lib.pdtd_obs_drain.restype = ctypes.c_int
    # foundation classes (reference parsec/class/*)
    lib.plifo_new.argtypes = [u32]
    lib.plifo_new.restype = p
    lib.plifo_free.argtypes = [p]
    lib.plifo_push.argtypes = [p, u64]
    lib.plifo_push.restype = ctypes.c_int
    lib.plifo_pop.argtypes = [p, ctypes.POINTER(u64)]
    lib.plifo_pop.restype = ctypes.c_int
    lib.plifo_size.argtypes = [p]
    lib.plifo_size.restype = u32
    lib.phash_new.argtypes = [u32]
    lib.phash_new.restype = p
    lib.phash_free.argtypes = [p]
    lib.phash_insert.argtypes = [p, u64, u64]
    lib.phash_insert.restype = ctypes.c_int
    lib.phash_find.argtypes = [p, u64, ctypes.POINTER(u64)]
    lib.phash_find.restype = ctypes.c_int
    lib.phash_remove.argtypes = [p, u64, ctypes.POINTER(u64)]
    lib.phash_remove.restype = ctypes.c_int
    lib.phash_size.argtypes = [p]
    lib.phash_size.restype = u64
    lib.pmempool_new.argtypes = [u32, ctypes.c_int]
    lib.pmempool_new.restype = p
    lib.pmempool_free.argtypes = [p]
    lib.pmempool_alloc.argtypes = [p, ctypes.c_int]
    lib.pmempool_alloc.restype = p
    lib.pmempool_release.argtypes = [p, ctypes.c_int, p]
    lib.pmempool_outstanding.argtypes = [p]
    lib.pmempool_outstanding.restype = u64
    lib.pmempool_allocated.argtypes = [p]
    lib.pmempool_allocated.restype = u64
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when it cannot be built/loaded."""
    global _lib, _tried, _build_error
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PARSEC_NO_NATIVE"):
            _build_error = "disabled by PARSEC_NO_NATIVE"
            return None
        if not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError as exc:
            _build_error = f"dlopen({_SO}) failed: {exc}"
            _lib = None
        except AttributeError as exc:
            # a stale .so missing newly-added symbols: the source-hash
            # stamp normally prevents this; surface it instead of a
            # confusing partial bind
            _build_error = f"stale {_SO}: {exc}"
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def build_error() -> Optional[str]:
    """Why the native library is unavailable (None when it loaded, or
    when load() was never attempted)."""
    load()
    return None if _lib is not None else \
        (_build_error or "native library unavailable")


def kahn_levels(n: int, edges) -> "Optional[list]":
    """Batch-level a DAG natively; edges = iterable of (src, dst).
    Returns per-task levels, or None if native is unavailable.
    Raises RuntimeError on a cycle."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    src = np.fromiter((e[0] for e in edges), dtype=np.uint32,
                      count=len(edges))
    dst = np.fromiter((e[1] for e in edges), dtype=np.uint32,
                      count=len(edges))
    out = np.zeros(n, dtype=np.int32)
    rc = lib.plevel_kahn(
        n, len(edges),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc == -1:
        raise RuntimeError("DAG has a cycle")
    if rc != 0:
        raise RuntimeError(f"plevel_kahn failed: {rc}")
    return out.tolist()
