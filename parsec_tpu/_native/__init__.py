"""Native C++ core loader.

Builds ``core.cpp`` into a shared library with g++ on first use (cached
next to the source, keyed by a hash of the source — an edited core.cpp
rebuilds instead of silently loading the stale binary) and exposes it
through ctypes. The Python runtime falls back to its pure-Python
implementations when the toolchain is unavailable (``load() -> None``),
so the package works everywhere; ``build_error()`` reports WHY the
library is missing so callers that require it (``runtime.native_dtd=1``)
can fail loudly instead of silently degrading. On a real deployment the
native engine carries the dependency-tracking, dynamic-task (DTD), and
static-DAG execution hot paths, mirroring the reference where those
layers are native C (parsec/parsec.c, parsec/scheduling.c,
parsec/interfaces/dtd/insert_function.c, parsec/class/*).

Sanitizer build lane (ISSUE 14): ``native.sanitize = off|tsan|asan|
ubsan`` (MCA knob; env ``PARSEC_NATIVE_SAN`` wins so sanitized
subprocesses need no MCA plumbing) selects a BUILD VARIANT. Each
variant compiles to its own cached binary (``libparsec_core.tsan.so``,
…) whose stamp records the source hash AND the flag set, so sanitized
and production binaries coexist and neither can be served stale for
the other. Sanitizer variants compile with ``-DPARSEC_SAN_YIELD=1``
(the seeded yield-injection points that widen the explored
interleaving space) at ``-O1 -g``; the production variant is exactly
the PR 10 build. Loading a sanitized variant into a Python process
requires the sanitizer runtime to be preloaded (``LD_PRELOAD`` of
:func:`sanitizer_runtime`'s path) — ``_native/sanlane.py`` wraps that
subprocess dance and the all-native stress driver
(``sanstress.cpp``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Dict, Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "core.cpp")
_SO = os.path.join(_HERE, "libparsec_core.so")
_STAMP = _SO + ".srchash"

#: sanitizer variants: variant -> the g++ flags that define it. The
#: production variant ("off") is the plain -O2 build; every sanitizer
#: variant compiles the PSAN_YIELD injection points in.
SAN_FLAGS = {
    "tsan": ("-fsanitize=thread",),
    "asan": ("-fsanitize=address", "-fno-omit-frame-pointer"),
    "ubsan": ("-fsanitize=undefined", "-fno-sanitize-recover=undefined"),
}
#: gcc runtime library each variant's .so needs preloaded when loaded
#: into an unsanitized host process (CPython)
SAN_RUNTIME_LIB = {"tsan": "libtsan.so", "asan": "libasan.so",
                   "ubsan": "libubsan.so"}
#: pdtd lock-discipline recorder domains, in C enum order (core.cpp
#: PdtdLockDomain) — index = domain id inside the pdtd_stats
#: ``lock_pairs`` bitmask (bit held*5+acquired)
PDTD_LOCK_DOMAINS = ("entry", "grow", "overflow", "cv", "ring")

try:                                    # MCA knob for the lane; the env
    from ..utils import mca_param as _mca   # var PARSEC_NATIVE_SAN wins
    _mca.register(
        "native.sanitize", "off",
        choices=("off", "tsan", "asan", "ubsan"),
        help="native-core build variant: off (production -O2) | "
             "tsan/asan/ubsan (sanitizer-instrumented, cached "
             "per-variant; env PARSEC_NATIVE_SAN overrides)")
except Exception:  # pragma: no cover — direct import outside the pkg
    _mca = None

_lock = threading.Lock()
_libs: Dict[str, Optional[ctypes.CDLL]] = {}
_tried_variants: set = set()
_build_errors: Dict[str, str] = {}

BODY_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32, ctypes.c_int32)

#: pdtd_stats slot names, in the C ABI's out[20] order. The obs_* rows
#: are the native observability plane (ISSUE 13): records written to /
#: dropped from the per-worker event rings, plus the current ring depth
#: (a gauge — excluded from the context's retired-pool folding, like
#: inflight/ready).
PDTD_STAT_KEYS = (
    "inserted", "linked_deps", "ready_pushed", "popped", "stolen",
    "overflow_pushed", "completed_native", "completed_python",
    "released_edges", "output_drops", "dropped_cancelled",
    "ring_highwater", "inflight", "ready", "pump_calls",
    "obs_recorded", "obs_dropped", "obs_ring_depth",
    # lock-discipline recorder (ISSUE 14): lock_pairs is the
    # (held*5+acquired) acquisition-pair BITMASK over
    # PDTD_LOCK_DOMAINS — OR-folded across engines, never summed;
    # lock_acquires counts recorded acquisitions (0 unless
    # pdtd_lockdbg_enable was called)
    "lock_pairs", "lock_acquires")

#: numpy dtype mirroring the C PdtdObsRec (48-byte fixed stride): one
#: binary record per completed native-engine task, expanded to the
#: PR 9 trace-record format at scrape time (profiling/trace.py)
OBS_REC_FIELDS = [("t0_ns", "<u8"), ("t1_ns", "<u8"), ("q_ns", "<u8"),
                  ("span", "<u8"), ("seq", "<u4"), ("parent_seq", "<u4"),
                  ("cls", "<u4"), ("worker", "<i4")]
OBS_PARENT_NONE = 0xFFFFFFFF


def obs_dtype():
    import numpy as np
    dt = np.dtype(OBS_REC_FIELDS)
    assert dt.itemsize == 48, dt.itemsize   # must match the C struct
    return dt


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def variant() -> str:
    """The ACTIVE build variant: env ``PARSEC_NATIVE_SAN`` first (so a
    sanitized subprocess lane needs only one env var), then the
    ``native.sanitize`` MCA knob. Unknown values raise — a typo'd
    sanitizer name must not silently mean "production build"."""
    v = os.environ.get("PARSEC_NATIVE_SAN", "").strip().lower()
    if not v and _mca is not None:
        v = str(_mca.get("native.sanitize", "off")).strip().lower()
    if v in ("", "0", "off", "none", "false"):
        return "off"
    if v not in SAN_FLAGS:
        raise ValueError(
            f"unknown native sanitizer variant {v!r}; choices are "
            f"off, {', '.join(sorted(SAN_FLAGS))}")
    return v


def so_path(var: str = "off") -> str:
    """Per-variant binary path: sanitized and production .so coexist."""
    return _SO if var == "off" else \
        os.path.join(_HERE, f"libparsec_core.{var}.so")


def build_flags(var: str = "off"):
    """The g++ flag set defining variant ``var`` (part of its cache
    stamp — a flag change rebuilds)."""
    if var == "off":
        return ["-O2", "-std=c++17"]
    return ["-O1", "-g", "-DPARSEC_SAN_YIELD=1", *SAN_FLAGS[var],
            "-std=c++17"]


def _stamp_want(var: str) -> str:
    # production stamp stays the bare source hash (the PR 10 format, so
    # an existing deployment's stamp remains valid); variant stamps add
    # the flag set
    h = _src_hash()
    return h if var == "off" else h + " " + " ".join(build_flags(var))


def sanitizer_runtime(var: str) -> Optional[str]:
    """Absolute path of the gcc sanitizer runtime to LD_PRELOAD when
    loading variant ``var``'s .so into an unsanitized process, or None
    when unresolvable (no g++ / static-only runtime)."""
    name = SAN_RUNTIME_LIB.get(var)
    if name is None:
        return None
    try:
        out = subprocess.run(["g++", f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
        path = out.stdout.strip()
        if path and path != name and os.path.exists(path):
            return os.path.abspath(path)
    except (OSError, subprocess.SubprocessError):
        pass
    return None


def _build(var: str = "off") -> bool:
    so = so_path(var)
    stamp = so + ".srchash"
    try:
        want = _stamp_want(var)
    except OSError as exc:
        _build_errors[var] = f"cannot read {_SRC}: {exc}"
        return False
    if os.path.exists(so):
        try:
            with open(stamp) as f:
                have = f.read().strip()
        except OSError:
            have = ""               # pre-hash .so (or stamp lost): rebuild
        if have == want:
            return True
    cmd = ["g++", *build_flags(var), "-shared", "-fPIC", "-pthread",
           "-o", so + ".tmp", _SRC]
    # never compile UNDER a sanitizer runtime: a sanitized Python lane
    # (LD_PRELOAD=libtsan) would otherwise run g++/cc1plus themselves
    # through TSan's shadow — observed as a multi-minute hang
    env = dict(os.environ)
    env.pop("LD_PRELOAD", None)
    try:
        proc = subprocess.run(cmd, check=True, capture_output=True,
                              timeout=240, env=env)
        del proc
        os.replace(so + ".tmp", so)
        with open(stamp, "w") as f:
            f.write(want)
        return True
    except FileNotFoundError:
        _build_errors[var] = "g++ not found on PATH"
    except subprocess.CalledProcessError as exc:
        tail = (exc.stderr or b"").decode(errors="replace")[-500:]
        _build_errors[var] = f"g++ failed (rc={exc.returncode}): {tail}"
    except (OSError, subprocess.SubprocessError) as exc:
        _build_errors[var] = f"build failed: {exc}"
    # rebuild impossible but a (prebuilt / stampless) .so exists: try
    # it — a deployment shipping the binary without the toolchain must
    # not lose the native engine; a STALE binary missing newly-added
    # symbols fails the bind cleanly (load()'s AttributeError guard).
    # Sanitizer variants never take this fallback: an unverifiable
    # sanitized binary would undermine the zero-report contract.
    return var == "off" and os.path.exists(so)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, i32, p = (ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int32,
                        ctypes.c_void_p)
    lib.pdep_new.restype = p
    lib.pdep_free.argtypes = [p]
    lib.pdep_size.argtypes = [p]
    lib.pdep_size.restype = u64
    lib.pdep_update.argtypes = [p, u64, u64, u32, ctypes.c_int, i32,
                                ctypes.POINTER(i32)]
    lib.pdep_update.restype = ctypes.c_int
    lib.pdep_finalize.argtypes = [p, u64, u64, ctypes.c_int,
                                  ctypes.POINTER(i32)]
    lib.pdep_finalize.restype = ctypes.c_int
    lib.plevel_kahn.argtypes = [u64, u64, ctypes.POINTER(u32),
                                ctypes.POINTER(u32), ctypes.POINTER(i32)]
    lib.plevel_kahn.restype = ctypes.c_int
    lib.pgraph_new.argtypes = [u32, ctypes.POINTER(i32), ctypes.POINTER(i32),
                               u64, ctypes.POINTER(u32), ctypes.POINTER(u32),
                               BODY_FN, ctypes.c_int]
    lib.pgraph_new.restype = p
    lib.pgraph_free.argtypes = [p]
    lib.pgraph_run.argtypes = [p]
    lib.pgraph_run.restype = ctypes.c_int
    lib.pgraph_remaining.argtypes = [p]
    lib.pgraph_remaining.restype = u32
    lib.pgraph_consume.argtypes = [p, u32]
    lib.pgraph_consume.restype = ctypes.c_int
    # pdtd: dynamic-task engine (DTD insert→release hot loop)
    lib.pdtd_new.argtypes = [ctypes.c_int, u32]
    lib.pdtd_new.restype = p
    lib.pdtd_free.argtypes = [p]
    lib.pdtd_insert.argtypes = [p, u32, ctypes.POINTER(i32),
                                ctypes.POINTER(ctypes.c_uint8),
                                ctypes.POINTER(u32), ctypes.POINTER(u32),
                                ctypes.POINTER(ctypes.c_uint8), u32]
    lib.pdtd_insert.restype = ctypes.c_int64
    lib.pdtd_arm.argtypes = [p, u32, u32]
    lib.pdtd_pump.argtypes = [p, ctypes.c_int, ctypes.POINTER(u32)]
    lib.pdtd_pump.restype = ctypes.c_int
    lib.pdtd_pump_batch.argtypes = [p, ctypes.c_int, ctypes.POINTER(u32),
                                    ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.pdtd_pump_batch.restype = ctypes.c_int
    lib.pdtd_complete.argtypes = [p, ctypes.c_int, u32,
                                  ctypes.POINTER(u32), i32,
                                  ctypes.POINTER(i32), u64, u64]
    lib.pdtd_complete.restype = ctypes.c_int
    lib.pdtd_complete_batch.argtypes = [p, ctypes.c_int,
                                        ctypes.POINTER(u32), ctypes.c_int,
                                        ctypes.POINTER(u64)]
    lib.pdtd_complete_batch.restype = ctypes.c_int
    lib.pdtd_inflight.argtypes = [p]
    lib.pdtd_inflight.restype = u32
    lib.pdtd_ready.argtypes = [p]
    lib.pdtd_ready.restype = u32
    lib.pdtd_wait_below.argtypes = [p, u32, ctypes.c_int]
    lib.pdtd_wait_below.restype = u32
    lib.pdtd_cancel.argtypes = [p]
    lib.pdtd_stats.argtypes = [p, ctypes.POINTER(u64)]
    # sanitizer lane + lock-discipline recorder (ISSUE 14)
    lib.psan_seed.argtypes = [u64]
    lib.psan_yield_enabled.restype = ctypes.c_int
    lib.pdtd_lockdbg_enable.argtypes = [p]
    # pdtd observability plane (ISSUE 13): per-worker event rings
    lib.pdtd_obs_now.argtypes = []
    lib.pdtd_obs_now.restype = u64
    lib.pdtd_obs_enable.argtypes = [p, u64, u32]
    lib.pdtd_obs_enable.restype = ctypes.c_int
    lib.pdtd_obs_disable.argtypes = [p]
    lib.pdtd_obs_drain.argtypes = [p, ctypes.c_int, p, u32]
    lib.pdtd_obs_drain.restype = ctypes.c_int
    # foundation classes (reference parsec/class/*)
    lib.plifo_new.argtypes = [u32]
    lib.plifo_new.restype = p
    lib.plifo_free.argtypes = [p]
    lib.plifo_push.argtypes = [p, u64]
    lib.plifo_push.restype = ctypes.c_int
    lib.plifo_pop.argtypes = [p, ctypes.POINTER(u64)]
    lib.plifo_pop.restype = ctypes.c_int
    lib.plifo_size.argtypes = [p]
    lib.plifo_size.restype = u32
    lib.phash_new.argtypes = [u32]
    lib.phash_new.restype = p
    lib.phash_free.argtypes = [p]
    lib.phash_insert.argtypes = [p, u64, u64]
    lib.phash_insert.restype = ctypes.c_int
    lib.phash_find.argtypes = [p, u64, ctypes.POINTER(u64)]
    lib.phash_find.restype = ctypes.c_int
    lib.phash_remove.argtypes = [p, u64, ctypes.POINTER(u64)]
    lib.phash_remove.restype = ctypes.c_int
    lib.phash_size.argtypes = [p]
    lib.phash_size.restype = u64
    lib.pmempool_new.argtypes = [u32, ctypes.c_int]
    lib.pmempool_new.restype = p
    lib.pmempool_free.argtypes = [p]
    lib.pmempool_alloc.argtypes = [p, ctypes.c_int]
    lib.pmempool_alloc.restype = p
    lib.pmempool_release.argtypes = [p, ctypes.c_int, p]
    lib.pmempool_outstanding.argtypes = [p]
    lib.pmempool_outstanding.restype = u64
    lib.pmempool_allocated.argtypes = [p]
    lib.pmempool_allocated.restype = u64
    return lib


def load(var: Optional[str] = None) -> Optional[ctypes.CDLL]:
    """The native library for build variant ``var`` (default: the
    ACTIVE variant — ``native.sanitize`` / ``PARSEC_NATIVE_SAN``), or
    None when it cannot be built/loaded. Loading a sanitizer variant
    requires its runtime preloaded into the process (sanlane.py runs
    that in a subprocess); a bare dlopen without it fails here and the
    error names the runtime."""
    try:
        v = variant() if var is None else var
    except ValueError:
        # build_error() re-derives the message from variant() itself
        return None
    with _lock:
        if v in _tried_variants:
            return _libs.get(v)
        _tried_variants.add(v)
        _libs[v] = None
        lib = None
        if os.environ.get("PARSEC_NO_NATIVE"):
            _build_errors[v] = "disabled by PARSEC_NO_NATIVE"
        elif not _build(v):
            _build_errors.setdefault(v, "build failed")
        else:
            so = so_path(v)
            try:
                lib = _bind(ctypes.CDLL(so))
            except OSError as exc:
                hint = ""
                if v != "off":
                    rt = sanitizer_runtime(v)
                    hint = (f" (sanitized variant: LD_PRELOAD="
                            f"{rt or SAN_RUNTIME_LIB[v]} is required)")
                _build_errors[v] = f"dlopen({so}) failed: {exc}{hint}"
            except AttributeError as exc:
                # a stale .so missing newly-added symbols: the
                # source-hash stamp normally prevents this; surface it
                # instead of a confusing partial bind
                _build_errors[v] = f"stale {so}: {exc}"
        _libs[v] = lib
        return lib


def available() -> bool:
    return load() is not None


def build_error() -> Optional[str]:
    """Why the native library is unavailable (None when it loaded, or
    when load() was never attempted)."""
    if load() is not None:
        return None
    try:
        v = variant()
    except ValueError as exc:
        return str(exc)
    return _build_errors.get(v) or "native library unavailable"


def kahn_levels(n: int, edges) -> "Optional[list]":
    """Batch-level a DAG natively; edges = iterable of (src, dst).
    Returns per-task levels, or None if native is unavailable.
    Raises RuntimeError on a cycle."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    src = np.fromiter((e[0] for e in edges), dtype=np.uint32,
                      count=len(edges))
    dst = np.fromiter((e[1] for e in edges), dtype=np.uint32,
                      count=len(edges))
    out = np.zeros(n, dtype=np.int32)
    rc = lib.plevel_kahn(
        n, len(edges),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc == -1:
        raise RuntimeError("DAG has a cycle")
    if rc != 0:
        raise RuntimeError(f"plevel_kahn failed: {rc}")
    return out.tolist()
