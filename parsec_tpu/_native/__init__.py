"""Native C++ core loader.

Builds ``core.cpp`` into a shared library with g++ on first use (cached
next to the source, keyed by source mtime) and exposes it through ctypes.
The Python runtime falls back to its pure-Python implementations when the
toolchain is unavailable (``load() -> None``), so the package works
everywhere; on a real deployment the native engine carries the
dependency-tracking and static-DAG execution hot paths, mirroring the
reference where those layers are native C (parsec/parsec.c,
parsec/scheduling.c, parsec/class/*).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "core.cpp")
_SO = os.path.join(_HERE, "libparsec_core.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

BODY_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_uint32, ctypes.c_int32)


def _build() -> bool:
    if os.path.exists(_SO) and \
            os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-o", _SO + ".tmp", _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, u32, i32, p = (ctypes.c_uint64, ctypes.c_uint32, ctypes.c_int32,
                        ctypes.c_void_p)
    lib.pdep_new.restype = p
    lib.pdep_free.argtypes = [p]
    lib.pdep_size.argtypes = [p]
    lib.pdep_size.restype = u64
    lib.pdep_update.argtypes = [p, u64, u64, u32, ctypes.c_int, i32,
                                ctypes.POINTER(i32)]
    lib.pdep_update.restype = ctypes.c_int
    lib.pdep_finalize.argtypes = [p, u64, u64, ctypes.c_int,
                                  ctypes.POINTER(i32)]
    lib.pdep_finalize.restype = ctypes.c_int
    lib.plevel_kahn.argtypes = [u64, u64, ctypes.POINTER(u32),
                                ctypes.POINTER(u32), ctypes.POINTER(i32)]
    lib.plevel_kahn.restype = ctypes.c_int
    lib.pgraph_new.argtypes = [u32, ctypes.POINTER(i32), ctypes.POINTER(i32),
                               u64, ctypes.POINTER(u32), ctypes.POINTER(u32),
                               BODY_FN, ctypes.c_int]
    lib.pgraph_new.restype = p
    lib.pgraph_free.argtypes = [p]
    lib.pgraph_run.argtypes = [p]
    lib.pgraph_run.restype = ctypes.c_int
    lib.pgraph_remaining.argtypes = [p]
    lib.pgraph_remaining.restype = u32
    # foundation classes (reference parsec/class/*)
    lib.plifo_new.argtypes = [u32]
    lib.plifo_new.restype = p
    lib.plifo_free.argtypes = [p]
    lib.plifo_push.argtypes = [p, u64]
    lib.plifo_push.restype = ctypes.c_int
    lib.plifo_pop.argtypes = [p, ctypes.POINTER(u64)]
    lib.plifo_pop.restype = ctypes.c_int
    lib.plifo_size.argtypes = [p]
    lib.plifo_size.restype = u32
    lib.phash_new.argtypes = [u32]
    lib.phash_new.restype = p
    lib.phash_free.argtypes = [p]
    lib.phash_insert.argtypes = [p, u64, u64]
    lib.phash_insert.restype = ctypes.c_int
    lib.phash_find.argtypes = [p, u64, ctypes.POINTER(u64)]
    lib.phash_find.restype = ctypes.c_int
    lib.phash_remove.argtypes = [p, u64, ctypes.POINTER(u64)]
    lib.phash_remove.restype = ctypes.c_int
    lib.phash_size.argtypes = [p]
    lib.phash_size.restype = u64
    lib.pmempool_new.argtypes = [u32, ctypes.c_int]
    lib.pmempool_new.restype = p
    lib.pmempool_free.argtypes = [p]
    lib.pmempool_alloc.argtypes = [p, ctypes.c_int]
    lib.pmempool_alloc.restype = p
    lib.pmempool_release.argtypes = [p, ctypes.c_int, p]
    lib.pmempool_outstanding.argtypes = [p]
    lib.pmempool_outstanding.restype = u64
    lib.pmempool_allocated.argtypes = [p]
    lib.pmempool_allocated.restype = u64
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None when it cannot be built/loaded."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PARSEC_NO_NATIVE"):
            return None
        if not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return load() is not None


def kahn_levels(n: int, edges) -> "Optional[list]":
    """Batch-level a DAG natively; edges = iterable of (src, dst).
    Returns per-task levels, or None if native is unavailable.
    Raises RuntimeError on a cycle."""
    import numpy as np
    lib = load()
    if lib is None:
        return None
    src = np.fromiter((e[0] for e in edges), dtype=np.uint32,
                      count=len(edges))
    dst = np.fromiter((e[1] for e in edges), dtype=np.uint32,
                      count=len(edges))
    out = np.zeros(n, dtype=np.int32)
    rc = lib.plevel_kahn(
        n, len(edges),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc == -1:
        raise RuntimeError("DAG has a cycle")
    if rc != 0:
        raise RuntimeError(f"plevel_kahn failed: {rc}")
    return out.tolist()
