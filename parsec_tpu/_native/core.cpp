// parsec_tpu native core — C++ runtime engine for the host-side task layer.
//
// Role (vs the reference): PaRSEC's runtime core is native C — dependency
// tracking (parsec/parsec.c:1503-1649), lock-free scheduler queues
// (parsec/class/lifo.h, mca/sched/*), and the worker progress loop
// (parsec/scheduling.c:537-676). This file provides the TPU build's native
// equivalents, exposed through a plain C ABI consumed via ctypes:
//
//   pdep_*    concurrent dependency table (striped-lock open hash) —
//             counter/mask dep accounting off the GIL
//   plevel_*  batch Kahn leveling of a static DAG (wavefront planner)
//   pgraph_*  static-DAG executor: dep counts + successor adjacency +
//             per-worker priority deques with stealing + C++ worker
//             threads; task bodies are invoked through a Python callback
//             (ctypes acquires the GIL per call; numpy/XLA bodies release
//             it during heavy work, so C++ threads overlap host compute)
//   pdtd_*    DYNAMIC-task engine (the DTD insert→release hot loop,
//             reference insert_function.c + scheduling.c): a growable
//             segmented task table fed by batched inserts (the Python
//             side stages rows in a reusable ring of arrays), the
//             counter/finalize dependency protocol of the pdep table
//             run on the dense task entries, per-worker plifo ready
//             stacks with work stealing and a shared overflow dequeue,
//             and native release (successor countdown + ready push +
//             refcounted output drop). Python workers pump it through
//             pdtd_pump — native-bodied (no-op) tasks complete without
//             ever re-entering Python; Python-bodied tasks surface one
//             at a time and complete through pdtd_complete.
//
// Everything here is original TPU-build code; reference citations are for
// behavioral parity only.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <memory>
#include <new>
#include <shared_mutex>
#include <thread>
#include <unordered_map>
#include <vector>

// monotonic ns for the observability rings (same clock family as
// Python's time.perf_counter — the loader still measures the exact
// offset with a pdtd_obs_now handshake rather than assuming it)
static inline uint64_t pdtd_now_ns() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---------------------------------------------------------------------------
// Sanitizer lane (ISSUE 14): seeded yield-injection points.
//
// Compiled in ONLY under the sanitizer build variants (the loader passes
// -DPARSEC_SAN_YIELD=1 for tsan/asan/ubsan — _native/__init__.py): each
// PSAN_YIELD() site runs a seeded per-thread xorshift and yields the OS
// slice on a fraction of visits, widening the interleaving space the
// stress suite (tests/test_native_san.py, _native/sanstress.py) explores
// per run — especially inside the plifo CAS windows, where the ABA-tag
// protocol needs contended retries to be exercised at all. Production
// builds compile every site to nothing.
// ---------------------------------------------------------------------------

// Timed cv waits under the sanitizer variants go against the SYSTEM
// clock: libstdc++ implements steady-clock waits (wait_for and
// steady wait_until) via pthread_cond_clockwait, which gcc-10's
// libtsan does not intercept — TSan then never sees the mutex release
// inside the wait and reports a bogus "double lock" on the next
// acquisition. The system-clock path runs the (intercepted)
// pthread_cond_timedwait. Production builds keep the steady clock
// (immune to wall-clock jumps); the sanitizer build trades that for a
// toolchain whose model matches the code — our code, no suppressions.
template <typename CV, typename LK, typename PRED>
static inline void pdtd_cv_wait_ms(CV& cv, LK& lk, int ms, PRED pred) {
#ifdef PARSEC_SAN_YIELD
  cv.wait_until(lk, std::chrono::system_clock::now() +
                        std::chrono::milliseconds(ms),
                pred);
#else
  cv.wait_for(lk, std::chrono::milliseconds(ms), pred);
#endif
}

// no-predicate form: ANY notify ends the wait (the pgraph idle park —
// a predicated wait would sleep through push_local's notify_one)
template <typename CV, typename LK>
static inline void pdtd_cv_wait_ms(CV& cv, LK& lk, int ms) {
#ifdef PARSEC_SAN_YIELD
  cv.wait_until(lk, std::chrono::system_clock::now() +
                        std::chrono::milliseconds(ms));
#else
  cv.wait_for(lk, std::chrono::milliseconds(ms));
#endif
}

#ifdef PARSEC_SAN_YIELD
static std::atomic<uint64_t> g_psan_seed{0x9e3779b97f4a7c15ull};
static thread_local uint64_t t_psan_state = 0;
static inline void psan_yield_point() {
  if (t_psan_state == 0)
    t_psan_state = g_psan_seed.fetch_add(0x9e3779b97f4a7c15ull,
                                         std::memory_order_relaxed) | 1;
  uint64_t x = t_psan_state;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  t_psan_state = x;
  if ((x & 7u) == 0) std::this_thread::yield();
}
#define PSAN_YIELD() psan_yield_point()
#else
#define PSAN_YIELD() ((void)0)
#endif

// ---------------------------------------------------------------------------
// Lock-discipline recorder (ISSUE 14): a debug-mode acquisition-pair
// table over the pdtd engine's mutex domains, kept on C++ atomics so
// recording never adds a lock of its own. Enabled per engine
// (pdtd_lockdbg_enable — the Python driver turns it on when the dfsan
// sanitizer is installed); when off, every site pays one relaxed bool
// load. ``pairs`` is a bitmask over (held_domain, acquired_domain):
// bit held*5+acquired set means "a thread acquired <acquired> while
// holding <held> of the same engine". Scraped through pdtd_stats
// (slots 18/19) and fed to dfsan's lock-order inversion detector,
// which flags any cycle — including the self-edge of two nested
// same-domain (entry) locks, the classic DTD deadlock shape. The
// shipped hot loop's discipline is nesting-free: a healthy run records
// ZERO pairs.
// ---------------------------------------------------------------------------

enum PdtdLockDomain {
  PLK_ENTRY = 0,     // per-task entry mutex (the seq-stripe lock's role)
  PLK_GROW = 1,      // task-table segment growth
  PLK_OVERFLOW = 2,  // shared overflow dequeue
  PLK_CV = 3,        // inserter-window / drain condition variable
  PLK_RING = 4,      // observability ring growth/drain
};
static constexpr int kLockDomains = 5;

struct PdtdLockDbg {
  std::atomic<bool> on{false};
  std::atomic<uint64_t> pairs{0};     // (held*5+acq) bitmask
  std::atomic<uint64_t> acquires{0};  // recorded acquisitions
};

struct PdtdHeldLock {
  const void* owner;  // the engine's PdtdLockDbg (identity)
  int domain;
};
// strictly scope-nested (every site is RAII), so the stack is LIFO
// even when engines interleave on one thread
static thread_local PdtdHeldLock t_lock_stack[16];
static thread_local int t_lock_depth = 0;

// record-only note: the CALLER owns the actual mutex (so cv waits can
// use unique_lock); construct after acquiring, destroy before release
struct PdtdLockNote {
  PdtdLockDbg* d_ = nullptr;
  PdtdLockNote(PdtdLockDbg* d, int domain) {
    if (!d->on.load(std::memory_order_relaxed)) return;
    d->acquires.fetch_add(1, std::memory_order_relaxed);
    for (int i = 0; i < t_lock_depth; ++i) {
      if (t_lock_stack[i].owner == d)
        d->pairs.fetch_or(
            1ull << (t_lock_stack[i].domain * kLockDomains + domain),
            std::memory_order_relaxed);
    }
    if (t_lock_depth < 16) {
      t_lock_stack[t_lock_depth++] = {d, domain};
      d_ = d;
    }
  }
  ~PdtdLockNote() {
    if (d_ != nullptr && t_lock_depth > 0) --t_lock_depth;
  }
  PdtdLockNote(const PdtdLockNote&) = delete;
  PdtdLockNote& operator=(const PdtdLockNote&) = delete;
};

// lock_guard + note in one RAII: the standard pdtd lock site
class PdtdLockRec {
  std::lock_guard<std::mutex> lk_;
  PdtdLockNote note_;  // declared after lk_: records while held,
                       // pops before the unlock
 public:
  PdtdLockRec(PdtdLockDbg* d, int domain, std::mutex& mu)
      : lk_(mu), note_(d, domain) {}
};

extern "C" {

// sanitizer-lane controls: reseed the yield-injection PRNG streams (a
// different seed explores a different interleaving neighborhood) and
// report whether this build compiled the injection points in at all —
// both bind on every variant so the loader's ABI stays uniform
void psan_seed(uint64_t seed) {
#ifdef PARSEC_SAN_YIELD
  g_psan_seed.store(seed | 1, std::memory_order_relaxed);
#else
  (void)seed;
#endif
}

int psan_yield_enabled(void) {
#ifdef PARSEC_SAN_YIELD
  return 1;
#else
  return 0;
#endif
}

// lock-discipline recorder control (ISSUE 14): per-engine opt-in. The
// enable is one relaxed store — the Python driver flips it at engine
// construction when the dfsan sanitizer is installed, before any
// worker can be pumping, so recording sites never observe a torn
// transition mid-acquisition.
void pdtd_lockdbg_enable(void* ep);  // defined after Pdtd below

// ---------------------------------------------------------------------------
// pdep: concurrent dependency table.
// Keys are 64-bit task keys (Python pre-hashes class id + locals).
// mode 0: counter — entry completes when count == goal
// mode 1: mask    — entry completes when mask == goal (dep_bit ORed in)
// ---------------------------------------------------------------------------

struct PdepEntry {
  uint64_t key;
  uint64_t acc;      // count or mask
  int32_t priority;  // max of contributing priorities
  bool used;
};

struct PdepStripe {
  std::mutex mu;
  std::unordered_map<uint64_t, PdepEntry> map;
};

struct Pdep {
  static constexpr int kStripes = 64;
  PdepStripe stripes[kStripes];
  std::atomic<uint64_t> size{0};

  PdepStripe& stripe(uint64_t key) {
    // mix so consecutive keys spread across stripes
    uint64_t h = key * 0x9e3779b97f4a7c15ull;
    return stripes[(h >> 58) & (kStripes - 1)];
  }
};

void* pdep_new(void) { return new (std::nothrow) Pdep(); }

void pdep_free(void* t) { delete static_cast<Pdep*>(t); }

uint64_t pdep_size(void* t) {
  return static_cast<Pdep*>(t)->size.load(std::memory_order_relaxed);
}

// Record one satisfied dependency. Returns 1 and removes the entry when the
// goal is reached (out_priority receives the accumulated max priority),
// 0 otherwise. Returns -1 on duplicate mask bit (protocol error).
int pdep_update(void* t, uint64_t key, uint64_t goal, uint32_t dep_bit,
                int mode, int32_t priority, int32_t* out_priority) {
  Pdep* p = static_cast<Pdep*>(t);
  PdepStripe& s = p->stripe(key);
  std::lock_guard<std::mutex> lk(s.mu);
  auto it = s.map.find(key);
  if (it == s.map.end()) {
    it = s.map.emplace(key, PdepEntry{key, 0, priority, true}).first;
    p->size.fetch_add(1, std::memory_order_relaxed);
  }
  PdepEntry& e = it->second;
  if (priority > e.priority) e.priority = priority;
  bool done;
  if (mode == 1) {
    uint64_t bit = 1ull << dep_bit;
    if (e.acc & bit) return -1;  // same dep satisfied twice
    e.acc |= bit;
    done = (e.acc == goal);
  } else {
    e.acc += 1;
    done = (e.acc == goal);
  }
  if (done) {
    if (out_priority) *out_priority = e.priority;
    s.map.erase(it);
    p->size.fetch_sub(1, std::memory_order_relaxed);
    return 1;
  }
  return 0;
}

// DTD finalize: goal becomes known after linking. Returns 1 (and removes)
// if the accumulated count/mask already meets the goal, 0 if not, -1 if no
// entry exists (nothing arrived yet).
int pdep_finalize(void* t, uint64_t key, uint64_t goal, int mode,
                  int32_t* out_priority) {
  (void)mode;  // count and mask entries finalize identically (acc==goal)
  Pdep* p = static_cast<Pdep*>(t);
  PdepStripe& s = p->stripe(key);
  std::lock_guard<std::mutex> lk(s.mu);
  auto it = s.map.find(key);
  if (it == s.map.end()) return -1;
  PdepEntry& e = it->second;
  bool done = (e.acc == goal);
  if (done) {
    if (out_priority) *out_priority = e.priority;
    s.map.erase(it);
    p->size.fetch_sub(1, std::memory_order_relaxed);
    return 1;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// plevel: batch Kahn topological leveling.
// Inputs: n tasks, m edges (src[i] -> dst[i]); out_level[n] receives the
// wave index of each task. Returns 0 on success, -1 if the graph has a
// cycle. Single batched call replaces the Python-loop leveler for large
// DAGs (the wavefront planner's hot phase).
// ---------------------------------------------------------------------------

int plevel_kahn(uint64_t n, uint64_t m, const uint32_t* src,
                const uint32_t* dst, int32_t* out_level) {
  std::vector<uint32_t> indeg(n, 0);
  std::vector<uint32_t> head(n + 1, 0);
  for (uint64_t i = 0; i < m; ++i) {
    if (src[i] >= n || dst[i] >= n) return -2;
    head[src[i] + 1]++;
    indeg[dst[i]]++;
  }
  for (uint64_t i = 0; i < n; ++i) head[i + 1] += head[i];
  std::vector<uint32_t> adj(m);
  {
    std::vector<uint32_t> cursor(head.begin(), head.end() - 1);
    for (uint64_t i = 0; i < m; ++i) adj[cursor[src[i]]++] = dst[i];
  }
  std::vector<uint32_t> frontier;
  frontier.reserve(n);
  for (uint64_t i = 0; i < n; ++i) {
    out_level[i] = 0;
    if (indeg[i] == 0) frontier.push_back((uint32_t)i);
  }
  uint64_t seen = frontier.size();
  std::vector<uint32_t> next;
  while (!frontier.empty()) {
    next.clear();
    for (uint32_t u : frontier) {
      for (uint32_t k = head[u]; k < head[u + 1]; ++k) {
        uint32_t v = adj[k];
        if (out_level[u] + 1 > out_level[v]) out_level[v] = out_level[u] + 1;
        if (--indeg[v] == 0) {
          next.push_back(v);
          seen++;
        }
      }
    }
    frontier.swap(next);
  }
  return seen == n ? 0 : -1;
}

// ---------------------------------------------------------------------------
// pgraph: static-DAG executor.
//
// The Python side enumerates the task space and successor edges once
// (closed-form PTG iterators), hands the arrays over, and provides a body
// callback. The C++ engine owns dependency countdown, per-worker priority
// scheduling with stealing (the lfq shape: local deque + steal + shared
// overflow), and the worker thread loop. This is the native analog of
// __parsec_context_wait + release_deps for statically-known DAGs.
// ---------------------------------------------------------------------------

typedef int (*pgraph_body_fn)(uint32_t task_id, int32_t worker);

struct PGraphWorker {
  std::deque<uint32_t> dq;  // local tasks, front = hottest
  std::mutex mu;
};

struct PGraph {
  uint32_t n = 0;
  std::vector<std::atomic<int32_t>> deps;  // remaining input deps
  // remaining consumers of each task's OUTPUT (= outdegree): the Python
  // executor drops its reference to a producer's outputs when this hits
  // zero (pgraph_consume) — atomic countdown instead of a Python-side
  // refcount dict under a global lock
  std::vector<std::atomic<int32_t>> consumers;
  std::vector<int32_t> priority;
  std::vector<uint32_t> head;  // CSR successor adjacency
  std::vector<uint32_t> adj;
  pgraph_body_fn body = nullptr;
  int nworkers = 1;
  std::vector<PGraphWorker> workers;
  std::atomic<uint32_t> remaining{0};
  std::atomic<int> error{0};
  // sleep/wake for starved workers
  std::mutex idle_mu;
  std::condition_variable idle_cv;

  void push_local(int w, uint32_t tid) {
    PGraphWorker& wk = workers[w];
    {
      std::lock_guard<std::mutex> lk(wk.mu);
      // priority order: higher priority to the front (simple insertion at
      // front/back; full sort is not needed — steal takes from the back)
      if (!wk.dq.empty() && priority[tid] < priority[wk.dq.front()])
        wk.dq.push_back(tid);
      else
        wk.dq.push_front(tid);
    }
    idle_cv.notify_one();
  }

  bool pop(int w, uint32_t* out) {
    PGraphWorker& wk = workers[w];
    {
      std::lock_guard<std::mutex> lk(wk.mu);
      if (!wk.dq.empty()) {
        *out = wk.dq.front();
        wk.dq.pop_front();
        return true;
      }
    }
    // steal: scan other workers' backs
    for (int i = 1; i < nworkers; ++i) {
      PGraphWorker& v = workers[(w + i) % nworkers];
      std::lock_guard<std::mutex> lk(v.mu);
      if (!v.dq.empty()) {
        *out = v.dq.back();
        v.dq.pop_back();
        return true;
      }
    }
    return false;
  }

  void worker_main(int w) {
    uint32_t tid;
    while (remaining.load(std::memory_order_acquire) > 0 &&
           error.load(std::memory_order_relaxed) == 0) {
      if (!pop(w, &tid)) {
        std::unique_lock<std::mutex> lk(idle_mu);
        pdtd_cv_wait_ms(idle_cv, lk, 1);
        continue;
      }
      int rc = body(tid, w);  // ctypes callback: takes the GIL per call
      if (rc != 0) {
        error.store(rc, std::memory_order_relaxed);
        idle_cv.notify_all();
        return;
      }
      // release successors
      for (uint32_t k = head[tid]; k < head[tid + 1]; ++k) {
        uint32_t v = adj[k];
        if (deps[v].fetch_sub(1, std::memory_order_acq_rel) == 1)
          push_local(w, v);
      }
      if (remaining.fetch_sub(1, std::memory_order_acq_rel) == 1)
        idle_cv.notify_all();
    }
  }
};

void* pgraph_new(uint32_t n, const int32_t* ndeps, const int32_t* priority,
                 uint64_t m, const uint32_t* esrc, const uint32_t* edst,
                 pgraph_body_fn body, int nworkers) {
  PGraph* g = new (std::nothrow) PGraph();
  if (!g) return nullptr;
  g->n = n;
  g->body = body;
  g->nworkers = nworkers < 1 ? 1 : nworkers;
  g->deps = std::vector<std::atomic<int32_t>>(n);
  g->priority.assign(priority, priority + n);
  for (uint32_t i = 0; i < n; ++i)
    g->deps[i].store(ndeps[i], std::memory_order_relaxed);
  g->head.assign(n + 1, 0);
  for (uint64_t i = 0; i < m; ++i) g->head[esrc[i] + 1]++;
  for (uint32_t i = 0; i < n; ++i) g->head[i + 1] += g->head[i];
  g->adj.resize(m);
  std::vector<uint32_t> cursor(g->head.begin(), g->head.end() - 1);
  for (uint64_t i = 0; i < m; ++i) g->adj[cursor[esrc[i]]++] = edst[i];
  g->consumers = std::vector<std::atomic<int32_t>>(n);
  for (uint32_t i = 0; i < n; ++i)
    g->consumers[i].store((int32_t)(g->head[i + 1] - g->head[i]),
                          std::memory_order_relaxed);
  g->workers = std::vector<PGraphWorker>(g->nworkers);
  g->remaining.store(n, std::memory_order_relaxed);
  return g;
}

void pgraph_free(void* gp) { delete static_cast<PGraph*>(gp); }

// Run the DAG to completion. Returns 0 on success, the body's nonzero
// return code on task failure, -1 on deadlock (tasks remain but none
// ready — indicates an inconsistent dep count).
//
// NOTE on the GIL: this function is called from Python through ctypes,
// which releases the GIL for the duration of the call; the worker threads'
// body callbacks each re-acquire it. The calling thread participates as
// worker 0.
int pgraph_run(void* gp) {
  PGraph* g = static_cast<PGraph*>(gp);
  // seed ready tasks round-robin across workers
  int w = 0;
  for (uint32_t i = 0; i < g->n; ++i) {
    if (g->deps[i].load(std::memory_order_relaxed) == 0) {
      g->push_local(w, i);
      w = (w + 1) % g->nworkers;
    }
  }
  std::vector<std::thread> threads;
  threads.reserve(g->nworkers - 1);
  for (int i = 1; i < g->nworkers; ++i)
    threads.emplace_back([g, i] { g->worker_main(i); });
  g->worker_main(0);
  for (auto& t : threads) t.join();
  if (g->error.load() != 0) return g->error.load();
  return g->remaining.load() == 0 ? 0 : -1;
}

uint32_t pgraph_remaining(void* gp) {
  return static_cast<PGraph*>(gp)->remaining.load();
}

// Count one consumed output of task ``tid`` (a body that read it just
// ran). Returns 1 when this was the LAST consumer — the caller may drop
// the retained outputs now — 0 otherwise, -1 on a bad id.
int pgraph_consume(void* gp, uint32_t tid) {
  PGraph* g = static_cast<PGraph*>(gp);
  if (tid >= g->n) return -1;
  return g->consumers[tid].fetch_sub(1, std::memory_order_acq_rel) == 1
             ? 1 : 0;
}

// ---------------------------------------------------------------------------
// plifo: lock-free LIFO of uint64 items (reference parsec/class/lifo.h —
// the basis of mempools and freelists). ABA protection: the head word
// packs (node index : 32 | generation tag : 32) and nodes live in a
// fixed pool, so a recycled index can't be mistaken for the old one
// unless the 32-bit tag also wraps within one CAS window.
// ---------------------------------------------------------------------------

struct PlifoNode {
  uint64_t item;
  // relaxed atomic: a popper may read the next of a node it does not yet
  // own; the stale value is discarded by the tag CAS, but the access
  // itself must not be a C++ data race
  std::atomic<uint32_t> next;
};

struct Plifo {
  static constexpr uint32_t kNil = 0xffffffffu;
  std::unique_ptr<PlifoNode[]> pool;
  std::atomic<uint64_t> head{((uint64_t)kNil) << 32};  // (idx<<32 | tag)... see pack
  std::atomic<uint64_t> free_head;
  std::atomic<uint32_t> size{0};

  static uint64_t pack(uint32_t idx, uint32_t tag) {
    return ((uint64_t)idx << 32) | tag;
  }
  static uint32_t idx_of(uint64_t w) { return (uint32_t)(w >> 32); }
  static uint32_t tag_of(uint64_t w) { return (uint32_t)w; }
};

void* plifo_new(uint32_t capacity) {
  Plifo* l = new (std::nothrow) Plifo();
  if (!l) return nullptr;
  if (capacity == 0) capacity = 1;
  l->pool.reset(new (std::nothrow) PlifoNode[capacity]);
  if (!l->pool) {
    delete l;
    return nullptr;
  }
  // chain every node onto the free list
  for (uint32_t i = 0; i < capacity; ++i)
    l->pool[i].next.store((i + 1 < capacity) ? i + 1 : Plifo::kNil,
                          std::memory_order_relaxed);
  l->free_head.store(Plifo::pack(0, 0), std::memory_order_relaxed);
  l->head.store(Plifo::pack(Plifo::kNil, 0), std::memory_order_relaxed);
  return l;
}

void plifo_free(void* lp) { delete static_cast<Plifo*>(lp); }

uint32_t plifo_size(void* lp) {
  return static_cast<Plifo*>(lp)->size.load(std::memory_order_relaxed);
}

// internal: pop a node index off a packed stack head
static uint32_t plifo_stack_pop(Plifo* l, std::atomic<uint64_t>& h) {
  uint64_t old = h.load(std::memory_order_acquire);
  while (true) {
    uint32_t idx = Plifo::idx_of(old);
    if (idx == Plifo::kNil) return Plifo::kNil;
    uint64_t next = Plifo::pack(
        l->pool[idx].next.load(std::memory_order_relaxed),
        Plifo::tag_of(old) + 1);
    PSAN_YIELD();  // widen the read-next → CAS window (the ABA target)
    if (h.compare_exchange_weak(old, next, std::memory_order_acq_rel))
      return idx;
  }
}

static void plifo_stack_push(Plifo* l, std::atomic<uint64_t>& h,
                             uint32_t idx) {
  uint64_t old = h.load(std::memory_order_acquire);
  while (true) {
    l->pool[idx].next.store(Plifo::idx_of(old), std::memory_order_relaxed);
    uint64_t desired = Plifo::pack(idx, Plifo::tag_of(old) + 1);
    PSAN_YIELD();  // widen the link-next → CAS window
    if (h.compare_exchange_weak(old, desired, std::memory_order_acq_rel))
      return;
  }
}

int plifo_push(void* lp, uint64_t item) {
  Plifo* l = static_cast<Plifo*>(lp);
  uint32_t idx = plifo_stack_pop(l, l->free_head);
  if (idx == Plifo::kNil) return -1;  // pool exhausted
  l->pool[idx].item = item;
  plifo_stack_push(l, l->head, idx);
  l->size.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

int plifo_pop(void* lp, uint64_t* out) {
  Plifo* l = static_cast<Plifo*>(lp);
  uint32_t idx = plifo_stack_pop(l, l->head);
  if (idx == Plifo::kNil) return 0;
  *out = l->pool[idx].item;
  plifo_stack_push(l, l->free_head, idx);
  l->size.fetch_sub(1, std::memory_order_relaxed);
  return 1;
}

// ---------------------------------------------------------------------------
// phash: bucket-locked resizable hash table, uint64 key -> uint64 value
// (reference parsec/class/parsec_hash_table.c: fine-grain bucket locks,
// resize when the load factor exceeds a threshold). Readers/writers hold
// the table lock shared + their bucket mutex; resize holds it unique.
// ---------------------------------------------------------------------------

struct PhashBucket {
  std::mutex mu;
  std::vector<std::pair<uint64_t, uint64_t>> items;
};

struct Phash {
  std::shared_mutex table_mu;
  std::vector<PhashBucket> buckets;
  // bucket count mirrored atomically: the resize fast-path check reads
  // it without the table lock (buckets.size() itself would race with a
  // concurrent swap under the unique lock)
  std::atomic<uint64_t> nbuckets{0};
  std::atomic<uint64_t> size{0};

  static uint64_t mix(uint64_t k) {
    k ^= k >> 33;
    k *= 0xff51afd7ed558ccdull;
    k ^= k >> 33;
    return k;
  }
  PhashBucket& bucket(uint64_t key) {
    return buckets[mix(key) & (buckets.size() - 1)];
  }
  void maybe_resize();
};

void Phash::maybe_resize() {
  // amortized: grow ×4 when avg bucket chain exceeds 4
  if (size.load(std::memory_order_relaxed) <=
      nbuckets.load(std::memory_order_relaxed) * 4)
    return;
  std::unique_lock<std::shared_mutex> lk(table_mu);
  if (size.load(std::memory_order_relaxed) <= buckets.size() * 4) return;
  std::vector<PhashBucket> next(buckets.size() * 4);
  for (auto& b : buckets)
    for (auto& kv : b.items)
      next[mix(kv.first) & (next.size() - 1)].items.push_back(kv);
  buckets.swap(next);
  nbuckets.store(buckets.size(), std::memory_order_relaxed);
}

void* phash_new(uint32_t nbuckets_hint) {
  Phash* h = new (std::nothrow) Phash();
  if (!h) return nullptr;
  if (nbuckets_hint > (1u << 20)) nbuckets_hint = 1u << 20;  // sane cap;
  // the table resizes itself past this anyway
  uint32_t n = 16;
  while (n < nbuckets_hint) n <<= 1;
  try {
    h->buckets = std::vector<PhashBucket>(n);
  } catch (...) {
    delete h;
    return nullptr;
  }
  h->nbuckets.store(n, std::memory_order_relaxed);
  return h;
}

void phash_free(void* hp) { delete static_cast<Phash*>(hp); }

uint64_t phash_size(void* hp) {
  return static_cast<Phash*>(hp)->size.load(std::memory_order_relaxed);
}

int phash_insert(void* hp, uint64_t key, uint64_t val) {
  Phash* h = static_cast<Phash*>(hp);
  {
    std::shared_lock<std::shared_mutex> tl(h->table_mu);
    PhashBucket& b = h->bucket(key);
    std::lock_guard<std::mutex> lk(b.mu);
    for (auto& kv : b.items)
      if (kv.first == key) {
        kv.second = val;
        return 1;  // replaced
      }
    b.items.emplace_back(key, val);
    h->size.fetch_add(1, std::memory_order_relaxed);
  }
  h->maybe_resize();
  return 0;
}

int phash_find(void* hp, uint64_t key, uint64_t* out) {
  Phash* h = static_cast<Phash*>(hp);
  std::shared_lock<std::shared_mutex> tl(h->table_mu);
  PhashBucket& b = h->bucket(key);
  std::lock_guard<std::mutex> lk(b.mu);
  for (auto& kv : b.items)
    if (kv.first == key) {
      if (out) *out = kv.second;
      return 1;
    }
  return 0;
}

int phash_remove(void* hp, uint64_t key, uint64_t* out) {
  Phash* h = static_cast<Phash*>(hp);
  std::shared_lock<std::shared_mutex> tl(h->table_mu);
  PhashBucket& b = h->bucket(key);
  std::lock_guard<std::mutex> lk(b.mu);
  for (size_t i = 0; i < b.items.size(); ++i)
    if (b.items[i].first == key) {
      if (out) *out = b.items[i].second;
      b.items[i] = b.items.back();
      b.items.pop_back();
      h->size.fetch_sub(1, std::memory_order_relaxed);
      return 1;
    }
  return 0;
}

// ---------------------------------------------------------------------------
// pmempool: per-thread freelists of fixed-size elements (reference
// parsec/mempool.c: thread-owned freelists with cross-thread release —
// an element released by another thread goes to the shared overflow).
// ---------------------------------------------------------------------------

struct Pmempool {
  uint32_t elt_size;
  int nthreads;
  std::vector<std::vector<void*>> local;  // per-thread freelist
  std::vector<std::mutex> local_mu;       // cross-thread release guard
  std::atomic<uint64_t> outstanding{0};
  std::atomic<uint64_t> allocated{0};
};

void* pmempool_new(uint32_t elt_size, int nthreads) {
  if (elt_size == 0 || nthreads < 1) return nullptr;
  Pmempool* p = new (std::nothrow) Pmempool();
  if (!p) return nullptr;
  p->elt_size = elt_size < 8 ? 8 : elt_size;
  p->nthreads = nthreads;
  try {
    p->local = std::vector<std::vector<void*>>(nthreads);
    p->local_mu = std::vector<std::mutex>(nthreads);
  } catch (...) {
    delete p;
    return nullptr;
  }
  return p;
}

void pmempool_free(void* pp) {
  Pmempool* p = static_cast<Pmempool*>(pp);
  for (auto& fl : p->local)
    for (void* e : fl) ::operator delete(e);
  delete p;
}

void* pmempool_alloc(void* pp, int thread) {
  Pmempool* p = static_cast<Pmempool*>(pp);
  if (thread < 0 || thread >= p->nthreads) thread = 0;
  void* e = nullptr;
  {
    std::lock_guard<std::mutex> lk(p->local_mu[thread]);
    auto& fl = p->local[thread];
    if (!fl.empty()) {
      e = fl.back();
      fl.pop_back();
    }
  }
  if (!e) {
    e = ::operator new(p->elt_size, std::nothrow);
    if (!e) return nullptr;
    p->allocated.fetch_add(1, std::memory_order_relaxed);
  }
  p->outstanding.fetch_add(1, std::memory_order_relaxed);
  return e;
}

void pmempool_release(void* pp, int thread, void* elt) {
  Pmempool* p = static_cast<Pmempool*>(pp);
  if (thread < 0 || thread >= p->nthreads) thread = 0;
  {
    std::lock_guard<std::mutex> lk(p->local_mu[thread]);
    p->local[thread].push_back(elt);
  }
  p->outstanding.fetch_sub(1, std::memory_order_relaxed);
}

uint64_t pmempool_outstanding(void* pp) {
  return static_cast<Pmempool*>(pp)->outstanding.load(
      std::memory_order_relaxed);
}

uint64_t pmempool_allocated(void* pp) {
  return static_cast<Pmempool*>(pp)->allocated.load(
      std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// pdtd: dynamic-task engine — the DTD insert→release hot loop off the GIL.
//
// Tasks are identified by their insertion sequence number (dense u32, the
// same cross-rank identity the Python DTD layer uses), which makes the
// pdep open-hash redundant: the SAME counter/finalize dependency protocol
// (accumulate arrivals against an unpublished goal, publish + finalize
// under the per-task lock — parsec.c:1554 / the DTD _GOAL_UNSET parking
// of remote_dep_mpi.c:1935) runs directly on the dense task entry, with
// the entry mutex playing the seq-stripe lock's role.
//
// Two-phase insert (pdtd_insert then pdtd_arm): phase A registers a batch
// and links it to in-flight predecessors (linked_out tells Python, per
// dependency slot, whether the edge was made — an unlinked slot means the
// producer already completed and committed, so Python snapshots the
// current tile version in program order, exactly the Python engine's
// rule). Tasks whose goal is already met DEFER instead of becoming
// runnable, so Python can finish attaching per-task state (input
// resolvers, retained-output records) before pdtd_arm makes the batch
// visible to the workers. Dependencies from OLDER batches completing in
// the window between the two phases also land in the deferred state.
//
// Ready queues: one plifo per worker + a locked overflow dequeue (the
// lfq local-buffer/system-dequeue shape); select pops local LIFO, then
// steals peers, then drains the overflow. Native-bodied tasks (flags
// bit0 clear) complete entirely inside pdtd_pump; Python-bodied tasks
// are returned one at a time and complete through pdtd_complete, which
// performs the successor countdown, ready pushes, and the refcounted
// output drop (nconsumers per producer; the drop list tells Python which
// retained outputs just died).
// ---------------------------------------------------------------------------

struct PdtdTask {
  std::mutex mu;
  std::vector<uint32_t> succs;    // tasks whose inputs I produce
  std::vector<uint32_t> lpreds;   // linked preds (refcounted outputs I read)
  int64_t goal = -1;              // -1 = unpublished (insert still linking)
  int64_t arrived = 0;            // satisfied deps (may precede goal)
  std::atomic<int32_t> nconsumers{0};  // linked readers of my outputs
  int32_t priority = 0;
  uint8_t flags = 0;              // bit0: needs a Python body
  bool done = false;
  bool armed = false;
  bool ready_deferred = false;    // goal met before arming
  // observability slots (pdtd_obs_enable; untouched when obs is off):
  // ready/select stamps feed the per-task queue-wait, parent_seq is the
  // predecessor whose completion made this task ready — the dependency
  // edge the span tree parents on (exactly complete_task's rule on the
  // Python engine), cls is the insert-side class id the adapter
  // expands to the task-class name at scrape time
  uint64_t t_ready_ns = 0;
  uint64_t t_sel_ns = 0;
  uint32_t cls = 0;
  uint32_t parent_seq = 0xffffffffu;
};

// one fixed-stride binary trace record per completed task (the PBT
// per-stream-buffer model of parsec/profiling.c, one level lower than
// the PR 9 Python rings): everything a span needs, formatted lazily at
// scrape by the Python adapter (profiling/trace.py NativeRingAdapter)
struct PdtdObsRec {
  uint64_t t0_ns;       // select (body dispatch) stamp
  uint64_t t1_ns;       // completion stamp
  uint64_t q_ns;        // ready -> select queue wait
  uint64_t span;        // span id: caller base | process-global counter
  uint32_t seq;         // task id (the pool-local identity)
  uint32_t parent_seq;  // releasing predecessor (0xffffffff = none)
  uint32_t cls;         // insert-side class id
  int32_t worker;
};  // 48 bytes, natural alignment — mirrored by _native.OBS_DTYPE

// per-worker SINGLE-PRODUCER ring: the owning worker appends lock-free
// (slot write, then release-store of wpos); growth (up to cap_max) and
// snapshot drains take the mutex. Once at cap_max the ring overwrites
// its oldest record and advances the drop counter — bounded memory is
// the contract, the drop counter is the honesty counter.
struct PdtdObsRing {
  std::mutex mu;                       // drain + growth only
  std::unique_ptr<PdtdObsRec[]> buf;
  uint32_t cap = 0;
  std::atomic<uint64_t> wpos{0};
};

// process-global span-id counter shared by every engine: ids stay
// unique across the one-pool-per-request serving churn without any
// cross-engine coordination
static std::atomic<uint64_t> g_obs_span{1};

struct Pdtd {
  static constexpr uint32_t kSegBits = 12;
  static constexpr uint32_t kSegSize = 1u << kSegBits;   // tasks per segment
  // 16384 directory slots (128 KB in the engine struct) x 4096 tasks =
  // 67M tasks per pool; engines are per-taskpool, so the directory is
  // deliberately small — serving churns one engine per submission
  static constexpr uint32_t kMaxSegs = 1u << 14;
  std::atomic<PdtdTask*> segs[kMaxSegs];
  std::atomic<uint32_t> ntasks{0};
  std::mutex grow_mu;

  int nworkers = 1;
  std::vector<Plifo*> queues;         // per-worker ready stacks
  std::mutex overflow_mu;             // plifo-full spill (system dequeue)
  std::deque<uint32_t> overflow;
  std::atomic<uint32_t> rr{0};        // arm-time round-robin cursor

  std::atomic<uint32_t> inflight{0};
  std::atomic<bool> cancelled{false};
  std::mutex cv_mu;
  std::condition_variable cv;
  std::atomic<int> waiters{0};

  // stats (pdtd_stats order — mirrored by the Python loader)
  std::atomic<uint64_t> s_inserted{0}, s_linked{0}, s_ready_pushed{0},
      s_popped{0}, s_stolen{0}, s_overflow{0}, s_completed_native{0},
      s_completed_python{0}, s_released{0}, s_drops{0}, s_dropped_cancel{0},
      s_ring_hw{0}, s_pump_calls{0};

  // observability plane (pdtd_obs_enable): off by default — the hot
  // loop pays ONE relaxed bool load per stamp site when off. Sites
  // that go on to DEREFERENCE obs_rings load it with acquire so the
  // enable-time ring construction is ordered before first use by the
  // atomic itself (standard C++ release/acquire — TSan models it
  // natively, no suppression needed); stamp-only sites (plain fields
  // on the task entry, published later by the ready-push/completion
  // chain) keep the relaxed load.
  std::atomic<bool> obs_on{false};
  uint64_t obs_span_base = 0;
  uint32_t obs_cap_max = 0;
  std::vector<PdtdObsRing*> obs_rings;
  std::atomic<uint64_t> s_obs_recorded{0}, s_obs_dropped{0};

  // lock-discipline recorder (ISSUE 14; see PdtdLockDbg above)
  PdtdLockDbg lockdbg;

  ~Pdtd() {
    for (uint32_t s = 0; s < kMaxSegs; ++s) {
      PdtdTask* seg = segs[s].load(std::memory_order_relaxed);
      if (seg == nullptr) break;     // ensure() fills segments densely
      delete[] seg;
    }
    for (Plifo* q : queues) plifo_free(q);
    for (PdtdObsRing* r : obs_rings) delete r;
  }

  // append one completion record to worker w's ring (single producer:
  // the worker that popped the task). Growth ×4 up to obs_cap_max,
  // then drop-oldest. The HEALTHY (non-wrapped) path is lock-free:
  // slots are append-only, published by the release-store of wpos, so
  // a concurrent drain can never read a torn record. Once the ring is
  // full (the already-degraded dropping regime) each overwrite takes
  // the ring mutex so drains stay exact — an uncontended lock per
  // record, paid only after capacity is exhausted.
  void obs_record(int w, uint32_t tid, PdtdTask* t, uint64_t t1) {
    PdtdObsRing* r = obs_rings[w];
    uint64_t wp = r->wpos.load(std::memory_order_relaxed);
    if (wp >= r->cap && r->cap < obs_cap_max) {
      PdtdLockRec lk(&lockdbg, PLK_RING, r->mu);
      uint32_t ncap = r->cap * 4;
      if (ncap > obs_cap_max || ncap < r->cap) ncap = obs_cap_max;
      PdtdObsRec* nb = new (std::nothrow) PdtdObsRec[ncap];
      if (nb != nullptr) {
        for (uint64_t i = 0; i < wp; ++i) nb[i % ncap] = r->buf[i % r->cap];
        r->buf.reset(nb);
        r->cap = ncap;
      }
    }
    PSAN_YIELD();  // between the fill and the wpos publish below
    if (wp >= r->cap) {
      PdtdLockRec lk(&lockdbg, PLK_RING, r->mu);
      s_obs_dropped.fetch_add(1, std::memory_order_relaxed);
      obs_fill(r->buf[wp % r->cap], w, tid, t, t1);
      r->wpos.store(wp + 1, std::memory_order_release);
    } else {
      obs_fill(r->buf[wp % r->cap], w, tid, t, t1);
      r->wpos.store(wp + 1, std::memory_order_release);
    }
    s_obs_recorded.fetch_add(1, std::memory_order_relaxed);
  }

  void obs_fill(PdtdObsRec& rec, int w, uint32_t tid, PdtdTask* t,
                uint64_t t1) {
    rec.t0_ns = t->t_sel_ns;
    rec.t1_ns = t1;
    rec.q_ns = t->t_sel_ns > t->t_ready_ns ? t->t_sel_ns - t->t_ready_ns
                                           : 0;
    rec.span = obs_span_base |
               g_obs_span.fetch_add(1, std::memory_order_relaxed);
    rec.seq = tid;
    rec.parent_seq = t->parent_seq;
    rec.cls = t->cls;
    rec.worker = w;
  }

  PdtdTask* task(uint32_t tid) {
    return &segs[tid >> kSegBits].load(std::memory_order_acquire)
               [tid & (kSegSize - 1)];
  }

  bool ensure(uint32_t upto) {  // segments covering task ids [0, upto)
    PdtdLockRec lk(&lockdbg, PLK_GROW, grow_mu);
    uint32_t need = (upto + kSegSize - 1) >> kSegBits;
    if (need > kMaxSegs) return false;
    for (uint32_t s = 0; s < need; ++s) {
      if (segs[s].load(std::memory_order_relaxed) == nullptr) {
        PdtdTask* seg = new (std::nothrow) PdtdTask[kSegSize];
        if (!seg) return false;
        segs[s].store(seg, std::memory_order_release);
      }
    }
    return true;
  }

  void push_ready(int w, uint32_t tid) {
    s_ready_pushed.fetch_add(1, std::memory_order_relaxed);
    if (obs_on.load(std::memory_order_relaxed))
      task(tid)->t_ready_ns = pdtd_now_ns();
    PSAN_YIELD();
    if (plifo_push(queues[w], tid) != 0) {
      PdtdLockRec lk(&lockdbg, PLK_OVERFLOW, overflow_mu);
      overflow.push_back(tid);
      s_overflow.fetch_add(1, std::memory_order_relaxed);
    }
  }

  bool pop_ready(int w, uint32_t* out) {
    uint64_t item;
    if (plifo_pop(queues[w], &item)) {
      *out = (uint32_t)item;
      s_popped.fetch_add(1, std::memory_order_relaxed);
      return true;
    }
    for (int i = 1; i < nworkers; ++i) {
      PSAN_YIELD();
      if (plifo_pop(queues[(w + i) % nworkers], &item)) {
        *out = (uint32_t)item;
        s_stolen.fetch_add(1, std::memory_order_relaxed);
        return true;
      }
    }
    {
      PdtdLockRec lk(&lockdbg, PLK_OVERFLOW, overflow_mu);
      if (!overflow.empty()) {
        *out = overflow.front();
        overflow.pop_front();
        s_stolen.fetch_add(1, std::memory_order_relaxed);
        return true;
      }
    }
    return false;
  }

  void retire_one() {
    if (inflight.fetch_sub(1, std::memory_order_acq_rel) == 1 ||
        waiters.load(std::memory_order_acquire) > 0) {
      PdtdLockRec lk(&lockdbg, PLK_CV, cv_mu);
      cv.notify_all();
    }
  }

  // successor countdown of a completing (or insert-time-ready) task;
  // returns how many successors became ready (pushed to worker w).
  // ``src`` is the completing task: when its arrival meets the goal it
  // becomes the successor's span parent (the dependency edge).
  int release_succs(int w, uint32_t src, const std::vector<uint32_t>& succs) {
    bool obs = obs_on.load(std::memory_order_relaxed);
    int newly = 0;
    for (uint32_t sid : succs) {
      PdtdTask* s = task(sid);
      bool ready = false, armed = false;
      {
        PdtdLockRec lk(&lockdbg, PLK_ENTRY, s->mu);
        s->arrived += 1;
        if (s->goal >= 0 && s->arrived == s->goal && !s->done) {
          if (obs) s->parent_seq = src;
          armed = s->armed;
          if (armed) ready = true;
          else s->ready_deferred = true;
        }
      }
      s_released.fetch_add(1, std::memory_order_relaxed);
      if (ready) {
        push_ready(w, sid);
        newly++;
      }
    }
    return newly;
  }

  // refcounted output drop: count one consumption of each linked pred;
  // preds whose last consumer this was land in drops_out (if provided)
  int drop_preds(const std::vector<uint32_t>& lpreds, uint32_t* drops_out,
                 int32_t cap) {
    int nd = 0;
    for (uint32_t pid : lpreds) {
      PdtdTask* p = task(pid);
      if (p->nconsumers.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        if (drops_out != nullptr && nd < cap) drops_out[nd] = pid;
        nd++;
        s_drops.fetch_add(1, std::memory_order_relaxed);
      }
    }
    return nd;
  }

  // complete a native-bodied task inline (no Python re-entry)
  void complete_native(int w, uint32_t tid) {
    PdtdTask* t = task(tid);
    std::vector<uint32_t> succs;
    {
      PdtdLockRec lk(&lockdbg, PLK_ENTRY, t->mu);
      t->done = true;
      succs.swap(t->succs);
    }
    // acquire: this site DEREFERENCES obs_rings, so the enable-time
    // ring construction must be ordered before first use
    if (obs_on.load(std::memory_order_acquire))
      obs_record(w, tid, t, pdtd_now_ns());
    release_succs(w, tid, succs);
    drop_preds(t->lpreds, nullptr, 0);
    s_completed_native.fetch_add(1, std::memory_order_relaxed);
    retire_one();
  }

  // cancelled-engine drop at select time: no body runs, but successors
  // MUST still count down — a dependent of a dropped task would
  // otherwise never become ready, never be dropped itself, and hold
  // inflight > 0 forever (the retiring engine would never fold). The
  // released dependents are pushed, popped, and dropped in turn, so a
  // whole cancelled chain drains.
  void drop_cancelled(int w, uint32_t tid) {
    PdtdTask* t = task(tid);
    std::vector<uint32_t> succs;
    {
      PdtdLockRec lk(&lockdbg, PLK_ENTRY, t->mu);
      t->done = true;
      succs.swap(t->succs);
    }
    release_succs(w, tid, succs);
    drop_preds(t->lpreds, nullptr, 0);
    s_dropped_cancel.fetch_add(1, std::memory_order_relaxed);
    retire_one();
  }
};

void* pdtd_new(int nworkers, uint32_t queue_capacity) {
  Pdtd* e = new (std::nothrow) Pdtd();
  if (!e) return nullptr;
  e->nworkers = nworkers < 1 ? 1 : nworkers;
  if (queue_capacity == 0) queue_capacity = 1u << 13;
  for (uint32_t s = 0; s < Pdtd::kMaxSegs; ++s)
    e->segs[s].store(nullptr, std::memory_order_relaxed);
  for (int i = 0; i < e->nworkers; ++i) {
    Plifo* q = static_cast<Plifo*>(plifo_new(queue_capacity));
    if (!q) {
      delete e;
      return nullptr;
    }
    e->queues.push_back(q);
  }
  return e;
}

void pdtd_free(void* ep) { delete static_cast<Pdtd*>(ep); }

// Phase A: register a batch of n tasks (dense ids continuing the table)
// and link them to in-flight predecessors. preds is the flat
// concatenation of each task's predecessor ids (npreds[i] per task);
// linked_out (same layout) receives 1 where the edge was made, 0 where
// the predecessor had already completed (Python then snapshots the
// committed tile version in program order). The batch stays invisible to
// the workers until pdtd_arm. Returns the first task id, or -1.
int64_t pdtd_insert(void* ep, uint32_t n, const int32_t* prio,
                    const uint8_t* flags, const uint32_t* npreds,
                    const uint32_t* preds, uint8_t* linked_out,
                    uint32_t cls) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  uint32_t first = e->ntasks.load(std::memory_order_relaxed);
  if (!e->ensure(first + n)) return -1;
  uint64_t pi = 0;
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t tid = first + i;
    PdtdTask* t = e->task(tid);
    t->priority = prio ? prio[i] : 0;
    t->flags = flags ? flags[i] : 1;
    t->cls = cls;
    int64_t goal = 0;
    uint32_t np = npreds ? npreds[i] : 0;
    for (uint32_t k = 0; k < np; ++k, ++pi) {
      uint32_t pid = preds[pi];
      if (pid >= tid) return -2;          // protocol error: forward edge
      PdtdTask* p = e->task(pid);
      bool linked = false;
      {
        PdtdLockRec lk(&e->lockdbg, PLK_ENTRY, p->mu);
        if (!p->done) {
          p->succs.push_back(tid);
          p->nconsumers.fetch_add(1, std::memory_order_relaxed);
          linked = true;
        }
      }
      if (linked_out) linked_out[pi] = linked ? 1 : 0;
      if (linked) {
        goal += 1;
        t->lpreds.push_back(pid);
        e->s_linked.fetch_add(1, std::memory_order_relaxed);
      }
    }
    // publish the goal and finalize against arrivals that raced ahead
    // (an already-linked pred may have completed before this point)
    {
      PdtdLockRec lk(&e->lockdbg, PLK_ENTRY, t->mu);
      t->goal = goal;
      if (t->arrived == goal) t->ready_deferred = true;
    }
    e->inflight.fetch_add(1, std::memory_order_relaxed);
  }
  e->ntasks.store(first + n, std::memory_order_release);
  e->s_inserted.fetch_add(n, std::memory_order_relaxed);
  uint64_t hw = e->s_ring_hw.load(std::memory_order_relaxed);
  while (n > hw &&
         !e->s_ring_hw.compare_exchange_weak(hw, n,
                                             std::memory_order_relaxed)) {
  }
  return (int64_t)first;
}

// Phase B: make the batch runnable. Tasks whose goal was already met
// (at insert, or by an older batch completing meanwhile) are pushed
// round-robin across the worker queues.
void pdtd_arm(void* ep, uint32_t first, uint32_t n) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  for (uint32_t tid = first; tid < first + n; ++tid) {
    PdtdTask* t = e->task(tid);
    bool ready = false;
    {
      PdtdLockRec lk(&e->lockdbg, PLK_ENTRY, t->mu);
      t->armed = true;
      if (t->ready_deferred) {
        t->ready_deferred = false;
        ready = true;
      }
    }
    if (ready) {
      uint32_t w = e->rr.fetch_add(1, std::memory_order_relaxed);
      e->push_ready((int)(w % e->nworkers), tid);
    }
  }
}

// Worker pump: run native-bodied ready tasks to completion until either
// a Python-bodied task surfaces (returns 1, *out_tid set — the caller
// runs its body and calls pdtd_complete) or the queues are dry (returns
// 2 if any native work was done this call, 0 if none). Cancelled
// engines drop queued tasks here, at select time.
int pdtd_pump(void* ep, int worker, uint32_t* out_tid) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  if (worker < 0 || worker >= e->nworkers) worker = 0;
  e->s_pump_calls.fetch_add(1, std::memory_order_relaxed);
  bool obs = e->obs_on.load(std::memory_order_relaxed);
  bool ran = false;
  uint32_t tid;
  while (e->pop_ready(worker, &tid)) {
    PdtdTask* t = e->task(tid);
    if (e->cancelled.load(std::memory_order_acquire)) {
      e->drop_cancelled(worker, tid);
      ran = true;
      continue;
    }
    if (obs) t->t_sel_ns = pdtd_now_ns();
    if (t->flags & 1) {
      *out_tid = tid;
      return 1;
    }
    e->complete_native(worker, tid);
    ran = true;
  }
  return ran ? 2 : 0;
}

// Batched pump: like pdtd_pump, but collects up to ``cap`` Python-bodied
// tasks per call (native-bodied ones still complete inline) so the
// Python worker pays ONE GIL round-trip per batch instead of per task —
// the GIL-convoy fix for the Python-bodied serving shape. Returns the
// number of tids written; *ran_native is set when native-bodied (or
// cancelled-dropped) work was done regardless.
int pdtd_pump_batch(void* ep, int worker, uint32_t* out_tids, int cap,
                    int* ran_native) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  if (worker < 0 || worker >= e->nworkers) worker = 0;
  e->s_pump_calls.fetch_add(1, std::memory_order_relaxed);
  bool obs = e->obs_on.load(std::memory_order_relaxed);
  bool ran = false;
  int n = 0;
  uint32_t tid;
  while (n < cap && e->pop_ready(worker, &tid)) {
    PdtdTask* t = e->task(tid);
    if (e->cancelled.load(std::memory_order_acquire)) {
      e->drop_cancelled(worker, tid);
      ran = true;
      continue;
    }
    if (obs) t->t_sel_ns = pdtd_now_ns();
    if (t->flags & 1) {
      out_tids[n++] = tid;
      continue;
    }
    e->complete_native(worker, tid);
    ran = true;
  }
  if (ran_native) *ran_native = ran ? 1 : 0;
  return n;
}

// Complete a Python-bodied task: successor countdown + ready pushes +
// refcounted output drop. drops_out (capacity drops_cap) receives the
// predecessor ids whose retained outputs just lost their last consumer;
// info_out[0] = successors made ready, info_out[1] = this task's final
// consumer count (0 → Python need not retain its outputs). t0_ns/t1_ns
// are the caller's BODY begin/end stamps for the event record (Python
// bodies of one pump batch run long after the pop — the select stamp
// would smear the whole batch's makespan over every task); 0 keeps the
// engine's own select/now stamps. Returns the drop count, or -1 on a
// bad id.
int pdtd_complete(void* ep, int worker, uint32_t tid, uint32_t* drops_out,
                  int32_t drops_cap, int32_t* info_out, uint64_t t0_ns,
                  uint64_t t1_ns) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  if (worker < 0 || worker >= e->nworkers) worker = 0;
  if (tid >= e->ntasks.load(std::memory_order_acquire)) return -1;
  PdtdTask* t = e->task(tid);
  std::vector<uint32_t> succs;
  {
    PdtdLockRec lk(&e->lockdbg, PLK_ENTRY, t->mu);
    if (t->done) return -1;
    t->done = true;
    succs.swap(t->succs);
  }
  // acquire before dereferencing obs_rings (see complete_native)
  if (e->obs_on.load(std::memory_order_acquire)) {
    if (t0_ns) t->t_sel_ns = t0_ns;
    e->obs_record(worker, tid, t, t1_ns ? t1_ns : pdtd_now_ns());
  }
  int newly = e->release_succs(worker, tid, succs);
  int nd = e->drop_preds(t->lpreds, drops_out, drops_cap);
  if (info_out) {
    info_out[0] = newly;
    info_out[1] = t->nconsumers.load(std::memory_order_acquire);
  }
  e->s_completed_python.fetch_add(1, std::memory_order_relaxed);
  e->retire_one();
  return nd;
}

// Batched completion for Python-bodied tasks that retained no outputs
// and consumed none (no drop/consumer reporting needed — the null-task
// and serving shapes): one GIL round-trip completes the whole batch.
// t01 (2n u64s, nullable) carries per-task body begin/end stamps for
// the event records — see pdtd_complete. Returns the number of
// successors made ready.
int pdtd_complete_batch(void* ep, int worker, const uint32_t* tids,
                        int n, const uint64_t* t01) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  if (worker < 0 || worker >= e->nworkers) worker = 0;
  // acquire before dereferencing obs_rings (see complete_native)
  bool obs = e->obs_on.load(std::memory_order_acquire);
  int newly = 0;
  std::vector<uint32_t> succs;
  for (int i = 0; i < n; ++i) {
    uint32_t tid = tids[i];
    if (tid >= e->ntasks.load(std::memory_order_acquire)) continue;
    PdtdTask* t = e->task(tid);
    succs.clear();
    {
      PdtdLockRec lk(&e->lockdbg, PLK_ENTRY, t->mu);
      if (t->done) continue;
      t->done = true;
      succs.swap(t->succs);
    }
    if (obs) {
      uint64_t t1 = 0;
      if (t01 != nullptr) {
        if (t01[2 * i]) t->t_sel_ns = t01[2 * i];
        t1 = t01[2 * i + 1];
      }
      e->obs_record(worker, tid, t, t1 ? t1 : pdtd_now_ns());
    }
    newly += e->release_succs(worker, tid, succs);
    e->drop_preds(t->lpreds, nullptr, 0);
    e->s_completed_python.fetch_add(1, std::memory_order_relaxed);
    e->retire_one();
  }
  return newly;
}

uint32_t pdtd_inflight(void* ep) {
  return static_cast<Pdtd*>(ep)->inflight.load(std::memory_order_acquire);
}

uint32_t pdtd_ready(void* ep) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  uint32_t n = 0;
  for (Plifo* q : e->queues) n += plifo_size(q);
  {
    PdtdLockRec lk(&e->lockdbg, PLK_OVERFLOW, e->overflow_mu);
    n += (uint32_t)e->overflow.size();
  }
  return n;
}

// Sliding-window park (the DTD inserter throttle off the GIL): wait
// until inflight <= threshold, the engine is cancelled, or timeout_ms
// elapses. Returns the current inflight count.
uint32_t pdtd_wait_below(void* ep, uint32_t threshold, int timeout_ms) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  std::unique_lock<std::mutex> lk(e->cv_mu);
  // record-only note: the cv wait needs the unique_lock itself; the
  // note pops before the unlock (declared after lk)
  PdtdLockNote note(&e->lockdbg, PLK_CV);
  e->waiters.fetch_add(1, std::memory_order_acq_rel);
  pdtd_cv_wait_ms(e->cv, lk, timeout_ms, [&] {
    return e->inflight.load(std::memory_order_acquire) <= threshold ||
           e->cancelled.load(std::memory_order_acquire);
  });
  e->waiters.fetch_sub(1, std::memory_order_acq_rel);
  return e->inflight.load(std::memory_order_acquire);
}

// Cancel: queued tasks are dropped at the next pop; parked waiters wake.
void pdtd_cancel(void* ep) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  e->cancelled.store(true, std::memory_order_release);
  PdtdLockRec lk(&e->lockdbg, PLK_CV, e->cv_mu);
  e->cv.notify_all();
}

void pdtd_lockdbg_enable(void* ep) {
  static_cast<Pdtd*>(ep)->lockdbg.on.store(true, std::memory_order_relaxed);
}

void pdtd_stats(void* ep, uint64_t* out20) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  out20[0] = e->s_inserted.load(std::memory_order_relaxed);
  out20[1] = e->s_linked.load(std::memory_order_relaxed);
  out20[2] = e->s_ready_pushed.load(std::memory_order_relaxed);
  out20[3] = e->s_popped.load(std::memory_order_relaxed);
  out20[4] = e->s_stolen.load(std::memory_order_relaxed);
  out20[5] = e->s_overflow.load(std::memory_order_relaxed);
  out20[6] = e->s_completed_native.load(std::memory_order_relaxed);
  out20[7] = e->s_completed_python.load(std::memory_order_relaxed);
  out20[8] = e->s_released.load(std::memory_order_relaxed);
  out20[9] = e->s_drops.load(std::memory_order_relaxed);
  out20[10] = e->s_dropped_cancel.load(std::memory_order_relaxed);
  out20[11] = e->s_ring_hw.load(std::memory_order_relaxed);
  out20[12] = e->inflight.load(std::memory_order_acquire);
  out20[13] = pdtd_ready(ep);
  out20[14] = e->s_pump_calls.load(std::memory_order_relaxed);
  // observability-plane rows (0 while pdtd_obs_enable was never called)
  out20[15] = e->s_obs_recorded.load(std::memory_order_relaxed);
  out20[16] = e->s_obs_dropped.load(std::memory_order_relaxed);
  uint64_t depth = 0;
  for (PdtdObsRing* r : e->obs_rings) {
    // cap is written under the ring mutex (growth, disable) — take it
    // so a scrape can't read a torn/stale capacity mid-regrow (the
    // PR 13 post-review race, pinned by the TSan stress lane)
    PdtdLockRec lk(&e->lockdbg, PLK_RING, r->mu);
    uint64_t wp = r->wpos.load(std::memory_order_acquire);
    depth += wp < r->cap ? wp : r->cap;
  }
  out20[17] = depth;
  // lock-discipline recorder rows: the acquisition-pair bitmask
  // ((held*5+acquired) bits over the PLK_* domains — OR-folded by the
  // Python side, never summed) and the recorded acquisition count
  out20[18] = e->lockdbg.pairs.load(std::memory_order_relaxed);
  out20[19] = e->lockdbg.acquires.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// pdtd observability plane: per-worker single-producer event rings.
// Enabled per engine BEFORE the first insert (the Python driver turns
// it on exactly when a live Trace is installed); recording costs three
// monotonic-clock reads and one 48-byte ring store per task, all off
// the GIL. Records are drained (snapshot, non-consuming) at scrape/
// dump time and expanded to the PR 9 trace-record format by
// profiling/trace.py — observation never changes which engine runs.
// ---------------------------------------------------------------------------

// current monotonic ns — the Python side pairs one call with a
// time.perf_counter() read to measure the clock offset exactly
uint64_t pdtd_obs_now(void) { return pdtd_now_ns(); }

// Enable the rings: span ids mint as (span_base | global counter);
// each worker ring starts small and grows ×4 up to cap_max records,
// then drop-oldest. Returns 0, or -1 on allocation failure.
int pdtd_obs_enable(void* ep, uint64_t span_base, uint32_t cap_max) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  if (e->obs_on.load(std::memory_order_relaxed)) return 0;
  if (cap_max < 64) cap_max = 64;
  e->obs_span_base = span_base;
  e->obs_cap_max = cap_max;
  uint32_t cap0 = cap_max < 1024 ? cap_max : 1024;
  for (int w = 0; w < e->nworkers; ++w) {
    PdtdObsRing* r = new (std::nothrow) PdtdObsRing();
    if (r != nullptr) {
      r->buf.reset(new (std::nothrow) PdtdObsRec[cap0]);
      if (!r->buf) {
        delete r;
        r = nullptr;
      } else {
        r->cap = cap0;
      }
    }
    if (r == nullptr) {
      for (PdtdObsRing* q : e->obs_rings) delete q;
      e->obs_rings.clear();
      return -1;
    }
    e->obs_rings.push_back(r);
  }
  e->obs_on.store(true, std::memory_order_release);
  return 0;
}

// Release the ring memory (counters survive). Called once the engine
// is quiescent (pool folded, rings snapshotted) so a persistent
// serving context does not pin one ring set per retired pool.
void pdtd_obs_disable(void* ep) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  e->obs_on.store(false, std::memory_order_release);
  for (PdtdObsRing* r : e->obs_rings) {
    PdtdLockRec lk(&e->lockdbg, PLK_RING, r->mu);
    r->buf.reset();
    r->cap = 0;
  }
}

// Snapshot worker w's ring into out[cap_out] (oldest first, NOT
// consumed — repeated dumps see the same events, like the Python trace
// rings). Exact under concurrency: published append-only slots are
// immutable, and overwrites (the wrapped regime) serialize against
// this drain on the ring mutex — no torn records, no discard
// heuristic. Returns the record count, -1 on a bad worker.
int pdtd_obs_drain(void* ep, int worker, PdtdObsRec* out,
                   uint32_t cap_out) {
  Pdtd* e = static_cast<Pdtd*>(ep);
  if (worker < 0 || worker >= (int)e->obs_rings.size()) return -1;
  PdtdObsRing* r = e->obs_rings[worker];
  PdtdLockRec lk(&e->lockdbg, PLK_RING, r->mu);
  if (r->cap == 0) return 0;
  uint64_t w2 = r->wpos.load(std::memory_order_acquire);
  uint64_t n = w2 < r->cap ? w2 : r->cap;
  if (n > cap_out) n = cap_out;
  uint64_t start = w2 - n;
  for (uint64_t i = 0; i < n; ++i)
    out[i] = r->buf[(start + i) % r->cap];
  return (int)n;
}

}  // extern "C"
