// parsec_tpu native core — C++ runtime engine for the host-side task layer.
//
// Role (vs the reference): PaRSEC's runtime core is native C — dependency
// tracking (parsec/parsec.c:1503-1649), lock-free scheduler queues
// (parsec/class/lifo.h, mca/sched/*), and the worker progress loop
// (parsec/scheduling.c:537-676). This file provides the TPU build's native
// equivalents, exposed through a plain C ABI consumed via ctypes:
//
//   pdep_*    concurrent dependency table (striped-lock open hash) —
//             counter/mask dep accounting off the GIL
//   plevel_*  batch Kahn leveling of a static DAG (wavefront planner)
//   pgraph_*  static-DAG executor: dep counts + successor adjacency +
//             per-worker priority deques with stealing + C++ worker
//             threads; task bodies are invoked through a Python callback
//             (ctypes acquires the GIL per call; numpy/XLA bodies release
//             it during heavy work, so C++ threads overlap host compute)
//
// Everything here is original TPU-build code; reference citations are for
// behavioral parity only.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <memory>
#include <new>
#include <shared_mutex>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// pdep: concurrent dependency table.
// Keys are 64-bit task keys (Python pre-hashes class id + locals).
// mode 0: counter — entry completes when count == goal
// mode 1: mask    — entry completes when mask == goal (dep_bit ORed in)
// ---------------------------------------------------------------------------

struct PdepEntry {
  uint64_t key;
  uint64_t acc;      // count or mask
  int32_t priority;  // max of contributing priorities
  bool used;
};

struct PdepStripe {
  std::mutex mu;
  std::unordered_map<uint64_t, PdepEntry> map;
};

struct Pdep {
  static constexpr int kStripes = 64;
  PdepStripe stripes[kStripes];
  std::atomic<uint64_t> size{0};

  PdepStripe& stripe(uint64_t key) {
    // mix so consecutive keys spread across stripes
    uint64_t h = key * 0x9e3779b97f4a7c15ull;
    return stripes[(h >> 58) & (kStripes - 1)];
  }
};

void* pdep_new(void) { return new (std::nothrow) Pdep(); }

void pdep_free(void* t) { delete static_cast<Pdep*>(t); }

uint64_t pdep_size(void* t) {
  return static_cast<Pdep*>(t)->size.load(std::memory_order_relaxed);
}

// Record one satisfied dependency. Returns 1 and removes the entry when the
// goal is reached (out_priority receives the accumulated max priority),
// 0 otherwise. Returns -1 on duplicate mask bit (protocol error).
int pdep_update(void* t, uint64_t key, uint64_t goal, uint32_t dep_bit,
                int mode, int32_t priority, int32_t* out_priority) {
  Pdep* p = static_cast<Pdep*>(t);
  PdepStripe& s = p->stripe(key);
  std::lock_guard<std::mutex> lk(s.mu);
  auto it = s.map.find(key);
  if (it == s.map.end()) {
    it = s.map.emplace(key, PdepEntry{key, 0, priority, true}).first;
    p->size.fetch_add(1, std::memory_order_relaxed);
  }
  PdepEntry& e = it->second;
  if (priority > e.priority) e.priority = priority;
  bool done;
  if (mode == 1) {
    uint64_t bit = 1ull << dep_bit;
    if (e.acc & bit) return -1;  // same dep satisfied twice
    e.acc |= bit;
    done = (e.acc == goal);
  } else {
    e.acc += 1;
    done = (e.acc == goal);
  }
  if (done) {
    if (out_priority) *out_priority = e.priority;
    s.map.erase(it);
    p->size.fetch_sub(1, std::memory_order_relaxed);
    return 1;
  }
  return 0;
}

// DTD finalize: goal becomes known after linking. Returns 1 (and removes)
// if the accumulated count/mask already meets the goal, 0 if not, -1 if no
// entry exists (nothing arrived yet).
int pdep_finalize(void* t, uint64_t key, uint64_t goal, int mode,
                  int32_t* out_priority) {
  Pdep* p = static_cast<Pdep*>(t);
  PdepStripe& s = p->stripe(key);
  std::lock_guard<std::mutex> lk(s.mu);
  auto it = s.map.find(key);
  if (it == s.map.end()) return -1;
  PdepEntry& e = it->second;
  bool done = (e.acc == goal);
  if (done) {
    if (out_priority) *out_priority = e.priority;
    s.map.erase(it);
    p->size.fetch_sub(1, std::memory_order_relaxed);
    return 1;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// plevel: batch Kahn topological leveling.
// Inputs: n tasks, m edges (src[i] -> dst[i]); out_level[n] receives the
// wave index of each task. Returns 0 on success, -1 if the graph has a
// cycle. Single batched call replaces the Python-loop leveler for large
// DAGs (the wavefront planner's hot phase).
// ---------------------------------------------------------------------------

int plevel_kahn(uint64_t n, uint64_t m, const uint32_t* src,
                const uint32_t* dst, int32_t* out_level) {
  std::vector<uint32_t> indeg(n, 0);
  std::vector<uint32_t> head(n + 1, 0);
  for (uint64_t i = 0; i < m; ++i) {
    if (src[i] >= n || dst[i] >= n) return -2;
    head[src[i] + 1]++;
    indeg[dst[i]]++;
  }
  for (uint64_t i = 0; i < n; ++i) head[i + 1] += head[i];
  std::vector<uint32_t> adj(m);
  {
    std::vector<uint32_t> cursor(head.begin(), head.end() - 1);
    for (uint64_t i = 0; i < m; ++i) adj[cursor[src[i]]++] = dst[i];
  }
  std::vector<uint32_t> frontier;
  frontier.reserve(n);
  for (uint64_t i = 0; i < n; ++i) {
    out_level[i] = 0;
    if (indeg[i] == 0) frontier.push_back((uint32_t)i);
  }
  uint64_t seen = frontier.size();
  std::vector<uint32_t> next;
  while (!frontier.empty()) {
    next.clear();
    for (uint32_t u : frontier) {
      for (uint32_t k = head[u]; k < head[u + 1]; ++k) {
        uint32_t v = adj[k];
        if (out_level[u] + 1 > out_level[v]) out_level[v] = out_level[u] + 1;
        if (--indeg[v] == 0) {
          next.push_back(v);
          seen++;
        }
      }
    }
    frontier.swap(next);
  }
  return seen == n ? 0 : -1;
}

// ---------------------------------------------------------------------------
// pgraph: static-DAG executor.
//
// The Python side enumerates the task space and successor edges once
// (closed-form PTG iterators), hands the arrays over, and provides a body
// callback. The C++ engine owns dependency countdown, per-worker priority
// scheduling with stealing (the lfq shape: local deque + steal + shared
// overflow), and the worker thread loop. This is the native analog of
// __parsec_context_wait + release_deps for statically-known DAGs.
// ---------------------------------------------------------------------------

typedef int (*pgraph_body_fn)(uint32_t task_id, int32_t worker);

struct PGraphWorker {
  std::deque<uint32_t> dq;  // local tasks, front = hottest
  std::mutex mu;
};

struct PGraph {
  uint32_t n = 0;
  std::vector<std::atomic<int32_t>> deps;  // remaining input deps
  std::vector<int32_t> priority;
  std::vector<uint32_t> head;  // CSR successor adjacency
  std::vector<uint32_t> adj;
  pgraph_body_fn body = nullptr;
  int nworkers = 1;
  std::vector<PGraphWorker> workers;
  std::atomic<uint32_t> remaining{0};
  std::atomic<int> error{0};
  // sleep/wake for starved workers
  std::mutex idle_mu;
  std::condition_variable idle_cv;

  void push_local(int w, uint32_t tid) {
    PGraphWorker& wk = workers[w];
    {
      std::lock_guard<std::mutex> lk(wk.mu);
      // priority order: higher priority to the front (simple insertion at
      // front/back; full sort is not needed — steal takes from the back)
      if (!wk.dq.empty() && priority[tid] < priority[wk.dq.front()])
        wk.dq.push_back(tid);
      else
        wk.dq.push_front(tid);
    }
    idle_cv.notify_one();
  }

  bool pop(int w, uint32_t* out) {
    PGraphWorker& wk = workers[w];
    {
      std::lock_guard<std::mutex> lk(wk.mu);
      if (!wk.dq.empty()) {
        *out = wk.dq.front();
        wk.dq.pop_front();
        return true;
      }
    }
    // steal: scan other workers' backs
    for (int i = 1; i < nworkers; ++i) {
      PGraphWorker& v = workers[(w + i) % nworkers];
      std::lock_guard<std::mutex> lk(v.mu);
      if (!v.dq.empty()) {
        *out = v.dq.back();
        v.dq.pop_back();
        return true;
      }
    }
    return false;
  }

  void worker_main(int w) {
    uint32_t tid;
    while (remaining.load(std::memory_order_acquire) > 0 &&
           error.load(std::memory_order_relaxed) == 0) {
      if (!pop(w, &tid)) {
        std::unique_lock<std::mutex> lk(idle_mu);
        idle_cv.wait_for(lk, std::chrono::milliseconds(1));
        continue;
      }
      int rc = body(tid, w);  // ctypes callback: takes the GIL per call
      if (rc != 0) {
        error.store(rc, std::memory_order_relaxed);
        idle_cv.notify_all();
        return;
      }
      // release successors
      for (uint32_t k = head[tid]; k < head[tid + 1]; ++k) {
        uint32_t v = adj[k];
        if (deps[v].fetch_sub(1, std::memory_order_acq_rel) == 1)
          push_local(w, v);
      }
      if (remaining.fetch_sub(1, std::memory_order_acq_rel) == 1)
        idle_cv.notify_all();
    }
  }
};

void* pgraph_new(uint32_t n, const int32_t* ndeps, const int32_t* priority,
                 uint64_t m, const uint32_t* esrc, const uint32_t* edst,
                 pgraph_body_fn body, int nworkers) {
  PGraph* g = new (std::nothrow) PGraph();
  if (!g) return nullptr;
  g->n = n;
  g->body = body;
  g->nworkers = nworkers < 1 ? 1 : nworkers;
  g->deps = std::vector<std::atomic<int32_t>>(n);
  g->priority.assign(priority, priority + n);
  for (uint32_t i = 0; i < n; ++i)
    g->deps[i].store(ndeps[i], std::memory_order_relaxed);
  g->head.assign(n + 1, 0);
  for (uint64_t i = 0; i < m; ++i) g->head[esrc[i] + 1]++;
  for (uint32_t i = 0; i < n; ++i) g->head[i + 1] += g->head[i];
  g->adj.resize(m);
  std::vector<uint32_t> cursor(g->head.begin(), g->head.end() - 1);
  for (uint64_t i = 0; i < m; ++i) g->adj[cursor[esrc[i]]++] = edst[i];
  g->workers = std::vector<PGraphWorker>(g->nworkers);
  g->remaining.store(n, std::memory_order_relaxed);
  return g;
}

void pgraph_free(void* gp) { delete static_cast<PGraph*>(gp); }

// Run the DAG to completion. Returns 0 on success, the body's nonzero
// return code on task failure, -1 on deadlock (tasks remain but none
// ready — indicates an inconsistent dep count).
//
// NOTE on the GIL: this function is called from Python through ctypes,
// which releases the GIL for the duration of the call; the worker threads'
// body callbacks each re-acquire it. The calling thread participates as
// worker 0.
int pgraph_run(void* gp) {
  PGraph* g = static_cast<PGraph*>(gp);
  // seed ready tasks round-robin across workers
  int w = 0;
  for (uint32_t i = 0; i < g->n; ++i) {
    if (g->deps[i].load(std::memory_order_relaxed) == 0) {
      g->push_local(w, i);
      w = (w + 1) % g->nworkers;
    }
  }
  std::vector<std::thread> threads;
  threads.reserve(g->nworkers - 1);
  for (int i = 1; i < g->nworkers; ++i)
    threads.emplace_back([g, i] { g->worker_main(i); });
  g->worker_main(0);
  for (auto& t : threads) t.join();
  if (g->error.load() != 0) return g->error.load();
  return g->remaining.load() == 0 ? 0 : -1;
}

uint32_t pgraph_remaining(void* gp) {
  return static_cast<PGraph*>(gp)->remaining.load();
}

// ---------------------------------------------------------------------------
// plifo: lock-free LIFO of uint64 items (reference parsec/class/lifo.h —
// the basis of mempools and freelists). ABA protection: the head word
// packs (node index : 32 | generation tag : 32) and nodes live in a
// fixed pool, so a recycled index can't be mistaken for the old one
// unless the 32-bit tag also wraps within one CAS window.
// ---------------------------------------------------------------------------

struct PlifoNode {
  uint64_t item;
  // relaxed atomic: a popper may read the next of a node it does not yet
  // own; the stale value is discarded by the tag CAS, but the access
  // itself must not be a C++ data race
  std::atomic<uint32_t> next;
};

struct Plifo {
  static constexpr uint32_t kNil = 0xffffffffu;
  std::unique_ptr<PlifoNode[]> pool;
  std::atomic<uint64_t> head{((uint64_t)kNil) << 32};  // (idx<<32 | tag)... see pack
  std::atomic<uint64_t> free_head;
  std::atomic<uint32_t> size{0};

  static uint64_t pack(uint32_t idx, uint32_t tag) {
    return ((uint64_t)idx << 32) | tag;
  }
  static uint32_t idx_of(uint64_t w) { return (uint32_t)(w >> 32); }
  static uint32_t tag_of(uint64_t w) { return (uint32_t)w; }
};

void* plifo_new(uint32_t capacity) {
  Plifo* l = new (std::nothrow) Plifo();
  if (!l) return nullptr;
  if (capacity == 0) capacity = 1;
  l->pool.reset(new (std::nothrow) PlifoNode[capacity]);
  if (!l->pool) {
    delete l;
    return nullptr;
  }
  // chain every node onto the free list
  for (uint32_t i = 0; i < capacity; ++i)
    l->pool[i].next.store((i + 1 < capacity) ? i + 1 : Plifo::kNil,
                          std::memory_order_relaxed);
  l->free_head.store(Plifo::pack(0, 0), std::memory_order_relaxed);
  l->head.store(Plifo::pack(Plifo::kNil, 0), std::memory_order_relaxed);
  return l;
}

void plifo_free(void* lp) { delete static_cast<Plifo*>(lp); }

uint32_t plifo_size(void* lp) {
  return static_cast<Plifo*>(lp)->size.load(std::memory_order_relaxed);
}

// internal: pop a node index off a packed stack head
static uint32_t plifo_stack_pop(Plifo* l, std::atomic<uint64_t>& h) {
  uint64_t old = h.load(std::memory_order_acquire);
  while (true) {
    uint32_t idx = Plifo::idx_of(old);
    if (idx == Plifo::kNil) return Plifo::kNil;
    uint64_t next = Plifo::pack(
        l->pool[idx].next.load(std::memory_order_relaxed),
        Plifo::tag_of(old) + 1);
    if (h.compare_exchange_weak(old, next, std::memory_order_acq_rel))
      return idx;
  }
}

static void plifo_stack_push(Plifo* l, std::atomic<uint64_t>& h,
                             uint32_t idx) {
  uint64_t old = h.load(std::memory_order_acquire);
  while (true) {
    l->pool[idx].next.store(Plifo::idx_of(old), std::memory_order_relaxed);
    uint64_t desired = Plifo::pack(idx, Plifo::tag_of(old) + 1);
    if (h.compare_exchange_weak(old, desired, std::memory_order_acq_rel))
      return;
  }
}

int plifo_push(void* lp, uint64_t item) {
  Plifo* l = static_cast<Plifo*>(lp);
  uint32_t idx = plifo_stack_pop(l, l->free_head);
  if (idx == Plifo::kNil) return -1;  // pool exhausted
  l->pool[idx].item = item;
  plifo_stack_push(l, l->head, idx);
  l->size.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

int plifo_pop(void* lp, uint64_t* out) {
  Plifo* l = static_cast<Plifo*>(lp);
  uint32_t idx = plifo_stack_pop(l, l->head);
  if (idx == Plifo::kNil) return 0;
  *out = l->pool[idx].item;
  plifo_stack_push(l, l->free_head, idx);
  l->size.fetch_sub(1, std::memory_order_relaxed);
  return 1;
}

// ---------------------------------------------------------------------------
// phash: bucket-locked resizable hash table, uint64 key -> uint64 value
// (reference parsec/class/parsec_hash_table.c: fine-grain bucket locks,
// resize when the load factor exceeds a threshold). Readers/writers hold
// the table lock shared + their bucket mutex; resize holds it unique.
// ---------------------------------------------------------------------------

struct PhashBucket {
  std::mutex mu;
  std::vector<std::pair<uint64_t, uint64_t>> items;
};

struct Phash {
  std::shared_mutex table_mu;
  std::vector<PhashBucket> buckets;
  // bucket count mirrored atomically: the resize fast-path check reads
  // it without the table lock (buckets.size() itself would race with a
  // concurrent swap under the unique lock)
  std::atomic<uint64_t> nbuckets{0};
  std::atomic<uint64_t> size{0};

  static uint64_t mix(uint64_t k) {
    k ^= k >> 33;
    k *= 0xff51afd7ed558ccdull;
    k ^= k >> 33;
    return k;
  }
  PhashBucket& bucket(uint64_t key) {
    return buckets[mix(key) & (buckets.size() - 1)];
  }
  void maybe_resize();
};

void Phash::maybe_resize() {
  // amortized: grow ×4 when avg bucket chain exceeds 4
  if (size.load(std::memory_order_relaxed) <=
      nbuckets.load(std::memory_order_relaxed) * 4)
    return;
  std::unique_lock<std::shared_mutex> lk(table_mu);
  if (size.load(std::memory_order_relaxed) <= buckets.size() * 4) return;
  std::vector<PhashBucket> next(buckets.size() * 4);
  for (auto& b : buckets)
    for (auto& kv : b.items)
      next[mix(kv.first) & (next.size() - 1)].items.push_back(kv);
  buckets.swap(next);
  nbuckets.store(buckets.size(), std::memory_order_relaxed);
}

void* phash_new(uint32_t nbuckets_hint) {
  Phash* h = new (std::nothrow) Phash();
  if (!h) return nullptr;
  if (nbuckets_hint > (1u << 20)) nbuckets_hint = 1u << 20;  // sane cap;
  // the table resizes itself past this anyway
  uint32_t n = 16;
  while (n < nbuckets_hint) n <<= 1;
  try {
    h->buckets = std::vector<PhashBucket>(n);
  } catch (...) {
    delete h;
    return nullptr;
  }
  h->nbuckets.store(n, std::memory_order_relaxed);
  return h;
}

void phash_free(void* hp) { delete static_cast<Phash*>(hp); }

uint64_t phash_size(void* hp) {
  return static_cast<Phash*>(hp)->size.load(std::memory_order_relaxed);
}

int phash_insert(void* hp, uint64_t key, uint64_t val) {
  Phash* h = static_cast<Phash*>(hp);
  {
    std::shared_lock<std::shared_mutex> tl(h->table_mu);
    PhashBucket& b = h->bucket(key);
    std::lock_guard<std::mutex> lk(b.mu);
    for (auto& kv : b.items)
      if (kv.first == key) {
        kv.second = val;
        return 1;  // replaced
      }
    b.items.emplace_back(key, val);
    h->size.fetch_add(1, std::memory_order_relaxed);
  }
  h->maybe_resize();
  return 0;
}

int phash_find(void* hp, uint64_t key, uint64_t* out) {
  Phash* h = static_cast<Phash*>(hp);
  std::shared_lock<std::shared_mutex> tl(h->table_mu);
  PhashBucket& b = h->bucket(key);
  std::lock_guard<std::mutex> lk(b.mu);
  for (auto& kv : b.items)
    if (kv.first == key) {
      if (out) *out = kv.second;
      return 1;
    }
  return 0;
}

int phash_remove(void* hp, uint64_t key, uint64_t* out) {
  Phash* h = static_cast<Phash*>(hp);
  std::shared_lock<std::shared_mutex> tl(h->table_mu);
  PhashBucket& b = h->bucket(key);
  std::lock_guard<std::mutex> lk(b.mu);
  for (size_t i = 0; i < b.items.size(); ++i)
    if (b.items[i].first == key) {
      if (out) *out = b.items[i].second;
      b.items[i] = b.items.back();
      b.items.pop_back();
      h->size.fetch_sub(1, std::memory_order_relaxed);
      return 1;
    }
  return 0;
}

// ---------------------------------------------------------------------------
// pmempool: per-thread freelists of fixed-size elements (reference
// parsec/mempool.c: thread-owned freelists with cross-thread release —
// an element released by another thread goes to the shared overflow).
// ---------------------------------------------------------------------------

struct Pmempool {
  uint32_t elt_size;
  int nthreads;
  std::vector<std::vector<void*>> local;  // per-thread freelist
  std::vector<std::mutex> local_mu;       // cross-thread release guard
  std::atomic<uint64_t> outstanding{0};
  std::atomic<uint64_t> allocated{0};
};

void* pmempool_new(uint32_t elt_size, int nthreads) {
  if (elt_size == 0 || nthreads < 1) return nullptr;
  Pmempool* p = new (std::nothrow) Pmempool();
  if (!p) return nullptr;
  p->elt_size = elt_size < 8 ? 8 : elt_size;
  p->nthreads = nthreads;
  try {
    p->local = std::vector<std::vector<void*>>(nthreads);
    p->local_mu = std::vector<std::mutex>(nthreads);
  } catch (...) {
    delete p;
    return nullptr;
  }
  return p;
}

void pmempool_free(void* pp) {
  Pmempool* p = static_cast<Pmempool*>(pp);
  for (auto& fl : p->local)
    for (void* e : fl) ::operator delete(e);
  delete p;
}

void* pmempool_alloc(void* pp, int thread) {
  Pmempool* p = static_cast<Pmempool*>(pp);
  if (thread < 0 || thread >= p->nthreads) thread = 0;
  void* e = nullptr;
  {
    std::lock_guard<std::mutex> lk(p->local_mu[thread]);
    auto& fl = p->local[thread];
    if (!fl.empty()) {
      e = fl.back();
      fl.pop_back();
    }
  }
  if (!e) {
    e = ::operator new(p->elt_size, std::nothrow);
    if (!e) return nullptr;
    p->allocated.fetch_add(1, std::memory_order_relaxed);
  }
  p->outstanding.fetch_add(1, std::memory_order_relaxed);
  return e;
}

void pmempool_release(void* pp, int thread, void* elt) {
  Pmempool* p = static_cast<Pmempool*>(pp);
  if (thread < 0 || thread >= p->nthreads) thread = 0;
  {
    std::lock_guard<std::mutex> lk(p->local_mu[thread]);
    p->local[thread].push_back(elt);
  }
  p->outstanding.fetch_sub(1, std::memory_order_relaxed);
}

uint64_t pmempool_outstanding(void* pp) {
  return static_cast<Pmempool*>(pp)->outstanding.load(
      std::memory_order_relaxed);
}

uint64_t pmempool_allocated(void* pp) {
  return static_cast<Pmempool*>(pp)->allocated.load(
      std::memory_order_relaxed);
}

}  // extern "C"
