"""Local-queue schedulers with work stealing.

Reference modules (parsec/mca/sched/):
- ``lfq``: local flat queues, hierarchical steal core→socket→node, bounded
  per-thread buffer with overflow to a system dequeue (sched/lfq, 365 LoC,
  sched_local_queues_utils.h).
- ``ll``: per-thread lock-free LIFO, steal from others (sched/ll, 406).
- ``llp``: per-thread LIFO kept priority-sorted (sched/llp, 790).
- ``pbq``: priority-based local flat queues (sched/pbq, 357).
- ``ltq``: local tree queues — tree-shaped steal order (sched/ltq, 448).
- ``lhq``: local hierarchical queues — one queue per topology level
  (sched/lhq, 386).

All steal only inside the stream's virtual process (vpmap scoping,
parsec.c:336-382). The Python implementations share a per-stream
deque-with-lock structure; the native C++ core supplies the lock-free
versions when loaded.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

from .base import Scheduler, vp_peers
from ..core.task import Task


class _LocalDeque:
    __slots__ = ("dq", "lock")

    def __init__(self) -> None:
        self.dq = deque()
        self.lock = threading.Lock()

    def push_front(self, items) -> None:
        with self.lock:
            self.dq.extendleft(reversed(items))

    def push_back(self, items) -> None:
        with self.lock:
            self.dq.extend(items)

    def pop_front(self) -> Optional[Task]:
        # empty fast path without the lock (deque truthiness is
        # GIL-atomic): steal scans walk every VP peer's deque, and
        # paying a lock acquire per EMPTY victim dominated the starved
        # select path. A push racing the check is caught by the next
        # scan / the schedule() wakeup, exactly like a pop that lost
        # the lock race.
        if not self.dq:
            return None
        with self.lock:
            return self.dq.popleft() if self.dq else None

    def pop_back(self) -> Optional[Task]:
        if not self.dq:
            return None
        with self.lock:
            return self.dq.pop() if self.dq else None

    def __len__(self) -> int:
        return len(self.dq)


class _LocalQueueScheduler(Scheduler):
    """Shared skeleton: per-stream deque; select = local pop, else steal
    from VP peers, else system overflow queue."""

    local_bound = 0          # >0: bounded local buffer, overflow to system
    # the native DTD engine's per-worker plifo queues + steal ARE this
    # family's structure in C++ — the worker loop pumps them when
    # select() comes up dry (runtime.native_dtd; dsl/dtd_native.py)
    native_dtd_capable = True

    def install(self, context) -> None:
        super().install(context)
        self.system = _LocalDeque()       # overflow / no-stream pushes

    def flow_init(self, es) -> None:
        es.sched_obj = _LocalDeque()
        es._steal_order = None      # invalidate on (re)install

    def _push_local(self, q: _LocalDeque, tasks, distance: int) -> None:
        if distance <= 0:
            q.push_front(tasks)
        else:
            q.push_back(tasks)

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        if es is None or getattr(es, "sched_obj", None) is None:
            self.system.push_back(tasks)
            return
        q = es.sched_obj
        if self.local_bound and len(q) + len(tasks) > self.local_bound:
            fit = max(0, self.local_bound - len(q))
            self._push_local(q, tasks[:fit], distance)
            self.system.push_back(tasks[fit:])
        else:
            self._push_local(q, tasks, distance)

    def _pop_local(self, q: _LocalDeque) -> Optional[Task]:
        return q.pop_front()

    def _steal(self, q: _LocalDeque) -> Optional[Task]:
        return q.pop_back()

    def select(self, es) -> Optional[Task]:
        while True:
            t = self._pop_local(es.sched_obj)
            if t is None:
                t = self._steal_and_system(es)
            if t is not None and \
                    getattr(getattr(t, "taskpool", None), "cancelled",
                            False):
                # cancelled pool (serving deadline): drop and keep
                # selecting — the decrement drains the already-
                # terminated pool's idempotent termdet counters
                # (getattr: fidelity harnesses feed bare fake tasks)
                t.taskpool.addto_nb_tasks(-1)
                continue
            return t

    def _steal_and_system(self, es) -> Optional[Task]:
        """Steal from VP peers (topology-fixed order, precomputed
        WITHOUT self and cached on the stream — no per-scan identity
        test), then drain the system overflow queue."""
        order = es._steal_order
        if order is None:
            order = es._steal_order = tuple(
                p for p in self._steal_order(es) if p is not es)
        for peer in order:
            t = self._steal(peer.sched_obj)
            if t is not None:
                es.stats["stolen"] += 1     # pins/print_steals counter
                return t
        t = self.system.pop_front()
        if t is not None:
            es.stats["stolen"] += 1
        return t

    def _steal_order(self, es):
        return vp_peers(es)

    def pending_tasks(self) -> int:
        n = len(self.system)
        for s in self.context.streams:
            q = getattr(s, "sched_obj", None)
            if q is not None:
                n += len(q)
        return n


def _span_order(es):
    """Hierarchical (core→pair→quad→…→VP) peer order: nearest
    topology neighbors first. Stands in for hwloc levels (vpmap-scoped;
    reference sched_local_queues_utils.h steal hierarchy)."""
    peers = sorted((s for s in es.context.streams if s.vp_id == es.vp_id),
                   key=lambda s: s.th_id)
    me = next(i for i, s in enumerate(peers) if s is es)
    order = []
    span = 2
    while span <= max(len(peers), 2):
        base = (me // span) * span
        for i in range(base, min(base + span, len(peers))):
            if peers[i] not in order:
                order.append(peers[i])
        span *= 2
    for p in peers:
        if p not in order:
            order.append(p)
    return order


class LFQScheduler(_LocalQueueScheduler):
    """Local flat queues: bounded per-thread buffer (reference hbbuffer),
    overflow to the system dequeue, HIERARCHICAL steal order
    (core→pair→quad→…, nearest first). ``distance > 0`` skips the local
    buffer entirely — the ordered-ring semantics of sched.h:243-250:
    far-distance tasks go where any starving thread finds them, which is
    what prevents the re-schedule livelock the reference warns about."""
    name = "lfq"
    local_bound = 64

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        if distance > 0 or es is None or \
                getattr(es, "sched_obj", None) is None:
            self.system.push_back(tasks)
            return
        super().schedule(es, tasks, distance)

    def _steal_order(self, es):
        return _span_order(es)


class LLScheduler(_LocalQueueScheduler):
    """Per-thread LIFO: local pushes/pops at the front (LIFO), steals from
    the back."""
    name = "ll"

    def _push_local(self, q, tasks, distance: int) -> None:
        q.push_front(tasks)


class PBQScheduler(_LocalQueueScheduler):
    """Priority-based local flat queues (reference sched/pbq): a small
    array of flat FIFO queues selected by priority BAND — tasks of
    similar priority stay FIFO-ordered (no total sort), high bands pop
    first. Distinct from llp's totally-ordered LIFO."""
    name = "pbq"
    # priority-policy module: the native LIFO queues would discard the
    # banding — DTD pools stay on the Python path (like wfq/ap/ip/spq)
    native_dtd_capable = False
    n_bands = 4
    band_shift = 4            # priority // 16 picks the band (clamped)

    def flow_init(self, es) -> None:
        super().flow_init(es)
        es.sched_obj = _BandedQueues(self.n_bands, self.band_shift)

    def _push_local(self, q, tasks, distance: int) -> None:
        q.push(tasks)

    def _pop_local(self, q):
        return q.pop_front()

    def _steal(self, q):
        return q.pop_back()


class _BandedQueues:
    """Priority-banded flat FIFO queues (pbq's structure)."""

    __slots__ = ("bands", "lock", "shift")

    def __init__(self, n_bands: int, shift: int) -> None:
        self.bands = [deque() for _ in range(n_bands)]
        self.lock = threading.Lock()
        self.shift = shift

    def _band(self, task: Task) -> int:
        b = max(0, int(task.priority)) >> self.shift
        return min(b, len(self.bands) - 1)

    def push(self, tasks) -> None:
        with self.lock:
            for t in tasks:
                self.bands[self._band(t)].append(t)

    def pop_front(self) -> Optional[Task]:
        if not any(self.bands):     # lock-free empty scan (see _LocalDeque)
            return None
        with self.lock:
            for band in reversed(self.bands):     # high band first
                if band:
                    return band.popleft()
        return None

    def pop_back(self) -> Optional[Task]:
        """Steal side: take from the LOWEST band's tail (leave the
        victim its high-priority work)."""
        if not any(self.bands):
            return None
        with self.lock:
            for band in self.bands:
                if band:
                    return band.pop()
        return None

    def __len__(self) -> int:
        return sum(len(b) for b in self.bands)


class LLPScheduler(_LocalQueueScheduler):
    """Per-thread list kept TOTALLY priority-sorted: inserts merge the
    incoming (sorted) batch into the sorted chain — the reference's
    detach/merge/reattach (sched/llp, 790 LoC) — rather than pbq's
    banded FIFO. Steals take the victim's lowest-priority tail."""
    name = "llp"
    native_dtd_capable = False      # priority policy — see pbq

    def _push_local(self, q, tasks, distance: int) -> None:
        batch = sorted(tasks, key=lambda t: -t.priority)
        with q.lock:
            merged, it = [], iter(q.dq)
            cur = next(it, None)
            for t in batch:
                while cur is not None and cur.priority >= t.priority:
                    merged.append(cur)
                    cur = next(it, None)
                merged.append(t)
            while cur is not None:
                merged.append(cur)
                cur = next(it, None)
            q.dq = deque(merged)


class LTQScheduler(_LocalQueueScheduler):
    """Local tree queues: steal order walks the VP as a binary tree rooted
    at the stealing stream (children 2i+1/2i+2), approximating the
    reference's tree-shaped steal topology."""
    name = "ltq"

    def _steal_order(self, es):
        peers = sorted((s for s in es.context.streams if s.vp_id == es.vp_id),
                       key=lambda s: s.th_id)
        n = len(peers)
        me = next(i for i, s in enumerate(peers) if s is es)
        order, frontier = [], [me]
        seen = set()
        while frontier:
            i = frontier.pop(0)
            if i in seen or i >= n:
                continue
            seen.add(i)
            order.append(peers[i])
            frontier.extend(((2 * i + 1) % n, (2 * i + 2) % n))
            if len(seen) == n:
                break
        for i in range(n):
            if i not in seen:
                order.append(peers[i])
        return order


class LHQScheduler(_LocalQueueScheduler):
    """Local hierarchical queues (reference sched/lhq): one ACTUAL queue
    per topology level — level 0 private, level 1 shared by the stream
    pair, level 2 by the quad, …, top level by the whole VP. Without
    hwloc the levels are the power-of-two groupings of the vpmap.

    ``distance`` is the ordered-ring hint of sched.h:243-250 realized
    structurally: a task scheduled at distance d is pushed to the
    level-d queue, visible to 2^d streams — the farther the hint, the
    wider the task's availability. select() walks levels inward-out,
    then steals peers' private queues (nearest-first), then the system
    dequeue."""
    name = "lhq"

    def install(self, context) -> None:
        super().install(context)
        self._shared = {}
        self._shared_lock = threading.Lock()
        self._level_cache = {}

    def flow_init(self, es) -> None:
        super().flow_init(es)
        self._level_cache.pop((es.vp_id, es.th_id), None)

    def _levels(self, es):
        """Level queues from private to VP-wide. Cache key is the
        stream's stable identity (vp, thread) — ``id(es)`` of a
        collected stream can be reused by a new object and silently
        serve the old stream's levels."""
        cached = self._level_cache.get((es.vp_id, es.th_id))
        if cached is not None:
            return cached
        n_vp = sum(1 for s in es.context.streams if s.vp_id == es.vp_id)
        levels = [es.sched_obj]
        span = 2
        while span < 2 * max(n_vp, 1):
            group = es.th_id // span
            with self._shared_lock:
                q = self._shared.setdefault(
                    (es.vp_id, span, group), _LocalDeque())
            levels.append(q)
            if span >= n_vp:
                break
            span *= 2
        self._level_cache[(es.vp_id, es.th_id)] = levels
        return levels

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        if es is None or getattr(es, "sched_obj", None) is None:
            self.system.push_back(tasks)
            return
        levels = self._levels(es)
        lvl = min(max(distance, 0), len(levels) - 1)
        if distance <= 0:
            levels[0].push_front(tasks)
        else:
            # distance > 0 clamped to the top level still goes to the
            # BACK: an AGAIN-rescheduled task push_front'ed on a
            # single-stream VP would forever precede the work it waits
            # for (the livelock sched.h:243-250 warns about)
            levels[lvl].push_back(tasks)

    def _steal_order(self, es):
        return _span_order(es)

    def select(self, es) -> Optional[Task]:
        levels = self._levels(es)
        t = levels[0].pop_front()
        if t is not None:
            return t
        for q in levels[1:]:
            t = q.pop_front()
            if t is not None:
                es.stats["level_pops"] = es.stats.get("level_pops", 0) + 1
                return t
        return self._steal_and_system(es)

    def pending_tasks(self) -> int:
        n = super().pending_tasks()
        with self._shared_lock:
            for q in self._shared.values():
                n += len(q)
        return n
