"""Local-queue schedulers with work stealing.

Reference modules (parsec/mca/sched/):
- ``lfq``: local flat queues, hierarchical steal core→socket→node, bounded
  per-thread buffer with overflow to a system dequeue (sched/lfq, 365 LoC,
  sched_local_queues_utils.h).
- ``ll``: per-thread lock-free LIFO, steal from others (sched/ll, 406).
- ``llp``: per-thread LIFO kept priority-sorted (sched/llp, 790).
- ``pbq``: priority-based local flat queues (sched/pbq, 357).
- ``ltq``: local tree queues — tree-shaped steal order (sched/ltq, 448).
- ``lhq``: local hierarchical queues — one queue per topology level
  (sched/lhq, 386).

All steal only inside the stream's virtual process (vpmap scoping,
parsec.c:336-382). The Python implementations share a per-stream
deque-with-lock structure; the native C++ core supplies the lock-free
versions when loaded.
"""

from __future__ import annotations

import random
import threading
from collections import deque
from typing import Optional, Sequence

from .base import Scheduler, vp_peers
from ..core.task import Task


class _LocalDeque:
    __slots__ = ("dq", "lock")

    def __init__(self) -> None:
        self.dq = deque()
        self.lock = threading.Lock()

    def push_front(self, items) -> None:
        with self.lock:
            self.dq.extendleft(reversed(items))

    def push_back(self, items) -> None:
        with self.lock:
            self.dq.extend(items)

    def pop_front(self) -> Optional[Task]:
        with self.lock:
            return self.dq.popleft() if self.dq else None

    def pop_back(self) -> Optional[Task]:
        with self.lock:
            return self.dq.pop() if self.dq else None

    def __len__(self) -> int:
        return len(self.dq)


class _LocalQueueScheduler(Scheduler):
    """Shared skeleton: per-stream deque; select = local pop, else steal
    from VP peers, else system overflow queue."""

    local_bound = 0          # >0: bounded local buffer, overflow to system

    def install(self, context) -> None:
        super().install(context)
        self.system = _LocalDeque()       # overflow / no-stream pushes

    def flow_init(self, es) -> None:
        es.sched_obj = _LocalDeque()
        es._steal_order = None      # invalidate on (re)install

    def _push_local(self, q: _LocalDeque, tasks, distance: int) -> None:
        if distance <= 0:
            q.push_front(tasks)
        else:
            q.push_back(tasks)

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        if es is None or getattr(es, "sched_obj", None) is None:
            self.system.push_back(tasks)
            return
        q = es.sched_obj
        if self.local_bound and len(q) + len(tasks) > self.local_bound:
            fit = max(0, self.local_bound - len(q))
            self._push_local(q, tasks[:fit], distance)
            self.system.push_back(tasks[fit:])
        else:
            self._push_local(q, tasks, distance)

    def _pop_local(self, q: _LocalDeque) -> Optional[Task]:
        return q.pop_front()

    def _steal(self, q: _LocalDeque) -> Optional[Task]:
        return q.pop_back()

    def select(self, es) -> Optional[Task]:
        t = self._pop_local(es.sched_obj)
        if t is not None:
            return t
        # steal order is topology-fixed: computed once, cached on the stream
        order = es._steal_order
        if order is None:
            order = es._steal_order = self._steal_order(es)
        for peer in order:
            if peer is es:
                continue
            t = self._steal(peer.sched_obj)
            if t is not None:
                es.stats["stolen"] += 1     # pins/print_steals counter
                return t
        t = self.system.pop_front()
        if t is not None:
            es.stats["stolen"] += 1
        return t

    def _steal_order(self, es):
        return vp_peers(es)

    def pending_tasks(self) -> int:
        n = len(self.system)
        for s in self.context.streams:
            q = getattr(s, "sched_obj", None)
            if q is not None:
                n += len(q)
        return n


class LFQScheduler(_LocalQueueScheduler):
    """Local flat queues, bounded buffer, hierarchical steal."""
    name = "lfq"
    local_bound = 64          # reference hbbuffer is bounded per-thread


class LLScheduler(_LocalQueueScheduler):
    """Per-thread LIFO: local pushes/pops at the front (LIFO), steals from
    the back."""
    name = "ll"

    def _push_local(self, q, tasks, distance: int) -> None:
        q.push_front(tasks)


class PBQScheduler(_LocalQueueScheduler):
    """Priority-based local flat queues: local ring kept priority-ordered."""
    name = "pbq"

    def _push_local(self, q, tasks, distance: int) -> None:
        with q.lock:
            q.dq.extend(tasks)
            q.dq = deque(sorted(q.dq, key=lambda t: -t.priority))


class LLPScheduler(PBQScheduler):
    """Per-thread LIFO sorted by priority (reference detaches, merges and
    reattaches the chain on insert — here a sort under the stream lock)."""
    name = "llp"


class LTQScheduler(_LocalQueueScheduler):
    """Local tree queues: steal order walks the VP as a binary tree rooted
    at the stealing stream (children 2i+1/2i+2), approximating the
    reference's tree-shaped steal topology."""
    name = "ltq"

    def _steal_order(self, es):
        peers = sorted((s for s in es.context.streams if s.vp_id == es.vp_id),
                       key=lambda s: s.th_id)
        n = len(peers)
        me = next(i for i, s in enumerate(peers) if s is es)
        order, frontier = [], [me]
        seen = set()
        while frontier:
            i = frontier.pop(0)
            if i in seen or i >= n:
                continue
            seen.add(i)
            order.append(peers[i])
            frontier.extend(((2 * i + 1) % n, (2 * i + 2) % n))
            if len(seen) == n:
                break
        for i in range(n):
            if i not in seen:
                order.append(peers[i])
        return order


class LHQScheduler(_LocalQueueScheduler):
    """Local hierarchical queues: one queue per topology level. Without
    hwloc, levels are (self, pair, quad, ... VP); steal walks levels
    outward — realized as pair-first steal order."""
    name = "lhq"

    def _steal_order(self, es):
        peers = sorted((s for s in es.context.streams if s.vp_id == es.vp_id),
                       key=lambda s: s.th_id)
        me = next(i for i, s in enumerate(peers) if s is es)
        order = []
        span = 2
        while span <= max(len(peers), 2):
            base = (me // span) * span
            for i in range(base, min(base + span, len(peers))):
                if peers[i] not in order:
                    order.append(peers[i])
            span *= 2
        for p in peers:
            if p not in order:
                order.append(p)
        return order
