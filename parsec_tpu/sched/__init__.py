"""Scheduler framework (reference parsec/mca/sched/, 11 modules).

Common interface (sched.h:183-353): ``install(context)``,
``flow_init(es)`` (per-stream structures), ``schedule(es, tasks, distance)``,
``select(es) -> task``, ``remove()``. The ``distance`` hint orders how soon
tasks should run; schedulers that ignore it can livelock (sched.h:243-250).

Work stealing respects virtual processes: an execution stream only steals
inside its VP (reference vpmap, parsec.c:336-382).

Selected MCA-style by the ``sched`` param (scheduling.c:246-272 analog).
"""

from .base import Scheduler
from .local_queues import LFQScheduler, LLScheduler, LLPScheduler, \
    PBQScheduler, LTQScheduler, LHQScheduler
from .global_queues import APScheduler, IPScheduler, GDScheduler, \
    SPQScheduler, RNDScheduler
from .fair import WFQScheduler
from ..utils import mca_param

_MODULES = {
    "wfq": WFQScheduler,   # weighted-fair across taskpools (serving)
    "lfq": LFQScheduler,   # local flat queues + hierarchical steal
    "lhq": LHQScheduler,   # local hierarchical queues
    "ltq": LTQScheduler,   # local tree queues
    "ll": LLScheduler,     # per-thread lock-free LIFO + steal
    "llp": LLPScheduler,   # per-thread priority-sorted LIFO
    "ap": APScheduler,     # single global priority list
    "ip": IPScheduler,     # inverse priorities
    "gd": GDScheduler,     # single global dequeue
    "pbq": PBQScheduler,   # priority-based local flat queues
    "spq": SPQScheduler,   # simple priority queue by (distance, priority)
    "rnd": RNDScheduler,   # random placement (stress/debug)
}

mca_param.register("sched", "lfq",
                   help=f"scheduler module ({', '.join(sorted(_MODULES))})")


def new_scheduler(name=None) -> Scheduler:
    name = name or mca_param.get("sched", "lfq")
    try:
        cls = _MODULES[name]
    except KeyError:
        raise ValueError(f"unknown scheduler {name!r}; have {sorted(_MODULES)}")
    return cls()


def available() -> list:
    return sorted(_MODULES)


def register_module(name: str, cls) -> None:
    _MODULES[name] = cls
