"""Scheduler base class (reference sched.h:183-353)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core.task import Task


class Scheduler:
    """Base scheduler module.

    Lifecycle: ``install(context)`` once, then ``flow_init(es)`` per
    execution stream, then concurrent ``schedule``/``select`` calls from
    worker threads, finally ``remove(context)``.
    """

    name = "base"

    # Opt-in for the native DTD engine (dsl/dtd_native.py): True means
    # this scheduler tolerates single-rank DTD pools draining through
    # the native per-worker queues instead of its own structures (the
    # worker loop pumps the engine when select() starves). Schedulers
    # whose POLICY must observe every task — wfq's weighted-fair
    # arbitration — keep this False so their pools stay on the
    # instrumented Python path.
    native_dtd_capable = False

    def install(self, context) -> None:
        self.context = context

    def flow_init(self, es) -> None:
        """Allocate per-execution-stream structures (sched.h flow_init)."""

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        """Insert a ring of ready tasks, `distance` hinting how soon they
        should run (0 = immediately / front of queue)."""
        raise NotImplementedError

    def select(self, es) -> Optional[Task]:
        """Pick the next task for this stream, or None if starved."""
        raise NotImplementedError

    def remove(self, context) -> None:
        pass

    # observability (reference PAPI-SDE pending-task gauges)
    def pending_tasks(self) -> int:
        return -1


def vp_peers(es) -> List:
    """Execution streams in the same virtual process as ``es``, steal order:
    self first, then co-VP streams by increasing distance (reference
    sched_local_queues_utils.h hierarchical steal simplified to ring order
    inside the VP). Streams and VP assignment are fixed at context
    construction, so the order is computed once and cached on the stream —
    select() sits on the worker hot path."""
    cached = getattr(es, "_vp_peers", None)
    if cached is not None:
        return cached
    streams = [s for s in es.context.streams if s.vp_id == es.vp_id]
    streams.sort(key=lambda s: (s.th_id - es.th_id) % max(len(streams), 1))
    es._vp_peers = streams
    return streams
