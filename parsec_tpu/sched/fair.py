"""Weighted-fair scheduler across live taskpools (``sched=wfq``).

The reference schedulers arbitrate between THREADS (steal orders); every
taskpool's tasks land in the same queues, so one tenant inserting faster
than another simply owns the runtime — the starvation mode ROADMAP item 4
names. This module arbitrates between TASKPOOLS: each live pool keeps its
own FIFO ring and the selector runs stride scheduling (Waldspurger-style)
over them — pool p is charged ``STRIDE1 / weight(p)`` virtual time per
selected task, and select() always picks the backlogged pool with the
least virtual time. Long-run service is proportional to
``Taskpool.fair_weight`` regardless of insertion rates, and a freshly
backlogged pool joins at the current virtual floor (start-time fairness:
it cannot retro-claim idle time and monopolize the streams).

Starvation is measurable, not anecdotal: per-pool counters (enqueued /
selected / pending / virtual pass, plus the last-selected wall clock) are
exported via :meth:`WFQScheduler.pool_stats` and surfaced by the
``tenant`` PINS module and ``bench.py --section serving``.

One global lock serializes the queue set. That is the right trade for the
serving shape this scheduler exists for — many concurrent tenants whose
task bodies dwarf the pop — and keeps selection O(live pools). The
throughput-bench schedulers (lfq & co) remain the default elsewhere.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional, Sequence

from .base import Scheduler
from ..core.task import Task
from ..utils import mca_param

#: virtual-time quantum charged to a weight-1.0 pool per selected task
_STRIDE1 = 1 << 20

mca_param.register("serving.kv_prefill_interleave", 4,
                   help="wfq per-pool prefill-lane cadence: when a pool "
                        "has BOTH decode and prefill (priority < 0) "
                        "tasks queued, every Nth selection from that "
                        "pool serves the prefill lane — long chunked "
                        "prefills make progress without starving the "
                        "pool's decode p99 (0/1 = strict alternation, "
                        "no decode preference)")


def lane_choice(ndq: int, npq: int, nsel: int, interleave: int) -> str:
    """Pure per-pool lane-selection semantics of :meth:`select` —
    which lane ("decode" | "prefill") serves the pool's next slot,
    given the lane backlogs, the pool's selection counter AFTER its
    increment, and ``serving.kv_prefill_interleave``.

    Factored out so the protocol models (analysis/protomodels.py) check
    the EXACT function the scheduler runs: when both lanes are
    backlogged the prefill lane gets every Nth slot of the pool's
    service — long prompts make progress, decode keeps its p99 —
    and ``interleave<=1`` clamps to strict alternation ("no decode
    preference"), never starvation.
    """
    if not ndq:
        return "prefill"
    if not npq:
        return "decode"
    if nsel % max(interleave, 2) == 0:
        return "prefill"
    return "decode"


class _PoolQueue:
    __slots__ = ("dq", "pq", "nsel", "vpass", "enqueued", "selected",
                 "last_selected_t")

    def __init__(self, vfloor: float):
        self.dq = deque()            # default (decode) lane
        self.pq = deque()            # prefill lane: priority < 0 tasks
        self.nsel = 0                # per-pool selection cadence counter
        self.vpass = vfloor
        self.enqueued = 0
        self.selected = 0
        self.last_selected_t = 0.0

    def backlogged(self) -> bool:
        return bool(self.dq) or bool(self.pq)

    def __len__(self) -> int:
        return len(self.dq) + len(self.pq)


class WFQScheduler(Scheduler):
    """Weighted-fair (stride) selection across live taskpools."""

    name = "wfq"
    # weighted-fair arbitration must SEE every task to charge virtual
    # time and populate pool_stats — DTD pools under wfq therefore stay
    # on the instrumented Python path even when runtime.native_dtd is
    # on (the documented serving-side arm of the fallback rule)
    native_dtd_capable = False

    def install(self, context) -> None:
        super().install(context)
        self._lock = threading.Lock()
        self._queues: Dict[object, _PoolQueue] = {}   # taskpool -> queue
        # global virtual clock: the vpass the last selection served at.
        # Non-decreasing (select always takes the minimum pass), and it
        # PERSISTS across idle instants — a pool created or rejoining
        # after the queues momentarily drained joins HERE, not at 0,
        # which would let it monopolize selection until it caught up
        # with the long-lived pools' accumulated vpass.
        self._vclock = 0.0

    def flow_init(self, es) -> None:
        es.sched_obj = None          # no per-stream structure

    def _vfloor_locked(self) -> float:
        """Join point for pools becoming backlogged: the global virtual
        clock (see install) — never 0-reset by an idle instant."""
        return self._vclock

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        with self._lock:
            floor = self._vfloor_locked()
            for t in tasks:
                q = self._queues.get(t.taskpool)
                if q is None:
                    q = self._queues[t.taskpool] = _PoolQueue(floor)
                elif not q.backlogged():
                    # idle pool rejoining: forfeit accumulated lag so it
                    # cannot burst past active pools (start-time fairness)
                    q.vpass = max(q.vpass, floor)
                # prefill lane (ISSUE 15): chunked-prefill tasks insert
                # at priority < 0 — within the pool they yield to decode
                # tasks at the kv_prefill_interleave cadence
                (q.pq if getattr(t, "priority", 0) < 0
                 else q.dq).append(t)
                q.enqueued += 1

    def _drop_cancelled_locked(self, tp, q: _PoolQueue) -> None:
        n = len(q)
        q.dq.clear()
        q.pq.clear()
        del self._queues[tp]
        for _ in range(n):
            # idempotent-termination contract: the cancelled pool already
            # force-terminated; these decrements only drain its counters
            tp.addto_nb_tasks(-1)

    def select(self, es) -> Optional[Task]:
        # cached_get: select() runs once per task on every worker — a
        # full registry get (global lock + env resolve) here would be
        # a cross-worker serialization point
        interleave = int(mca_param.cached_get(
            "serving.kv_prefill_interleave", 4))
        with self._lock:
            # a persistent serving context sees thousands of pools over
            # its lifetime: drop the bookkeeping of finished ones here
            # (empty queue + terminated pool) or _queues grows forever
            done = [tp for tp, q in self._queues.items()
                    if not q.backlogged() and (tp.completed
                                               or tp.cancelled)]
            for tp in done:
                del self._queues[tp]
            while True:
                best_tp, best_q = None, None
                for tp, q in self._queues.items():
                    if not q.backlogged():
                        continue
                    if tp.cancelled:
                        self._drop_cancelled_locked(tp, q)
                        break        # dict mutated: rescan
                    if best_q is None or q.vpass < best_q.vpass:
                        best_tp, best_q = tp, q
                else:
                    if best_q is None:
                        return None
                    best_q.nsel += 1
                    lane = lane_choice(len(best_q.dq), len(best_q.pq),
                                       best_q.nsel, interleave)
                    task = (best_q.pq if lane == "prefill"
                            else best_q.dq).popleft()
                    if best_q.vpass > self._vclock:
                        self._vclock = best_q.vpass
                    w = max(float(getattr(best_tp, "fair_weight", 1.0)),
                            1e-6)
                    best_q.vpass += _STRIDE1 / w
                    best_q.selected += 1
                    best_q.last_selected_t = time.monotonic()
                    return task

    def pending_tasks(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def pool_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-pool service accounting keyed by taskpool name — the
        starvation evidence (selected vs enqueued vs pending, and how
        stale the pool's last service is)."""
        now = time.monotonic()
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for tp, q in self._queues.items():
                key = tp.name
                if key in out:
                    # Taskpool names are not unique: suffix collisions
                    # so no pool's starvation row shadows another's
                    key = f"{tp.name}#{tp.taskpool_id}"
                out[key] = {
                    "tenant": getattr(tp, "tenant_name", None),
                    "weight": float(getattr(tp, "fair_weight", 1.0)),
                    "enqueued": q.enqueued,
                    "selected": q.selected,
                    "pending": len(q),
                    "prefill_pending": len(q.pq),
                    "vpass": q.vpass,
                    "since_selected_s": (
                        round(now - q.last_selected_t, 6)
                        if q.last_selected_t else None),
                }
        return out

    def remove(self, context) -> None:
        with self._lock:
            self._queues.clear()
