"""Global-queue schedulers.

Reference modules (parsec/mca/sched/):
- ``ap``: single global list ordered by absolute priority (sched/ap, 259).
- ``ip``: inverse priorities — LIFO-ish global order (sched/ip, 258).
- ``gd``: single global dequeue, FIFO (sched/gd, 314).
- ``spq``: simple priority queue sorted by (distance, priority); the
  documented walkthrough scheduler (sched.h:100-170; sched/spq, 347).
- ``rnd``: random placement for stress/debug (sched/rnd, 271).
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from collections import deque
from typing import Optional, Sequence

from .base import Scheduler
from ..core.task import Task

_tie = itertools.count()


class _HeapScheduler(Scheduler):
    # ap/ip/spq are PRIORITY-policy schedulers: the whole module is the
    # ordering key, and the native DTD engine's LIFO/steal queues would
    # silently discard it — like wfq, they keep DTD pools on the Python
    # path (native_dtd_capable stays False from the base class)

    def install(self, context) -> None:
        super().install(context)
        self.heap = []
        self.lock = threading.Lock()

    def _key(self, task: Task, distance: int):
        raise NotImplementedError

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        with self.lock:
            for t in tasks:
                heapq.heappush(self.heap, (self._key(t, distance), next(_tie), t))

    def select(self, es) -> Optional[Task]:
        with self.lock:
            if not self.heap:
                return None
            return heapq.heappop(self.heap)[2]

    def pending_tasks(self) -> int:
        return len(self.heap)


class APScheduler(_HeapScheduler):
    """Absolute priorities: highest priority first."""
    name = "ap"

    def _key(self, task: Task, distance: int):
        return -task.priority


class IPScheduler(_HeapScheduler):
    """Inverse priorities: lowest priority first (LIFO-ish drain order)."""
    name = "ip"

    def _key(self, task: Task, distance: int):
        return task.priority


class SPQScheduler(_HeapScheduler):
    """Sorted by (distance, -priority): tasks hinted to run sooner win, then
    priority breaks ties (sched.h:100-170)."""
    name = "spq"

    def _key(self, task: Task, distance: int):
        return (distance, -task.priority)


class GDScheduler(Scheduler):
    """Single global dequeue: distance 0 pushes to the front, others to the
    back; select pops the front."""
    name = "gd"
    native_dtd_capable = True

    def install(self, context) -> None:
        super().install(context)
        self.dq = deque()
        self.lock = threading.Lock()

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        with self.lock:
            if distance <= 0:
                self.dq.extendleft(reversed(tasks))
            else:
                self.dq.extend(tasks)

    def select(self, es) -> Optional[Task]:
        with self.lock:
            return self.dq.popleft() if self.dq else None

    def pending_tasks(self) -> int:
        return len(self.dq)


class RNDScheduler(Scheduler):
    """Random selection — scheduling-order stress tests (sched/rnd)."""
    name = "rnd"

    def install(self, context) -> None:
        super().install(context)
        self.tasks = []
        self.lock = threading.Lock()
        self.rng = random.Random(0xC0FFEE)

    def schedule(self, es, tasks: Sequence[Task], distance: int = 0) -> None:
        with self.lock:
            self.tasks.extend(tasks)

    def select(self, es) -> Optional[Task]:
        with self.lock:
            if not self.tasks:
                return None
            i = self.rng.randrange(len(self.tasks))
            self.tasks[i], self.tasks[-1] = self.tasks[-1], self.tasks[i]
            return self.tasks.pop()

    def pending_tasks(self) -> int:
        return len(self.tasks)
