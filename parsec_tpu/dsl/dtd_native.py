"""Native DTD engine: the insert→release hot loop behind the C ABI.

PaRSEC's entire dynamic-task core is native C — insertion
(insert_function.c), the dependency hash table (parsec.c:1503-1649), the
scheduler queues (mca/sched/*) and the worker progress loop
(scheduling.c:537-676) — precisely so per-task overhead stays in the
microseconds. This module is the TPU build's equivalent: it drives the
``pdtd_*`` engine in ``_native/core.cpp`` so that insert, dependency
countdown, select, steal, and release all run in C++ with the GIL
released, and Python is entered only to run task bodies. A body
registered with :func:`register_native_body` (a no-op) lets null tasks
complete entirely inside the native pump — the shape of the classic
tasks/s scheduling microbenchmark.

Engine selection (``runtime.native_dtd``, resolved once per taskpool at
first insert):

- ``auto`` (default): native when the library builds AND the pool is
  eligible; silently the Python path otherwise.
- ``1``: same eligibility rules, but an unavailable toolchain is a hard
  error instead of a silent fallback.
- ``0``: always the Python path.

Eligibility — the **instrumented-fallback rule** (ISSUE 13 moved the
line; ISSUE 14 moved it again for dfsan: observation must never change
which engine runs, PaRSEC's PINS/profiling contract): a pool stays on
the (instrumented) Python engine only when one of these holds, with
the reason stated per row:

- distributed (``nb_ranks > 1``) — replay/shell semantics are Python;
- a **semantically-intrusive** observer with no native source is
  live: the Grapher (records every dep edge as it is released), the
  debug-history EXE ring (expects an EXE mark per task), or a
  per-task PINS sampler with no native equivalent (alperf — per-task
  rusage deltas; counters — per-task counter snapshots;
  iterators_checker — walks each task's iterator state; and the
  straggler watchdog when no live Trace feeds it ring records);
- the context scheduler does not opt in (``native_dtd_capable`` — the
  lfq/ll/ltq/lhq/gd families do; ``wfq`` keeps Python pools so its
  weighted-fair arbitration and ``pool_stats`` observe every task, and
  the PRIORITY-policy modules — llp, pbq, ap, ip, spq — likewise,
  since the native LIFO/steal queues would discard their ordering key);
- a non-CPU device is registered (bodies would route through device
  managers the native pump bypasses).

What does NOT disqualify anymore: a live :class:`~parsec_tpu.
profiling.trace.Trace` (the engine records begin/end/queue-wait spans
into its own per-worker binary event rings — ``pdtd_obs_*`` — which
the trace expands byte-compatibly at dump/scrape time), the always-on
metrics registry, ``runtime.stage_timers`` (stage totals read from the
engine's C++ atomics at scrape), scrape-only PINS modules (``tenant``
— native completions folded per tenant at scrape — and ``overhead``),
and — since ISSUE 14 — the **dfsan race sanitizer** for local DTD
pools: the engine captures insert-time access manifests (tile keys +
modes + linked-pred edges, resolved while the inserter already holds
the tile locks) and enables the event rings, and dfsan replays the
pool at FOLD time over the frozen ring snapshots + manifests
(``DataflowSanitizer.replay_native_pool``) — same happens-before
model, same race reports, bitwise-identical per-tile version digests,
at ring-record cost per task instead of a Python hot loop. The C
lock-discipline recorder (``pdtd_lockdbg_enable``, scraped through
``pdtd_stats``) feeds dfsan's lock-order inversion detector at the
same fold. The ring capacity knob is
``profiling.native_ring_events``.

Serving hooks do NOT force a fallback: ``Taskpool.admission`` runs on
the inserting thread as usual, and a pool with ``on_retire`` simply
marks every task Python-bodied so the tenant window drains exactly once
per completion. ``Taskpool.cancel`` is honored at select time inside
the native pump.

Program-order semantics are preserved exactly (the functional-WAR
guarantee of dsl/dtd.py): the two-phase insert (``pdtd_insert`` links
against in-flight writers, ``pdtd_arm`` makes the batch runnable) lets
the inserter snapshot the committed tile version whenever the linked
writer turns out to have already completed — at that instant no other
writer of the tile can be in flight, because all later writers are in
the still-unarmed batch.
"""

from __future__ import annotations

import ctypes
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import _native
from ..core.task import FlowAccess
from ..utils import mca_param
from ..utils.debug import warning

mca_param.register(
    "runtime.native_dtd", "auto",
    help="run single-rank DTD pools on the native C++ engine: auto "
         "(when the library builds and no per-task observer is live) "
         "| 1 (error if the toolchain is missing) | 0 (Python path)")

# staging-ring row capacity: one pdtd_insert call per ring fill; the
# native side reports the high-water mark as ring_highwater
_RING = 1024
_MAX_PREDS_INIT = 64
# Python-bodied tasks fetched per pump call: one GIL round-trip (and
# one batched completion) per _PUMP_BATCH bodies instead of two ctypes
# calls per task — at 4 workers the per-task calls convoyed on the GIL
_PUMP_BATCH = 32

# fns registered as native no-op bodies: zero-arg, returns None — tasks
# inserted with one of these (and no per-task retire hook) complete
# entirely inside the native pump, never re-entering Python
_NATIVE_BODIES: set = set()


def register_native_body(fn: Callable) -> Callable:
    """Declare ``fn`` a no-op body (zero arguments, returns ``None``):
    tasks inserted with it skip Python entirely on the native engine.
    Returns ``fn`` so it can be used as a decorator."""
    _NATIVE_BODIES.add(fn)
    return fn


def is_native_body(fn: Callable) -> bool:
    return fn in _NATIVE_BODIES


class _NativeWriter:
    """In-flight-writer marker parked in ``_Tile.last_writer`` by the
    native engine (the Python engine parks the Task object there).
    ``dtd.Taskpool.flush`` treats it as busy like a Task."""

    __slots__ = ("seq",)

    def __init__(self, seq: int):
        self.seq = seq


class _Shim:
    """Just enough of a Task for the DTD chore hooks, which only read
    ``task.dsl['argspec']`` (both the eager and the pure/jit hook)."""

    __slots__ = ("dsl",)

    def __init__(self, argspec):
        self.dsl = {"argspec": argspec}


def resolve_mode() -> str:
    """'off' | 'auto' | 'force' from the runtime.native_dtd MCA param."""
    v = str(mca_param.get("runtime.native_dtd", "auto")).lower()
    if v in ("0", "off", "false", "no"):
        return "off"
    if v in ("1", "on", "true", "force", "yes"):
        return "force"
    return "auto"


def engine_for(tp) -> Optional["NativeDTD"]:
    """Build the native engine for ``tp`` if it is eligible (see module
    docstring), else None. Raises when ``runtime.native_dtd=1`` is
    forced but the library cannot be built/loaded — a silent fallback
    would misreport every rate the caller measures."""
    mode = resolve_mode()
    if mode == "off":
        return None
    lib = _native.load()
    if lib is None:
        if mode == "force":
            raise RuntimeError(
                "runtime.native_dtd=1 but the native core is "
                f"unavailable: {_native.build_error()} — install g++ "
                "or set runtime.native_dtd=0/auto")
        return None
    ctx = tp.context
    if ctx is None or tp.nb_ranks > 1:
        return None
    # instrumented-fallback rule (the ISSUE 13 line, ISSUE 14 moved
    # dfsan off it): only SEMANTICALLY-INTRUSIVE observers with no
    # native source keep the pool on the Python path. A live Trace
    # records through the engine's own event rings, the metrics
    # registry and stage timers read C++ atomics at scrape, scrape-only
    # PINS callbacks are registered native_ok, and dfsan replays the
    # pool from ring snapshots + insert manifests at fold — see the
    # module docstring for the exact residual list.
    if ctx.grapher is not None:
        return None
    if ctx.pins.needs_python_engine(trace_live=ctx.trace is not None):
        return None
    from ..utils import debug_history
    if debug_history.enabled():     # EXE-mark ring expects every task
        return None
    if not getattr(ctx.scheduler, "native_dtd_capable", False):
        return None
    # a REAL accelerator module registered: bodies must route through
    # the device managers (async dispatch, batching, per-device load)
    # — the native pump runs them inline on the worker thread, which is
    # only equivalent when every device executes on the host anyway
    # (virtual CPU-platform modules). Tests that pin the device-manager
    # plane itself (load splitting across modules) set
    # runtime.native_dtd=0 explicitly.
    if any(getattr(d, "platform", "cpu") != "cpu"
           for d in ctx.devices.devices):
        return None
    return NativeDTD(tp, lib)


class NativeDTD:
    """Per-taskpool driver of the native ``pdtd_*`` engine."""

    def __init__(self, tp, lib):
        self.tp = tp
        self.lib = lib
        ctx = tp.context
        self.nworkers = ctx.nb_cores
        # per-worker plifo capacity sized to the inserter window (ready
        # tasks are bounded by inflight <= window; 2x slack across the
        # round-robin spread) — a fixed large capacity was pure per-pool
        # allocation churn on the serving admission path. Overspill goes
        # to the engine's locked overflow dequeue.
        qcap = max(1024, 2 * tp._window // max(1, self.nworkers))
        self._e = lib.pdtd_new(self.nworkers, qcap)
        if not self._e:
            raise MemoryError("pdtd_new failed")
        # per-python-task state, keyed by seq: (hook, out_flow_names,
        # argspec, resolvers, out_tiles, n_lpreds)
        self.rows: Dict[int, tuple] = {}
        # retained outputs of completed writers, keyed by seq — dropped
        # by the native refcount (pdtd_complete's drop list)
        self.outputs: Dict[int, Dict[str, Any]] = {}
        # staging ring: reusable arrays, one native call per fill
        self._prio = np.zeros(_RING, np.int32)
        self._flags = np.zeros(_RING, np.uint8)
        self._npreds = np.zeros(_RING, np.uint32)
        self._preds = np.zeros(_RING * 4, np.uint32)
        self._linked = np.zeros(_RING * 4, np.uint8)
        # per-worker pump/complete scratch (workers never share a slot)
        self._tidbuf = [(ctypes.c_uint32 * _PUMP_BATCH)()
                        for _ in range(self.nworkers)]
        self._ranbuf = [ctypes.c_int() for _ in range(self.nworkers)]
        self._batchbuf = [(ctypes.c_uint32 * _PUMP_BATCH)()
                          for _ in range(self.nworkers)]
        # per-task body begin/end stamps for the event rings (obs only)
        self._t01buf = [(ctypes.c_uint64 * (2 * _PUMP_BATCH))()
                        for _ in range(self.nworkers)]
        self._infobuf = [(ctypes.c_int32 * 2)()
                         for _ in range(self.nworkers)]
        self._dropbuf = [(ctypes.c_uint32 * _MAX_PREDS_INIT)()
                         for _ in range(self.nworkers)]
        # class-info cache: (fn, shape, device, pure) -> (hook,
        # out_flow_names); resolution goes through the taskpool's
        # task-class cache so pure=True bodies share the process-wide
        # jit cache with the Python engine
        self._class_info: Dict[Any, tuple] = {}
        self._lock = threading.Lock()       # insert-side ring guard
        self._unarmed = None    # (first, n) between pdtd_insert and arm
        self._cancelled = False
        # set when the pool terminated with tasks still in flight (an
        # abort): the workers keep pumping this engine and fold it into
        # the context totals once the last task drains
        self.retiring = False
        # in-engine observability plane (ISSUE 13): when a live Trace
        # is installed — or the dfsan sanitizer needs the rings as its
        # completion evidence (ISSUE 14) — enable the per-worker binary
        # event rings so the pool KEEPS the native engine under
        # observation — records carry seq/class/worker/t0/t1/queue-wait/
        # span and are expanded to the PR 9 event shape at scrape time
        # by the trace's NativeRingAdapter. class_names is the
        # insert-side id→name table the expansion (and the dfsan
        # replay's task labels) reads; the rid rides at the pool level
        # (tp.trace_rid — the serving Submission's deterministic id).
        self.class_names: List[str] = []
        self._cls_by_fn: Dict[Any, int] = {}
        self._obs = False
        self._obs_adapter = None
        self._obs_cap = 0
        self.obs_offset_s = 0.0
        # ring-fed dfsan (ISSUE 14): insert-time access manifests +
        # fold-time replay keep the race sanitizer live on the native
        # engine — see replay_native_pool in analysis/dfsan.py
        self._dfsan = getattr(ctx, "dfsan", None)
        if self._dfsan is not None:
            self._dfsan_manifest: Optional[Dict[int, tuple]] = {}
            self._dfsan_commits: Dict[int, tuple] = {}
            self._dfsan_violations: List[tuple] = []
            if hasattr(lib, "pdtd_lockdbg_enable"):
                # C lock-discipline recorder: acquisition pairs scraped
                # via pdtd_stats feed dfsan's inversion detector at fold
                lib.pdtd_lockdbg_enable(self._e)
        else:
            self._dfsan_manifest = None
        tr = ctx.trace
        if (tr is not None or self._dfsan is not None) and \
                hasattr(lib, "pdtd_obs_enable"):
            from ..profiling import spans as spans_mod
            cap = max(64, int(mca_param.get(
                "profiling.native_ring_events", 16384)))
            if lib.pdtd_obs_enable(
                    self._e, spans_mod.native_span_base(ctx.my_rank),
                    cap) == 0:
                # clock handshake: exact offset from the engine's
                # monotonic-ns domain to time.perf_counter (no
                # assumption that the two share an epoch)
                self.obs_offset_s = (time.perf_counter() -
                                     lib.pdtd_obs_now() / 1e9)
                self._obs = True
                self._obs_cap = cap
                if tr is not None:
                    from ..profiling.trace import NativeRingAdapter
                    self._obs_adapter = NativeRingAdapter(self)
                    tr.add_native_source(self._obs_adapter)
        ctx._ndtd_register(self)

    # -------------------------------------------------------------- insert
    def _cls_id(self, fn) -> int:
        """Insert-side class id for the event rings: the expansion maps
        it back to the task-class name (``fn.__name__`` — the same name
        the Python engine's task class carries, so span trees match
        across engines). One dict hit per insert chunk."""
        cid = self._cls_by_fn.get(fn)
        if cid is None:
            cid = len(self.class_names)
            self.class_names.append(getattr(fn, "__name__", "dtd_task"))
            self._cls_by_fn[fn] = cid
        return cid

    def _class_for(self, fn, shape, device, pure):
        key = (fn, shape, device, pure)
        info = self._class_info.get(key)
        if info is None:
            tc = self.tp._task_class_for(fn, shape, device, pure=pure)
            hook = tc.incarnations[0].hook if tc.incarnations else None
            # flow-access layout captured ONCE PER CLASS (ISSUE 14):
            # the dfsan replay's dynamic access-mode check reads it to
            # flag bodies that returned values for READ/CTL flows
            info = (hook, tuple(f.name for f in tc.output_flows),
                    tc.name,
                    {f.name: (int(f.access), bool(f.is_ctl))
                     for f in tc.flows})
            self._class_info[key] = info
        return info

    def insert_rows(self, fn, rows, priority, device, pure) -> List[int]:
        """Batched insert through the native engine; returns the task
        sequence numbers (the opaque per-task handles — native tasks
        have no Python Task object)."""
        out: List[int] = []
        n = len(rows)
        for start in range(0, n, _RING):
            out.extend(self._insert_chunk(
                fn, rows[start:start + _RING], priority, device, pure))
            self._throttle()
        return out

    def _insert_chunk(self, fn, rows, priority, device, pure) -> List[int]:
        with self._lock:
            try:
                return self._insert_chunk_locked(fn, rows, priority,
                                                 device, pure)
            except BaseException as exc:
                # a raise mid-chunk (stage_read failure, bad argspec)
                # leaves registered-but-unarmed tasks and/or a bumped
                # tp._seq behind — unrecoverable for this pool. Abort it
                # so wait()-ers get the error instead of hanging, and
                # arm whatever the engine registered so the cancelled
                # tasks drain through the drop path.
                pending = self._unarmed
                if pending is not None:
                    self._unarmed = None
                    self.lib.pdtd_arm(self._e, pending[0], pending[1])
                self.tp.abort(exc)
                raise

    def _insert_chunk_locked(self, fn, rows, priority, device,
                             pure) -> List[int]:
        from .dtd import ScratchArg, ValueArg
        tp = self.tp
        ctx = tp.context
        lib = self.lib
        native_ok = (fn in _NATIVE_BODIES and tp.on_retire is None)
        n = len(rows)
        tile_cache: Dict[Any, Any] = {}
        prio_a, flags_a, npreds_a = self._prio, self._flags, self._npreds
        preds_a, linked_a = self._preds, self._linked
        seqs: List[int] = []
        # pending[(row_i)] = per-row python-side record
        pend: List[Optional[tuple]] = []
        # dfsan access manifests (ISSUE 14), one list per tile-bearing
        # row: ("sync", dc, key) — program-order snapshot read (the
        # tile-lock/retire protocol orders it; replayed as a sync
        # join); ("link", dc, key, slot, pred_seq) — resolved against
        # linked_out in pass 2 to an HB edge or a sync read; ("write",
        # dc, key, fname) — committed-or-not decided at completion.
        # Entry order mirrors the Python engine's observation order
        # exactly (reads at insert, writes at commit, arg order).
        cap = self._dfsan_manifest is not None
        mans: List[Optional[list]] = []
        pi = 0
        max_lp = 0
        for args in rows:
            seq = tp._seq
            tp._seq += 1
            seqs.append(seq)
            i = len(pend)
            spec: List[tuple] = []
            resolvers: List[tuple] = []
            out_tiles: List[tuple] = []
            man: Optional[list] = [] if cap else None
            seen: Dict[Any, int] = {}       # tile -> primary flow idx
            flow_i = 0
            row_np = 0
            for a in args:
                if isinstance(a, ValueArg):
                    spec.append(("value", a.value))
                    continue
                if isinstance(a, ScratchArg):
                    spec.append(("scratch", (a.shape, a.dtype)))
                    continue
                tile = tp._tile_of_cached(a.collection, a.key,
                                          tile_cache)
                fname = f"f{flow_i}"
                idx = flow_i
                flow_i += 1
                spec.append(("tile", None))
                primary = seen.get(tile)
                if primary is not None:
                    # same tile twice in one insert: alias to the
                    # first occurrence (no self-link)
                    resolvers.append((2, primary))
                else:
                    seen[tile] = idx
                    with tile.lock:
                        writer = tile.last_writer
                        writer_flow = tile.last_writer_flow
                    if isinstance(writer, _NativeWriter):
                        if pi >= len(preds_a):
                            preds_a = self._grow_preds(pi + n)
                            linked_a = self._linked
                        preds_a[pi] = writer.seq
                        # snap-vs-link decided by pdtd_insert's
                        # linked_out (slot pi) in pass 2
                        resolvers.append(
                            (1, writer.seq, writer_flow, tile, pi))
                        if cap:
                            man.append(("link", a.collection, a.key,
                                        pi, writer.seq))
                        pi += 1
                        row_np += 1
                    else:
                        # no writer in flight: snapshot the current
                        # version NOW (program order; stage-through
                        # like the Python engine)
                        resolvers.append((0, ctx.stage_read(
                            a.collection, a.key,
                            a.collection.data_of(a.key))))
                        if cap:
                            man.append(("sync", a.collection, a.key))
                if a.access & FlowAccess.WRITE:
                    with tile.lock:
                        tile.last_writer = _NativeWriter(seq)
                        tile.last_writer_flow = fname
                    out_tiles.append((tile, fname, idx))
                    if cap:
                        man.append(("write", a.collection, a.key,
                                    fname))
            needs_python = not (native_ok and not spec)
            flags_a[i] = 1 if needs_python else 0
            prio_a[i] = priority
            npreds_a[i] = row_np
            max_lp = max(max_lp, row_np)
            pend.append((spec, resolvers, out_tiles)
                        if needs_python else None)
            if cap:
                mans.append(man if man else None)
        if max_lp > _MAX_PREDS_INIT and \
                max_lp > len(self._dropbuf[0]):
            self._dropbuf = [(ctypes.c_uint32 * (2 * max_lp))()
                             for _ in range(self.nworkers)]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        cid = self._cls_id(fn)
        first = lib.pdtd_insert(
            self._e, n, prio_a.ctypes.data_as(i32p),
            flags_a.ctypes.data_as(u8p),
            npreds_a.ctypes.data_as(u32p),
            preds_a.ctypes.data_as(u32p),
            linked_a.ctypes.data_as(u8p),
            cid)
        if first < 0:
            raise RuntimeError(
                f"pdtd_insert failed (rc={first}): task table "
                "exhausted or inconsistent predecessor ids")
        # registered but not yet runnable: _insert_chunk's except path
        # arms this range so an abort still drains the engine
        self._unarmed = (int(first), n)
        if first != seqs[0]:
            raise RuntimeError(
                f"native DTD id drift: table at {first}, pool seq "
                f"at {seqs[0]} — mixed-engine insertion?")
        # pass 2: resolve snap-vs-link from linked_out, attach the
        # python-side rows, THEN arm the batch (a task must not be
        # runnable before its resolvers exist)
        hook_info = None
        for i, rec in enumerate(pend):
            if rec is None:
                continue
            spec, resolvers, out_tiles = rec
            n_lp = 0
            for j, r in enumerate(resolvers):
                if r[0] != 1:
                    continue
                if linked_a[r[4]]:
                    resolvers[j] = (1, r[1], r[2])
                    n_lp += 1
                else:
                    # writer already completed and committed: the
                    # collection holds exactly its version (every
                    # later writer is in this still-unarmed batch)
                    tile = r[3]
                    resolvers[j] = (0, ctx.stage_read(
                        tile.collection, tile.key,
                        tile.collection.data_of(tile.key)))
            if hook_info is None:
                hook_info = {}
            shape = tp._shape_of(rows[i])
            info = hook_info.get(shape)
            if info is None:
                info = hook_info[shape] = self._class_for(
                    fn, shape, device, pure)
            self.rows[seqs[i]] = (info, tuple(spec), resolvers,
                                  out_tiles, n_lp)
        if cap:
            # resolve the manifests' snap-vs-link against linked_out
            # (same rule as the resolvers above) and freeze them for
            # the fold-time dfsan replay
            manifest = self._dfsan_manifest
            for i, man in enumerate(mans):
                if man is None:
                    continue
                for j, m in enumerate(man):
                    if m[0] == "link":
                        man[j] = ("edge", m[4]) if linked_a[m[3]] \
                            else ("sync", m[1], m[2])
                manifest[seqs[i]] = (cid, tuple(man))
        self._unarmed = None
        lib.pdtd_arm(self._e, first, n)
        evt = ctx._work_evt
        if not evt.is_set():
            evt.set()
        return seqs

    def _grow_preds(self, need: int) -> np.ndarray:
        cap = max(2 * len(self._preds), need)
        self._preds = np.resize(self._preds, cap)
        self._linked = np.zeros(cap, np.uint8)
        return self._preds

    def _throttle(self) -> None:
        """Sliding-window inserter park off the GIL (the pdtd cv): the
        same window/threshold contract as the Python engine, released
        event-driven on drain and on abort/cancel."""
        tp = self.tp
        lib = self.lib
        if lib.pdtd_inflight(self._e) < tp._window:
            return
        while not tp._closed and tp.error is None:
            left = lib.pdtd_wait_below(self._e, tp._threshold, 250)
            if left <= tp._threshold or self._cancelled:
                break
        if tp.error is not None:
            raise RuntimeError(
                f"taskpool {tp.name} aborted: {tp.error}") from tp.error

    # ---------------------------------------------------------------- pump
    def pump(self, es) -> bool:
        """Worker-side progress: drain native-bodied ready tasks inside
        the C ABI call (GIL released), run Python-bodied ones here in
        batches of up to _PUMP_BATCH per GIL round-trip. Returns True
        when any task was completed."""
        lib = self.lib
        w = es.th_id if es.th_id < self.nworkers else 0
        tids = self._tidbuf[w]
        rann = self._ranbuf[w]
        ran = False
        while True:
            n = lib.pdtd_pump_batch(self._e, w, tids, _PUMP_BATCH,
                                    ctypes.byref(rann))
            if rann.value:
                ran = True
            if n == 0:
                if self.retiring and lib.pdtd_inflight(self._e) == 0:
                    # aborted pool fully drained: fold the counters now
                    self.tp.context._ndtd_unregister(self)
                return ran
            ran = True
            self._run_batch(tids, n, w)

    def _obs_ns(self, t: float) -> int:
        """perf_counter seconds → the engine's monotonic-ns domain
        (inverse of the enable-time clock handshake)."""
        return int((t - self.obs_offset_s) * 1e9)

    def _run_batch(self, tids, n: int, w: int) -> None:
        """Run up to _PUMP_BATCH Python bodies. Tasks with no tile
        traffic (no retained outputs, no consumed predecessors — the
        null-task and serving shapes) complete through ONE batched
        native call; tile-bearing tasks take the full individual path
        (write-back, retained outputs, drop reporting). With the event
        rings live, per-task body begin/end stamps ride the completion
        call — the batch's single completion instant would otherwise
        smear the whole batch's makespan over every task's span."""
        tp = self.tp
        rows = self.rows
        obs = self._obs
        # (seq, tc_name, t0_ns, t1_ns) batch-completable
        done: List[tuple] = []
        try:
            for i in range(n):
                seq = tids[i]
                row = rows.pop(seq, None)
                if row is None:
                    done.append((seq, "dtd_task", 0, 0))
                    continue
                info, spec, resolvers, out_tiles, n_lp = row
                if out_tiles or n_lp:
                    self._run_full(seq, info, spec, resolvers,
                                   out_tiles, n_lp, w)
                    continue
                hook = info[0]
                vals = self._resolve(resolvers)
                tb = time.perf_counter() if obs else 0.0
                result = hook(_Shim(spec), *vals) \
                    if hook is not None else None
                te = time.perf_counter() if obs else 0.0
                self._normalize(result, info[1], seq)   # validate-only:
                # no output flow can exist without an out tile
                if type(result) is dict and \
                        self._dfsan_manifest is not None:
                    # dynamic access-mode check (dfsan): a dict return
                    # may target a declared READ flow — record for the
                    # fold-time replay's access-violation report
                    self._dfsan_check_modes(seq, info, result)
                done.append((seq, info[2],
                             self._obs_ns(tb) if obs else 0,
                             self._obs_ns(te) if obs else 0))
        except BaseException as exc:  # noqa: BLE001 — worker must survive
            self._flush_batch(done, w)
            self._fail(seq, exc, w)
            # account the popped-but-unrun remainder so the engine still
            # drains (the pool is aborted; their bodies never run)
            rest = [(tids[j], "dtd_task", 0, 0) for j in range(i + 1, n)]
            for s, _, _, _ in rest:
                rows.pop(s, None)
            self._flush_batch(rest, w, retire=False)
            return
        self._flush_batch(done, w)

    def _flush_batch(self, done: List[tuple], w: int,
                     retire: bool = True) -> None:
        if not done:
            return
        tp = self.tp
        # retire hooks + lineage BEFORE the native completion: wait()'s
        # drain returns when the engine's inflight hits zero, and the
        # Python engine guarantees every on_retire happened-before wait
        # returns (the tenant-window accounting tests rely on it). The
        # finally keeps the completion unconditional — a raising retire
        # hook must not strand popped tasks (inflight would never drain)
        try:
            if retire and tp.on_retire is not None:
                for _ in done:
                    tp.on_retire(tp)
            if tp.context._track_completed:
                add = tp.completed_tasks.add
                for s, nm, _tb, _te in done:
                    add((nm, (s,)))
        finally:
            arr = self._batchbuf[w]
            t01 = None
            if self._obs:
                t01 = self._t01buf[w]
                for j, (s, _nm, tb, te) in enumerate(done):
                    arr[j] = s
                    t01[2 * j] = tb
                    t01[2 * j + 1] = te
            else:
                for j, (s, _nm, _tb, _te) in enumerate(done):
                    arr[j] = s
            newly = self.lib.pdtd_complete_batch(self._e, w, arr,
                                                 len(done), t01)
            if newly:
                evt = tp.context._work_evt
                if not evt.is_set():
                    evt.set()

    def _resolve(self, resolvers) -> List[Any]:
        vals = [None] * len(resolvers)
        outputs = self.outputs
        for i, r in enumerate(resolvers):
            k = r[0]
            if k == 0:
                vals[i] = r[1]
            elif k == 1:
                out = outputs.get(r[1])
                vals[i] = None if out is None else out.get(r[2])
            else:                               # alias of an earlier flow
                vals[i] = vals[r[1]]
        return vals

    def _run_full(self, seq: int, info, spec, resolvers, out_tiles,
                  n_lp: int, w: int) -> None:
        """Individual path for tile-bearing tasks: body, write-back +
        writer-marker retire (write BEFORE clear, the Python engine's
        retire protocol), retained outputs for linked readers, native
        completion with drop reporting."""
        tp = self.tp
        hook, out_flows, tc_name = info[0], info[1], info[2]
        obs = self._obs
        t0ns = t1ns = 0
        try:
            vals = self._resolve(resolvers)
            tb = time.perf_counter() if obs else 0.0
            result = hook(_Shim(spec), *vals) if hook is not None \
                else None
            if obs:
                t0ns = self._obs_ns(tb)
                t1ns = self._obs_ns(time.perf_counter())
            outs = self._normalize(result, out_flows, seq)
            if self._dfsan_manifest is not None:
                if out_tiles:
                    # committed-output evidence for the dfsan replay:
                    # only flows the body actually produced stamp a
                    # write (the Python engine's observe_write rule)
                    self._dfsan_commits[seq] = tuple(
                        f for (_t, f, _i) in out_tiles if f in outs)
                if type(result) is dict:
                    self._dfsan_check_modes(seq, info, result)
            if out_tiles:
                # retained per-flow value for linked readers: the
                # produced output, else the input that flowed through
                # (INOUT chain semantics)
                retained: Dict[str, Any] = {}
                for (tile, fname, idx) in out_tiles:
                    v = outs.get(fname, vals[idx] if idx < len(vals)
                                 else None)
                    retained[fname] = v
                    if fname in outs:
                        tile.collection.write_tile(tile.key, outs[fname])
                    with tile.lock:
                        lw = tile.last_writer
                        if isinstance(lw, _NativeWriter) and \
                                lw.seq == seq:
                            tile.last_writer = None
                            tile.last_writer_flow = None
                self.outputs[seq] = retained
        except BaseException as exc:  # noqa: BLE001 — worker must survive
            self._fail(seq, exc, w)
            return
        # retire before the native completion — see _flush_batch; the
        # finally keeps the completion unconditional on a raising hook
        try:
            if tp.on_retire is not None:
                tp.on_retire(tp)
            if tp.context._track_completed:
                tp.completed_tasks.add((tc_name, (seq,)))
        finally:
            self._complete(seq, w, n_lp, drop_own=not out_tiles,
                           t0ns=t0ns, t1ns=t1ns)

    def _dfsan_check_modes(self, seq: int, info, result: dict) -> None:
        """Dynamic access-mode capture (ISSUE 14): a dict return whose
        key names a declared non-WRITE flow is the violation dfsan's
        ``_release_begin`` flags on the Python engine — recorded here
        (class-level flow layout, captured once per class in
        ``_class_for``) and reported at the fold-time replay."""
        flows = info[3]
        for name in result:
            fa = flows.get(name)
            if fa is None:
                continue
            access, is_ctl = fa
            if is_ctl or not (access & FlowAccess.WRITE):
                self._dfsan_violations.append(
                    (seq, info[2], name, access))

    def _complete(self, seq: int, w: int, n_lp: int,
                  drop_own: bool, t0ns: int = 0, t1ns: int = 0) -> None:
        lib = self.lib
        info = self._infobuf[w]
        drops = self._dropbuf[w] if n_lp else None
        nd = lib.pdtd_complete(self._e, w, seq, drops,
                               n_lp, info, t0ns, t1ns)
        if nd > 0:
            outputs = self.outputs
            for i in range(min(nd, n_lp)):
                outputs.pop(drops[i], None)
        if not drop_own and info[1] == 0:
            # no linked reader will ever consume these outputs
            self.outputs.pop(seq, None)
        if info[0]:
            evt = self.tp.context._work_evt
            if not evt.is_set():
                evt.set()

    def _fail(self, seq: int, exc: BaseException, w: int) -> None:
        """A Python body raised: abort the pool (which cancels this
        engine via _on_terminated), then account the failed task so the
        engine still drains."""
        tp = self.tp
        warning("scheduling", "native DTD task seq=%d of %s raised: %s",
                seq, tp.name, exc)
        import traceback
        traceback.print_exc()
        tp.abort(exc)
        self._complete(seq, w, 0, drop_own=True)

    # ----------------------------------------------------- drain / cancel
    def drain(self) -> None:
        """Block until every inserted task left flight (wait()); exits
        early when the pool aborted (cancel() already released the
        queued tasks)."""
        lib = self.lib
        tp = self.tp
        while lib.pdtd_inflight(self._e) > 0:
            if tp.error is not None:
                return
            lib.pdtd_wait_below(self._e, 0, 250)

    def cancel(self) -> None:
        self._cancelled = True
        self.lib.pdtd_cancel(self._e)

    def inflight(self) -> int:
        return int(self.lib.pdtd_inflight(self._e))

    def release_refs(self) -> None:
        """Drop retained per-task state once the engine is FOLDED (the
        pool terminated AND inflight hit zero — no body can resolve a
        value anymore). The abort path completes failed/unrun tasks
        without drop reporting, so without this sweep an aborted pool's
        retained tile outputs would stay pinned until the pool object
        itself is collected."""
        self.rows.clear()
        self.outputs.clear()
        if self._dfsan_manifest is not None:
            self._dfsan_manifest.clear()
            self._dfsan_commits.clear()
            del self._dfsan_violations[:]

    # ------------------------------------------------------------- observe
    def stats(self) -> Dict[str, int]:
        buf = (ctypes.c_uint64 * len(_native.PDTD_STAT_KEYS))()
        self.lib.pdtd_stats(self._e, buf)
        return {k: int(v) for k, v in zip(_native.PDTD_STAT_KEYS, buf)}

    def obs_drain(self) -> List[np.ndarray]:
        """Snapshot every worker's event ring (non-consuming): one
        structured array per non-empty ring, dtype
        ``_native.obs_dtype()``. One memcpy per ring — no per-event
        Python work; the trace adapter expands lazily at dump time."""
        if not self._obs:
            return []
        lib = self.lib
        cap = self._obs_cap
        dt = _native.obs_dtype()
        vp = ctypes.c_void_p
        out: List[np.ndarray] = []
        for w in range(self.nworkers):
            buf = np.empty(cap, dt)
            n = lib.pdtd_obs_drain(self._e, w,
                                   buf.ctypes.data_as(vp), cap)
            if n > 0:
                out.append(buf[:n].copy())
        return out

    def obs_dropped(self) -> int:
        """Records lost to in-engine ring wraps."""
        return self.stats().get("obs_dropped", 0) if self._obs else 0

    def obs_retire(self) -> None:
        """Pool folded (terminated AND drained): freeze the adapter's
        snapshot, feed ring-fed PINS modules (the straggler watchdog's
        native path), run the dfsan replay over the frozen rings +
        insert manifests (ISSUE 14 — before the context's termination
        barrier advances the sanitizer base on the clean path; an
        aborted pool folds after its barrier, so the replay seeds from
        the pre-barrier base snapshot ``_ndtd_retire`` stashed on the
        engine), and free the C ring memory — a persistent serving
        context must not pin one ring set per retired pool."""
        ad = self._obs_adapter
        if ad is not None:
            ad.snapshot()
            ctx = self.tp.context
            if ctx is not None:
                for mod in getattr(ctx, "pins_modules", ()):
                    feed = getattr(mod, "observe_native_rings", None)
                    if feed is not None:
                        try:
                            feed(ad.raw_arrays(), self.class_names)
                        except Exception:  # noqa: BLE001 — observer
                            pass
        san = self._dfsan
        if san is not None:
            try:
                san.replay_native_pool(self)
            except Exception as exc:  # noqa: BLE001 — an observer
                # failure must not sink the serving fold, but a silent
                # one would fake a clean race report: be loud
                warning("analysis",
                        "dfsan native replay of %s failed: %s",
                        self.tp.name, exc)
        if self._obs:
            self._obs = False
            self.lib.pdtd_obs_disable(self._e)

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _normalize(result, out_flows, seq) -> Dict[str, Any]:
        """Body result → output-flow dict: the ONE shared contract
        (core.task.normalize_outputs — also the device layer's), so
        engine choice never changes what a return value means."""
        from ..core.task import normalize_outputs
        return normalize_outputs(result, out_flows,
                                 f"dtd task seq={seq}")

    def __del__(self):
        e = getattr(self, "_e", None)
        lib = getattr(self, "lib", None)
        if e and lib is not None:
            try:
                lib.pdtd_free(e)
            except (AttributeError, TypeError, OSError):
                pass        # interpreter teardown: the OS reclaims it
        try:
            self._e = None
        except Exception:  # noqa: BLE001 — __del__ must never raise
            pass
