"""JDF: the textual parameterized-task-graph language + compiler.

Reference: the JDF language and the ``parsec_ptgpp`` source-to-source
compiler (parsec/interfaces/ptg/ptg-compiler/: lexer parsec.l, grammar
parsec.y, AST jdf.h:117-365, sanity checks jdf.c, code generator jdf2c.c
8,636 LoC). The reference compiles ``.jdf`` → C implementing the task-class
vtable. Here the same language (Python expressions instead of inline C)
compiles directly to :mod:`parsec_tpu.dsl.ptg` closures — the "generated
code" is a set of lambdas over the taskpool globals, preserving PTG's key
property of closed-form O(1) dependency discovery.

Language surface (mirrors the reference; Python expressions)::

    extern "python" %{
    from parsec_tpu.ops.tile_kernels import potrf_tile
    %}

    NT  [ type = int ]
    A   [ type = tiled_matrix ]

    POTRF(k)                      /* task class: name(parameters) */
      k = 0 .. NT-1               # parameter range (inclusive, JDF-style)
      h = k + 1                   # derived local
      : A(k, k)                   # partitioning / affinity predicate
      RW T <- (k == 0) ? A(k, k) : C SYRK(k, k-1)
           -> L TRSM(k+1 .. NT-1, k)
           -> A(k, k)
      ; (NT - k) ** 2             # priority expression
    BODY [ type = tpu ]
      T = potrf_tile(T)
    END

    Comments: ``#`` and ``/* */`` (NOT ``//``, which is Python floor
    division inside expressions).

Dependency targets: ``FLOW Class(args)`` (task dep), ``Collection(args)``
(memory dep), ``NULL`` (no dep), ``NEW(expr)`` (fresh value). ``->`` args
may contain inclusive ranges ``lo .. hi [.. step]`` (Cartesian product).
Guards are ``(expr) ?`` with an optional ``:`` else-branch. Bodies are
Python: flow names are bound to input values; after execution the WRITE
flow names are read back as the outputs. Properties ``[ k = v ... ]`` are
retained on globals, task classes, deps and bodies (e.g. the reference's
``type_remote`` reshape hints ride along for the reshape engine).
"""

from __future__ import annotations

import itertools
import re
import textwrap
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.task import DeviceType
from . import ptg

# Structural limits (reference parsec_config_bottom.h:159-163)
MAX_LOCAL_COUNT = 20
MAX_PARAM_COUNT = 20
MAX_DEP_IN_COUNT = 10
MAX_DEP_OUT_COUNT = 10


class JDFSyntaxError(SyntaxError):
    """Lex/parse error with source position."""

    def __init__(self, msg: str, line: int, col: int = 0):
        super().__init__(f"JDF:{line}:{col}: {msg}")
        self.line = line
        self.col = col


class JDFSemanticError(ValueError):
    """Post-parse sanity-check failure (reference jdf_sanity_checks)."""


# --------------------------------------------------------------------- lexer

_TOKEN_RE = re.compile(r"""
    (?P<WS>[ \t\r]+)
  | (?P<COMMENT>\#[^\n]*)
  | (?P<CCOMMENT>/\*.*?\*/)
  | (?P<NL>\n)
  | (?P<VERBATIM>%\{.*?%\})
  | (?P<RANGE>\.\.)
  | (?P<ARROW_IN><-)
  | (?P<ARROW_OUT>->)
  | (?P<NUMBER>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<STRING>"(?:[^"\\]|\\.)*"|'(?:[^'\\]|\\.)*')
  | (?P<IDENT>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<OP>\*\*|==|!=|<=|>=|//|&&|\|\||[-+*/%%<>=?:,()\[\]{}.!&|^~@;])
""", re.VERBOSE | re.DOTALL)


@dataclass
class Tok:
    kind: str
    text: str
    line: int
    col: int
    pos: int          # char offset of token start in source


def tokenize(source: str) -> List[Tok]:
    toks: List[Tok] = []
    i, line, bol = 0, 1, 0
    n = len(source)
    while i < n:
        m = _TOKEN_RE.match(source, i)
        if m is None:
            raise JDFSyntaxError(f"unexpected character {source[i]!r}",
                                 line, i - bol + 1)
        kind = m.lastgroup
        text = m.group()
        if kind not in ("WS", "COMMENT", "CCOMMENT"):
            toks.append(Tok(kind, text, line, i - bol + 1, i))
        nl = text.count("\n")
        if nl:
            line += nl
            bol = i + text.rfind("\n") + 1
        i = m.end()
        # Raw-capture a BODY block: after `BODY [props] \n` everything up
        # to a line consisting of END is body code, not JDF tokens.
        if kind == "IDENT" and text == "BODY":
            # consume props + the rest of the BODY line normally
            j = i
            depth = 0
            while j < n and (source[j] != "\n" or depth > 0):
                if source[j] == "[":
                    depth += 1
                elif source[j] == "]":
                    depth -= 1
                j += 1
            # tokenize the props segment through the main regex
            seg = source[i:j]
            off = i
            while off < j:
                sm = _TOKEN_RE.match(source, off)
                if sm is None:
                    raise JDFSyntaxError("bad BODY properties", line, 1)
                if sm.lastgroup not in ("WS", "COMMENT", "CCOMMENT"):
                    toks.append(Tok(sm.lastgroup, sm.group(), line,
                                    off - bol + 1, off))
                off = sm.end()
            i = j
            # find the END line
            em = re.compile(r"^[ \t]*END[ \t]*$", re.M).search(source, i)
            if em is None:
                raise JDFSyntaxError("BODY without END", line, 1)
            code = source[i:em.start()]
            toks.append(Tok("BODYCODE", code, line + 1, 1, i))
            line += source.count("\n", i, em.end())
            i = em.end()
            bol = i
            toks.append(Tok("NL", "\n", line, 1, i))
            continue
    toks.append(Tok("EOF", "", line, 1, n))
    return toks


# ----------------------------------------------------------------------- AST
# (reference jdf.h:117-365: jdf_t / jdf_function_entry_t / jdf_dataflow /
#  jdf_dep / jdf_guarded_call)

@dataclass
class Expr:
    """A Python expression captured from the source, compiled lazily."""
    text: str
    line: int = 0
    _code: Any = None

    def code(self):
        if self._code is None:
            try:
                self._code = compile(self.text.strip(), f"<jdf:{self.line}>",
                                     "eval")
            except SyntaxError as exc:
                raise JDFSemanticError(
                    f"JDF:{self.line}: bad expression {self.text!r}: {exc}")
        return self._code

    def __repr__(self):
        return f"Expr({self.text.strip()!r})"


@dataclass
class CallRef:
    """``name(args)`` — a task-class or collection reference. Each arg is
    an Expr or a (lo, hi, step) range triple of Exprs (ranged -> deps)."""
    name: str
    args: List[Any]
    flow: Optional[str] = None      # set for task deps: FLOW Class(args)
    line: int = 0

    @property
    def is_task_ref(self) -> bool:
        return self.flow is not None


@dataclass
class DepTarget:
    """One side of a dependency: a call ref, NEW(expr), or NULL."""
    call: Optional[CallRef] = None
    new: Optional[Expr] = None
    is_null: bool = False


@dataclass
class JdfDep:
    """A guarded dependency of a flow (jdf_dep / jdf_guarded_call)."""
    direction: str                   # "in" | "out"
    guard: Optional[Expr]
    then: DepTarget
    otherwise: Optional[DepTarget]   # the ':' branch of a ternary guard
    props: Dict[str, Expr] = field(default_factory=dict)
    line: int = 0


@dataclass
class JdfFlow:
    name: str
    access: str                      # RW | READ | WRITE | CTL
    deps: List[JdfDep] = field(default_factory=list)
    props: Dict[str, Expr] = field(default_factory=dict)


@dataclass
class JdfBody:
    code: str
    props: Dict[str, Expr] = field(default_factory=dict)
    line: int = 0


@dataclass
class JdfLocal:
    name: str
    # either a range (lo, hi, step Exprs) for parameters, or a value Expr
    range: Optional[Tuple[Expr, Expr, Optional[Expr]]] = None
    value: Optional[Expr] = None
    line: int = 0


@dataclass
class JdfTaskClass:
    name: str
    params: List[str]
    locals: List[JdfLocal] = field(default_factory=list)
    partitioning: Optional[CallRef] = None
    flows: List[JdfFlow] = field(default_factory=list)
    priority: Optional[Expr] = None
    bodies: List[JdfBody] = field(default_factory=list)
    props: Dict[str, Expr] = field(default_factory=dict)
    line: int = 0


@dataclass
class JdfGlobal:
    name: str
    props: Dict[str, Expr] = field(default_factory=dict)
    line: int = 0


@dataclass
class JdfFile:
    prologues: List[str] = field(default_factory=list)
    globals: List[JdfGlobal] = field(default_factory=list)
    task_classes: List[JdfTaskClass] = field(default_factory=list)


# -------------------------------------------------------------------- parser

_ACCESS_KW = ("RW", "READ", "WRITE", "CTL")


class _Parser:
    def __init__(self, source: str):
        self.src = source
        self.toks = tokenize(source)
        self.i = 0

    # -- token helpers ----------------------------------------------------
    def peek(self, k: int = 0) -> Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        if t.kind != "EOF":
            self.i += 1
        return t

    def skip_nl(self):
        while self.peek().kind == "NL":
            self.next()

    def expect(self, kind: str, text: Optional[str] = None) -> Tok:
        t = self.next()
        if t.kind != kind or (text is not None and t.text != text):
            want = text or kind
            raise JDFSyntaxError(f"expected {want!r}, got {t.text!r}",
                                 t.line, t.col)
        return t

    def at(self, kind: str, text: Optional[str] = None, k: int = 0) -> bool:
        t = self.peek(k)
        return t.kind == kind and (text is None or t.text == text)

    # -- expression capture ----------------------------------------------
    def capture_expr(self, stop: Sequence[str], stop_nl: bool = False,
                     allow_empty: bool = False) -> Expr:
        """Capture source text of one expression: tokens until a stop
        OP/RANGE at bracket depth 0 (or newline when stop_nl)."""
        start_tok = self.peek()
        depth = 0
        start = start_tok.pos
        end = start
        while True:
            t = self.peek()
            if t.kind == "EOF":
                break
            if t.kind == "NL":
                if stop_nl and depth == 0:
                    break
                self.next()
                continue
            if depth == 0 and (t.text in stop or t.kind in stop):
                break
            if t.kind == "VERBATIM":
                # inline %{ return expr; %} — splice as a Python expression
                inner = t.text[2:-2].strip()
                inner = re.sub(r"^return\s+", "", inner).rstrip("; \t\n")
                self.next()
                text = self.src[start:t.pos] + f"({inner})"
                # continue capture after the verbatim with rebuilt text
                # (an expression may END at the verbatim → empty suffix)
                rest = self.capture_expr(stop, stop_nl, allow_empty=True)
                return Expr(text + rest.text, start_tok.line)
            if t.text in "([{":
                depth += 1
            elif t.text in ")]}":
                if depth == 0:
                    break
                depth -= 1
            end = t.pos + len(t.text)
            self.next()
        text = self.src[start:end]
        if not text.strip():
            if allow_empty:
                return Expr("", start_tok.line)
            t = self.peek()
            raise JDFSyntaxError("expected expression", t.line, t.col)
        return Expr(text, start_tok.line)

    def capture_range_or_expr(self, stop: Sequence[str],
                              stop_nl: bool = False):
        """expr | expr .. expr [.. expr] — returns Expr or a range triple."""
        stop2 = list(stop) + ["RANGE"]
        e1 = self.capture_expr(stop2, stop_nl)
        if not self.at("RANGE"):
            return e1
        self.next()
        e2 = self.capture_expr(stop2, stop_nl)
        step = None
        if self.at("RANGE"):
            self.next()
            step = self.capture_expr(stop, stop_nl)
        return (e1, e2, step)

    # -- properties block -------------------------------------------------
    def parse_props(self) -> Dict[str, Expr]:
        """``[ key = expr key = expr ... ]``"""
        props: Dict[str, Expr] = {}
        if not self.at("OP", "["):
            return props
        self.next()
        self.skip_nl()
        while not self.at("OP", "]"):
            key = self.expect("IDENT").text
            self.expect("OP", "=")
            # value ends at ']' or at the start of the next `ident =` pair
            start_tok = self.peek()
            depth = 0
            start = start_tok.pos
            end = start
            while True:
                t = self.peek()
                if t.kind == "EOF":
                    raise JDFSyntaxError("unterminated properties", t.line,
                                         t.col)
                if t.kind == "NL":
                    self.next()
                    continue
                if depth == 0 and t.text == "]":
                    break
                if depth == 0 and t.kind == "IDENT" and \
                        self.at("OP", "=", 1):
                    break
                if t.text in "([{":
                    depth += 1
                elif t.text in ")]}":
                    depth -= 1
                end = t.pos + len(t.text)
                self.next()
            props[key] = Expr(self.src[start:end], start_tok.line)
            self.skip_nl()
        self.expect("OP", "]")
        return props

    # -- top level --------------------------------------------------------
    def parse(self) -> JdfFile:
        jdf = JdfFile()
        while True:
            self.skip_nl()
            t = self.peek()
            if t.kind == "EOF":
                break
            if t.kind == "VERBATIM":
                jdf.prologues.append(t.text[2:-2])
                self.next()
                continue
            if t.kind == "IDENT" and t.text == "extern":
                # extern "python" %{ ... %}
                self.next()
                if self.peek().kind == "STRING":
                    self.next()
                v = self.expect("VERBATIM")
                jdf.prologues.append(v.text[2:-2])
                continue
            if t.kind != "IDENT":
                raise JDFSyntaxError(f"unexpected {t.text!r}", t.line, t.col)
            # IDENT '(' → task class; otherwise a global declaration
            if self.at("OP", "(", 1):
                jdf.task_classes.append(self.parse_task_class())
            else:
                name = self.next().text
                props = self.parse_props()
                jdf.globals.append(JdfGlobal(name, props, t.line))
        return jdf

    def parse_task_class(self) -> JdfTaskClass:
        name_tok = self.expect("IDENT")
        tc = JdfTaskClass(name_tok.text, [], line=name_tok.line)
        self.expect("OP", "(")
        while not self.at("OP", ")"):
            tc.params.append(self.expect("IDENT").text)
            if self.at("OP", ","):
                self.next()
        self.expect("OP", ")")
        tc.props = self.parse_props()
        self.skip_nl()
        # locals: IDENT = range-or-expr (newline terminated)
        while self.at("IDENT") and self.at("OP", "=", 1) and \
                self.peek().text not in _ACCESS_KW:
            ltok = self.next()
            self.expect("OP", "=")
            r = self.capture_range_or_expr(stop=(), stop_nl=True)
            if isinstance(r, tuple):
                tc.locals.append(JdfLocal(ltok.text, range=r, line=ltok.line))
            else:
                tc.locals.append(JdfLocal(ltok.text, value=r, line=ltok.line))
            self.skip_nl()
        # partitioning: ': A(k, n)'
        if self.at("OP", ":"):
            self.next()
            tc.partitioning = self.parse_call_ref(allow_flow=False,
                                                  allow_range=False)
            self.skip_nl()
        # flows
        while self.at("IDENT") and self.peek().text in _ACCESS_KW:
            tc.flows.append(self.parse_flow())
            self.skip_nl()
        # priority: '; expr'
        if self.at("OP", ";"):
            self.next()
            tc.priority = self.capture_expr(stop=(), stop_nl=True)
            self.skip_nl()
        # bodies
        while self.at("IDENT", "BODY"):
            btok = self.next()
            props = self.parse_props()
            code_tok = self.expect("BODYCODE")
            tc.bodies.append(JdfBody(textwrap.dedent(code_tok.text),
                                     props, btok.line))
            self.skip_nl()
        if not tc.bodies:
            raise JDFSyntaxError(f"task class {tc.name} has no BODY",
                                 name_tok.line, name_tok.col)
        return tc

    def parse_flow(self) -> JdfFlow:
        access = self.next().text
        fname = self.expect("IDENT").text
        flow = JdfFlow(fname, access)
        flow.props = self.parse_props()
        self.skip_nl()
        while self.at("ARROW_IN") or self.at("ARROW_OUT"):
            flow.deps.append(self.parse_dep())
            self.skip_nl()
        return flow

    def parse_dep(self) -> JdfDep:
        t = self.next()
        direction = "in" if t.kind == "ARROW_IN" else "out"
        guard = None
        then = otherwise = None
        if self.at("OP", "("):
            # '(' expr ')' '?' then [':' else]
            self.next()
            guard = self.capture_expr(stop=(")",))
            self.expect("OP", ")")
            self.expect("OP", "?")
            then = self.parse_target(direction)
            if self.at("OP", ":"):
                self.next()
                otherwise = self.parse_target(direction)
        else:
            then = self.parse_target(direction)
        props = self.parse_props()
        return JdfDep(direction, guard, then, otherwise, props, t.line)

    def parse_target(self, direction: str) -> DepTarget:
        t = self.peek()
        if t.kind == "IDENT" and t.text == "NULL":
            self.next()
            return DepTarget(is_null=True)
        if t.kind == "IDENT" and t.text == "NEW":
            self.next()
            self.expect("OP", "(")
            e = self.capture_expr(stop=(")",))
            self.expect("OP", ")")
            return DepTarget(new=e)
        call = self.parse_call_ref(allow_flow=True,
                                   allow_range=True)   # ranged IN = CTL
                                                       # gather (checked
                                                       # semantically)
        return DepTarget(call=call)

    def parse_call_ref(self, allow_flow: bool,
                       allow_range: bool) -> CallRef:
        id1 = self.expect("IDENT")
        flow = None
        name = id1.text
        if allow_flow and self.at("IDENT"):
            flow = id1.text
            name = self.next().text
        self.expect("OP", "(")
        args: List[Any] = []
        while not self.at("OP", ")"):
            if allow_range:
                args.append(self.capture_range_or_expr(stop=(",", ")")))
            else:
                args.append(self.capture_expr(stop=(",", ")")))
            if self.at("OP", ","):
                self.next()
        self.expect("OP", ")")
        return CallRef(name, args, flow, id1.line)


def parse(source: str) -> JdfFile:
    """Parse JDF text to the AST (reference parsec.y analog)."""
    jdf = _Parser(source).parse()
    _sanity_check(jdf)
    return jdf


# ------------------------------------------------------- sanity (jdf.c analog)

def _sanity_check(jdf: JdfFile) -> None:
    gnames = set()
    for g in jdf.globals:
        if g.name in gnames:
            raise JDFSemanticError(f"duplicate global {g.name!r}")
        gnames.add(g.name)
    class_names = set()
    for tc in jdf.task_classes:
        if tc.name in class_names:
            raise JDFSemanticError(f"duplicate task class {tc.name!r}")
        class_names.add(tc.name)
    flows_of = {tc.name: {f.name for f in tc.flows} for tc in jdf.task_classes}
    for tc in jdf.task_classes:
        if len(tc.params) > MAX_PARAM_COUNT:
            raise JDFSemanticError(
                f"{tc.name}: {len(tc.params)} parameters exceeds "
                f"MAX_PARAM_COUNT={MAX_PARAM_COUNT}")
        if len(tc.locals) > MAX_LOCAL_COUNT:
            raise JDFSemanticError(
                f"{tc.name}: {len(tc.locals)} locals exceeds "
                f"MAX_LOCAL_COUNT={MAX_LOCAL_COUNT}")
        local_names = [l.name for l in tc.locals]
        if len(set(local_names)) != len(local_names):
            raise JDFSemanticError(f"{tc.name}: duplicate local definition")
        ranged = {l.name for l in tc.locals if l.range is not None}
        for p in tc.params:
            if p not in ranged:
                raise JDFSemanticError(
                    f"{tc.name}: parameter {p!r} has no range definition")
        extra = ranged - set(tc.params)
        if extra:
            raise JDFSemanticError(
                f"{tc.name}: ranged locals {sorted(extra)} are not "
                f"parameters")
        fnames = set()
        for f in tc.flows:
            if f.name in fnames:
                raise JDFSemanticError(
                    f"{tc.name}: duplicate flow {f.name!r}")
            fnames.add(f.name)
            n_in = sum(1 for d in f.deps if d.direction == "in")
            n_out = sum(1 for d in f.deps if d.direction == "out")
            if n_in > MAX_DEP_IN_COUNT:
                raise JDFSemanticError(
                    f"{tc.name}.{f.name}: {n_in} input deps exceeds "
                    f"MAX_DEP_IN_COUNT={MAX_DEP_IN_COUNT}")
            if n_out > MAX_DEP_OUT_COUNT:
                raise JDFSemanticError(
                    f"{tc.name}.{f.name}: {n_out} output deps exceeds "
                    f"MAX_DEP_OUT_COUNT={MAX_DEP_OUT_COUNT}")
            if f.access == "READ" and n_in == 0:
                raise JDFSemanticError(
                    f"{tc.name}.{f.name}: READ flow with no input dep")
            for d in f.deps:
                for target in (d.then, d.otherwise):
                    if target is None or target.call is None:
                        continue
                    c = target.call
                    if c.is_task_ref:
                        if d.direction == "in" and f.access != "CTL" and \
                                any(isinstance(a, tuple) for a in c.args):
                            raise JDFSemanticError(
                                f"JDF:{d.line}: {tc.name}.{f.name}: ranged "
                                f"input deps (CTL gather) are only allowed "
                                f"on CTL flows")
                        if c.name not in class_names:
                            raise JDFSemanticError(
                                f"{tc.name}.{f.name}: unknown task class "
                                f"{c.name!r}")
                        if c.flow not in flows_of[c.name]:
                            raise JDFSemanticError(
                                f"{tc.name}.{f.name}: task class {c.name} "
                                f"has no flow {c.flow!r}")
                        n_params = len(
                            next(t for t in jdf.task_classes
                                 if t.name == c.name).params)
                        if len(c.args) != n_params:
                            raise JDFSemanticError(
                                f"{tc.name}.{f.name}: {c.name} takes "
                                f"{n_params} parameters, got {len(c.args)}")
                    elif c.name not in gnames:
                        raise JDFSemanticError(
                            f"{tc.name}.{f.name}: unknown collection "
                            f"{c.name!r} (not a declared global)")
                    elif any(isinstance(a, tuple) for a in c.args):
                        raise JDFSemanticError(
                            f"{tc.name}.{f.name}: ranged arguments are "
                            f"only allowed on task references, not on "
                            f"collection {c.name!r}")
        if tc.partitioning is not None and \
                tc.partitioning.name not in gnames:
            raise JDFSemanticError(
                f"{tc.name}: partitioning references unknown collection "
                f"{tc.partitioning.name!r}")


# ------------------------------------------------------------------- codegen
# (jdf2c.c analog: emit the task-class vtable as closures over globals)

_SAFE_BUILTINS = {
    "min": min, "max": max, "abs": abs, "range": range, "len": len,
    "int": int, "float": float, "bool": bool, "sum": sum, "divmod": divmod,
    "round": round, "tuple": tuple, "list": list, "enumerate": enumerate,
    "zip": zip, "print": print, "True": True, "False": False, "None": None,
}


class _Env:
    """Per-task-class expression evaluation: params + derived locals over
    the taskpool globals and prologue namespace, memoized per instance."""

    def __init__(self, tc: JdfTaskClass, ns: Dict[str, Any]):
        self.tc = tc
        self.ns = ns                # globals + prologue names
        self._cache: Dict[Tuple, Dict[str, Any]] = {}

    def env(self, params: Tuple[int, ...]) -> Dict[str, Any]:
        hit = self._cache.get(params)
        if hit is not None:
            return hit
        env = dict(self.ns)
        env.update(zip(self.tc.params, params))
        for l in self.tc.locals:
            if l.value is not None:
                env[l.name] = eval(l.value.code(), env)
        if len(self._cache) > 65536:
            self._cache.clear()
        self._cache[params] = env
        return env

    def eval(self, expr: Expr, params: Tuple[int, ...]) -> Any:
        return eval(expr.code(), self.env(params))


def _range_values(env: Dict[str, Any], rng) -> Iterable[int]:
    lo = eval(rng[0].code(), env)
    hi = eval(rng[1].code(), env)
    step = eval(rng[2].code(), env) if rng[2] else 1
    return range(int(lo), int(hi) + (1 if step > 0 else -1), int(step))


def _expand_args(ev: _Env, call: CallRef, params: Tuple[int, ...]):
    """Expand a -> target's args: Cartesian product over ranged args."""
    env = ev.env(params)
    dims: List[List[int]] = []
    for a in call.args:
        if isinstance(a, tuple):
            dims.append(list(_range_values(env, a)))
        else:
            dims.append([eval(a.code(), env)])
    return [tuple(c) for c in itertools.product(*dims)]


_DEVICE_NAMES = {
    "tpu": DeviceType.TPU, "cpu": DeviceType.CPU,
    "recursive": DeviceType.RECURSIVE, "all": DeviceType.ALL,
    # reference BODY [type=CUDA] — accelerator body maps to the TPU device
    "cuda": DeviceType.TPU, "gpu": DeviceType.TPU,
}


class CompiledJDF:
    """The compiled form: builds :class:`ptg.Taskpool` instances bound to
    concrete global values (the ``parsec_<name>_new`` constructor analog,
    jdf2c.c:4483-4798)."""

    def __init__(self, ast: JdfFile, name: str = "jdf"):
        self.ast = ast
        self.name = name

    # -- constructor ------------------------------------------------------
    def taskpool(self, *, lint: Optional[str] = None,
                 **global_values) -> ptg.Taskpool:
        """Build a taskpool bound to ``global_values``.

        ``lint`` optionally runs the static hazard checker on the freshly
        compiled taskpool (``"warn"`` logs findings, ``"error"`` raises
        :class:`~parsec_tpu.analysis.lint.HazardError`) — the ptgpp
        compile-time sanity checks cover syntax/shape, the lint covers
        the *instantiated* dataflow (undeclared producers, WAW/WAR
        hazards, cycles) the compiler cannot see without the globals.
        """
        declared = {g.name for g in self.ast.globals}
        if "lint" in declared:
            # the parameter would silently capture the global's value
            raise JDFSemanticError(
                "global name 'lint' is reserved by taskpool(lint=...); "
                "rename the JDF global")
        ns: Dict[str, Any] = dict(_SAFE_BUILTINS)
        for g in self.ast.globals:
            if g.name in global_values:
                ns[g.name] = global_values[g.name]
            elif "default" in g.props:
                ns[g.name] = eval(g.props["default"].code(), dict(ns))
            else:
                raise JDFSemanticError(
                    f"global {g.name!r} not provided and has no default")
        unknown = set(global_values) - declared
        if unknown:
            raise JDFSemanticError(f"unknown globals: {sorted(unknown)}")
        # prologue: Python exec'd with the globals visible (the reference
        # inlines `extern "C" %{...%}` verbatim into the generated C)
        for code in self.ast.prologues:
            exec(compile(textwrap.dedent(code), "<jdf-prologue>", "exec"), ns)

        tp = ptg.Taskpool(self.name, **{g.name: ns[g.name]
                                        for g in self.ast.globals})
        envs: Dict[str, _Env] = {}
        for tc_ast in self.ast.task_classes:
            envs[tc_ast.name] = _Env(tc_ast, ns)
            tp.task_class(
                tc_ast.name, params=tuple(tc_ast.params),
                space=self._make_space(tc_ast, envs[tc_ast.name]),
                flows=self._make_flows(tc_ast, envs[tc_ast.name], tp),
                affinity=self._make_affinity(tc_ast, envs[tc_ast.name]),
                priority=self._make_priority(tc_ast, envs[tc_ast.name]))
        for tc_ast in self.ast.task_classes:
            ptc = tp.task_class_by_name(tc_ast.name)
            for b in tc_ast.bodies:
                self._attach_body(ptc, tc_ast, b, envs[tc_ast.name])
        if lint:
            tp.validate(mode=lint)
        return tp

    # -- space (startup-task enumerator analog, jdf2c.c:2989) -------------
    def _make_space(self, tc: JdfTaskClass, ev: _Env):
        # Walk the locals in declaration order: ranged locals (= the
        # parameters) are loop dimensions; derived locals are evaluated
        # into the environment so later ranges can use them (reference
        # stencil_1D.jdf: `m = t %% lmt` between the t and n ranges).
        order = list(tc.locals)
        params = tc.params

        def space(g):
            def rec(i, env):
                if i == len(order):
                    yield tuple(env[p] for p in params)
                    return
                l = order[i]
                if l.range is not None:
                    for v in _range_values(env, l.range):
                        env2 = dict(env)
                        env2[l.name] = v
                        yield from rec(i + 1, env2)
                else:
                    env2 = dict(env)
                    env2[l.name] = eval(l.value.code(), env2)
                    yield from rec(i + 1, env2)
            yield from rec(0, dict(ev.ns))
        return space

    def _make_affinity(self, tc: JdfTaskClass, ev: _Env):
        part = tc.partitioning
        if part is None:
            return None

        def affinity(g, *p):
            env = ev.env(p)
            dc = env[part.name]
            key = tuple(eval(a.code(), env)
                        for a in part.args)
            return dc, key
        return affinity

    def _make_priority(self, tc: JdfTaskClass, ev: _Env):
        if tc.priority is None:
            return None
        return lambda g, *p: int(ev.eval(tc.priority, p))

    # -- flows -------------------------------------------------------------
    def _make_flows(self, tc: JdfTaskClass, ev: _Env, tp) -> List[ptg.FlowSpec]:
        access_map = {"RW": ptg.RW, "READ": ptg.READ,
                      "WRITE": ptg.WRITE, "CTL": ptg.CTL}
        specs = []
        for f in tc.flows:
            ins: List[ptg.In] = []
            outs: List[ptg.Out] = []
            tile_fn = None
            for d in f.deps:
                branches = [(d.guard, d.then, False)]
                if d.otherwise is not None:
                    branches.append((d.guard, d.otherwise, True))
                for guard_e, target, negate in branches:
                    gfn = self._guard_fn(ev, guard_e, negate)
                    if target.is_null:
                        continue
                    if d.direction == "in":
                        ins.append(self._make_in(ev, tp, target, gfn, d))
                    else:
                        outs.append(self._make_out(ev, tp, target, gfn, d))
                    c = target.call
                    if tile_fn is None and c is not None and \
                            not c.is_task_ref:
                        tile_fn = self._data_fn(ev, c)
                if "tile" in d.props:
                    tile_fn = self._tile_prop_fn(ev, d.props["tile"])
            if "tile" in f.props:
                tile_fn = self._tile_prop_fn(ev, f.props["tile"])
            specs.append(ptg.FlowSpec(f.name, access_map[f.access],
                                      ins=ins, outs=outs, tile=tile_fn))
        return specs

    def _guard_fn(self, ev: _Env, guard: Optional[Expr], negate: bool):
        if guard is None:
            return None
        if negate:
            return lambda g, *p: not bool(ev.eval(guard, p))
        return lambda g, *p: bool(ev.eval(guard, p))

    def _data_fn(self, ev: _Env, call: CallRef):
        def data(g, *p):
            env = ev.env(p)
            dc = env[call.name]
            key = tuple(eval(a.code(), env)
                        for a in call.args)
            return dc, key
        return data

    def _tile_prop_fn(self, ev: _Env, expr: Expr):
        # property value is `A(k, k)`-shaped: reparse as a call ref
        sub = _Parser(expr.text.strip())
        call = sub.parse_call_ref(allow_flow=False, allow_range=False)
        return self._data_fn(ev, call)

    def _make_in(self, ev: _Env, tp, target: DepTarget, gfn, dep: JdfDep):
        if target.new is not None:
            e = target.new
            return ptg.In(new=lambda g, *p: ev.eval(e, p), guard=gfn)
        c = target.call
        if c.is_task_ref:
            if any(isinstance(a, tuple) for a in c.args):
                # ranged IN dep = CTL gather (ctlgat.jdf syntax:
                # `CTL C <- C W(0 .. N-1)`): wait for every producer in
                # the expanded range
                def gather_fn(g, *p, _c=c):
                    return _expand_args(ev, _c, p)
                return ptg.In(src=(c.name, gather_fn, c.flow),
                              guard=gfn, gather=True)

            def params_fn(g, *p, _c=c):
                env = ev.env(p)
                return tuple(eval(a.code(), env)
                             for a in _c.args)
            return ptg.In(src=(c.name, params_fn, c.flow), guard=gfn)
        return ptg.In(data=self._data_fn(ev, c), guard=gfn)

    def _make_out(self, ev: _Env, tp, target: DepTarget, gfn, dep: JdfDep):
        c = target.call
        if c is None:
            raise JDFSemanticError("NEW is not a valid -> target")
        if c.is_task_ref:
            ranged = any(isinstance(a, tuple) for a in c.args)
            if ranged:
                params_fn = lambda g, *p, _c=c: _expand_args(ev, _c, p)
            else:
                def params_fn(g, *p, _c=c):
                    env = ev.env(p)
                    return tuple(eval(a.code(), env)
                                 for a in _c.args)
            return ptg.Out(dst=(c.name, params_fn, c.flow), guard=gfn)
        return ptg.Out(data=self._data_fn(ev, c), guard=gfn)

    # -- bodies (jdf_generate_code_hook analog, jdf2c.c:6913) --------------
    def _attach_body(self, ptc: ptg.PTGTaskClass, tc: JdfTaskClass,
                     body: JdfBody, ev: _Env):
        device = DeviceType.ALL
        if "type" in body.props:
            dname = body.props["type"].text.strip().strip("\"'").lower()
            if dname not in _DEVICE_NAMES:
                raise JDFSemanticError(
                    f"{tc.name}: unknown BODY type {dname!r}")
            device = _DEVICE_NAMES[dname]
        code = compile(body.code or "pass", f"<jdf-body:{tc.name}>", "exec")
        in_flows = [f.name for f in ptc.flows if not f.is_ctl]
        out_flows = [f.name for f in ptc.output_flows]
        # A body that references no params/locals is shape-uniform across
        # the class → batchable (vmap) on the compiled executors.
        def _code_names(c):
            names = set(c.co_names) | set(c.co_freevars)
            for const in c.co_consts:
                if hasattr(const, "co_names"):
                    names |= _code_names(const)
            return names
        uses_instance = bool(_code_names(code) &
                             (set(tc.params) | {l.name for l in tc.locals}))

        def hook(task, *inputs, _code=code):
            if task is not None:
                env = dict(ev.env(tuple(task.locals)))
            else:
                env = dict(ev.ns)
            env.update(zip(in_flows, inputs))
            exec(_code, env)
            outs = [env.get(f) for f in out_flows]
            if len(outs) == 1:
                return outs[0]
            return tuple(outs)

        ptc.body(hook, device=device, batchable=not uses_instance)


def compile_jdf(source: str, name: str = "jdf") -> CompiledJDF:
    """Compile JDF text (the parsec_ptgpp entry point analog)."""
    return CompiledJDF(parse(source), name)


def compile_file(path: str, name: Optional[str] = None) -> CompiledJDF:
    with open(path) as fh:
        src = fh.read()
    if name is None:
        name = re.sub(r"\.jdf$", "", path.rsplit("/", 1)[-1])
    return compile_jdf(src, name)


# ------------------------------------------------------------------ unparser
# (jdf_unparse.c analog: AST → JDF text round-trip)

def _unparse_props(props: Dict[str, Expr]) -> str:
    if not props:
        return ""
    inner = " ".join(f"{k} = {v.text.strip()}" for k, v in props.items())
    return f" [ {inner} ]"


def _unparse_target(t: DepTarget) -> str:
    if t.is_null:
        return "NULL"
    if t.new is not None:
        return f"NEW({t.new.text.strip()})"
    c = t.call
    args = []
    for a in c.args:
        if isinstance(a, tuple):
            s = f"{a[0].text.strip()} .. {a[1].text.strip()}"
            if a[2] is not None:
                s += f" .. {a[2].text.strip()}"
            args.append(s)
        else:
            args.append(a.text.strip())
    head = f"{c.flow} {c.name}" if c.is_task_ref else c.name
    return f"{head}({', '.join(args)})"


def unparse(jdf: JdfFile) -> str:
    """AST → JDF source (round-trips through :func:`parse`)."""
    out: List[str] = []
    for p in jdf.prologues:
        out.append("extern \"python\" %{" + p + "%}\n")
    for g in jdf.globals:
        out.append(f"{g.name}{_unparse_props(g.props)}")
    out.append("")
    for tc in jdf.task_classes:
        out.append(f"{tc.name}({', '.join(tc.params)})"
                   f"{_unparse_props(tc.props)}")
        for l in tc.locals:
            if l.range is not None:
                s = f"  {l.name} = {l.range[0].text.strip()} .. " \
                    f"{l.range[1].text.strip()}"
                if l.range[2] is not None:
                    s += f" .. {l.range[2].text.strip()}"
            else:
                s = f"  {l.name} = {l.value.text.strip()}"
            out.append(s)
        if tc.partitioning is not None:
            out.append(
                f"  : {_unparse_target(DepTarget(call=tc.partitioning))}")
        for f in tc.flows:
            head = f"  {f.access} {f.name}{_unparse_props(f.props)}"
            pad = " " * len(f"  {f.access} {f.name}")
            for i, d in enumerate(f.deps):
                arrow = "<-" if d.direction == "in" else "->"
                s = f"{head if i == 0 else pad} {arrow} "
                if d.guard is not None:
                    s += f"({d.guard.text.strip()}) ? "
                s += _unparse_target(d.then)
                if d.otherwise is not None:
                    s += f" : {_unparse_target(d.otherwise)}"
                s += _unparse_props(d.props)
                out.append(s)
            if not f.deps:
                out.append(head)
        if tc.priority is not None:
            out.append(f"  ; {tc.priority.text.strip()}")
        for b in tc.bodies:
            out.append(f"BODY{_unparse_props(b.props)}")
            out.append(b.code.rstrip("\n"))
            out.append("END")
        out.append("")
    return "\n".join(out) + "\n"
