"""DSL layer: the two front ends that produce taskpools.

Reference: PTG (compiled parameterized task graphs, the JDF language +
parsec_ptgpp compiler, parsec/interfaces/ptg/) and DTD (dynamic task
discovery, parsec/interfaces/dtd/insert_function.c). Both sit strictly
above the core and only produce Taskpool/TaskClass structures.
"""

from . import dtd
from . import jdf
from . import ptg
