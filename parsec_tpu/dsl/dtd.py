"""DTD: dynamic task discovery.

Reference: parsec/interfaces/dtd/insert_function.c (3,612 LoC) — tasks are
inserted at runtime with varargs flags (INPUT/OUTPUT/INOUT/VALUE/SCRATCH +
AFFINITY/..., insert_function.h:60-78); task classes are created lazily per
(function, argument-shape) (insert_function.c:1015); per-tile
``last_writer``/``last_user`` tracking orders accesses
(insert_function_internal.h:191-211, overlap_strategies.c); a sliding
window throttles insertion (insert_function.h:131-142).

TPU-first divergence: task bodies are functional (values in → new values
out), so WAR hazards vanish — a reader snapshots the version current at
*insert* time (program order), immutable arrays keep it valid, and a later
writer simply produces a new version. Only RAW (value flows from the
in-flight last writer) and WAW (writer chain) edges are materialized, which
strictly increases available parallelism versus the reference's read-list
serialization (overlap_strategies.c:38-120).

Distributed DTD (reference: every rank replays the same insertion
sequence; remote activations for undiscovered tasks are parked,
remote_dep_mpi.c:1935-1961; bcast restricted to star, remote_dep.c:543):
tasks are identified by their per-taskpool insertion sequence number
(identical on every rank). A task placed on another rank becomes a
*shell*: no body runs locally, but tile tracking is updated so the
dataflow crosses ranks correctly. Each tile carries a ``holder_rank`` —
the rank holding the version current at this point in program order,
updated identically on every rank during replay — so:

- a local reader whose version is held remotely counts one extra dep and
  receives the value as a remote activation (sent by the holder, which
  replays the same insert as a shell);
- a local completion delivers values to remote shells linked as
  successors (star fan-out);
- a shell read of a tile this rank holds (no writer in flight) triggers
  an eager push of the current version.

``flush()`` is collective in distributed mode: each rank quiesces its
local writers, pushes tiles it holds back to their owners
(parsec_dtd_data_flush analog), waits for acks, and barriers.

Usage::

    tp = dtd.Taskpool("gemm")
    ctx.add_taskpool(tp)
    tp.insert_task(body, dtd.TileArg(A, (i, k), dtd.INPUT),
                         dtd.TileArg(C, (i, j), dtd.INOUT),
                         dtd.ValueArg(alpha))
    ...
    tp.wait()
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.task import Chore, DeviceType, Flow, FlowAccess, Task
from ..core.taskpool import DEPS_COUNTER, SuccessorRef, TaskClass
from ..core.taskpool import Taskpool as CoreTaskpool
from ..data.collection import DataCollection
from ..utils import mca_param

# access flags (insert_function.h:60-78 analog)
INPUT = FlowAccess.READ
OUTPUT = FlowAccess.WRITE
INOUT = FlowAccess.RW

_GOAL_UNSET = 1 << 40       # sentinel while an insert is still linking

# process-wide jit cache for pure=True bodies: (fn, argspec sig) →
# jitted woven callable. Keyed by the fn OBJECT (kept alive by the
# cache — no id-reuse aliasing), so module-level bodies compile once
# per process even across taskpools
_PURE_JIT_CACHE: Dict[Any, Callable] = {}
_PURE_JIT_LOCK = threading.Lock()

mca_param.register("dtd.window_size", 4096,
                   help="max in-flight inserted tasks before the inserter throttles")
mca_param.register("dtd.threshold_size", 2048,
                   help="inserter resumes below this many in-flight tasks")


@dataclass
class TileArg:
    """A data argument: tile ``key`` of ``collection`` with an access mode.
    ``affinity=True`` marks the argument whose owner rank places the task
    (PARSEC_AFFINITY analog)."""
    collection: DataCollection
    key: Tuple
    access: FlowAccess
    affinity: bool = False


@dataclass
class ValueArg:
    """Pass-by-value argument (PARSEC_VALUE analog)."""
    value: Any


@dataclass
class ScratchArg:
    """Per-task scratch allocation (PARSEC_SCRATCH analog): the body
    receives a fresh numpy buffer of ``shape``/``dtype``."""
    shape: Tuple[int, ...]
    dtype: Any = "float32"


class _Shell:
    """Placeholder for a task placed on another rank (the reference's
    remote shell task, insert_function.c distributed path)."""

    __slots__ = ("seq", "rank")

    def __init__(self, seq: int, rank: int):
        self.seq = seq
        self.rank = rank


class _Tile:
    """Per-(collection, key) tracking state (parsec_dtd_tile_t analog).

    ``holder_rank`` is the rank holding the version current at this point
    of the replayed insertion order (None = the collection owner)."""

    __slots__ = ("collection", "key", "lock", "last_writer",
                 "last_writer_flow", "holder_rank")

    def __init__(self, collection: DataCollection, key):
        self.collection = collection
        self.key = key
        self.lock = threading.Lock()
        # not-yet-complete writer: local Task or remote _Shell
        self.last_writer = None
        self.last_writer_flow: Optional[str] = None
        self.holder_rank: Optional[int] = None


class _TileBank:
    """parsec_dtd_tile_of analog: lazily materialized tracking tiles."""

    def __init__(self) -> None:
        self._tiles: Dict[Tuple[int, Any], _Tile] = {}
        self._lock = threading.Lock()

    def tile_of(self, dc: DataCollection, key) -> _Tile:
        hkey = (dc.dc_id, tuple(key) if isinstance(key, (tuple, list)) else key)
        # insertion fast path: dict reads are GIL-atomic, so a hit costs
        # no lock (every tile arg of every insert lands here); the lock
        # only serializes first-touch materialization
        t = self._tiles.get(hkey)
        if t is None:
            with self._lock:
                t = self._tiles.get(hkey)
                if t is None:
                    t = _Tile(dc, hkey[1])
                    self._tiles[hkey] = t
        if t.collection is not dc:
            # two live collections sharing one dc_id would silently
            # alias each other's writer tracking (values vanish);
            # dc_id is the wire identity, so it must be unique
            raise ValueError(
                f"distinct collections share dc_id={dc.dc_id}; "
                f"tile {hkey[1]} would alias "
                f"{getattr(t.collection, 'name', t.collection)!r} and "
                f"{getattr(dc, 'name', dc)!r} — give each collection "
                "a unique dc_id")
        return t

    def all(self) -> List[_Tile]:
        with self._lock:
            return list(self._tiles.values())


class Taskpool(CoreTaskpool):
    """DTD taskpool (parsec_dtd_taskpool_new analog)."""

    def __init__(self, name: str = "dtd"):
        super().__init__(name=name)
        self.tiles = _TileBank()
        self._classes: Dict[Any, TaskClass] = {}
        self._class_lock = threading.Lock()
        self._goals: Dict[int, int] = {}
        self._tasks_by_seq: Dict[int, Task] = {}
        # Per-seq striped locks: goal publication + pending-finalize
        # (insert_task) and goal read + count (activate_dep) must be one
        # critical section *per seq* — a single global lock here would
        # serialize every dependency activation of every DTD task. Dict
        # accesses themselves are GIL-atomic; only the per-seq ordering
        # needs the lock.
        self._seq_locks = [threading.Lock() for _ in range(64)]
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._throttle_waiters = 0   # completers notify only when an
        #                              inserter is actually parked
        self._window = int(mca_param.get("dtd.window_size", 4096))
        self._threshold = int(mca_param.get("dtd.threshold_size", 2048))
        self._closed = False
        # multi-tenant serving hooks (serving/runtime.py). ``admission``
        # is called with (taskpool, n_rows) BEFORE rows are inserted —
        # it applies the tenant's cross-pool window: park briefly for
        # backpressure, or raise AdmissionRejected when the tenant's
        # queue depth / HBM reservation is exceeded (explicit rejection
        # instead of unbounded parking). ``on_retire`` fires once per
        # admitted row leaving flight (local completion or remote-shell
        # handoff) so the tenant window drains.
        self.admission = None
        self.on_retire: Optional[Callable[["Taskpool"], None]] = None
        # per-stage overhead accounting (runtime.stage_timers /
        # profiling `overhead` module): wall time spent in insert_task
        # on the inserting thread(s)
        self.insert_s = 0.0
        self.insert_calls = 0
        # native dynamic-task engine (dsl/dtd_native.py): resolved once
        # at first insert per the runtime.native_dtd knob and the
        # instrumented-fallback rule; None = the Python engine below
        self._native = None
        self._native_checked = False
        # per-taskpool insertion sequence: the cross-rank task identity
        # (every rank replays the same sequence → same numbering)
        self._seq = 0
        # wire "class" used to address DTD activations rank-to-rank
        self._wire_tc = TaskClass("__dtd__", -1, params=("seq",), flows=[])
        self._wire_tc.make_key = lambda locals: ("dtd", locals[0])
        self._tc_by_name["__dtd__"] = self._wire_tc
        # collective pin: the reference restricts DTD broadcasts to the
        # star topology (remote_dep.c:543-551) — the data plane reads
        # this before comm.bcast_topology (collectives.resolve_topology)
        self.bcast_topology = "star"
        self._flush_lock = threading.Lock()
        self._flush_acks = 0
        self._flush_cv = threading.Condition(self._flush_lock)
        # count of remote activations that arrived BEFORE the local
        # replay discovered their task (parked against _GOAL_UNSET) —
        # observability for the remote_dep_mpi.c:1935-1961 analog
        # (incremented under the seq lock; GIL-atomic reads)
        self.parked_activations = 0
        # hold the taskpool open while the user is still inserting
        # (reference: DTD keeps a pending action until taskpool_wait)
        # _enqueue_counted: the +1 only happens when registration
        # completes (a broken-mesh refusal in taskpool_registered stops
        # add_taskpool BEFORE on_enqueue) — wait() must not decrement a
        # count that was never incremented (runtime_actions would go
        # negative and mask the peer-death diagnostic)
        self._enqueue_counted = False

        def _on_enqueue(tp):
            tp.addto_runtime_actions(1)
            tp._enqueue_counted = True

        self.on_enqueue = _on_enqueue

    def _seq_lock(self, stripe: int):
        """Seq-stripe lock, wrapped for acquisition-order reporting when
        the dfsan sanitizer is installed (analysis/dfsan.py); a bare
        Lock otherwise — the hot path pays one attribute read."""
        lock = self._seq_locks[stripe]
        ctx = self.context
        san = ctx.dfsan if ctx is not None else None
        if san is not None:
            return san.wrap_lock(lock, "dtd-seq", stripe)
        return lock

    # -- rank helpers ------------------------------------------------------
    @property
    def my_rank(self) -> int:
        return self.context.my_rank if self.context is not None else 0

    @property
    def nb_ranks(self) -> int:
        return self.context.nb_ranks if self.context is not None else 1

    def _on_terminated(self) -> None:
        # release an inserter blocked in the sliding-window throttle (the
        # pool may have aborted while insert_task was waiting for drain)
        with self._inflight_cv:
            self._closed = self._closed or (self.error is not None)
            self._inflight_cv.notify_all()
        eng = self._native
        if eng is not None:
            if self.error is not None:
                # abort/cancel: release the native queues (queued tasks
                # drop at select time) and any natively-parked inserter
                eng.cancel()
            ctx = self.context
            if ctx is not None:
                # fold the engine's counters into the context totals so
                # parsec_tasks_completed_total survives the pool; an
                # aborted pool with tasks still in flight keeps its
                # engine pumped until they drain (retiring state)
                ctx._ndtd_retire(eng)
        super()._on_terminated()

    # ------------------------------------------------------------- classes
    def _task_class_for(self, fn: Callable, shape: Tuple,
                        device: DeviceType,
                        pure: bool = False) -> TaskClass:
        """Lazily create a task class per (fn, arg shape)
        (insert_function.c:1015 analog). Resolution is on the insertion
        hot path, so a cache hit is a lock-free dict read (GIL-atomic);
        the lock only serializes creation."""
        key = (fn, shape, device, pure)
        tc = self._classes.get(key)
        if tc is not None:
            return tc
        with self._class_lock:
            tc = self._classes.get(key)
            if tc is not None:
                return tc
            # flow names must match insert_task's tile-only numbering
            # (value/scratch args don't consume a flow slot)
            flows = []
            for kind, access in shape:
                if kind == "tile":
                    flows.append(Flow(f"f{len(flows)}",
                                      access if access else FlowAccess.READ))
            tc = TaskClass(getattr(fn, "__name__", "dtd_task"),
                           len(self.task_classes), params=("seq",),
                           flows=flows, deps_mode=DEPS_COUNTER)
            # task identity is the insertion sequence number — identical on
            # every rank, so activations address tasks unambiguously
            tc.make_key = lambda locals: ("dtd", locals[0])
            tc.deps_goal = lambda locals: self._goals.get(locals[0], _GOAL_UNSET)
            tc.iterate_successors = self._iterate_successors
            tc.data_lookup = self._data_lookup

            if pure:
                # pure=True contract (insert_task): fn is a pure
                # function of its arguments, so the whole woven body is
                # jitted once per (argspec signature, arg shapes) and
                # every task of the class dispatches asynchronously —
                # eager per-op dispatch through a remote backend costs
                # ~0.3 s/task where the jitted call pipelines at ~1.4 ms
                # (the reference's DTD bodies are BLAS/CUDA kernels,
                # i.e. pure by construction; impure Python bodies keep
                # the default eager path). The jit cache is process-wide
                # (keyed by fn identity + argspec signature) so repeated
                # taskpools over the same body compile once.
                jit_cache = _PURE_JIT_CACHE
                jit_lock = _PURE_JIT_LOCK

                def _spec_key(spec):
                    parts = []
                    for kind, payload in spec:
                        if kind == "tile":
                            parts.append(("tile",))
                        elif kind == "scratch":
                            parts.append(("scratch", tuple(payload[0]),
                                          str(payload[1])))
                        elif isinstance(payload, (int, float, str, bool,
                                                  type(None))):
                            parts.append(("value", payload))
                        else:
                            # unhashable payload: identity-keyed. The
                            # closure keeps the object alive (no id
                            # reuse), but the payload's CONTENTS are
                            # baked in at trace time — mutating an
                            # array payload in place between inserts
                            # would silently serve the stale compile.
                            # Contract (insert_task docstring): ValueArg
                            # payloads under pure=True are immutable.
                            parts.append(("value", id(payload)))
                    return tuple(parts)

                def _make_woven(spec, _fn=fn):
                    import jax.numpy as jnp

                    def woven(*fv, _spec=tuple(spec)):
                        args: List[Any] = []
                        it = iter(fv)
                        for (kind, payload) in _spec:
                            if kind == "tile":
                                args.append(next(it))
                            elif kind == "value":
                                args.append(payload)
                            else:
                                args.append(jnp.zeros(
                                    payload[0], dtype=payload[1]))
                        return _fn(*args)

                    return woven

                def _hook(task: Task, *flow_vals, _fn=fn):
                    import jax
                    from ..ops.tile_kernels import matmul_precision
                    spec = task.dsl["argspec"]
                    # the MXU precision knob is read at TRACE time by
                    # the tile kernels, so it must be part of the cache
                    # identity — otherwise a later precision change
                    # would silently keep serving the old compile
                    skey = (_fn, _spec_key(spec), matmul_precision())
                    # lock-free fast path (dict reads are GIL-atomic);
                    # the lock only serializes compile-on-miss
                    jf = jit_cache.get(skey)
                    if jf is not None:
                        return jf(*flow_vals)
                    with jit_lock:
                        jf = jit_cache.get(skey)
                        if jf is None:
                            jf = jax.jit(_make_woven(spec))
                            jit_cache[skey] = jf
                    return jf(*flow_vals)

                # manager batching (device.tpu.batch_dispatch): tasks
                # whose woven bodies are identical — same argspec
                # signature at the same precision — may be vmapped into
                # one dispatch even though the hook itself reads
                # per-task metadata
                def _batch_sig(task: Task):
                    # fn identity is already in the manager's group key
                    # via id(chore)
                    from ..ops.tile_kernels import matmul_precision
                    return (_spec_key(task.dsl["argspec"]),
                            matmul_precision())

                def _batch_body(task: Task):
                    return _make_woven(task.dsl["argspec"])
            else:
                def _hook(task: Task, *flow_vals, _fn=fn):
                    args: List[Any] = []
                    it = iter(flow_vals)
                    for (kind, payload) in task.dsl["argspec"]:
                        if kind == "tile":
                            args.append(next(it))
                        elif kind == "value":
                            args.append(payload)
                        else:  # scratch
                            args.append(np.zeros(payload[0],
                                                 dtype=payload[1]))
                    return _fn(*args)

            if pure:
                # batchable=False: the hook self-jits (the device's
                # _run_sync wrapper would double-jit); batch_sig/
                # batch_body let the batching manager vmap same-woven
                # groups anyway
                tc.add_chore(Chore(device, _hook, batchable=False,
                                   batch_sig=_batch_sig,
                                   batch_body=_batch_body))
            else:
                tc.add_chore(Chore(device, _hook, batchable=False))
            self.add_task_class(tc)
            self._classes[key] = tc
            return tc

    # ------------------------------------------------------------- insert
    def _placement(self, args) -> int:
        """Owner rank of the task: the AFFINITY tile's owner, else the
        first tile argument's owner, else round-robin by sequence
        (PARSEC_AFFINITY analog — deterministic across the replay)."""
        first = None
        for a in args:
            if isinstance(a, TileArg):
                if a.affinity:
                    return a.collection.rank_of(a.key)
                if first is None:
                    first = a
        if first is not None:
            return first.collection.rank_of(first.key)
        return self._seq % self.nb_ranks

    def insert_task(self, fn: Callable, *args, priority: int = 0,
                    device: DeviceType = DeviceType.ALL,
                    name: Optional[str] = None,
                    pure: bool = False) -> Optional[Any]:
        """parsec_dtd_insert_task analog (insert_function.c:3488). In
        distributed mode every rank calls this with the identical sequence;
        returns the local Task (Python engine) or the task's insertion
        sequence number as an opaque int handle (native engine — no
        Python Task object exists there, by design), or None when the
        task is placed remotely (a shell — only tile tracking is
        updated here). Callers must treat the result as opaque
        not-None evidence; the ``name`` hint is display-only and unused
        by both engines.

        ``pure=True`` declares ``fn`` a pure function of its arguments:
        the body is jitted (per arg-shape/value signature) so device
        dispatch is asynchronous — the performance path for tile math
        (side-effecting Python bodies must keep the default). Non-scalar
        ``ValueArg`` payloads are baked into the compiled body at trace
        time and cached by object identity, so they must be treated as
        IMMUTABLE once inserted — mutating an array payload in place
        between inserts would silently serve the stale compile."""
        timed = self.context is not None and self.context.stage_timers
        t0 = time.perf_counter() if timed else None
        self._check_insertable()
        if self.admission is not None:
            self.admission.admit(self, 1)
        eng = self._engine()
        if eng is not None:
            # native hot loop: returns the task's sequence number (the
            # opaque handle — native tasks have no Python Task object).
            # Stage timers no longer force the Python engine (ISSUE
            # 13), so the insert-stage row is accounted here too.
            out = eng.insert_rows(fn, [args], priority, device, pure)[0]
            if timed:
                self.insert_s += time.perf_counter() - t0
                self.insert_calls += 1
            return out
        tc = self._task_class_for(fn, self._shape_of(args), device,
                                  pure=pure)
        task = self._insert_one(tc, args, priority, None, None)
        self._throttle()
        if timed:
            self.insert_s += time.perf_counter() - t0
            self.insert_calls += 1
        return task

    def insert_tasks(self, fn: Callable, rows, *, priority: int = 0,
                     priorities: Optional[List[int]] = None,
                     device: DeviceType = DeviceType.ALL,
                     pure: bool = False) -> List[Optional[Any]]:
        """Batched :meth:`insert_task` — the insertion fast path. All
        ``rows`` (sequences of Tile/Value/Scratch args) are inserted with
        the same body, paying the per-insert lookup costs ONCE per batch
        where possible: one task-class resolution per distinct arg shape,
        a shared tile-handle cache, one ``schedule()`` call for every
        task that becomes ready during the batch, and one
        sliding-window check per batch tail (re-checked mid-batch so a
        batch larger than the window still throttles; any accumulated
        ready tasks are flushed to the scheduler BEFORE parking, or the
        drain the window waits for could never happen).

        Semantically identical to calling ``insert_task`` per row —
        program order, tile tracking, and the cross-rank replay sequence
        are unchanged. Returns one opaque handle per row: a ``Task``
        (Python engine) or an int seq (native engine), ``None`` for a
        remote shell.

        ``priorities`` (optional, one int per row) overrides
        ``priority`` per row — the KV state layer uses it to put a
        request's chunked-prefill rows on the wfq PREFILL lane
        (priority < 0, ``sched/fair.py``) while its decode rows keep
        the default lane, inside ONE batch (one admission check: a
        request's task graph is admitted all-or-nothing). Per-row
        priorities are a scheduling-lane hint consumed by the Python
        engine's schedulers; the native engine receives the scalar
        ``priority`` (lane-aware pools — wfq — never run native)."""
        timed = self.context is not None and self.context.stage_timers
        t0 = time.perf_counter() if timed else None
        self._check_insertable()
        rows = list(rows)
        out: List[Optional[Task]] = []
        if not rows:
            return out
        if priorities is not None:
            priorities = list(priorities)
            if len(priorities) != len(rows):
                raise ValueError(
                    f"priorities ({len(priorities)}) must match rows "
                    f"({len(rows)})")
        if self.admission is not None:
            self.admission.admit(self, len(rows))
        eng = self._engine()
        if eng is not None:
            handles = eng.insert_rows(fn, rows, priority, device, pure)
            if timed:
                self.insert_s += time.perf_counter() - t0
                self.insert_calls += len(rows)
            return handles
        shape0 = self._shape_of(rows[0])
        tc0 = self._task_class_for(fn, shape0, device, pure=pure)
        ready: List[Task] = []
        tile_cache: Dict[Any, _Tile] = {}
        for i, args in enumerate(rows):
            if self.error is not None:
                # the pool failed mid-batch (poison body, peer death):
                # flush what is already ready, then surface the abort to
                # the inserter instead of feeding a dead pool
                if ready:
                    self.context.schedule(None, ready)
                self._check_insertable()
            shape = self._shape_of(args)
            tc = tc0 if shape == shape0 else \
                self._task_class_for(fn, shape, device, pure=pure)
            out.append(self._insert_one(
                tc, args,
                priorities[i] if priorities is not None else priority,
                ready, tile_cache))
            if len(ready) >= 512:
                # chunked flush: keep the workers fed while a long batch
                # is still inserting (one schedule() per chunk, not per
                # task)
                self.context.schedule(None, ready)
                ready = []
            if self._inflight >= self._window:   # lock-free pre-check
                if ready:
                    self.context.schedule(None, ready)
                    ready = []
                self._throttle()
        if ready:
            self.context.schedule(None, ready)
        if timed:
            self.insert_s += time.perf_counter() - t0
            self.insert_calls += len(rows)
        return out

    # -- insertion internals ----------------------------------------------
    def _engine(self):
        """The native dynamic-task engine, or None (the Python path).
        Resolved ONCE at first insert — the observers the fallback rule
        checks are installed before work starts; a pool never switches
        engines mid-flight (the tile tracking marks differ). A raising
        resolution (forced runtime.native_dtd=1 without a toolchain) is
        deliberately NOT cached: every retried insert must keep raising
        rather than silently proceeding on the Python engine."""
        if self._native_checked:
            return self._native
        from . import dtd_native
        eng = dtd_native.engine_for(self)   # may raise (forced mode)
        self._native = eng
        self._native_checked = True
        return eng

    def _check_insertable(self) -> None:
        if self.error is not None:
            raise RuntimeError(
                f"taskpool {self.name} aborted: {self.error}") from self.error
        if self._closed:
            raise RuntimeError("taskpool already drained by wait()")
        if self.context is None:
            raise RuntimeError("add_taskpool(tp) before insert_task")
        if not self.context._started:
            # reference: the context must be started before DTD insertion
            # (insert_function.c checks the same and the sliding window
            # would deadlock otherwise)
            self.context.start()

    @staticmethod
    def _shape_of(args) -> Tuple:
        return tuple(
            ("tile", a.access) if isinstance(a, TileArg)
            else ("value", None) if isinstance(a, ValueArg)
            else ("scratch", None)
            for a in args)

    def _tile_of_cached(self, dc, key, cache) -> _Tile:
        if cache is None:
            return self.tiles.tile_of(dc, key)
        hkey = (dc.dc_id, tuple(key) if isinstance(key, (tuple, list))
                else key)
        t = cache.get(hkey)
        if t is None:
            t = cache[hkey] = self.tiles.tile_of(dc, key)
        return t

    def _throttle(self) -> None:
        """Sliding-window inserter throttle. The pre-check is lock-free
        (GIL-atomic int read) so an un-throttled insert never touches the
        condition variable here.

        Failure wakeup: an abort (poison body, peer death) sets
        ``_closed`` and notifies under this CV (``_on_terminated``), so
        a parked inserter is released EVENT-DRIVEN — and then raises the
        pool's error instead of silently resuming inserts into a dead
        pool. Waiter registration and the completer's notify share the
        CV lock, so no wakeup can be lost; the residual timeout is a
        belt-and-braces bound, not the exit mechanism."""
        if self._inflight < self._window:
            return
        with self._inflight_cv:
            if self._inflight < self._window:
                return
            self._throttle_waiters += 1
            try:
                while self._inflight > self._threshold and not self._closed:
                    self._inflight_cv.wait(timeout=0.25)
            finally:
                self._throttle_waiters -= 1
        if self.error is not None:
            raise RuntimeError(
                f"taskpool {self.name} aborted: {self.error}") from self.error

    def _insert_one(self, tc: TaskClass, args, priority: int,
                    ready_out: Optional[List[Task]],
                    tile_cache: Optional[Dict]) -> Optional[Task]:
        """One insert under an already-resolved task class. With
        ``ready_out`` set (batch mode), tasks that become ready are
        appended there instead of being scheduled immediately."""
        seq = self._seq
        self._seq += 1
        my_rank = self.my_rank
        if self.nb_ranks > 1:
            target_rank = self._placement(args)
            if target_rank != my_rank:
                self._insert_shell(seq, target_rank, args, priority)
                if self.on_retire is not None:
                    # a shell never enters local flight: retire the
                    # admitted row now so the tenant window drains
                    self.on_retire(self)
                return None

        task = Task(self, tc, (seq,), priority=priority)
        task.dsl.update(argspec=[], out_tiles=[], succ=[], done=False,
                        lock=threading.Lock(), affinity=None, aliases={})

        # register before linking so a racing writer completion can route
        # activations to this task
        with self._seq_lock(seq & 63):
            self._goals[seq] = _GOAL_UNSET
            self._tasks_by_seq[seq] = task
        with self._inflight_cv:
            self._inflight += 1
        self.addto_nb_tasks(1)

        goal = 0
        flow_i = 0
        seen_tiles: Dict[Any, str] = {}   # tile → primary flow of THIS task
        for a in args:
            if isinstance(a, ValueArg):
                task.dsl["argspec"].append(("value", a.value))
                continue
            if isinstance(a, ScratchArg):
                task.dsl["argspec"].append(("scratch", (a.shape, a.dtype)))
                continue
            tile = self._tile_of_cached(a.collection, a.key, tile_cache)
            fname = f"f{flow_i}"
            flow_i += 1
            task.dsl["argspec"].append(("tile", None))
            if a.affinity:
                task.dsl["affinity"] = (a.collection, a.key)
            primary = seen_tiles.get(tile)
            if primary is not None:
                # same tile passed twice in one insert: alias the flow to
                # the first occurrence instead of linking the task as its
                # own predecessor (which would deadlock); resolved by
                # _data_lookup just before execution
                task.dsl["aliases"][fname] = primary
            else:
                seen_tiles[tile] = fname
                with tile.lock:
                    writer = tile.last_writer
                    # capture the writer's flow ATOMICALLY with the
                    # writer: the completer clears both under this lock
                    # (retire, step 1) BEFORE publishing done (step 2),
                    # so re-reading it later could yield None for a
                    # writer whose done flag we still observe False —
                    # the successor would then receive a None value
                    writer_flow = tile.last_writer_flow
                    holder = tile.holder_rank
                if holder is None:
                    holder = a.collection.rank_of(a.key)
                linked = False
                if isinstance(writer, Task):
                    with writer.dsl["lock"]:
                        if not writer.dsl["done"]:
                            ref = SuccessorRef(task_class=tc,
                                               locals=task.locals,
                                               flow_name=fname, value=None,
                                               priority=priority)
                            ref.src_flow = writer_flow
                            writer.dsl["succ"].append(ref)
                            goal += 1
                            linked = True
                elif isinstance(writer, _Shell):
                    # in-flight remote writer: its rank replays this insert
                    # and will deliver the value at completion
                    goal += 1
                    linked = True
                if not linked:
                    if holder == my_rank:
                        san = self.context.dfsan
                        if san is not None:
                            # sync read: the tile-lock + retire protocol
                            # orders this snapshot after the last commit
                            # (write_tile happens-before last_writer is
                            # cleared), so join the tile's write clock
                            # into this task instead of race-checking —
                            # also what keeps a LATER write by this task
                            # WAW-ordered after a retired writer that
                            # left no dep edge behind
                            san.observe_read(task, a.collection, a.key,
                                             sync=True)
                        # current version is local: snapshot the
                        # program-order value now (immutable arrays keep
                        # the snapshot valid); stage-through so one H2D
                        # serves every reader (Context.stage_read)
                        task.data[fname] = self.context.stage_read(
                            a.collection, a.key,
                            a.collection.data_of(a.key))
                    else:
                        # version held remotely: the holder replays this
                        # insert as a shell and pushes the value eagerly
                        goal += 1
            if a.access & FlowAccess.WRITE:
                with tile.lock:
                    tile.last_writer = task
                    tile.last_writer_flow = fname
                    tile.holder_rank = my_rank
                task.dsl["out_tiles"].append((tile, fname))

        # Finalize the goal; racing activations may already have counted.
        # The lock must span both the goal publication AND the finalize
        # check: activate_dep reads the goal and counts under the same
        # lock, so an activation can never count against a stale
        # _GOAL_UNSET after we finalized (that interleaving left the
        # entry uncompletable forever — a lost-wakeup hang).
        with self._seq_lock(seq & 63):
            self._goals[seq] = goal
            ent = None if goal == 0 else self.pending.finalize(
                tc.make_key(task.locals), goal, DEPS_COUNTER)
        ready = None
        if goal == 0:
            ready = task
        elif ent is not None:
            task.data.update(ent["data"])
            task.priority = max(task.priority, ent["priority"])
            ready = task
        if ready is not None:
            if ready_out is not None:
                ready_out.append(ready)     # batch: one schedule() at flush
            else:
                self.context.schedule(None, [ready])
        return task

    def _insert_shell(self, seq: int, target_rank: int, args,
                      priority: int) -> None:
        """Replay a remotely-placed insert: update tile tracking and feed
        the remote task any version this rank holds (star topology — the
        reference restricts DTD collectives to star, remote_dep.c:543)."""
        my_rank = self.my_rank
        flow_i = 0
        seen: set = set()
        for a in args:
            if not isinstance(a, TileArg):
                continue
            tile = self.tiles.tile_of(a.collection, a.key)
            fname = f"f{flow_i}"
            flow_i += 1
            if tile in seen:
                continue
            seen.add(tile)
            with tile.lock:
                writer = tile.last_writer
                # atomic with the writer — see the local-insert path
                writer_flow = tile.last_writer_flow
                holder = tile.holder_rank
            if holder is None:
                holder = a.collection.rank_of(a.key)
            if a.access & FlowAccess.READ and not (a.access & FlowAccess.CTL):
                if isinstance(writer, Task):
                    # local in-flight writer feeds the remote task
                    sent = False
                    with writer.dsl["lock"]:
                        if not writer.dsl["done"]:
                            writer.dsl["succ"].append(
                                ("remote", target_rank, seq, fname,
                                 writer_flow, priority))
                            sent = True
                    if not sent and holder == my_rank:
                        self._send_value(target_rank, seq, fname,
                                         a.collection.data_of(a.key),
                                         priority)
                elif writer is None and holder == my_rank:
                    # quiescent version held here: eager push (PULLIN)
                    self._send_value(target_rank, seq, fname,
                                     a.collection.data_of(a.key), priority)
                # else: another rank holds/produces it — not our edge
            if a.access & FlowAccess.WRITE:
                with tile.lock:
                    tile.last_writer = _Shell(seq, target_rank)
                    tile.last_writer_flow = fname
                    tile.holder_rank = target_rank

    def _send_value(self, target_rank: int, seq: int, fname: str,
                    value, priority: int = 0) -> None:
        """Ship one input value of remote task ``seq`` (eager activation)."""
        import types as _types
        ref = SuccessorRef(task_class=self._wire_tc, locals=(seq,),
                           flow_name=fname, value=value, dep_index=0,
                           priority=priority)
        # eager pushes have no producing task: the wire span parents to
        # the submission root (prof empty -> _span_attach falls back)
        shim = _types.SimpleNamespace(taskpool=self, prof={})
        self.context.comm.remote_dep_activate(shim, ref, target_rank)

    # ----------------------------------------------------- class callbacks
    def _data_lookup(self, task: Task) -> None:
        """prepare_input analog: resolve aliased flows (same tile passed
        twice in one insert) from their primary flow's delivered value."""
        for alias, primary in task.dsl.get("aliases", {}).items():
            if alias not in task.data:
                task.data[alias] = task.data.get(primary)

    def _iterate_successors(self, task: Task):
        ctx = self.context
        san = ctx.dfsan if ctx is not None else None
        # 1) write produced versions back and retire the writer slot, so
        #    late-inserted readers snapshot the new value
        for tile, fname in task.dsl["out_tiles"]:
            if fname in task.output:
                if san is not None:
                    # stamp BEFORE the commit and the retire: an insert
                    # that observes last_writer cleared is guaranteed to
                    # find this write already clocked (sync-read join)
                    san.observe_write(task, tile.collection, tile.key)
                tile.collection.write_tile(tile.key, task.output[fname])
            with tile.lock:
                if tile.last_writer is task:
                    tile.last_writer = None
                    tile.last_writer_flow = None
        # 2) only then mark done and deliver the linked successors
        with task.dsl["lock"]:
            task.dsl["done"] = True
            succ = list(task.dsl["succ"])
            task.dsl["succ"].clear()
        refs: List[SuccessorRef] = []
        # remote shell deliveries grouped per (rank, produced value):
        # one packed activation per rank carries the payload ONCE even
        # when several shells on that rank read it (star fan-out from
        # the producer — the DTD collective pin, remote_dep.c:543-551)
        rsends: Dict[tuple, List[SuccessorRef]] = {}
        for ref in succ:
            if isinstance(ref, tuple):      # remote shell successor
                _, rank, seq, dst_fname, src_flow, prio = ref
                value = task.output.get(src_flow, task.data.get(src_flow)) \
                    if src_flow is not None else None
                rsends.setdefault((rank, id(value)), []).append(
                    SuccessorRef(task_class=self._wire_tc, locals=(seq,),
                                 flow_name=dst_fname, value=value,
                                 dep_index=0, priority=prio))
                continue
            src_flow = getattr(ref, "src_flow", None)
            if src_flow is not None and src_flow in task.output:
                ref.value = task.output[src_flow]
            elif src_flow is not None:
                ref.value = task.data.get(src_flow)
            refs.append(ref)
        if rsends:
            import types as _types
            # prof rides along so the wire hop's span is parented to
            # THIS completing task (profiling/spans.py)
            shim = _types.SimpleNamespace(taskpool=self, prof=task.prof)
            for (rank, _vid), wire_refs in rsends.items():
                self.context.comm.remote_dep_activate_multi(
                    shim, rank, wire_refs)
        seq = task.locals[0]
        with self._seq_lock(seq & 63):
            self._goals.pop(seq, None)
            self._tasks_by_seq.pop(seq, None)
        with self._inflight_cv:
            self._inflight -= 1
            # notify only when an inserter is actually parked in the
            # window throttle (or the pool is draining) — notify_all per
            # completion is pure overhead on the release hot path; the
            # waiter registers under this CV before waiting, so the
            # conditional notify cannot lose a wakeup
            if self._throttle_waiters or self._closed:
                self._inflight_cv.notify_all()
        if self.on_retire is not None:
            self.on_retire(self)
        return refs

    # -------------------------------------------------------------- drain
    def activate_dep(self, ref: SuccessorRef) -> Optional[Task]:
        """DTD successors already exist at activation time — count down on
        the pre-built task instead of constructing a new one. Activations
        for a not-yet-inserted task (remote values racing the replay)
        accumulate in the pending table against the _GOAL_UNSET sentinel
        until insert_task finalizes the goal — the parked-undiscovered-task
        protocol (remote_dep_mpi.c:1935-1961)."""
        seq = ref.locals[0]
        with self._seq_lock(seq & 63):
            return self._activate_one_locked(ref)

    def _activate_one_locked(self, ref: SuccessorRef) -> Optional[Task]:
        """One dep activation; the caller holds ``ref``'s seq-stripe
        lock. The single copy shared by the scalar and batched paths:
        goal read + count must be one critical section against
        insert_task's goal publication + finalize (see there)."""
        seq = ref.locals[0]
        goal = self._goals.get(seq, _GOAL_UNSET)
        if goal == _GOAL_UNSET:
            # activation raced ahead of local discovery — the
            # parked-undiscovered-task path (stress tests assert
            # this actually fires at 4 ranks)
            self.parked_activations += 1
        task = self._tasks_by_seq.get(seq)
        ent = self.pending.update(("dtd", seq),
                                  ref.flow_name, ref.value, ref.dep_index,
                                  goal, DEPS_COUNTER, ref.priority)
        if ent is None:
            return None
        if task is None:
            raise RuntimeError(f"DTD successor seq={seq} vanished")
        task.data.update(ent["data"])
        task.priority = max(task.priority, ent["priority"])
        return task

    def activate_deps(self, refs) -> List[Task]:
        """Batched :meth:`activate_dep` (runtime.release_batch): group a
        completed task's successor refs by seq-lock stripe so each stripe
        is locked once per completion instead of once per dep. The
        per-seq critical section is `_activate_one_locked`, shared with
        the scalar path — only lock acquisitions are coalesced."""
        if len(refs) == 1:
            task = self.activate_dep(refs[0])
            return [task] if task is not None else []
        by_stripe: Dict[int, List] = {}
        for ref in refs:
            by_stripe.setdefault(ref.locals[0] & 63, []).append(ref)
        out: List[Task] = []
        for stripe, group in by_stripe.items():
            with self._seq_lock(stripe):
                for ref in group:
                    task = self._activate_one_locked(ref)
                    if task is not None:
                        out.append(task)
        return out

    def wait(self, context=None) -> None:
        """parsec_dtd_taskpool_wait analog: drain all inserted tasks.
        Idempotent — only the first call releases the enqueue-time runtime
        action; later calls just join."""
        with self._inflight_cv:
            first = not self._closed
            self._closed = True
            self._inflight_cv.notify_all()
        if self._native is not None:
            # native pools never tick nb_tasks (per-task monitor traffic
            # is exactly the overhead the engine removes): drain the
            # engine's inflight count FIRST, so releasing the enqueue
            # action below is what fires termdet
            self._native.drain()
        if first and self._enqueue_counted:
            self.addto_runtime_actions(-1)
        self.wait_completed()

    def flush(self, collection: Optional[DataCollection] = None,
              timeout: float = 60.0) -> None:
        """parsec_dtd_data_flush analog: wait until no in-flight LOCAL
        writer remains for the collection's tiles (produced versions are
        written back at completion, so afterwards ``data_of`` is current).
        In distributed mode this is a COLLECTIVE: after the local quiesce,
        each rank pushes the tiles it holds back to their owners, waits
        for the owners' acks, and barriers."""
        from .dtd_native import _NativeWriter
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.error is not None:
                # a task body failed — its tile writes can never quiesce;
                # surface the abort instead of spinning to the timeout
                raise RuntimeError(
                    f"taskpool {self.name} aborted: {self.error}") \
                    from self.error
            busy = False
            for tile in self.tiles.all():
                if collection is not None and tile.collection is not collection:
                    continue
                with tile.lock:
                    if isinstance(tile.last_writer, (Task, _NativeWriter)):
                        busy = True
                        break
            if not busy:
                break
            time.sleep(0.001)
        else:
            raise TimeoutError("DTD flush timed out")
        if self.nb_ranks > 1:
            self._flush_distributed(collection, timeout)

    def _flush_distributed(self, collection, timeout: float) -> None:
        from ..comm.engine import AMTag
        comm = self.context.comm
        my_rank = self.my_rank
        sent = 0
        for tile in self.tiles.all():
            if collection is not None and tile.collection is not collection:
                continue
            owner = tile.collection.rank_of(tile.key)
            with tile.lock:
                holder = tile.holder_rank
            if holder == my_rank and owner != my_rank:
                # writeback to the owner (parsec_dtd_data_flush); device
                # values snapshot to host HERE (worker thread) so the
                # comm thread never pays a D2H sync mid-progress
                value = tile.collection.data_of(tile.key)
                to_wire = getattr(comm, "wire_value", None)
                if to_wire is not None:
                    value = to_wire(value)
                comm.send_am(
                    AMTag.DTD_CONTROL, owner,
                    {"taskpool": self.name, "op": "flush",
                     "dc_id": tile.collection.dc_id, "key": tile.key,
                     "value": value,
                     "src": my_rank})
                sent += 1
        with self._flush_cv:
            deadline = time.monotonic() + timeout
            while self._flush_acks < sent:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError("DTD distributed flush: acks missing")
                self._flush_cv.wait(timeout=min(0.05, left))
            self._flush_acks -= sent
        comm.sync()

    def _on_dtd_control(self, src: int, msg: Dict) -> None:
        """Handle DTD control AMs (flush writebacks + acks); invoked by
        the comm engine's DTD_CONTROL dispatcher."""
        from ..comm.engine import AMTag
        if msg["op"] == "flush":
            dc = next((t.collection for t in self.tiles.all()
                       if t.collection.dc_id == msg["dc_id"]), None)
            if dc is not None:
                dc.write_tile(msg["key"], msg["value"])
                tile = self.tiles.tile_of(dc, msg["key"])
                with tile.lock:
                    tile.holder_rank = self.my_rank
            self.context.comm.send_am(
                AMTag.DTD_CONTROL, src,
                {"taskpool": self.name, "op": "flush_ack"})
        elif msg["op"] == "flush_ack":
            with self._flush_cv:
                self._flush_acks += 1
                self._flush_cv.notify_all()
