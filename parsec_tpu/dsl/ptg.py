"""PTG: parameterized task graphs (the JDF-language equivalent).

Reference: the JDF language + parsec_ptgpp source-to-source compiler
(parsec/interfaces/ptg/ptg-compiler/: parsec.l, parsec.y, jdf2c.c 8,636
LoC). A JDF task class declares parameters with ranges, a partitioning
predicate (``: A(k, k)``), per-flow guarded dependencies
(``RW T <- (k == 0) ? A(k, k) : T SYRK(k-1, k)``; ``-> T TRSM(k+1..NT, k)``)
and per-device bodies. The generated C gives PTG its key property:
**O(1) distributed dependency discovery** — each rank evaluates, from
closed-form expressions, which tasks exist, who their successors are, and
which are remote, with no global graph materialization.

Here the same structure is expressed directly in Python: guards, parameter
ranges and dependency targets are closures over the taskpool globals, so
discovery stays closed-form (no graph is ever materialized). Both sides of
each edge are declared (``ins`` on the consumer, ``outs`` on the producer)
exactly as in JDF; :func:`check_taskpool` cross-validates the two views the
way the reference's iterators_checker PINS module does at runtime.

Dependency counting uses the mask strategy with one bit per consumer flow
(a JDF flow has exactly one active input dependency per task instance, so
flow-granular bits are sufficient and duplicate activations are caught —
reference mask mode, parsec.c:1601). Exception: classes with a CTL-gather
flow (``In(gather=True)``) use counter mode — N producers feed one flow,
so the per-flow bit cannot count them and duplicate detection is traded
away exactly as in the reference's counter mode (parsec.c:1554).

Example (tiled Cholesky's POTRF class)::

    tp = ptg.Taskpool("potrf", NT=4, A=A)
    POTRF = tp.task_class(
        "POTRF", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        flows=[
          ptg.FlowSpec("T", ptg.RW,
            ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                        guard=lambda g, k: k == 0),
                 ptg.In(src=("SYRK", lambda g, k: (k - 1, k), "T"),
                        guard=lambda g, k: k > 0)],
            outs=[ptg.Out(dst=("TRSM",
                               lambda g, k: [(m, k) for m in range(k + 1, g.NT)],
                               "A"),),
                  ptg.Out(data=lambda g, k: (g.A, (k, k)))]),
        ])
    @POTRF.body
    def potrf_body(task, T):
        return cholesky_tile(T)
"""

from __future__ import annotations

import types
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core.future import DataCopyFuture
from ..core.reshape import compose_specs
from ..core.task import Chore, DeviceType, Flow, FlowAccess, Task
from ..core.taskpool import DEPS_COUNTER, DEPS_MASK, DataRef, \
    SuccessorRef, TaskClass
from ..core.taskpool import Taskpool as CoreTaskpool

READ = FlowAccess.READ
WRITE = FlowAccess.WRITE
RW = FlowAccess.RW
CTL = FlowAccess.CTL


@dataclass
class In:
    """Consumer-side dependency of a flow (JDF ``<-``).

    Exactly one of:
    - ``src=(class_name, params_fn, flow_name)``: value produced by another
      task (``<- T SYRK(k-1, k)``)
    - ``data=lambda g, *p: (collection, key)``: read from a collection
      (``<- A(k, k)``)
    - ``new=lambda g, *p: value``: materialize a fresh value (JDF ``NEW``)
    ``guard`` selects whether this dep is active for a task instance; the
    guards of a flow's ins must be disjoint (one active input per flow).
    ``reshape`` (core.reshape.ReshapeSpec) converts the incoming value to
    this consumer's datatype/layout — the JDF ``[type = ...]`` annotation
    (reshape promises, parsec_reshape.c).

    ``gather=True`` (CTL flows only): ``src``'s params_fn returns a LIST
    of producer coordinates and the flow waits for ALL of them — the
    reference's CTL-gather fan-in (tests/dsl/ptg/controlgather/
    ctlgat.jdf, PARSEC_HAS_CTL_GATHER). A class with a gather flow uses
    counter-mode dependency tracking.
    """
    src: Optional[Tuple[str, Callable, str]] = None
    data: Optional[Callable] = None
    new: Optional[Callable] = None
    guard: Optional[Callable] = None
    reshape: Optional[Any] = None
    gather: bool = False

    def active(self, g, params) -> bool:
        return self.guard is None or bool(self.guard(g, *params))


@dataclass
class Out:
    """Producer-side dependency of a flow (JDF ``->``).

    Exactly one of:
    - ``dst=(class_name, params_fn, flow_name)``: feed another task;
      ``params_fn`` may return one tuple or a list of tuples (ranged deps,
      ``-> T TRSM(k+1..NT-1, k)``)
    - ``data=lambda g, *p: (collection, key)``: terminal write-back
    ``reshape`` converts the produced value before it reaches this dep's
    target (producer-side ``[type = ...]``); it composes with the
    consumer's ``In.reshape``.
    """
    dst: Optional[Tuple[str, Callable, str]] = None
    data: Optional[Callable] = None
    guard: Optional[Callable] = None
    reshape: Optional[Any] = None

    def active(self, g, params) -> bool:
        return self.guard is None or bool(self.guard(g, *params))


@dataclass
class FlowSpec:
    """One flow of a task class.

    ``tile``: optional ``lambda g, *p: (collection, key)`` naming the
    logical tile this flow reads/writes. Not needed by the host runtime
    (values travel with activations) but required by the compiled
    wavefront/SPMD executors, which gather/scatter tiles from stacked
    HBM stores instead of chasing values (JDF's data-placement info).
    """
    name: str
    access: FlowAccess
    ins: List[In] = field(default_factory=list)
    outs: List[Out] = field(default_factory=list)
    tile: Optional[Callable] = None


class PTGTaskClass(TaskClass):
    """Task class built from closed-form flow specs."""

    def __init__(self, tp: "Taskpool", name: str, tc_id: int,
                 params: Sequence[str], specs: List[FlowSpec],
                 space: Callable, affinity: Optional[Callable],
                 priority: Optional[Callable]):
        flows = [Flow(s.name, s.access) for s in specs]
        for s in specs:
            for d in s.ins:
                if d.gather and not (s.access & FlowAccess.CTL):
                    raise ValueError(
                        f"{name}.{s.name}: gather ins are CTL-only (data "
                        f"fan-in needs one flow per producer)")
                if d.gather and d.src is None:
                    raise ValueError(
                        f"{name}.{s.name}: gather requires a src "
                        f"producer list")
        # gather fan-in needs counting, not one-bit-per-flow masking
        mode = DEPS_COUNTER if any(d.gather for s in specs
                                   for d in s.ins) else DEPS_MASK
        super().__init__(name, tc_id, params, flows, deps_mode=mode)
        self.tp = tp
        self.specs = {s.name: s for s in specs}
        self.spec_list = specs
        self.space = space
        self.affinity = affinity
        if priority is not None:
            self.priority_fn = lambda locals: priority(tp.g, *locals)
        self.iterate_successors = self._iterate_successors
        self.deps_goal = self._deps_goal
        self.data_lookup = self._data_lookup
        # deps_goal runs once per ARRIVING activation (activate_dep), so
        # gather classes would re-enumerate their N-element target list
        # N times without this (the reference computes goals once per
        # task instance); the closed form is pure, so cache per locals
        self._goal_cache: Dict[Tuple[int, ...], int] = {}

    # -- body decorators --------------------------------------------------
    def body(self, fn: Callable = None, device: DeviceType = DeviceType.ALL,
             evaluate: Optional[Callable] = None, batchable: bool = True,
             batch_hook: Optional[Callable] = None,
             batch_hook_shared=None):
        """Attach an incarnation (JDF ``BODY [type=...] ... END``).
        ``batch_hook``/``batch_hook_shared``: optional hand-batched form
        for the compiled executor (see core.task.Chore)."""
        def deco(f):
            self.add_chore(Chore(device, f, evaluate=evaluate,
                                 batchable=batchable,
                                 batch_hook=batch_hook,
                                 batch_hook_shared=batch_hook_shared))
            return f
        return deco(fn) if fn is not None else deco

    def body_cpu(self, fn=None, **kw):
        return self.body(fn, device=DeviceType.CPU, **kw)

    def body_tpu(self, fn=None, **kw):
        return self.body(fn, device=DeviceType.TPU, **kw)

    # -- closed-form vtable ----------------------------------------------
    def _active_in(self, g, spec: FlowSpec, params) -> Optional[In]:
        active = [d for d in spec.ins if d.active(g, params)]
        if len(active) > 1:
            raise RuntimeError(
                f"{self.name}{tuple(params)}: flow {spec.name} has "
                f"{len(active)} active input deps (guards must be disjoint)")
        return active[0] if active else None

    @staticmethod
    def _coord_set(targets) -> set:
        """Normalize a gather target list to a set of coordinate tuples
        (accepts generators; duplicates collapse — each producer sends
        exactly one activation, so a duplicated coordinate must not
        inflate the goal into an unreachable count). A bare tuple means
        ONE coordinate, matching the Out-dst convention."""
        if isinstance(targets, tuple):
            targets = [targets]
        return {tuple(x) if isinstance(x, (tuple, list)) else (x,)
                for x in targets}

    def _deps_goal(self, locals) -> int:
        """Mask of flow bits (mask mode) or count (counter mode, used by
        CTL-gather classes) of *task*-fed deps; collection reads and NEW
        are resolved locally at prepare_input, not counted."""
        g = self.tp.g
        if self.deps_mode == DEPS_COUNTER:
            key = tuple(locals)
            cached = self._goal_cache.get(key)
            if cached is not None:
                return cached
            count = 0
            for f in self.flows:
                dep = self._active_in(g, self.specs[f.name], locals)
                if dep is None or dep.src is None:
                    continue
                if dep.gather:
                    count += len(self._coord_set(dep.src[1](g, *locals)))
                else:
                    count += 1
            self._goal_cache[key] = count
            return count
        mask = 0
        for f in self.flows:
            dep = self._active_in(g, self.specs[f.name], locals)
            if dep is not None and dep.src is not None:
                mask |= 1 << f.index
        return mask

    def _data_lookup(self, task: Task) -> None:
        """Resolve collection-sourced and NEW inputs (generated
        data_lookup / jdf_generate_code_data_lookup analog)."""
        g = self.tp.g
        for f in self.flows:
            if f.name in task.data:
                continue
            dep = self._active_in(g, self.specs[f.name], task.locals)
            if dep is None:
                continue
            if dep.data is not None:
                dc, key = dep.data(g, *task.locals)
                value = dc.data_of(key)
                ctx = self.tp.context
                if ctx is not None:
                    san = ctx.dfsan
                    if san is not None:
                        # race-checked: a collection read unordered with
                        # a terminal writer of the same tile observes a
                        # schedule-dependent version (analysis/dfsan.py)
                        san.observe_read(task, dc, key)
                    # stage-through: the collection keeps the device
                    # copy so one H2D serves every reader (Context.
                    # stage_read; no-op without an accelerator)
                    value = ctx.stage_read(dc, key, value)
            elif dep.new is not None:
                value = dep.new(g, *task.locals)
            else:
                continue
            if dep.reshape is not None:
                value = dep.reshape.apply(value)
            task.data[f.name] = value

    def _reshape_in(self, flow_name: str) -> bool:
        """Does any In of this class's ``flow_name`` declare a reshape?
        (cached — keeps the no-reshape hot path free of guard evals)"""
        cache = self.__dict__.setdefault("_reshape_in_cache", {})
        hit = cache.get(flow_name)
        if hit is None:
            hit = any(d.reshape is not None
                      for d in self.specs[flow_name].ins)
            cache[flow_name] = hit
        return hit

    def _iterate_successors(self, task: Task):
        """Producer-side expansion (generated iterate_successors analog,
        jdf2c.c; consumed by parsec_release_dep_fct parsec.c:1783)."""
        g = self.tp.g
        for f in self.flows:
            spec = self.specs[f.name]
            value = None
            if not f.is_ctl:
                value = task.output.get(f.name, task.data.get(f.name))
            promise = None   # one shared DataCopyFuture per produced flow
            for dep in spec.outs:
                if not dep.active(g, task.locals):
                    continue
                if dep.data is not None:
                    dc, key = dep.data(g, *task.locals)
                    v = value if dep.reshape is None \
                        else dep.reshape.apply(value)
                    yield DataRef(collection=dc, key=key, value=v)
                    continue
                cls_name, params_fn, dst_flow = dep.dst
                dst_tc = self.tp.task_class_by_name(cls_name)
                targets = params_fn(g, *task.locals)
                if isinstance(targets, tuple):
                    targets = [targets]
                dst_bit_flow = dst_tc.flow_by_name[dst_flow]
                consumer_reshapes = dst_tc._reshape_in(dst_flow)
                for tgt in targets:
                    tgt = tuple(tgt) if isinstance(tgt, (tuple, list)) else (tgt,)
                    composed = None
                    if dep.reshape is not None or consumer_reshapes:
                        dst_in = dst_tc._active_in(
                            g, dst_tc.specs[dst_flow], tgt)
                        composed = compose_specs(
                            dep.reshape,
                            dst_in.reshape if dst_in is not None else None)
                    v = None if dst_bit_flow.is_ctl else value
                    if composed is not None and v is not None:
                        if promise is None:
                            promise = DataCopyFuture(value)
                        v = promise
                    yield SuccessorRef(
                        task_class=dst_tc, locals=tgt, flow_name=dst_flow,
                        value=v, reshape_spec=composed,
                        dep_index=dst_bit_flow.index,
                        priority=dst_tc.priority_fn(tgt),
                        src_flow=f.name)

    # -- distribution -----------------------------------------------------
    def affinity_rank(self, locals) -> int:
        if self.affinity is None:
            return 0
        dc, key = self.affinity(self.tp.g, *locals)
        return dc.rank_of(key)

    def enumerate_space(self) -> Iterable[Tuple[int, ...]]:
        for p in self.space(self.tp.g):
            yield tuple(p) if isinstance(p, (tuple, list)) else (p,)

    def nb_local_tasks(self, my_rank: int = 0, nb_ranks: int = 1) -> int:
        """Closed-form local-task count (generated nb_local_tasks analog)."""
        n = 0
        for p in self.enumerate_space():
            if nb_ranks == 1 or self.affinity_rank(p) == my_rank:
                n += 1
        return n


class Taskpool(CoreTaskpool):
    """PTG taskpool: globals namespace + task classes
    (the ``__parsec_<name>_internal_taskpool_t`` analog)."""

    def __init__(self, name: str = "ptg", **globals_kw):
        super().__init__(name=name)
        self.g = types.SimpleNamespace(**globals_kw)
        self.startup_hook = self._startup

    def task_class_by_name(self, name: str) -> PTGTaskClass:
        return self._tc_by_name[name]

    def task_class(self, name: str, params: Sequence[str],
                   space: Callable, flows: List[FlowSpec],
                   affinity: Optional[Callable] = None,
                   priority: Optional[Callable] = None) -> PTGTaskClass:
        tc = PTGTaskClass(self, name, len(self.task_classes), params,
                          flows, space, affinity, priority)
        self.add_task_class(tc)
        return tc

    # -- startup (jdf_generate_startup_tasks analog) ----------------------
    def _startup(self, tp) -> List[Task]:
        ctx = self.context
        my_rank = ctx.my_rank if ctx is not None else 0
        nb_ranks = ctx.nb_ranks if ctx is not None else 1
        total = 0
        ready: List[Task] = []
        for tc in self.task_classes:
            for p in tc.enumerate_space():
                if nb_ranks > 1 and tc.affinity_rank(p) != my_rank:
                    continue
                total += 1
                if tc.deps_goal(p) == 0:
                    t = Task(self, tc, p, priority=tc.priority_fn(p))
                    ready.append(t)
        self.set_nb_tasks(total)
        return ready


def taskpool_uses_reshape(tp: Taskpool) -> bool:
    """True if any dep of any task class declares a reshape spec. The
    compiled (wavefront/SPMD) and native executors move raw tile values
    and must refuse such taskpools instead of silently skipping the
    conversions (the host runtime resolves them in complete_task)."""
    for tc in tp.task_classes:
        for spec in tc.spec_list:
            if any(d.reshape is not None for d in spec.ins) or \
                    any(d.reshape is not None for d in spec.outs):
                return True
    return False


def check_taskpool(tp: Taskpool, nb_ranks: int = 1) -> None:
    """Cross-validate producer (outs) and consumer (ins) dep declarations
    by enumerating the whole space — the iterators_checker PINS module
    equivalent (mca/pins/iterators_checker), used by tests.

    Verifies: every SuccessorRef lands on an existing task instance and a
    flow whose active In names the producer back; every task's goal mask is
    covered by exactly the refs aimed at it.
    """
    g = tp.g
    exists: Dict[str, set] = {tc.name: set(tc.enumerate_space())
                              for tc in tp.task_classes}
    incoming: Dict[Tuple[str, Tuple], int] = {}
    # counter-mode consumers additionally track WHICH producer fed them
    # how many times — a duplicate edge compensated by a missing one
    # passes a bare count but breaks the gather barrier at runtime
    incoming_pairs: Dict[Tuple[str, Tuple], Dict[Tuple, int]] = {}
    for tc in tp.task_classes:
        for p in tc.enumerate_space():
            task = Task(tp, tc, p)
            for f in tc.flows:
                task.data[f.name] = 0
                task.output[f.name] = 0
            for ref in tc.iterate_successors(task):
                if isinstance(ref, DataRef):
                    continue
                if ref.locals not in exists[ref.task_class.name]:
                    raise AssertionError(
                        f"{tc.name}{p} -> {ref.task_class.name}{ref.locals}: "
                        f"target task does not exist")
                spec = ref.task_class.specs[ref.flow_name]
                dep = ref.task_class._active_in(g, spec, ref.locals)
                if dep is None or dep.src is None:
                    raise AssertionError(
                        f"{tc.name}{p} -> {ref.task_class.name}{ref.locals}."
                        f"{ref.flow_name}: consumer declares no task input")
                src_cls, src_params_fn, src_flow = dep.src
                sp = src_params_fn(g, *ref.locals)
                if dep.gather:
                    members = PTGTaskClass._coord_set(sp)
                    if src_cls != tc.name or tuple(p) not in members:
                        raise AssertionError(
                            f"{ref.task_class.name}{ref.locals}."
                            f"{ref.flow_name}: gather over {src_cls} does "
                            f"not name {tc.name}{p}")
                else:
                    sp = tuple(sp) if isinstance(sp, (tuple, list)) else (sp,)
                    if src_cls != tc.name or tuple(sp) != tuple(p):
                        raise AssertionError(
                            f"{ref.task_class.name}{ref.locals}."
                            f"{ref.flow_name} expects {src_cls}{sp}, "
                            f"got {tc.name}{p}")
                k = (ref.task_class.name, ref.locals)
                if ref.task_class.deps_mode == DEPS_COUNTER:
                    incoming[k] = incoming.get(k, 0) + 1
                    pk = (tc.name, tuple(p), ref.flow_name)
                    pairs = incoming_pairs.setdefault(k, {})
                    pairs[pk] = pairs.get(pk, 0) + 1
                else:
                    incoming[k] = incoming.get(k, 0) | (1 << ref.dep_index)
    for tc in tp.task_classes:
        for p in tc.enumerate_space():
            goal = tc.deps_goal(p)
            got = incoming.get((tc.name, p), 0)
            if got != goal:
                kind = "count" if tc.deps_mode == DEPS_COUNTER else "mask"
                raise AssertionError(
                    f"{tc.name}{p}: goal {kind} {goal} but incoming deps "
                    f"{got}")
            if tc.deps_mode != DEPS_COUNTER:
                continue
            # every expected producer must feed EXACTLY once
            expected: Dict[Tuple, int] = {}
            for f in tc.flows:
                dep = tc._active_in(g, tc.specs[f.name], p)
                if dep is None or dep.src is None:
                    continue
                src_cls, src_params_fn, _sf = dep.src
                if dep.gather:
                    for coord in PTGTaskClass._coord_set(
                            src_params_fn(g, *p)):
                        expected[(src_cls, coord, f.name)] = 1
                else:
                    sp = src_params_fn(g, *p)
                    sp = tuple(sp) if isinstance(sp, (tuple, list)) else (sp,)
                    key = (src_cls, sp, f.name)
                    expected[key] = expected.get(key, 0) + 1
            got_pairs = incoming_pairs.get((tc.name, p), {})
            if got_pairs != expected:
                raise AssertionError(
                    f"{tc.name}{p}: producer multiplicity mismatch — "
                    f"expected {expected}, got {got_pairs}")
