"""Termination-detection framework (reference parsec/mca/termdet/).

A termdet *monitor* is wired into every taskpool (parsec_internal.h:145) and
drives the state machine NOT_READY → BUSY → IDLE → TERMINATED
(termdet.h:27-120). Modules:

- ``local``: counts local tasks + pending runtime actions; terminated when
  both hit zero (termdet/local, 369 LoC).
- ``fourcounter``: distributed four-counter wave algorithm for DAGs whose
  task count cannot be precomputed (termdet/fourcounter, 887 LoC).
- ``user_trigger``: the user explicitly signals termination.

Selection is MCA-style by name (param ``termdet``).
"""

from .base import TermdetMonitor, TermdetState
from .local import LocalTermdet
from .fourcounter import FourCounterTermdet
from .user_trigger import UserTriggerTermdet
from ..utils import mca_param

_MODULES = {
    "local": LocalTermdet,
    "fourcounter": FourCounterTermdet,
    "user_trigger": UserTriggerTermdet,
}

mca_param.register("termdet", "local",
                   help="termination detection module (local, fourcounter, user_trigger)")


def new_monitor(name=None, **kwargs) -> TermdetMonitor:
    name = name or mca_param.get("termdet", "local")
    try:
        cls = _MODULES[name]
    except KeyError:
        raise ValueError(f"unknown termdet module {name!r}; have {sorted(_MODULES)}")
    return cls(**kwargs)


def register_module(name: str, cls) -> None:
    _MODULES[name] = cls
