"""User-triggered termination (reference parsec/mca/termdet/user_trigger).

The taskpool terminates only when the user calls :meth:`trigger`, regardless
of the task counters — for open-ended DAGs where the runtime cannot know the
end (reference: own AM tag at parsec_comm_engine.h:36 propagates the trigger
to all ranks; here the comm engine's control broadcast does the same).
"""

from .base import TermdetMonitor, TermdetState


class UserTriggerTermdet(TermdetMonitor):
    def __init__(self, comm=None) -> None:
        super().__init__(comm=comm)
        self._triggered = False

    def _idle_to_terminated_locked(self) -> bool:
        if self._triggered:
            self._state = TermdetState.TERMINATED
            return True
        return False    # stay IDLE until the user triggers

    def trigger(self, propagate: bool = True) -> None:
        fire = False
        with self._lock:
            self._triggered = True
            if self._state in (TermdetState.IDLE, TermdetState.BUSY) \
                    and self._nb_tasks == 0 and self._runtime_actions == 0:
                self._state = TermdetState.TERMINATED
                fire = True
        if propagate and self.comm is not None and self.comm.nb_ranks > 1:
            self.comm.broadcast_user_trigger(self)
        if fire:
            self._fire()
