"""Four-counter distributed termination detection
(reference parsec/mca/termdet/fourcounter, 887 LoC).

The reference runs a wave algorithm over its own AM tag
(PARSEC_TERMDET_FOURCOUNTER_MSG_TAG, parsec_comm_engine.h:35) tracking four
counters: messages sent/received and tasks created/completed. A taskpool
terminates when a wave observes every rank idle and sent == received
globally.

Here the wave rides the comm engine's control channel. Waves are requested
when a rank's monitor goes IDLE and launched from the post-transition hook
(outside the monitor lock — the loopback engine delivers results
synchronously). A failed wave is not retried in a spin: the next counter
transition on any rank (e.g. the last in-flight message delivering)
triggers a fresh wave, and the engine delivers a successful wave's result
to every rank's monitor. Single-process contexts degenerate to the local
policy (rank count 1).
"""

from __future__ import annotations

import threading
from .base import TermdetMonitor, TermdetState


class FourCounterTermdet(TermdetMonitor):
    def __init__(self, comm=None) -> None:
        super().__init__(comm=comm)
        self._sent = 0
        self._received = 0
        self._wave_lock = threading.Lock()
        self._wave_requested = False

    # -- comm hooks -------------------------------------------------------
    def outgoing_message_start(self, dst_rank: int, nbytes: int = 0) -> None:
        with self._wave_lock:
            self._sent += 1
        # a message in flight is a pending runtime action: the taskpool may
        # not appear idle while data it produced is still undelivered
        self.addto_runtime_actions(1)

    def outgoing_message_end(self, dst_rank: int) -> None:
        self.addto_runtime_actions(-1)

    def incoming_message_start(self, src_rank: int, nbytes: int = 0) -> None:
        with self._wave_lock:
            self._received += 1
        self.addto_runtime_actions(1)

    def incoming_message_end(self, src_rank: int) -> None:
        self.addto_runtime_actions(-1)

    # -- wave -------------------------------------------------------------
    def _idle_to_terminated_locked(self) -> bool:
        nranks = self.comm.nb_ranks if self.comm is not None else 1
        if nranks <= 1:
            self._state = TermdetState.TERMINATED
            return True
        # request a wave; launched by _post_transition outside the lock
        self._wave_requested = True
        return False

    def _post_transition(self) -> None:
        with self._wave_lock:
            req, self._wave_requested = self._wave_requested, False
        if req and self.comm is not None:
            self.comm.start_termdet_wave(self)

    def local_wave_contribution(self):
        # _state read without the monitor lock: a stale BUSY only fails the
        # wave (retried on the next transition), never falsely terminates
        idle = self._state in (TermdetState.IDLE, TermdetState.TERMINATED)
        with self._wave_lock:
            return (self._sent, self._received, idle)

    def wave_result(self, total_sent: int, total_received: int,
                    all_idle: bool) -> None:
        fire = False
        with self._lock:
            if all_idle and total_sent == total_received \
                    and self._state == TermdetState.IDLE:
                self._state = TermdetState.TERMINATED
                fire = True
        if fire:
            self._fire()
