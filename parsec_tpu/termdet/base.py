"""Termdet monitor interface (reference termdet.h:27-120)."""

from __future__ import annotations

import enum
import threading
from typing import Callable, Optional


class TermdetState(enum.IntEnum):
    NOT_READY = 0    # taskpool still being constructed; cannot terminate
    BUSY = 1         # tasks or runtime actions outstanding
    IDLE = 2         # locally quiet; distributed modules may still wait
    TERMINATED = 3


class TermdetMonitor:
    """Base monitor: counts tasks and pending runtime actions.

    ``nb_tasks`` mirrors taskpool->nb_tasks, ``runtime_actions`` mirrors
    taskpool->nb_pending_actions (parsec_internal.h:123-143). The taskpool
    is NOT_READY until ``ready()`` (reference: the DSL calls set_nb_tasks /
    starts enqueue), then BUSY until both counters reach zero.
    """

    def __init__(self, comm=None) -> None:
        self.comm = comm            # comm engine (None = single rank)
        self._lock = threading.Lock()
        self._nb_tasks = 0
        self._runtime_actions = 0
        self._state = TermdetState.NOT_READY
        self._on_terminated: Optional[Callable[[], None]] = None
        # False until set_nb_tasks()/ready() closes the startup window:
        # Context.add_taskpool publishes the pool to the comm engine
        # BEFORE the DSL counts local tasks, so a parked remote
        # activation delivered at registration can execute and COMPLETE
        # a task ahead of set_nb_tasks — that decrement must carry as a
        # deficit, not raise
        self._counted = False

    # -- wiring -----------------------------------------------------------
    def monitor(self, on_terminated: Callable[[], None]) -> None:
        self._on_terminated = on_terminated

    # -- counters ---------------------------------------------------------
    @property
    def nb_tasks(self) -> int:
        return self._nb_tasks

    @property
    def state(self) -> TermdetState:
        return self._state

    def set_nb_tasks(self, n: int) -> None:
        with self._lock:
            # fold in completions that raced the startup enumeration
            # (see _counted): n counts ALL local tasks, including any
            # already completed, so the carried deficit subtracts
            deficit = self._nb_tasks if self._nb_tasks < 0 else 0
            self._nb_tasks = n + deficit
            self._counted = True
            self._rearm_locked()
            if self._nb_tasks < 0:
                raise RuntimeError("nb_tasks went negative")
            fire = self._maybe_idle_locked()
        if fire:
            self._fire()
        self._post_transition()

    def addto_nb_tasks(self, d: int) -> None:
        with self._lock:
            self._nb_tasks += d
            self._rearm_locked()
            if self._nb_tasks < 0 and self._counted:
                raise RuntimeError("nb_tasks went negative")
            fire = self._maybe_idle_locked()
        if fire:
            self._fire()
        self._post_transition()

    def addto_runtime_actions(self, d: int) -> None:
        with self._lock:
            self._runtime_actions += d
            self._rearm_locked()
            if self._runtime_actions < 0:
                raise RuntimeError("runtime_actions went negative")
            fire = self._maybe_idle_locked()
        if fire:
            self._fire()
        self._post_transition()

    def _rearm_locked(self) -> None:
        """NOT_READY→BUSY on first counter activity, and IDLE→BUSY when new
        work appears after a quiet period (reference termdet.h state
        machine: IDLE is not final for modules that wait on remote
        confirmation — a late local task or message must re-arm the
        monitor or termination is missed forever)."""
        if self._state == TermdetState.NOT_READY:
            self._state = TermdetState.BUSY
        elif self._state == TermdetState.IDLE and \
                (self._nb_tasks > 0 or self._runtime_actions > 0):
            self._state = TermdetState.BUSY

    def ready(self) -> None:
        """Transition NOT_READY → BUSY (taskpool fully constructed)."""
        with self._lock:
            self._counted = True     # startup window closed either way
            if self._state == TermdetState.NOT_READY:
                self._state = TermdetState.BUSY
            fire = self._maybe_idle_locked()
        if fire:
            self._fire()
        self._post_transition()

    def _post_transition(self) -> None:
        """Hook invoked after every counter mutation, OUTSIDE the monitor
        lock — distributed modules launch their waves here (launching from
        inside the lock would deadlock when the comm engine delivers the
        wave result synchronously, e.g. the loopback engine)."""

    # -- module-specific idle → terminated policy -------------------------
    def _maybe_idle_locked(self) -> bool:
        """Called with lock held when counters change; returns True when the
        TERMINATED transition fired (callback invoked by caller outside the
        lock)."""
        if (self._state == TermdetState.BUSY
                and self._nb_tasks == 0 and self._runtime_actions == 0):
            self._state = TermdetState.IDLE
            return self._idle_to_terminated_locked()
        return False

    def _idle_to_terminated_locked(self) -> bool:
        """Default (local) policy: IDLE is final → TERMINATED immediately."""
        self._state = TermdetState.TERMINATED
        return True

    def _fire(self) -> None:
        if self._on_terminated is not None:
            self._on_terminated()

    # -- comm hooks (reference: message start/end, remote_dep.c:578) ------
    def outgoing_message_start(self, dst_rank: int, nbytes: int = 0) -> None:
        pass

    def outgoing_message_end(self, dst_rank: int) -> None:
        pass

    def incoming_message_start(self, src_rank: int, nbytes: int = 0) -> None:
        pass

    def incoming_message_end(self, src_rank: int) -> None:
        pass
