"""Local termination detection (reference parsec/mca/termdet/local).

Counts local tasks and pending runtime actions; the taskpool is terminated
when both reach zero. This is the default monitor installed by
``context.add_taskpool`` when the DSL did not choose one
(scheduling.c:692-697).
"""

from .base import TermdetMonitor


class LocalTermdet(TermdetMonitor):
    pass
