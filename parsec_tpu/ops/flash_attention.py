"""Flash attention as a hand-written pallas TPU kernel.

The framework's hot-op escape hatch: XLA fuses most elementwise work
into matmuls, but attention's online-softmax recurrence leaves HBM
round-trips between the s = QKᵀ, softmax, and PV stages that XLA does
not eliminate at long sequence lengths. This kernel keeps the whole
per-(head, q-block) recurrence in VMEM scratch across the KV grid
dimension — the standard flash-attention tiling (Dao et al. 2022)
expressed in pallas (see /opt/skills/guides/pallas_guide.md; reference
runtime analog: user .jdf BODY CUDA kernels — the runtime schedules
them, the kernel owns the device).

Public entry: :func:`flash_attention` over ``(S, H, dh)`` operands (the
layout `compiled.ring_attention` uses). Falls back to pallas interpret
mode off-TPU so the same code path is exercised by CPU tests.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import mca_param

mca_param.register("ops.flash_attention_block_q", 1024,
                   help="flash-attention query block size")
mca_param.register("ops.flash_attention_block_k", 1024,
                   help="flash-attention key/value block size")
# block-size note (v5e, S=16384, H=8, dh=64): 1024/1024 measured 3.2 ms
# vs 9.9 ms at 512/512 and 11.1 ms at 1024/512 — the (bq, bk) score
# tile must be large enough to amortize the dh-narrow QK^T contraction;
# 2048-query blocks fail to compile (VMEM) and 2048-key blocks regress.

_NEG = -1e30          # finite -inf: exp() stays NaN-free for fully
#                       masked rows (same convention as ring_attention)
_MINLANE = 128        # f32 lane tile: scalar-per-row state is stored
#                       broadcast to a full lane tile


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
               l_ref, *, scale: float, causal: bool, bq: int, bk: int,
               prec=None):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)
    # causal: KV blocks entirely in the future contribute nothing —
    # skip their compute outright (halves the causal work)
    live = (qi + 1) * bq > ki * bk if causal else ki >= 0

    @pl.when(live)
    def _fold():
        q = q_ref[0]                 # (bq, dh)
        k = k_ref[0]                 # (bk, dh)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=prec) * scale

        if causal:
            qpos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            kpos = ki * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            # fully-masked rows: keep p exactly zero (m_new == _NEG)
            p = jnp.where(s > _NEG / 2, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc_ref[...] = acc_ref[...] * corr[:, None] + jax.lax.dot_general(
            p, v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, None]).astype(o_ref.dtype)
        # log-sum-exp per query row: the merge key for combining partial
        # attention states (ring attention folds visiting KV blocks by
        # merging (o, lse) pairs). Stored broadcast across the lane tile
        # — TPU lowering requires lane-aligned output blocks.
        lse_ref[0] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(l))[:, None], lse_ref.shape[1:])


# pallas imports deferred so the module imports on builds without pallas
try:  # pragma: no cover - exercised implicitly by every call
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PALLAS = True
except Exception:  # noqa: BLE001
    _HAVE_PALLAS = False


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: int = 0, block_k: int = 0,
                    interpret: Optional[bool] = None,
                    return_lse: bool = False):
    """Softmax attention over ``(S, H, dh)`` operands via the pallas
    flash kernel. ``interpret=None`` auto-selects interpret mode off-TPU
    (so CPU tests run the identical kernel). ``return_lse=True`` also
    returns the per-row log-sum-exp ``(S, H)`` — the merge key for
    combining partial attention states (ring attention)."""
    if not _HAVE_PALLAS:
        raise RuntimeError("pallas unavailable in this jax build")
    S, H, dh = q.shape
    Sk = k.shape[0]
    scale = scale if scale is not None else 1.0 / math.sqrt(dh)
    bq = block_q or int(mca_param.get("ops.flash_attention_block_q", 1024))
    bk = block_k or int(mca_param.get("ops.flash_attention_block_k", 1024))
    bq = min(bq, S)
    bk = min(bk, Sk)
    if not block_q:          # default blocks adapt to the sequence; an
        while S % bq:        # explicit block size is a strict contract
            bq //= 2
    if not block_k:
        while Sk % bk:
            bk //= 2
    if S % bq or Sk % bk:
        raise ValueError(f"sequence lengths ({S}, {Sk}) must divide the "
                         f"block sizes ({bq}, {bk})")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # (S, H, dh) → (H, S, dh); pad head dim to the f32 lane tile
    qT = jnp.swapaxes(q, 0, 1).astype(jnp.float32)
    kT = jnp.swapaxes(k, 0, 1).astype(jnp.float32)
    vT = jnp.swapaxes(v, 0, 1).astype(jnp.float32)
    dh_p = max(_MINLANE, ((dh + _MINLANE - 1) // _MINLANE) * _MINLANE)
    if dh_p != dh:
        pad = [(0, 0), (0, 0), (0, dh_p - dh)]
        qT, kT, vT = (jnp.pad(x, pad) for x in (qT, kT, vT))

    # honor the global MXU precision knob like every other tile kernel
    # (ops.matmul_precision): "highest" runs the kernel's dots in full
    # f32 — the TPU test mode and precision-variant benches rely on it.
    # Mosaic's dot lowering supports only DEFAULT and HIGHEST; "high"
    # (3-pass, fine for jnp kernels) maps to HIGHEST here rather than
    # failing to compile.
    from .tile_kernels import matmul_precision
    prec = matmul_precision()
    if prec == "high":
        prec = "highest"
    kern = functools.partial(_fa_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk, prec=prec)
    out, lse = pl.pallas_call(
        kern,
        grid=(H, S // bq, Sk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, dh_p), lambda h, qi, ki: (h, qi, 0)),
            pl.BlockSpec((1, bk, dh_p), lambda h, qi, ki: (h, ki, 0)),
            pl.BlockSpec((1, bk, dh_p), lambda h, qi, ki: (h, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, dh_p), lambda h, qi, ki: (h, qi, 0)),
            pl.BlockSpec((1, bq, _MINLANE), lambda h, qi, ki: (h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, S, dh_p), q.dtype),
            jax.ShapeDtypeStruct((H, S, _MINLANE), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, dh_p), jnp.float32),
            pltpu.VMEM((bq, _MINLANE), jnp.float32),
            pltpu.VMEM((bq, _MINLANE), jnp.float32),
        ],
        interpret=interpret,
    )(qT, kT, vT)
    o = jnp.swapaxes(out[:, :, :dh], 0, 1)
    if return_lse:
        return o, jnp.swapaxes(lse[:, :, 0], 0, 1)
    return o


def merge_attention_states(o1, lse1, o2, lse2):
    """Combine two partial softmax-attention results over disjoint key
    sets: ``o_i`` (..., dh) normalized partial outputs, ``lse_i`` (...)
    their log-sum-exps. Returns the merged ``(o, lse)`` — the standard
    flash/ring state-merge identity."""
    M = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - M)
    w2 = jnp.exp(lse2 - M)
    den = w1 + w2
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / den[..., None]
    return o, M + jnp.log(den)
