"""Tile-level kernels (jnp; MXU-friendly shapes).

These are the FLOP-carrying bodies of the shipped linear-algebra
taskpools — the role CUDA kernels in user .jdf BODY sections play in the
reference (e.g. DPLASMA's dpotrf/dgemm tiles). All operate on full
(mb × nb) tiles; ``preferred_element_type=float32`` keeps MXU accumulation
in f32 even for bf16 tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import mca_param

# On TPU, f32 matmuls default to bf16 MXU passes (~1e-2 relative error).
# "highest" runs the 6-pass f32 emulation — DPLASMA-grade accuracy at a
# throughput cost; "default" is the TPU-native speed setting.
mca_param.register("ops.matmul_precision", "default",
                   help="MXU precision for tile matmuls: default|high|highest")


def matmul_precision():
    """The configured MXU precision for tile matmuls (None = TPU-native
    bf16 passes; 'highest' = 6-pass f32 emulation). Public so non-LA
    bodies (attention, FFN, ring attention) honor the same knob."""
    p = str(mca_param.get("ops.matmul_precision", "default"))
    return None if p == "default" else p


_prec = matmul_precision


def gemm_tile(C, A, B, alpha=1.0, beta=1.0, ta=False, tb=False):
    """C ← α·op(A)·op(B) + β·C (tile GEMM)."""
    opA = A.T if ta else A
    opB = B.T if tb else B
    acc = jnp.matmul(opA, opB, preferred_element_type=jnp.float32,
                     precision=_prec())
    return (alpha * acc + beta * C).astype(C.dtype)


def syrk_tile(C, A, alpha=-1.0, beta=1.0):
    """C ← α·A·Aᵀ + β·C (symmetric rank-k update, lower)."""
    acc = jnp.matmul(A, A.T, preferred_element_type=jnp.float32,
                     precision=_prec())
    return (alpha * acc + beta * C).astype(C.dtype)


def trsm_tile(B, L):
    """B ← B·L⁻ᵀ — right-solve with the lower-triangular factor L of the
    panel tile (the dpotrf TRSM update: A[m,k] = A[m,k] L[k,k]^-T)."""
    x = jax.scipy.linalg.solve_triangular(
        L.astype(jnp.float32), B.astype(jnp.float32).T,
        lower=True, trans=0)
    return x.T.astype(B.dtype)


def trsm_tiles_wide(L, Bs):
    """Batched B_i ← B_i·L⁻ᵀ with a SHARED factor L, formulated as ONE
    wide-RHS triangular solve: L · Y = [B₁ᵀ | B₂ᵀ | …]. On TPU this is
    several times faster than vmapping per-tile solves (batched
    triangular-solve lowering is poor); used as the TRSM batch_hook in
    the compiled POTRF path."""
    nbatch, nb, _ = Bs.shape
    rhs = jnp.swapaxes(Bs, 1, 2).transpose(1, 0, 2).reshape(nb, nbatch * nb)
    Y = jax.scipy.linalg.solve_triangular(
        L.astype(jnp.float32), rhs.astype(jnp.float32), lower=True)
    return Y.reshape(nb, nbatch, nb).transpose(1, 2, 0).astype(Bs.dtype)


def potrf_tile(A):
    """A ← chol(A) lower (diagonal-tile Cholesky)."""
    return jnp.linalg.cholesky(A.astype(jnp.float32)).astype(A.dtype)


def add_tile(A, B):
    return A + B


def scale_tile(A, alpha):
    return alpha * A


# ---- tiled-QR kernels (DPLASMA dgeqrf tile operations) -----------------
# Functional variant: the reference's Householder kernels (GEQRT/TSQRT/
# UNMQR/TSMQR with compact V+T storage) are re-expressed with explicit
# per-tile orthogonal factors — Q values flow between tasks as tiles,
# which is what XLA can batch; compact-V storage is a memory optimization
# tied to in-place BLAS that functional dataflow doesn't need.

def geqrt_tile(A):
    """Diagonal-tile QR: A = Q·R → (Q, R)."""
    Q, R = jnp.linalg.qr(A.astype(jnp.float32), mode="complete")
    return Q.astype(A.dtype), R.astype(A.dtype)


def unmqr_tile(Q, C):
    """C ← Qᵀ·C (apply a diagonal-tile factor to a row-panel tile)."""
    out = jnp.matmul(Q.T, C, preferred_element_type=jnp.float32,
                     precision=_prec())
    return out.astype(C.dtype)


def tsqrt_tile(R, A):
    """Triangular-on-top-of-square QR: [R; A] = Q₂·R' → (Q₂, R').
    Q₂ is the full (2nb × 2nb) factor; R' the updated nb × nb triangle."""
    nb = R.shape[0]
    S = jnp.concatenate([R, A], axis=0).astype(jnp.float32)
    Q2, Rfull = jnp.linalg.qr(S, mode="complete")
    return Q2.astype(R.dtype), Rfull[:nb].astype(R.dtype)


def tsmqr_tile(Q2, C1, C2):
    """Apply a TSQRT factor to a stacked pair: [C1; C2] ← Q₂ᵀ·[C1; C2]."""
    nb = C1.shape[0]
    S = jnp.concatenate([C1, C2], axis=0)
    out = jnp.matmul(Q2.T, S, preferred_element_type=jnp.float32,
                     precision=_prec()).astype(C1.dtype)
    return out[:nb], out[nb:]
