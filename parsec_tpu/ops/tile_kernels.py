"""Tile-level kernels (jnp; MXU-friendly shapes).

These are the FLOP-carrying bodies of the shipped linear-algebra
taskpools — the role CUDA kernels in user .jdf BODY sections play in the
reference (e.g. DPLASMA's dpotrf/dgemm tiles). All operate on full
(mb × nb) tiles; ``preferred_element_type=float32`` keeps MXU accumulation
in f32 even for bf16 tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import compile_cache, mca_param

# On TPU, f32 matmuls default to bf16 MXU passes (~1e-2 relative error).
# "highest" runs the 6-pass f32 emulation — DPLASMA-grade accuracy at a
# throughput cost; "default" is the TPU-native speed setting.
mca_param.register("ops.matmul_precision", "default",
                   help="MXU precision for tile matmuls: default|high|highest")
# these knobs choose what gets TRACED into compiled tile kernels —
# every shared/persistent compile-cache key snapshots them
compile_cache.register_trace_knob("ops.matmul_precision")


def matmul_precision():
    """The configured MXU precision for tile matmuls (None = TPU-native
    bf16 passes; 'highest' = 6-pass f32 emulation). Public so non-LA
    bodies (attention, FFN, ring attention) honor the same knob."""
    p = str(mca_param.get("ops.matmul_precision", "default"))
    return None if p == "default" else p


_prec = matmul_precision


def gemm_tile(C, A, B, alpha=1.0, beta=1.0, ta=False, tb=False):
    """C ← α·op(A)·op(B) + β·C (tile GEMM)."""
    opA = A.T if ta else A
    opB = B.T if tb else B
    acc = jnp.matmul(opA, opB, preferred_element_type=jnp.float32,
                     precision=_prec())
    return (alpha * acc + beta * C).astype(C.dtype)


def syrk_tile(C, A, alpha=-1.0, beta=1.0):
    """C ← α·A·Aᵀ + β·C (symmetric rank-k update, lower)."""
    acc = jnp.matmul(A, A.T, preferred_element_type=jnp.float32,
                     precision=_prec())
    return (alpha * acc + beta * C).astype(C.dtype)


def trsm_tile(B, L):
    """B ← B·L⁻ᵀ — right-solve with the lower-triangular factor L of the
    panel tile (the dpotrf TRSM update: A[m,k] = A[m,k] L[k,k]^-T)."""
    x = jax.scipy.linalg.solve_triangular(
        L.astype(jnp.float32), B.astype(jnp.float32).T,
        lower=True, trans=0)
    return x.T.astype(B.dtype)


def trsm_tiles_wide(L, Bs):
    """Batched B_i ← B_i·L⁻ᵀ with a SHARED factor L, formulated as ONE
    wide-RHS triangular solve: L · Y = [B₁ᵀ | B₂ᵀ | …]. On TPU this is
    several times faster than vmapping per-tile solves (batched
    triangular-solve lowering is poor); used as the TRSM batch_hook in
    the compiled POTRF path."""
    nbatch, nb, _ = Bs.shape
    rhs = jnp.swapaxes(Bs, 1, 2).transpose(1, 0, 2).reshape(nb, nbatch * nb)
    Y = jax.scipy.linalg.solve_triangular(
        L.astype(jnp.float32), rhs.astype(jnp.float32), lower=True)
    return Y.reshape(nb, nbatch, nb).transpose(1, 2, 0).astype(Bs.dtype)


def potrf_tile(A):
    """A ← chol(A) lower (diagonal-tile Cholesky)."""
    return jnp.linalg.cholesky(A.astype(jnp.float32)).astype(A.dtype)


# ---- MXU-rich variants of the triangular kernels -----------------------
# XLA's triangular_solve and cholesky lower to blocked substitution whose
# throughput on TPU is a small fraction of matmul peak (measured ~20-50
# GF/s/chip at nb=2048 vs ~178 TF/s for batched GEMM). The compiled POTRF
# path therefore reformulates both around matmuls, the MAGMA/DPLASMA GPU
# trick (invert the diagonal block once, turn every solve into a GEMM);
# the reference gets the same effect by linking vendor BLAS into .jdf
# bodies (dplasma's dpotrf_L gpu chores).

mca_param.register("ops.tri_base", 256,
                   help="base block size for matmul-rich triangular "
                        "kernels (tri_inv_tile / potrf_tile_blocked)")
compile_cache.register_trace_knob("ops.tri_base")


def tri_inv_tile(L, base: int = 0):
    """L⁻¹ of a lower-triangular tile via recursive block inversion:
    [[L11, 0], [L21, L22]]⁻¹ = [[L11⁻¹, 0], [-L22⁻¹·L21·L11⁻¹, L22⁻¹]].
    All flops above the base case are matmuls."""
    base = base or int(mca_param.get("ops.tri_base", 256))
    Lf = L.astype(jnp.float32)

    def rec(T):
        n = T.shape[0]
        if n <= base or n % 2:
            return jax.lax.linalg.triangular_solve(
                T, jnp.eye(n, dtype=T.dtype), left_side=True, lower=True)
        h = n // 2
        i11 = rec(T[:h, :h])
        i22 = rec(T[h:, h:])
        i21 = -jnp.matmul(
            jnp.matmul(i22, T[h:, :h], preferred_element_type=jnp.float32,
                       precision=_prec()),
            i11, preferred_element_type=jnp.float32, precision=_prec())
        top = jnp.concatenate([i11, jnp.zeros((h, n - h), T.dtype)], axis=1)
        return jnp.concatenate([top, jnp.concatenate([i21, i22], axis=1)],
                               axis=0)

    return rec(Lf).astype(L.dtype)


def chol_inv_tile(A, base: int = 128):
    """(L, L⁻¹) of an SPD tile in ONE recursion. Sharing the traversal
    beats chol-then-invert two ways: the panel solve uses the already-
    computed I11 as a matmul (L21 = A21·I11ᵀ) instead of a wide
    triangular solve, and the inverse assembles from blocks the chol
    recursion already has (I21 = −I22·L21·I11). Measured 5.9 vs 7.3
    ms/step at nb=1024 on a v5e against separate potrf_tile_blocked +
    tri_inv_tile — but that delta is inter-dispatch overhead: INSIDE
    one fused XLA program the two forms run identically (105-107 TF/s
    flagship both ways) and the fused program deserializes slower from
    the persistent cache, so the panel fusers keep chol-then-invert.
    Kept (tested) as the standalone-dispatch form of the pair."""
    Af = jnp.asarray(A, jnp.float32)

    def rec(T):
        n = T.shape[0]
        if n <= base or n % 2:
            L = jnp.linalg.cholesky(T)
            return L, jax.lax.linalg.triangular_solve(
                L, jnp.eye(n, dtype=T.dtype), left_side=True, lower=True)
        h = n // 2
        L11, I11 = rec(T[:h, :h])
        L21 = jnp.matmul(T[h:, :h], I11.T,
                         preferred_element_type=jnp.float32,
                         precision=_prec())
        S = T[h:, h:] - jnp.matmul(L21, L21.T,
                                   preferred_element_type=jnp.float32,
                                   precision=_prec())
        L22, I22 = rec(0.5 * (S + S.T))
        I21 = -jnp.matmul(
            I22, jnp.matmul(L21, I11, preferred_element_type=jnp.float32,
                            precision=_prec()),
            preferred_element_type=jnp.float32, precision=_prec())
        Z = jnp.zeros((h, n - h), jnp.float32)
        L = jnp.concatenate(
            [jnp.concatenate([L11, Z], axis=1),
             jnp.concatenate([L21, L22], axis=1)], axis=0)
        Inv = jnp.concatenate(
            [jnp.concatenate([I11, Z], axis=1),
             jnp.concatenate([I21, I22], axis=1)], axis=0)
        return L, Inv

    L, Inv = rec(Af)
    return L.astype(A.dtype), Inv.astype(A.dtype)


def potrf_tile_blocked(A, base: int = 0):
    """Blocked right-looking in-tile Cholesky: factor a ``base``-sized
    diagonal block with the XLA cholesky, invert it (cheap at base size),
    and apply panel solve + trailing update as matmuls. Keeps the MXU
    busy where ``jnp.linalg.cholesky`` on the full tile would serialize."""
    base = base or int(mca_param.get("ops.tri_base", 256))
    n = A.shape[0]
    if n <= base:
        return potrf_tile(A)
    Af = jnp.asarray(A, jnp.float32)
    L = jnp.zeros_like(Af)
    for j in range(0, n, base):
        b = min(base, n - j)
        l11 = jnp.linalg.cholesky(Af[j:j + b, j:j + b])
        L = L.at[j:j + b, j:j + b].set(l11)
        if j + b < n:
            inv11 = jax.lax.linalg.triangular_solve(
                l11, jnp.eye(b, dtype=jnp.float32),
                left_side=True, lower=True)
            panel = jnp.matmul(Af[j + b:, j:j + b], inv11.T,
                               preferred_element_type=jnp.float32,
                               precision=_prec())
            L = L.at[j + b:, j:j + b].set(panel)
            Af = Af.at[j + b:, j + b:].add(
                -jnp.matmul(panel, panel.T,
                            preferred_element_type=jnp.float32,
                            precision=_prec()))
    return L.astype(A.dtype)


def trsm_tiles_gemm(L, Bs):
    """Batched B_i ← B_i·L⁻ᵀ with a SHARED factor L, as one inversion
    plus one wide matmul: Y = [B₁; B₂; …]·(L⁻¹)ᵀ. The inversion is
    amortized over the whole wave; the matmul runs at MXU speed where
    the wide triangular solve runs an order of magnitude slower."""
    nbatch, nb, _ = Bs.shape
    Linv = tri_inv_tile(L)
    wide = Bs.reshape(nbatch * nb, nb)
    Y = jnp.matmul(wide.astype(jnp.float32), Linv.T.astype(jnp.float32),
                   preferred_element_type=jnp.float32, precision=_prec())
    return Y.reshape(nbatch, nb, nb).astype(Bs.dtype)


def add_tile(A, B):
    return A + B


def scale_tile(A, alpha):
    return alpha * A


# ---- tiled-LU kernels (DPLASMA dgetrf_nopiv tile operations) -----------
# In-tile LU without pivoting: XLA has no unpivoted-LU primitive (and
# lax.linalg.lu's row permutation would have to flow through the whole
# block row), so the factorization is a Schur-complement recursion whose
# every flop above the tiny base case is a matmul or triangular solve —
# the same MXU-first reformulation as potrf_tile_blocked. Valid for the
# diagonally-dominant / well-conditioned regime tile LU targets (the
# no-pivot variant is the standard accelerator formulation; pivoted
# fallback = jax.lax.linalg.lu at user level).

def _lu_base(T):
    """Masked rank-1 eliminations as ONE fori_loop — a handful of traced
    ops regardless of the block size (an unrolled loop would put ~n ops
    per tile into the fused whole-DAG program). A rank-2 variant
    (second column's post-elimination state derived algebraically) was
    tried in round 5 and measured SLOWER in the full fused LU (53.9 vs
    56.9 TF/s at N=32768): the longer dependent-op body beat the saved
    loop iterations."""
    n = T.shape[0]
    idx = jnp.arange(n)

    def step(i, M):
        piv = M[i, i]
        col = jnp.where(idx > i, M[:, i] / piv, 0.0)   # multipliers
        row = jnp.where(idx > i, M[i, :], 0.0)         # U row, cols > i
        M = M - col[:, None] * row[None, :]
        return M.at[:, i].set(jnp.where(idx > i, col, M[:, i]))

    return jax.lax.fori_loop(0, n - 1, step, T)


def getrf_nopiv_tile(A, base: int = 64):
    """A ← packed LU (unit-lower L below the diagonal, U on/above)
    without pivoting, via blocked Schur recursion."""
    Af = jnp.asarray(A, jnp.float32)

    def rec(T):
        n = T.shape[0]
        if n <= base or n % 2:
            return _lu_base(T)
        h = n // 2
        A11 = rec(T[:h, :h])
        # A12 <- L11^-1 A12 (unit-lower), A21 <- A21 U11^-1
        A12 = jax.lax.linalg.triangular_solve(
            A11, T[:h, h:], left_side=True, lower=True,
            unit_diagonal=True)
        A21 = jax.lax.linalg.triangular_solve(
            A11, T[h:, :h], left_side=False, lower=False)
        S = T[h:, h:] - jnp.matmul(A21, A12,
                                   preferred_element_type=jnp.float32,
                                   precision=_prec())
        A22 = rec(S)
        top = jnp.concatenate([A11, A12], axis=1)
        return jnp.concatenate(
            [top, jnp.concatenate([A21, A22], axis=1)], axis=0)

    return rec(Af).astype(A.dtype)


def lu_inv_tile(A, base: int = 64):
    """``(packed LU, L⁻¹, U⁻¹)`` of a tile in ONE Schur recursion — the
    LU analog of :func:`chol_inv_tile` (the MAGMA diagonal-inversion
    trick applied to BOTH solve stages). With the child inverses in
    hand, the recursion's panel solves become matmuls
    (U12 = L11⁻¹·A12, L21 = A21·U11⁻¹ — plain dots against the
    already-computed inverses instead of triangular solves) and the
    inverses assemble from blocks the recursion already has
    (L⁻¹₂₁ = −L22⁻¹·L21·L11⁻¹, U⁻¹₁₂ = −U11⁻¹·U12·U22⁻¹), so every
    flop above the base case is a matmul. Consumed by the GETRF panel
    fuser under ``getrf.trsm_hook=gemm``: the step's two panel TRSMs
    run as MXU matmuls against the returned inverses, and the two
    standalone nb-sized ``tri_inv_tile`` recursions (each with its own
    internal triangular solves) disappear — their results fall out of
    the factorization recursion."""
    Af = jnp.asarray(A, jnp.float32)

    def mm(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32,
                          precision=_prec())

    def rec(T):
        n = T.shape[0]
        if n <= base or n % 2:
            LU = _lu_base(T)
            eye = jnp.eye(n, dtype=jnp.float32)
            L = jnp.tril(LU, -1) + eye
            Li = jax.lax.linalg.triangular_solve(
                L, eye, left_side=True, lower=True, unit_diagonal=True)
            Ui = jax.lax.linalg.triangular_solve(
                jnp.triu(LU), eye, left_side=True, lower=False)
            return LU, Li, Ui
        h = n // 2
        LU11, Li11, Ui11 = rec(T[:h, :h])
        U12 = mm(Li11, T[:h, h:])
        L21 = mm(T[h:, :h], Ui11)
        S = T[h:, h:] - mm(L21, U12)
        LU22, Li22, Ui22 = rec(S)
        Li21 = -mm(Li22, mm(L21, Li11))
        Ui12 = -mm(Ui11, mm(U12, Ui22))
        Ztop = jnp.zeros((h, n - h), jnp.float32)
        Zbot = jnp.zeros((n - h, h), jnp.float32)
        LU = jnp.concatenate(
            [jnp.concatenate([LU11, U12], axis=1),
             jnp.concatenate([L21, LU22], axis=1)], axis=0)
        Li = jnp.concatenate(
            [jnp.concatenate([Li11, Ztop], axis=1),
             jnp.concatenate([Li21, Li22], axis=1)], axis=0)
        Ui = jnp.concatenate(
            [jnp.concatenate([Ui11, Ui12], axis=1),
             jnp.concatenate([Zbot, Ui22], axis=1)], axis=0)
        return LU, Li, Ui

    LU, Li, Ui = rec(Af)
    return LU.astype(A.dtype), Li.astype(A.dtype), Ui.astype(A.dtype)


def lu_split(LU):
    """Unpack (L unit-lower, U upper) from a packed LU tile."""
    L = jnp.tril(LU, -1) + jnp.eye(LU.shape[0], dtype=LU.dtype)
    return L, jnp.triu(LU)


def trsm_lower_unit(LU, C):
    """C ← L⁻¹·C with L the unit-lower factor of a packed LU tile (the
    dgetrf row-panel update, left solve)."""
    return jax.lax.linalg.triangular_solve(
        jnp.asarray(LU, jnp.float32), jnp.asarray(C, jnp.float32),
        left_side=True, lower=True, unit_diagonal=True).astype(C.dtype)


def trsm_upper_right(LU, C):
    """C ← C·U⁻¹ with U the upper factor of a packed LU tile (the
    dgetrf column-panel update, right solve)."""
    return jax.lax.linalg.triangular_solve(
        jnp.asarray(LU, jnp.float32), jnp.asarray(C, jnp.float32),
        left_side=False, lower=False).astype(C.dtype)


# ---- tiled-QR kernels (DPLASMA dgeqrf tile operations) -----------------
# Functional variant: the reference's Householder kernels (GEQRT/TSQRT/
# UNMQR/TSMQR with compact V+T storage) are re-expressed with explicit
# per-tile orthogonal factors — Q values flow between tasks as tiles,
# which is what XLA can batch; compact-V storage is a memory optimization
# tied to in-place BLAS that functional dataflow doesn't need.

def geqrt_tile(A):
    """Diagonal-tile QR: A = Q·R → (Q, R)."""
    Q, R = jnp.linalg.qr(A.astype(jnp.float32), mode="complete")
    return Q.astype(A.dtype), R.astype(A.dtype)


# ---- panel QR (whole block-column at once, MXU-formulated) -------------
# The compiled GEQRF path factors an entire (mk x nb) panel per step.
# XLA's blocked-Householder QR serializes badly on TPU (measured ~20 ms
# at 16384x1024 where the CholeskyQR2 pipeline below takes ~5 ms), so the
# panel kernel is CholeskyQR2 — two Gram+Cholesky orthogonalization
# rounds, everything but the nb-sized factorizations a matmul — followed
# by an exact orthogonal-completion reconstruction:
#
#     given the reduced factor Q_r (mk x nb) with top block Q1, set
#         V = Q_r - E1,   X = I - Q1
#     then  H = I - V X^-T V^T  satisfies  H E1 = Q_r  (exact algebra:
#     V^T E1 = (Q1 - I)^T = -X^T) and
#           H^T H = I + V X^-1 (Q_r^T Q_r - I) X^-T V^T
#
# i.e. H is orthogonal exactly when Q_r is orthonormal — CholeskyQR2's
# job — and the trailing update H^T C = C - V X^-T (V^T C) is two large
# matmuls. This is the Householder-reconstruction idea of Ballard et al.
# / Yamamoto (public algorithm), reformulated around an explicit nb x nb
# inverse instead of an unpivoted LU (X's diagonal is >= 1 after the
# sign fix below, the same conditioning argument). Reference analog: the
# GEQRT+TSQRT panel chain of dplasma's dgeqrf
# (reference parsec/data_dist/matrix/ + BASELINE.md dgeqrf config).

mca_param.register("ops.panel_qr", "cholqr2",
                   help="panel QR kernel for the fused GEQRF path: "
                        "cholqr2 (all-matmul, needs full column rank) | "
                        "xla (jnp.linalg.qr, slower, more robust)")
compile_cache.register_trace_knob("ops.panel_qr")


def panel_qr_tile(Pt):
    """Factor a panel given TRANSPOSED ``Pt`` (nb x mk, P = Ptᵀ).

    Returns ``(Vt, Xinv, R)`` with ``Vt`` (nb x mk) the transposed
    reconstruction factor, ``Xinv = X⁻¹`` (nb x nb), and ``R`` (nb x nb
    upper) such that ``H = I - Vtᵀ·Xinvᵀ·Vt`` is orthogonal,
    ``Hᵀ·P = [R; 0]`` and ``H·E1 = Q_r``. All heavy ops are matmuls at
    f32 accumulation.
    """
    nb = Pt.shape[0]
    Pt = Pt.astype(jnp.float32)
    if str(mca_param.get("ops.panel_qr", "cholqr2")) == "xla":
        Q, R = jnp.linalg.qr(Pt.T)      # reduced: (mk, nb), (nb, nb)
        Qt = Q.T
    else:
        # CholeskyQR2: Q1 = P L1^-T, Q = Q1 L2^-T, R = (L1 L2)^T.
        # Grams accumulate in f32; the nb-sized chol/solves are exact.
        G1 = jnp.matmul(Pt, Pt.T, preferred_element_type=jnp.float32,
                        precision=_prec())
        L1 = jnp.linalg.cholesky(G1)
        Q1t = jax.scipy.linalg.solve_triangular(L1, Pt, lower=True)
        G2 = jnp.matmul(Q1t, Q1t.T, preferred_element_type=jnp.float32,
                        precision=_prec())
        L2 = jnp.linalg.cholesky(G2)
        Qt = jax.scipy.linalg.solve_triangular(L2, Q1t, lower=True)
        # nb x nb product: always full f32 — R must match the H the
        # trailing update applies, and this matmul's cost is noise
        R = jnp.matmul(L1, L2, preferred_element_type=jnp.float32,
                       precision="highest").T
    # sign fix: scale columns of Q (rows of Qt) so diag(Q1) <= 0 and
    # X = I - Q1 has diagonal >= 1 (well-conditioned inverse); R's rows
    # absorb the signs, so Q·R is unchanged
    d = jnp.diagonal(Qt[:, :nb])
    s = jnp.where(d >= 0, -1.0, 1.0).astype(jnp.float32)
    Qt = s[:, None] * Qt
    R = s[:, None] * R
    Vt = Qt.at[:, :nb].add(-jnp.eye(nb, dtype=jnp.float32))
    X = jnp.eye(nb, dtype=jnp.float32) - Qt[:, :nb].T
    Xinv = jnp.linalg.inv(X)
    return Vt, Xinv, R


def panel_qr_apply(Vt, Xinv, Ct):
    """Trailing update in transposed storage: given ``Ct = Cᵀ``
    (ncols x mk), return ``(Hᵀ·C)ᵀ = Ct - (Ct·Vtᵀ)·Xinvᵀ·Vt`` — two
    large matmuls plus one small (ncols x nb)·(nb x nb)."""
    W = jnp.matmul(Ct, Vt.T, preferred_element_type=jnp.float32,
                   precision=_prec())
    W = jnp.matmul(W, Xinv.T, preferred_element_type=jnp.float32,
                   precision=_prec())
    return (Ct - jnp.matmul(W, Vt, preferred_element_type=jnp.float32,
                            precision=_prec())).astype(Ct.dtype)


def unmqr_tile(Q, C):
    """C ← Qᵀ·C (apply a diagonal-tile factor to a row-panel tile)."""
    out = jnp.matmul(Q.T, C, preferred_element_type=jnp.float32,
                     precision=_prec())
    return out.astype(C.dtype)


def tsqrt_tile(R, A):
    """Triangular-on-top-of-square QR: [R; A] = Q₂·R' → (Q₂, R').
    Q₂ is the full (2nb × 2nb) factor; R' the updated nb × nb triangle."""
    nb = R.shape[0]
    S = jnp.concatenate([R, A], axis=0).astype(jnp.float32)
    Q2, Rfull = jnp.linalg.qr(S, mode="complete")
    return Q2.astype(R.dtype), Rfull[:nb].astype(R.dtype)


def tsmqr_tile(Q2, C1, C2):
    """Apply a TSQRT factor to a stacked pair: [C1; C2] ← Q₂ᵀ·[C1; C2]."""
    nb = C1.shape[0]
    S = jnp.concatenate([C1, C2], axis=0)
    out = jnp.matmul(Q2.T, S, preferred_element_type=jnp.float32,
                     precision=_prec()).astype(C1.dtype)
    return out[:nb], out[nb:]
