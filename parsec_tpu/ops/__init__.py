"""Tile kernels for dense linear algebra and ML blocks.

The FLOP-carrying bodies used by the shipped taskpools (GEMM, POTRF,
TRSM, SYRK, QR kernels, transformer blocks). jnp implementations let XLA
fuse and tile for the MXU; pallas variants cover what XLA won't fuse.
"""

from .tile_kernels import (gemm_tile, syrk_tile, trsm_tile, potrf_tile,
                           add_tile, scale_tile)
