"""Tiled GEMM: C ← α·A·B + β·C.

Two builders, matching the reference's two front ends:
- :func:`build_gemm_ptg` — PTG taskpool with a k-chain per C tile (the
  dgemm JDF shape).
- :func:`insert_gemm_dtd` — DTD insertion (the reference's
  tests/dsl/dtd tiled-GEMM config from BASELINE.md).
"""

from __future__ import annotations

from ..compiled.panels import (SegRead, SegStep, SegWrite, bucket_tiles,
                               register_panel_kernel)
from ..dsl import dtd, ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import gemm_tile
from ..utils import compile_cache, mca_param

mca_param.register(
    "gemm.k_block", 0,
    help="panel-fused GEMM: consecutive k-waves fused into one deep "
         "matmul (0 = the whole k range; 1 = per-wave rank-nb updates)")
compile_cache.register_trace_knob("gemm.k_block")


def build_gemm_ptg(A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
                   alpha: float = 1.0, beta: float = 1.0) -> ptg.Taskpool:
    if A.nt != B.mt or A.mt != C.mt or B.nt != C.nt:
        raise ValueError("tile-grid mismatch")
    tp = ptg.Taskpool("gemm", A=A, B=B, C=C,
                      MT=C.mt, NT=C.nt, KT=A.nt)

    GEMM = tp.task_class(
        "GEMM", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for m in range(g.MT)
                         for n in range(g.NT) for k in range(g.KT)),
        affinity=lambda g, m, n, k: (g.C, (m, n)),
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, k)))]),
            ptg.FlowSpec(
                "B", ptg.READ,
                tile=lambda g, m, n, k: (g.B, (k, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.B, (k, n)))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, n, k: (g.C, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.C, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("GEMM",
                                 lambda g, m, n, k: (m, n, k - 1), "C"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[ptg.Out(dst=("GEMM",
                                   lambda g, m, n, k: (m, n, k + 1), "C"),
                              guard=lambda g, m, n, k: k < g.KT - 1),
                      ptg.Out(data=lambda g, m, n, k: (g.C, (m, n)),
                              guard=lambda g, m, n, k: k == g.KT - 1)])])

    @GEMM.body
    def gemm_body(task, A_, B_, C_, _alpha=alpha, _beta=beta):
        return gemm_tile(C_, A_, B_, alpha=_alpha, beta=_beta)

    tp.wave_fuser = _make_gemm_wave_fuser(alpha, beta)
    tp.panel_segment_fuser = _make_gemm_segment_fuser(alpha, beta)
    return tp


def _make_gemm_wave_fuser(alpha: float, beta: float):
    """Panel-fused lowering of the GEMM k-chain (compiled.panels, the
    multi-collection case), **k-blocked**: instead of one rank-nb update
    per wave (which re-reads and rewrites all of Cᵀ every wave, capping
    arithmetic intensity at nb), the fuser emits ONE deep matmul per
    block of ``gemm.k_block`` consecutive waves —

        Cᵀ ← α·(Bᵀ[:, k0:k1]·W)·Aᵀ[k0:k1, :] + β^{k1-k0}·Cᵀ

    — over contiguous slices of the transposed stores (no copies), with
    W the per-block-column scaling β^{k1-1-r} that reproduces the
    per-tile body's β-per-chain-step semantics exactly. The remaining
    waves of a block lower to the identity (the composed program's final
    state is unchanged; only write granularity moves). Default block =
    the whole k range: the chain becomes a single full-depth MXU matmul
    per C pass — measured 66.7 → ~150 TF/s at n=8192/nb=1024 on a v5e
    (the 65%-of-peak BASELINE line is ~101 TF/s)."""

    def fuser(wave, geoms):
        import numpy as np
        import jax.numpy as jnp
        from ..ops.tile_kernels import matmul_precision
        from ..utils import mca_param

        if sorted(g.tc.name for g in wave) != ["GEMM"]:
            return None
        (grp,) = wave
        ks = {t[2] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        g = grp.tc.tp.g
        ga, gb, gc = g.A.name, g.B.name, g.C.name
        gA, gB, gC = geoms[ga], geoms[gb], geoms[gc]
        # the wave must cover the full (m, n) grid — partial waves would
        # need masking this lowering doesn't do
        want = {(m, n) for m in range(gC.mt) for n in range(gC.nt)}
        if {(m, n) for (m, n, _k) in grp.tasks} != want:
            return None
        KT = gA.nt
        KB = int(mca_param.get("gemm.k_block", 0)) or KT
        if k % KB:
            return lambda st: st        # folded into its block's head wave
        k0, k1 = k, min(k + KB, KT)
        nblk = k1 - k0
        prec = matmul_precision()
        # per-block-column β weights (constant, fused into the operand
        # read); identity when β == 1 or the block is a single wave
        w = None
        if beta != 1.0 and nblk > 1:
            w = np.repeat(beta ** np.arange(nblk - 1, -1, -1,
                                            dtype=np.float32), gB.mb)

        def do_kblock(st, k0=k0, k1=k1):
            At, Bt, Ct = st[ga], st[gb], st[gc]
            # Aᵀ store is (K, M): block-rows k0:k1 (= A's column panels)
            # are contiguous; Bᵀ store is (N, K): column blocks k0:k1
            # span B's block-ROW extent (gB.mb per block)
            Bs = Bt[:, k0 * gB.mb:k1 * gB.mb]
            if w is not None:
                Bs = Bs * w[None, :]
            acc = jnp.matmul(Bs, At[k0 * gA.nb:k1 * gA.nb, :],
                             preferred_element_type=jnp.float32,
                             precision=prec)
            st[gc] = (alpha * acc +
                      (beta ** nblk) * Ct).astype(Ct.dtype)
            return st

        return do_kblock

    return fuser


@register_panel_kernel("gemm.kblock")
def _seg_kblock_kernel(in_sds, static):
    """(Bs (NC,Kb), At (Kb,MC), Ct (NC,MC), w (Kb,), α (), β^nblk ())
    → αΒsᵂ·At + β^nblk·Ct. The contraction extent is bucketed —
    extraction zero-masks past the true k-block, so padded lanes add
    exact zeros; α/β/w ride as traced inputs, keeping ONE kernel per
    (C shape, contraction bucket, dtype) reused across every k-block
    of every run at those shapes."""
    del in_sds, static
    import jax.numpy as jnp
    from ..ops.tile_kernels import matmul_precision
    prec = matmul_precision()

    def fn(Bs, At, Ct, w, alpha_s, beta_pow):
        acc = jnp.matmul(Bs * w[None, :], At,
                         preferred_element_type=jnp.float32,
                         precision=prec)
        return (alpha_s * acc + beta_pow * Ct).astype(Ct.dtype)

    return fn


def _make_gemm_segment_fuser(alpha: float, beta: float):
    """Segmented (compile-once) lowering of the k-blocked panel GEMM:
    the same math as :func:`_make_gemm_wave_fuser`, emitted as ONE
    ``gemm.kblock`` dispatch per block head (non-head waves lower to
    no steps) over a bucketed contraction extent."""

    def fuser(wave, geoms):
        import numpy as np

        if sorted(g.tc.name for g in wave) != ["GEMM"]:
            return None
        (grp,) = wave
        ks = {t[2] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        g = grp.tc.tp.g
        ga, gb, gc = g.A.name, g.B.name, g.C.name
        gA, gB, gC = geoms[ga], geoms[gb], geoms[gc]
        want = {(m, n) for m in range(gC.mt) for n in range(gC.nt)}
        if {(m, n) for (m, n, _k) in grp.tasks} != want:
            return None
        KT = gA.nt
        KB = int(mca_param.get("gemm.k_block", 0)) or KT
        if k % KB:
            return []           # folded into its block's head wave
        k0, k1 = k, min(k + KB, KT)
        nblk = k1 - k0
        bt = bucket_tiles(nblk, KT - k0)
        NC, MC = gC.nb * gC.nt, gC.mb * gC.mt
        w = np.ones(bt * gB.mb, np.float32)
        if beta != 1.0 and nblk > 1:
            w[:nblk * gB.mb] = np.repeat(
                beta ** np.arange(nblk - 1, -1, -1, dtype=np.float32),
                gB.mb)
        return [SegStep(
            kernel="gemm.kblock",
            reads=(SegRead("state", gb, 0, k0 * gB.mb,
                           NC, nblk * gB.mb, NC, bt * gB.mb),
                   SegRead("state", ga, k0 * gA.nb, 0,
                           nblk * gA.nb, MC, bt * gA.nb, MC),
                   SegRead("state", gc, 0, 0, NC, MC, NC, MC),
                   SegRead("const", "w", value=w),
                   SegRead("const", "alpha",
                           value=np.float32(alpha)),
                   SegRead("const", "beta_pow",
                           value=np.float32(beta ** nblk))),
            writes=(SegWrite("state", gc, 0, 0, NC, MC),))]

    return fuser


def _gemm_dtd_body(a, b, c, alpha, beta):
    # module-level (stable identity): the pure-body jit cache is keyed
    # by fn, so every GEMM taskpool in the process shares one compile
    return gemm_tile(c, a, b, alpha=alpha, beta=beta)


def insert_gemm_dtd(tp: "dtd.Taskpool", A: TiledMatrix, B: TiledMatrix,
                    C: TiledMatrix, alpha: float = 1.0,
                    beta: float = 1.0) -> None:
    """Insert the full tiled-GEMM DAG into a DTD taskpool (the
    dtd_test-style driver loop, insert_function.c varargs shape).

    Batched per C tile-row: one ``insert_tasks`` call per ``m`` shares
    the task-class resolution, the tile-handle cache (the A(m, k) and
    C(m, n) handles repeat across the row) and a single ``schedule()``
    flush — the insertion fast path, instead of paying every lookup per
    task."""
    va, vb = dtd.ValueArg(alpha), dtd.ValueArg(beta)
    for m in range(C.mt):
        tp.insert_tasks(
            _gemm_dtd_body,
            [(dtd.TileArg(A, (m, k), dtd.INPUT),
              dtd.TileArg(B, (k, n), dtd.INPUT),
              dtd.TileArg(C, (m, n), dtd.INOUT, affinity=True),
              va, vb)
             for n in range(C.nt) for k in range(A.nt)],
            pure=True)


def gemm_flops(m: int, n: int, k: int) -> float:
    return 2.0 * m * n * k
