"""Tiled GEMM: C ← α·A·B + β·C.

Two builders, matching the reference's two front ends:
- :func:`build_gemm_ptg` — PTG taskpool with a k-chain per C tile (the
  dgemm JDF shape).
- :func:`insert_gemm_dtd` — DTD insertion (the reference's
  tests/dsl/dtd tiled-GEMM config from BASELINE.md).
"""

from __future__ import annotations

from ..dsl import dtd, ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import gemm_tile


def build_gemm_ptg(A: TiledMatrix, B: TiledMatrix, C: TiledMatrix,
                   alpha: float = 1.0, beta: float = 1.0) -> ptg.Taskpool:
    if A.nt != B.mt or A.mt != C.mt or B.nt != C.nt:
        raise ValueError("tile-grid mismatch")
    tp = ptg.Taskpool("gemm", A=A, B=B, C=C,
                      MT=C.mt, NT=C.nt, KT=A.nt)

    GEMM = tp.task_class(
        "GEMM", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for m in range(g.MT)
                         for n in range(g.NT) for k in range(g.KT)),
        affinity=lambda g, m, n, k: (g.C, (m, n)),
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, k)))]),
            ptg.FlowSpec(
                "B", ptg.READ,
                tile=lambda g, m, n, k: (g.B, (k, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.B, (k, n)))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, n, k: (g.C, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.C, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("GEMM",
                                 lambda g, m, n, k: (m, n, k - 1), "C"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[ptg.Out(dst=("GEMM",
                                   lambda g, m, n, k: (m, n, k + 1), "C"),
                              guard=lambda g, m, n, k: k < g.KT - 1),
                      ptg.Out(data=lambda g, m, n, k: (g.C, (m, n)),
                              guard=lambda g, m, n, k: k == g.KT - 1)])])

    @GEMM.body
    def gemm_body(task, A_, B_, C_, _alpha=alpha, _beta=beta):
        return gemm_tile(C_, A_, B_, alpha=_alpha, beta=_beta)

    tp.wave_fuser = _make_gemm_wave_fuser(alpha, beta)
    return tp


def _make_gemm_wave_fuser(alpha: float, beta: float):
    """Panel-fused lowering of the GEMM k-chain (compiled.panels, the
    multi-collection case): wave k = every GEMM(·,·,k) = ONE dense
    rank-nb update Cᵀ ← α·Bᵀ[:, k]·Aᵀ[k, :] + β·Cᵀ over the three
    transposed stores. Mirrors the per-tile body exactly (including β
    applied per chain step)."""

    def fuser(wave, geoms):
        import jax.numpy as jnp
        from ..ops.tile_kernels import matmul_precision

        if sorted(g.tc.name for g in wave) != ["GEMM"]:
            return None
        (grp,) = wave
        ks = {t[2] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        g = grp.tc.tp.g
        ga, gb, gc = g.A.name, g.B.name, g.C.name
        gA, gB, gC = geoms[ga], geoms[gb], geoms[gc]
        # the wave must cover the full (m, n) grid — partial waves would
        # need masking this lowering doesn't do
        want = {(m, n) for m in range(gC.mt) for n in range(gC.nt)}
        if {(m, n) for (m, n, _k) in grp.tasks} != want:
            return None
        prec = matmul_precision()

        def do_rank_update(st, k=k):
            At, Bt, Ct = st[ga], st[gb], st[gc]
            # Aᵀ store is (K, M): its block-row k (= A's column panel k)
            # is contiguous; Bᵀ store is (N, K): its column block k
            # spans B's block-ROW extent (gB.mb per block)
            acc = jnp.matmul(Bt[:, k * gB.mb:(k + 1) * gB.mb],
                             At[k * gA.nb:(k + 1) * gA.nb, :],
                             preferred_element_type=jnp.float32,
                             precision=prec)
            st[gc] = (alpha * acc + beta * Ct).astype(Ct.dtype)
            return st

        return do_rank_update

    return fuser


def insert_gemm_dtd(tp: "dtd.Taskpool", A: TiledMatrix, B: TiledMatrix,
                    C: TiledMatrix, alpha: float = 1.0,
                    beta: float = 1.0) -> None:
    """Insert the full tiled-GEMM DAG into a DTD taskpool (the
    dtd_test-style driver loop, insert_function.c varargs shape)."""
    def body(a, b, c):
        return gemm_tile(c, a, b, alpha=alpha, beta=beta)

    for m in range(C.mt):
        for n in range(C.nt):
            for k in range(A.nt):
                tp.insert_task(
                    body,
                    dtd.TileArg(A, (m, k), dtd.INPUT),
                    dtd.TileArg(B, (k, n), dtd.INPUT),
                    dtd.TileArg(C, (m, n), dtd.INOUT, affinity=True),
                    name=f"GEMM({m},{n},{k})")


def gemm_flops(m: int, n: int, k: int) -> float:
    return 2.0 * m * n * k
