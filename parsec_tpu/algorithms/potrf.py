"""Tiled Cholesky factorization (right-looking, lower) as a PTG taskpool.

The DPLASMA dpotrf_L equivalent — the reference's headline workload class
(BASELINE.md: "DPLASMA-style tiled Cholesky ≥65% of peak"). Task classes
and dataflow mirror the classic dpotrf JDF:

    POTRF(k):  T = chol(A[k,k] after k SYRK updates)
    TRSM(m,k): C = A[m,k] · T^{-T}
    SYRK(m,k): diag update A[m,m] -= C·Cᵀ            (k-th update)
    GEMM(m,n,k): A[m,n] -= A[m,k]·A[n,k]ᵀ            (k-th update)

Every flow carries its logical tile (FlowSpec.tile), so the taskpool runs
on the host runtime AND on the compiled wavefront/SPMD executors.
"""

from __future__ import annotations

from ..compiled.panels import (SegRead, SegStep, SegWrite, bucket_tiles,
                               register_panel_kernel)
from ..dsl import ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import (gemm_tile, potrf_tile, potrf_tile_blocked,
                                syrk_tile, trsm_tile,
                                trsm_tiles_gemm, trsm_tiles_wide)
from ..utils import compile_cache, mca_param

# The compiled path's batched kernels. "solve" (default) is the exact
# wide triangular solve — reference numerics (dplasma TRSM). "gemm"
# inverts the shared diagonal factor once per wave and runs every solve
# as an MXU matmul (MAGMA-style; measured ~5-8x the wide-solve
# throughput at nb=2048) at the cost of squaring the factor's
# condition-number contribution — fine for the well-conditioned
# dense-LA regime DPLASMA targets, and what bench.py opts into for the
# headline (measured bound at N=40960 bf16: residual 4.1e-6 gemm vs the
# solve+highest variant's 4.5e-7; see PARITY.md divergence notes).
# Default "solve": a library default must not silently diverge from
# reference numerics for ill-conditioned inputs.
mca_param.register("potrf.trsm_hook", "solve",
                   help="compiled-path TRSM wave kernel: solve (exact, "
                        "reference numerics) | gemm (inverted-triangle "
                        "MXU multiply, ~5-8x faster, squares the "
                        "condition-number contribution)")
mca_param.register("potrf.blocked_tile_chol", 1,
                   help="use the matmul-rich blocked in-tile Cholesky in "
                        "the compiled path (0 = XLA cholesky)")
# both knobs pick the kernels traced into compiled programs — every
# shared/persistent compile-cache key must cover their values
compile_cache.register_trace_knob("potrf.trsm_hook")
compile_cache.register_trace_knob("potrf.blocked_tile_chol")


def build_potrf(A: TiledMatrix) -> ptg.Taskpool:
    """Build the POTRF taskpool over tiled matrix ``A`` (lower)."""
    NT = A.nt
    if A.mt != A.nt:
        raise ValueError("POTRF needs a square tile grid")
    if A.mb != A.nb:
        # the wave fusers index the transposed store with nb-granular
        # row panels and mb-granular columns interchangeably — non-
        # square tiles would silently produce wrong slices
        raise ValueError("POTRF needs square tiles (mb == nb)")
    tp = ptg.Taskpool("potrf", A=A, NT=NT)

    POTRF = tp.task_class(
        "POTRF", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 3 * (g.NT - k) ** 2,
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, k: (g.A, (k, k)),
            ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                        guard=lambda g, k: k == 0),
                 ptg.In(src=("SYRK", lambda g, k: (k, k - 1), "C"),
                        guard=lambda g, k: k > 0)],
            outs=[ptg.Out(dst=("TRSM",
                               lambda g, k: [(m, k) for m in range(k + 1, g.NT)],
                               "L")),
                  ptg.Out(data=lambda g, k: (g.A, (k, k)))])])

    TRSM = tp.task_class(
        "TRSM", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "L", ptg.READ,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("POTRF", lambda g, m, k: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("GEMM", lambda g, m, k: (m, k, k - 1), "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[
                    ptg.Out(dst=("SYRK", lambda g, m, k: (m, k), "A")),
                    # row operand of the GEMMs updating row m
                    ptg.Out(dst=("GEMM",
                                 lambda g, m, k: [(m, n, k)
                                                  for n in range(k + 1, m)],
                                 "A")),
                    # transposed operand of the GEMMs updating column m
                    ptg.Out(dst=("GEMM",
                                 lambda g, m, k: [(i, m, k)
                                                  for i in range(m + 1, g.NT)],
                                 "B")),
                    ptg.Out(data=lambda g, m, k: (g.A, (m, k)))])])

    SYRK = tp.task_class(
        "SYRK", params=("m", "k"),
        space=lambda g: ((m, k) for m in range(1, g.NT)
                         for k in range(m)),
        affinity=lambda g, m, k: (g.A, (m, m)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(src=("TRSM", lambda g, m, k: (m, k), "C"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, m)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, m)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("SYRK", lambda g, m, k: (m, k - 1), "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[ptg.Out(dst=("SYRK", lambda g, m, k: (m, k + 1), "C"),
                              guard=lambda g, m, k: k < m - 1),
                      ptg.Out(dst=("POTRF", lambda g, m, k: (m,), "T"),
                              guard=lambda g, m, k: k == m - 1)])])

    GEMM = tp.task_class(
        "GEMM", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for m in range(2, g.NT)
                         for n in range(1, m) for k in range(n)),
        affinity=lambda g, m, n, k: (g.A, (m, n)),
        priority=lambda g, m, n, k: (g.NT - k) ** 2 - m - n,
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (m, k)),
                ins=[ptg.In(src=("TRSM", lambda g, m, n, k: (m, k), "C"))]),
            ptg.FlowSpec(
                "B", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (n, k)),
                ins=[ptg.In(src=("TRSM", lambda g, m, n, k: (n, k), "C"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("GEMM",
                                 lambda g, m, n, k: (m, n, k - 1), "C"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[ptg.Out(dst=("GEMM",
                                   lambda g, m, n, k: (m, n, k + 1), "C"),
                              guard=lambda g, m, n, k: k < n - 1),
                      ptg.Out(dst=("TRSM", lambda g, m, n, k: (m, n), "C"),
                              guard=lambda g, m, n, k: k == n - 1)])])

    def _potrf_hook(Ts):
        import jax
        if mca_param.get("potrf.blocked_tile_chol", 1):
            return jax.vmap(potrf_tile_blocked)(Ts) if Ts.shape[0] > 1 \
                else potrf_tile_blocked(Ts[0])[None]
        return jax.vmap(potrf_tile)(Ts)

    @POTRF.body(batch_hook=_potrf_hook)
    def potrf_body(task, T):
        return potrf_tile(T)

    def _trsm_hook(Ls, Cs):
        if mca_param.get("potrf.trsm_hook", "solve") == "gemm":
            return trsm_tiles_gemm(Ls[0], Cs)
        return trsm_tiles_wide(Ls[0], Cs)

    # compiled-path batched form: every TRSM(m, k) of one wave shares the
    # same factor L = POTRF(k).T, so the whole group is one inversion +
    # wide matmul (or one wide-RHS solve; the executor verifies the
    # shared-L grouping per wave)
    @TRSM.body(batch_hook=_trsm_hook, batch_hook_shared=("L",))
    def trsm_body(task, L, C):
        return trsm_tile(C, L)

    @SYRK.body
    def syrk_body(task, A_, C):
        return syrk_tile(C, A_, alpha=-1.0, beta=1.0)

    @GEMM.body
    def gemm_body(task, A_, B_, C):
        return gemm_tile(C, A_, B_, alpha=-1.0, beta=1.0, tb=True)

    tp.wave_fuser = _potrf_wave_fuser
    return tp


def _fuser_helpers(geom):
    import jax.numpy as jnp
    from ..ops.tile_kernels import matmul_precision

    prec = matmul_precision()

    def mm(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32,
                          precision=prec)

    def tile_chol(blk):
        if mca_param.get("potrf.blocked_tile_chol", 1):
            return potrf_tile_blocked(blk)
        return potrf_tile(blk)

    return jnp, mm, tile_chol


def _potrf_wave_fuser(wave, geoms):
    """Lower one right-looking POTRF wave to Aᵀ-dense ops
    (compiled.panels contract).

    ASAP leveling makes every wave one of three shapes per step k —
    [POTRF(k)], [TRSM(·,k)], [SYRK(·,k) (+GEMM(·,·,k))]. In the
    transposed store, block-column panels of A are leading-dim row
    slices, so the TRSM panel solve and every trailing strip are
    contiguous reads/writes. The shapes are verified from the actual
    task lists (never wave-index arithmetic); unrecognized waves return
    None.
    """
    (geom,) = geoms.values()      # single-collection DAG
    jnp, mm, tile_chol = _fuser_helpers(geom)
    names = sorted(g.tc.name for g in wave)
    mb, nb = geom.mb, geom.nb

    if names == ["POTRF"]:
        (grp,) = wave
        if len(grp.tasks) != 1:
            return None
        (k,) = grp.tasks[0]

        def do_potrf(st, k=k):
            D = st[geom.name]
            r, c = geom.rows(k), geom.cols(k)
            # diag tile of Aᵀ = (A[k,k])ᵀ, symmetric → chol directly;
            # store Lᵀ (upper) back
            st[geom.name] = D.at[c, r].set(tile_chol(D[c, r]).T)
            return st

        return do_potrf

    if names == ["TRSM"]:
        (grp,) = wave
        ks = {t[1] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        ms = sorted(t[0] for t in grp.tasks)
        if ms != list(range(ms[0], ms[0] + len(ms))):
            return None        # rows must be one contiguous panel

        solve_mode = mca_param.get("potrf.trsm_hook", "solve") == "solve"

        def do_trsm(st, k=k, lo=ms[0], hi=ms[-1] + 1):
            import jax
            from ..ops.tile_kernels import tri_inv_tile
            D = st[geom.name]
            c = geom.cols(k)
            # Lᵀ[k,k] stored upper → recover L
            L = D[c, geom.rows(k)].T
            rest = D[c, lo * mb:hi * mb]
            if solve_mode:        # exact wide solve, no inversion
                solved = jax.scipy.linalg.solve_triangular(
                    L.astype(jnp.float32), rest.astype(jnp.float32),
                    lower=True).astype(D.dtype)
            else:                 # invert once per wave, solve as matmul
                solved = mm(tri_inv_tile(L), rest).astype(D.dtype)
            # C ← C·L⁻ᵀ transposed: Cᵀ ← L⁻¹·Cᵀ, one contiguous row panel
            st[geom.name] = D.at[c, lo * mb:hi * mb].set(solved)
            return st

        return do_trsm

    if names in (["SYRK"], ["GEMM", "SYRK"]):
        syrk = next(g for g in wave if g.tc.name == "SYRK")
        ks = {t[1] for t in syrk.tasks}
        gemm = next((g for g in wave if g.tc.name == "GEMM"), None)
        if gemm is not None:
            ks |= {t[2] for t in gemm.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        rows = sorted(t[0] for t in syrk.tasks)
        lo, hi = rows[0], rows[-1] + 1
        if rows != list(range(lo, hi)):
            return None
        want = {(m, n) for m in range(lo, hi) for n in range(lo, m)}
        have = {(m, n) for (m, n, _k) in (gemm.tasks if gemm else [])}
        if want != have:
            return None        # trailing block-triangle must be complete

        def do_trailing(st, k=k, lo=lo, hi=hi):
            # strip j updates A[j.., j] — in Aᵀ: row panel j, trailing
            # columns; SYRK (diag tile) + GEMM (below) together, never
            # touching strictly-upper tiles
            D = st[geom.name]
            Pt = D[geom.cols(k), lo * mb:hi * mb]     # (nb, R) = panelᵀ
            for j in range(lo, hi):
                pj = Pt[:, (j - lo) * mb:(j - lo + 1) * mb]
                old = D[geom.cols(j), j * mb:hi * mb]
                D = D.at[geom.cols(j), j * mb:hi * mb].set(
                    old - mm(pj.T, Pt[:, (j - lo) * mb:]))
            st[geom.name] = D
            return st

        return do_trailing

    return None


def potrf_flops(n: int) -> float:
    """Useful FLOPs of an n×n Cholesky (LAPACK count)."""
    return n ** 3 / 3.0 + n ** 2 / 2.0 + n / 6.0


def build_potrf_left(A: TiledMatrix) -> ptg.Taskpool:
    """Left-looking tiled Cholesky (LAPACK-style blocked ``potrf``).

    The right-looking :func:`build_potrf` spreads a tile's updates over
    k-indexed SYRK/GEMM chains; this variant concentrates them: each
    tile receives ALL its k<j contributions in a single ``UPDATE`` task
    that CTL-gathers its producer TRSMs (the reference's CTL-gather
    fan-in, tests/dsl/ptg/controlgather/ctlgat.jdf) and reads their
    written-back tiles from the collection inside the body — the same
    direct-memory pattern reference JDF bodies use for gathered
    operands. ASAP leveling then yields exactly three waves per step k
    ([UPDATE(·,k)], [POTRF(k)], [TRSM(·,k)]), and the panel fuser turns
    each UPDATE wave into ONE dense matmul over all previously factored
    panels — the MXU-optimal schedule (measured ~98-106 TF/s/chip vs
    ~68 for the fused right-looking form at N=32768-40960).

    Distribution: UPDATE's gathered operands are resolved with the
    direct-memory pattern of reference JDF bodies — local tiles read
    from the collection, remote tiles through the comm engine's
    one-sided :meth:`~..comm.engine.CommEngine.fetch_tile` (the
    rendezvous-GET analog, remote_dep_mpi.c:1594-1729). The CTL-gather
    guarantees every gathered TRSM wrote its tile back on its owner
    before UPDATE runs, so the fetch is race-free; the same taskpool
    runs single-process panel-fused AND multi-rank.
    """
    NT = A.nt
    if A.mt != A.nt:
        raise ValueError("POTRF needs a square tile grid")
    if A.mb != A.nb:
        raise ValueError("POTRF needs square tiles (mb == nb)")
    tp = ptg.Taskpool("potrf_left", A=A, NT=NT)

    def _gathered(g, m, k):
        """Producer TRSMs whose tiles UPDATE(m, k) reads: row m and
        row k, all columns j < k."""
        seen = []
        for row in (m, k):
            for j in range(k):
                if (row, j) not in seen:
                    seen.append((row, j))
        return seen

    UPDATE = tp.task_class(
        "UPDATE", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(1, g.NT)
                         for m in range(k, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m + 1,
        flows=[
            ptg.FlowSpec(
                "G", ptg.CTL,
                ins=[ptg.In(src=("TRSM", _gathered, "G"), gather=True)]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)))],
                outs=[ptg.Out(dst=("POTRF", lambda g, m, k: (k,), "T"),
                              guard=lambda g, m, k: m == k),
                      ptg.Out(dst=("TRSM", lambda g, m, k: (m, k), "C"),
                              guard=lambda g, m, k: m > k)])])

    POTRF = tp.task_class(
        "POTRF", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 3 * (g.NT - k) ** 2,
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, k: (g.A, (k, k)),
            ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                        guard=lambda g, k: k == 0),
                 ptg.In(src=("UPDATE", lambda g, k: (k, k), "C"),
                        guard=lambda g, k: k > 0)],
            outs=[ptg.Out(dst=("TRSM",
                               lambda g, k: [(m, k)
                                             for m in range(k + 1, g.NT)],
                               "L")),
                  ptg.Out(data=lambda g, k: (g.A, (k, k)))])])

    TRSM = tp.task_class(
        "TRSM", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "L", ptg.READ,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("POTRF", lambda g, m, k: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("UPDATE", lambda g, m, k: (m, k), "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[ptg.Out(data=lambda g, m, k: (g.A, (m, k)))]),
            ptg.FlowSpec(
                "G", ptg.CTL,
                outs=[ptg.Out(
                    dst=("UPDATE",
                         lambda g, m, k: sorted(
                             {(m, kk) for kk in range(k + 1, m + 1)} |
                             {(m2, m) for m2 in range(m, g.NT)}),
                         "G"))])])

    # the CTL-gather contract guarantees every gathered TRSM has written
    # its tile back (on its owner rank) before the UPDATE body runs, so
    # direct local reads / remote one-sided fetches are race-free.
    # Fetched tiles are FINAL for the taskpool's lifetime (column j is
    # never rewritten after step j), so remote fetches cache per rank on
    # the taskpool — each remote tile crosses the wire once, not once
    # per consuming UPDATE.
    tp._fetch_cache = {}

    @UPDATE.body(batchable=False)
    def update_body(task, C):
        import numpy as np
        from ..comm.engine import resolve_column_tiles
        g = task.taskpool.g
        ctx = task.taskpool.context
        cache = task.taskpool._fetch_cache
        m, k = task.locals
        remote = ctx is not None and ctx.nb_ranks > 1
        my = ctx.my_rank if remote else 0
        # resolve the two gathered rows up front: local reads inline,
        # uncached remote tiles in ONE concurrent batch fetch (a
        # sequential fetch per tile would serialize ~2k link RTTs)
        keys = []
        for row in (m, k) if m != k else (m,):
            for j in range(k):
                key = (row, j)
                if remote and g.A.rank_of(key) != my \
                        and key not in cache:
                    keys.append(key)
        if keys:
            for key, v in zip(keys,
                              resolve_column_tiles(task, g.A, keys)):
                cache[key] = v          # benign race: idempotent value

        def tile(row, j):
            hit = cache.get((row, j))
            if hit is not None:
                return hit
            return np.asarray(g.A.data_of((row, j)), dtype=np.float32)

        acc = np.asarray(C, dtype=np.float32).copy()
        for j in range(k):
            acc -= tile(m, j) @ tile(k, j).T
        return acc.astype(np.asarray(C).dtype)

    @POTRF.body
    def potrf_body(task, T):
        return potrf_tile(T)

    @TRSM.body(batchable=False)
    def trsm_body(task, L, C):
        return {"C": trsm_tile(C, L)}

    tp.wave_fuser = _potrf_left_wave_fuser
    tp.panel_segment_fuser = _potrf_left_segment_fuser
    tp.requires_fuser = True     # compiled per-tile executors can't feed
    #                              the UPDATE body's collection reads
    return tp


def _potrf_left_wave_fuser(wave, geoms):
    """Lower one left-looking POTRF wave to Aᵀ-dense ops.

    Wave shapes per step k: [UPDATE(·,k)] → one matmul applying every
    prior panel's contribution to block-column k; [POTRF(k)] → diagonal
    chol (inverse stashed in the carry); [TRSM(·,k)] → one panel solve
    via the stashed inverse."""
    (geom,) = geoms.values()      # single-collection DAG
    jnp, mm, tile_chol = _fuser_helpers(geom)
    names = sorted(g.tc.name for g in wave)
    mb, nb = geom.mb, geom.nb

    if names == ["UPDATE"]:
        (grp,) = wave
        ks = {t[1] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        ms = sorted(t[0] for t in grp.tasks)
        lo, hi = ms[0], ms[-1] + 1
        if ms != list(range(lo, hi)) or lo != k:
            return None

        def do_update(st, k=k, hi=hi):
            # carry the updated row panel to the POTRF/TRSM waves of
            # this step instead of writing it to D — the step's panel is
            # written exactly ONCE (by do_trsm / do_potrf), halving the
            # DUS traffic and HBM liveness vs a write-per-wave lowering
            D = st[geom.name]
            r0, r1 = k * nb, (k + 1) * nb
            # Aᵀ[k-row, k..hi) −= (Lᵀ[:k, k])ᵀ · Lᵀ[:k, k..hi)
            U = D[0:r0, r0:r1]
            S = D[0:r0, r0:hi * mb]
            st["_rowk"] = D[r0:r1, r0:hi * mb] - mm(U.T, S)
            return st

        return do_update

    solve_mode = mca_param.get("potrf.trsm_hook", "solve") == "solve"

    if names == ["POTRF"]:
        (grp,) = wave
        if len(grp.tasks) != 1:
            return None
        (k,) = grp.tasks[0]

        def do_potrf(st, k=k, last=(k == geom.nt - 1)):
            from ..ops.tile_kernels import tri_inv_tile
            D = st[geom.name]
            c, r = geom.cols(k), geom.rows(k)
            rowk = st.pop("_rowk", None)
            diag = rowk[:, :nb] if rowk is not None else D[c, r]
            # symmetrize (identity for symmetric input; elementwise triu
            # masking here measurably breaks XLA's in-place scheduling —
            # the average form fuses cleanly)
            diag = 0.5 * (diag + diag.T)
            L = tile_chol(diag)
            if not solve_mode:
                # chol-then-invert, NOT ops.chol_inv_tile: measured
                # identical in-program runtime (105-107 TF/s both ways
                # at N=40960 — the fused kernel's standalone win is
                # dispatch overhead, absent inside one XLA program) and
                # the fused program deserializes 2-4x slower from the
                # persistent cache
                st["_potrf_inv"] = tri_inv_tile(L)
            if last:
                # no TRSM wave follows: this step's single write is ours
                st[geom.name] = D.at[c, r].set(L.T)
            else:
                # defer the write — the TRSM wave writes the whole row
                # panel (Lᵀ diag + solved rest) as ONE contiguous DUS;
                # split writes double the panel's HBM liveness
                st["_potrf_L"] = L
                if rowk is not None:
                    st["_rowk_rest"] = rowk[:, nb:]
            return st

        return do_potrf

    if names == ["TRSM"]:
        (grp,) = wave
        ks = {t[1] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        ms = sorted(t[0] for t in grp.tasks)
        if ms != list(range(ms[0], ms[0] + len(ms))):
            return None

        def do_trsm(st, k=k, lo=ms[0], hi=ms[-1] + 1):
            import jax
            from ..ops.tile_kernels import tri_inv_tile
            D = st[geom.name]
            c = geom.cols(k)
            L = st.pop("_potrf_L", None)
            rest = st.pop("_rowk_rest", None)
            if rest is None:     # k = 0: no UPDATE wave preceded
                rest = D[c, lo * mb:hi * mb]
            if solve_mode:
                # exact wide triangular solve (potrf.trsm_hook=solve):
                # no inversion, no condition-number squaring
                if L is None:
                    L = D[c, geom.rows(k)].T
                st.pop("_potrf_inv", None)
                solved = jax.scipy.linalg.solve_triangular(
                    L.astype(jnp.float32), rest.astype(jnp.float32),
                    lower=True)
            else:
                inv = st.pop("_potrf_inv", None)
                if inv is None:  # robustness: recompute from the factor
                    inv = tri_inv_tile(D[c, geom.rows(k)].T)
                solved = mm(inv, rest)
            if L is not None and lo == k + 1:
                # one contiguous row-panel write: Lᵀ diag + solved rest
                st[geom.name] = D.at[c, k * mb:hi * mb].set(
                    jnp.concatenate([L.T, solved.astype(D.dtype)],
                                    axis=1))
            else:
                st[geom.name] = D.at[c, lo * mb:hi * mb].set(
                    solved.astype(D.dtype))
            return st

        return do_trsm

    return None


# ---------------------------------------------------------------------------
# segmented panel lowering (compile-once serving)
# ---------------------------------------------------------------------------
# The monolith fusers above bake k into static slices of the full Aᵀ
# array: the whole-DAG program is specific to N and its compile time is
# linear in waves. The segment lowering expresses the SAME math as
# named kernels over extracted panels whose shapes are rounded up to
# the bucket lattice (compiled.panels.bucket_tiles) — each kernel is
# keyed by (NB, bucket, dtype, trsm_hook/chol knobs), INDEPENDENT of N,
# so a new problem size at a served NB re-uses every compiled bucket
# and the persistent store makes the second process compile nothing.
# Padding is exact: extraction zero-masks past the true extents (zero
# rows contribute nothing to the update matmul; zero RHS columns solve
# to zero) and write-back masks to the true window.

def _seg_mm():
    import jax.numpy as jnp
    from ..ops.tile_kernels import matmul_precision
    prec = matmul_precision()

    def mm(a, b):
        return jnp.matmul(a, b, preferred_element_type=jnp.float32,
                          precision=prec)

    return jnp, mm


@register_panel_kernel("potrf_left.update")
def _seg_update_kernel(in_sds, static):
    """(U (Kb,nb), S (Kb,Wb), Drow (nb,Wb)) → rowk = Drow − UᵀS."""
    del in_sds, static
    jnp, mm = _seg_mm()

    def fn(U, S, Drow):
        return Drow - mm(U.T, S)

    return fn


@register_panel_kernel("potrf_left.diag")
def _seg_diag_kernel(in_sds, static):
    """(rowk (nb, Wb)) → (Lᵀ write, L carry[, L⁻¹ carry]): symmetrized
    diag chol of the panel head; the inverse carry exists only under
    potrf.trsm_hook=gemm (key covered by the trace-knob snapshot)."""
    del static
    (rowk_sds,) = in_sds
    nb = rowk_sds.shape[0]
    jnp, mm = _seg_mm()
    solve_mode = mca_param.get("potrf.trsm_hook", "solve") == "solve"

    def tile_chol(blk):
        if mca_param.get("potrf.blocked_tile_chol", 1):
            return potrf_tile_blocked(blk)
        return potrf_tile(blk)

    def fn(rowk):
        from ..ops.tile_kernels import tri_inv_tile
        diag = rowk[:, :nb]
        diag = 0.5 * (diag + diag.T)
        L = tile_chol(diag.astype(jnp.float32))
        if solve_mode:
            return L.T.astype(rowk.dtype), L
        return L.T.astype(rowk.dtype), L, tri_inv_tile(L)

    return fn


@register_panel_kernel("potrf_left.trsm")
def _seg_trsm_kernel(in_sds, static):
    """(L (nb,nb)[, inv], rest-or-rowk (nb, W)) → solved panel. static
    ``skip``: 1 when the panel input is the rowk carry (diag in its
    first nb columns, skipped), 0 when it is the k=0 state read."""
    (skip,) = static
    nb = in_sds[0].shape[0]
    jnp, mm = _seg_mm()
    solve_mode = mca_param.get("potrf.trsm_hook", "solve") == "solve"

    if solve_mode:
        def fn(L, panel):
            import jax
            rest = panel[:, nb:] if skip else panel
            return jax.scipy.linalg.solve_triangular(
                L.astype(jnp.float32), rest.astype(jnp.float32),
                lower=True)
    else:
        def fn(L, inv, panel):
            del L
            rest = panel[:, nb:] if skip else panel
            return mm(inv, rest)

    return fn


def _potrf_left_segment_fuser(wave, geoms):
    """Lower one left-looking POTRF wave to bucketed SegSteps
    (compiled.panels segmented contract). Wave-shape recognition is
    identical to the monolith fuser; the emitted steps express the same
    math over bucketed panels with masked reads/writes."""
    (geom,) = geoms.values()      # single-collection DAG
    names = sorted(g.tc.name for g in wave)
    nb, NT = geom.nb, geom.nt
    name = geom.name
    solve_mode = mca_param.get("potrf.trsm_hook", "solve") == "solve"

    def wb(tiles):               # bucketed element width of `tiles`
        return bucket_tiles(tiles, NT) * nb

    if names == ["UPDATE"]:
        (grp,) = wave
        ks = {t[1] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        ms = sorted(t[0] for t in grp.tasks)
        lo, hi = ms[0], ms[-1] + 1
        if ms != list(range(lo, hi)) or lo != k or hi != NT:
            return None
        r0, W = k * nb, (NT - k) * nb
        Kb, Wb = wb(k), wb(NT - k)
        return [SegStep(
            kernel="potrf_left.update",
            reads=(SegRead("state", name, 0, r0, r0, nb, Kb, nb),
                   SegRead("state", name, 0, r0, r0, W, Kb, Wb),
                   SegRead("state", name, r0, r0, nb, W, nb, Wb)),
            writes=(SegWrite("carry", "_rowk"),))]

    if names == ["POTRF"]:
        (grp,) = wave
        if len(grp.tasks) != 1:
            return None
        (k,) = grp.tasks[0]
        r0 = k * nb
        carries = (SegWrite("carry", "_L"),) if solve_mode else \
            (SegWrite("carry", "_L"), SegWrite("carry", "_inv"))
        if k == 0:
            reads = (SegRead("state", name, 0, 0, nb, nb, nb, nb),)
        else:
            reads = (SegRead("carry", "_rowk"),)
        return [SegStep(
            kernel="potrf_left.diag", reads=reads,
            writes=(SegWrite("state", name, r0, r0, nb, nb),) + carries)]

    if names == ["TRSM"]:
        (grp,) = wave
        ks = {t[1] for t in grp.tasks}
        if len(ks) != 1:
            return None
        k = ks.pop()
        ms = sorted(t[0] for t in grp.tasks)
        lo, hi = ms[0], ms[-1] + 1
        if ms != list(range(lo, hi)) or lo != k + 1 or hi != NT:
            return None
        r0 = k * nb
        rest_w = (NT - k - 1) * nb
        if k == 0:
            panel = SegRead("state", name, 0, nb, nb, rest_w,
                            nb, wb(NT - 1))
            skip = 0
        else:
            panel = SegRead("carry", "_rowk")
            skip = 1
        reads = (SegRead("carry", "_L"), panel) if solve_mode else \
            (SegRead("carry", "_L"), SegRead("carry", "_inv"), panel)
        return [SegStep(
            kernel="potrf_left.trsm", reads=reads, static=(skip,),
            writes=(SegWrite("state", name, r0, (k + 1) * nb,
                             nb, rest_w),))]

    return None
