"""Tiled Cholesky factorization (right-looking, lower) as a PTG taskpool.

The DPLASMA dpotrf_L equivalent — the reference's headline workload class
(BASELINE.md: "DPLASMA-style tiled Cholesky ≥65% of peak"). Task classes
and dataflow mirror the classic dpotrf JDF:

    POTRF(k):  T = chol(A[k,k] after k SYRK updates)
    TRSM(m,k): C = A[m,k] · T^{-T}
    SYRK(m,k): diag update A[m,m] -= C·Cᵀ            (k-th update)
    GEMM(m,n,k): A[m,n] -= A[m,k]·A[n,k]ᵀ            (k-th update)

Every flow carries its logical tile (FlowSpec.tile), so the taskpool runs
on the host runtime AND on the compiled wavefront/SPMD executors.
"""

from __future__ import annotations

from ..dsl import ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import (gemm_tile, potrf_tile, potrf_tile_blocked,
                                syrk_tile, trsm_tile,
                                trsm_tiles_gemm, trsm_tiles_wide)
from ..utils import mca_param

# The compiled path's batched kernels. "gemm" inverts the shared diagonal
# factor once per wave and runs every solve as an MXU matmul (MAGMA-style;
# measured ~5-8x the wide-solve throughput at nb=2048) at the cost of
# squaring the factor's condition-number contribution — fine for the
# well-conditioned dense-LA regime DPLASMA targets; set "solve" for the
# exact wide triangular solve.
mca_param.register("potrf.trsm_hook", "gemm",
                   help="compiled-path TRSM wave kernel: gemm|solve")
mca_param.register("potrf.blocked_tile_chol", 1,
                   help="use the matmul-rich blocked in-tile Cholesky in "
                        "the compiled path (0 = XLA cholesky)")


def build_potrf(A: TiledMatrix) -> ptg.Taskpool:
    """Build the POTRF taskpool over tiled matrix ``A`` (lower)."""
    NT = A.nt
    if A.mt != A.nt:
        raise ValueError("POTRF needs a square tile grid")
    tp = ptg.Taskpool("potrf", A=A, NT=NT)

    POTRF = tp.task_class(
        "POTRF", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 3 * (g.NT - k) ** 2,
        flows=[ptg.FlowSpec(
            "T", ptg.RW,
            tile=lambda g, k: (g.A, (k, k)),
            ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                        guard=lambda g, k: k == 0),
                 ptg.In(src=("SYRK", lambda g, k: (k, k - 1), "C"),
                        guard=lambda g, k: k > 0)],
            outs=[ptg.Out(dst=("TRSM",
                               lambda g, k: [(m, k) for m in range(k + 1, g.NT)],
                               "L")),
                  ptg.Out(data=lambda g, k: (g.A, (k, k)))])])

    TRSM = tp.task_class(
        "TRSM", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.NT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "L", ptg.READ,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("POTRF", lambda g, m, k: (k,), "T"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("GEMM", lambda g, m, k: (m, k, k - 1), "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[
                    ptg.Out(dst=("SYRK", lambda g, m, k: (m, k), "A")),
                    # row operand of the GEMMs updating row m
                    ptg.Out(dst=("GEMM",
                                 lambda g, m, k: [(m, n, k)
                                                  for n in range(k + 1, m)],
                                 "A")),
                    # transposed operand of the GEMMs updating column m
                    ptg.Out(dst=("GEMM",
                                 lambda g, m, k: [(i, m, k)
                                                  for i in range(m + 1, g.NT)],
                                 "B")),
                    ptg.Out(data=lambda g, m, k: (g.A, (m, k)))])])

    SYRK = tp.task_class(
        "SYRK", params=("m", "k"),
        space=lambda g: ((m, k) for m in range(1, g.NT)
                         for k in range(m)),
        affinity=lambda g, m, k: (g.A, (m, m)),
        priority=lambda g, m, k: 2 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(src=("TRSM", lambda g, m, k: (m, k), "C"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, k: (g.A, (m, m)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, m)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("SYRK", lambda g, m, k: (m, k - 1), "C"),
                            guard=lambda g, m, k: k > 0)],
                outs=[ptg.Out(dst=("SYRK", lambda g, m, k: (m, k + 1), "C"),
                              guard=lambda g, m, k: k < m - 1),
                      ptg.Out(dst=("POTRF", lambda g, m, k: (m,), "T"),
                              guard=lambda g, m, k: k == m - 1)])])

    GEMM = tp.task_class(
        "GEMM", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for m in range(2, g.NT)
                         for n in range(1, m) for k in range(n)),
        affinity=lambda g, m, n, k: (g.A, (m, n)),
        priority=lambda g, m, n, k: (g.NT - k) ** 2 - m - n,
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (m, k)),
                ins=[ptg.In(src=("TRSM", lambda g, m, n, k: (m, k), "C"))]),
            ptg.FlowSpec(
                "B", ptg.READ,
                tile=lambda g, m, n, k: (g.A, (n, k)),
                ins=[ptg.In(src=("TRSM", lambda g, m, n, k: (n, k), "C"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("GEMM",
                                 lambda g, m, n, k: (m, n, k - 1), "C"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[ptg.Out(dst=("GEMM",
                                   lambda g, m, n, k: (m, n, k + 1), "C"),
                              guard=lambda g, m, n, k: k < n - 1),
                      ptg.Out(dst=("TRSM", lambda g, m, n, k: (m, n), "C"),
                              guard=lambda g, m, n, k: k == n - 1)])])

    def _potrf_hook(Ts):
        import jax
        if mca_param.get("potrf.blocked_tile_chol", 1):
            return jax.vmap(potrf_tile_blocked)(Ts) if Ts.shape[0] > 1 \
                else potrf_tile_blocked(Ts[0])[None]
        return jax.vmap(potrf_tile)(Ts)

    @POTRF.body(batch_hook=_potrf_hook)
    def potrf_body(task, T):
        return potrf_tile(T)

    def _trsm_hook(Ls, Cs):
        if mca_param.get("potrf.trsm_hook", "gemm") == "gemm":
            return trsm_tiles_gemm(Ls[0], Cs)
        return trsm_tiles_wide(Ls[0], Cs)

    # compiled-path batched form: every TRSM(m, k) of one wave shares the
    # same factor L = POTRF(k).T, so the whole group is one inversion +
    # wide matmul (or one wide-RHS solve; the executor verifies the
    # shared-L grouping per wave)
    @TRSM.body(batch_hook=_trsm_hook, batch_hook_shared=("L",))
    def trsm_body(task, L, C):
        return trsm_tile(C, L)

    @SYRK.body
    def syrk_body(task, A_, C):
        return syrk_tile(C, A_, alpha=-1.0, beta=1.0)

    @GEMM.body
    def gemm_body(task, A_, B_, C):
        return gemm_tile(C, A_, B_, alpha=-1.0, beta=1.0, tb=True)

    return tp


def potrf_flops(n: int) -> float:
    """Useful FLOPs of an n×n Cholesky (LAPACK count)."""
    return n ** 3 / 3.0 + n ** 2 / 2.0 + n / 6.0
