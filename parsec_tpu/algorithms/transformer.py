"""Transformer block (multi-head attention + FFN) as a PTG taskpool.

The BASELINE.md stretch config ("transformer FFN+attention PTG DAG").
Attention is expressed as a *streaming online-softmax chain over KV
tiles* — per (head h, query tile i), task ATT(h,i,j) folds KV tile j
into a running (accumulator, row-max, row-sum) state:

    ATT(h,i,0) → ATT(h,i,1) → ... → ATT(h,i,T-1) → NORM(h,i)

This is exactly the ring-attention dataflow: distributed over ranks with
KV tiles owner-placed round-robin, the chain's state activation is the
ring's send/recv (SURVEY §5 "long-context": chain dataflow + the
redistribute engine). The compiled XLA twin of this DAG lives in
``parsec_tpu.compiled.ring_attention`` (shard_map + ppermute over a
mesh); this taskpool is the runtime-scheduled, arbitrarily-overlappable
form of the same computation.

Head outputs are gathered per query tile (GATH chain over heads), output
projected, then a 2-layer FFN with residuals; results land in the ``Y``
collection.
"""

from __future__ import annotations

import math

from ..dsl import ptg
from ..data.collection import DataCollection
from ..ops.tile_kernels import matmul_precision


def build_transformer_block(Qc: DataCollection, Kc: DataCollection,
                            Vc: DataCollection, Y: DataCollection,
                            n_heads: int, n_tiles: int, tile_s: int,
                            d_head: int, Wo, W1, W2) -> ptg.Taskpool:
    """Attention+FFN taskpool.

    ``Qc/Kc/Vc`` hold per-(head, seq-tile) tiles of shape
    ``(tile_s, d_head)`` keyed ``(h, i)``; ``Y`` receives per-seq-tile
    block outputs keyed ``(i,)``. ``Wo`` is ``(H·dh, D)``, ``W1/W2`` the
    FFN weights (``(D, F)`` / ``(F, D)``)."""
    import jax.numpy as jnp

    scale = 1.0 / math.sqrt(d_head)
    tp = ptg.Taskpool("transformer", Qc=Qc, Kc=Kc, Vc=Vc, Y=Y,
                      H=n_heads, T=n_tiles, TS=tile_s, DH=d_head,
                      Wo=Wo, W1=W1, W2=W2)

    def _init_state(g, h, i, j):
        return (jnp.zeros((g.TS, g.DH), jnp.float32),       # accumulator
                jnp.full((g.TS,), -jnp.inf, jnp.float32),   # running max
                jnp.zeros((g.TS,), jnp.float32))            # running sum

    ATT = tp.task_class(
        "ATT", params=("h", "i", "j"),
        space=lambda g: ((h, i, j) for h in range(g.H)
                         for i in range(g.T) for j in range(g.T)),
        affinity=lambda g, h, i, j: (g.Kc, (h, j)),   # owner of the KV tile
        priority=lambda g, h, i, j: g.T - j,
        flows=[
            ptg.FlowSpec(
                "Q", ptg.READ,
                tile=lambda g, h, i, j: (g.Qc, (h, i)),
                ins=[ptg.In(data=lambda g, h, i, j: (g.Qc, (h, i)))]),
            ptg.FlowSpec(
                "K", ptg.READ,
                tile=lambda g, h, i, j: (g.Kc, (h, j)),
                ins=[ptg.In(data=lambda g, h, i, j: (g.Kc, (h, j)))]),
            ptg.FlowSpec(
                "V", ptg.READ,
                tile=lambda g, h, i, j: (g.Vc, (h, j)),
                ins=[ptg.In(data=lambda g, h, i, j: (g.Vc, (h, j)))]),
            ptg.FlowSpec(
                "S", ptg.RW,
                ins=[ptg.In(new=_init_state,
                            guard=lambda g, h, i, j: j == 0),
                     ptg.In(src=("ATT", lambda g, h, i, j: (h, i, j - 1),
                                 "S"),
                            guard=lambda g, h, i, j: j > 0)],
                outs=[ptg.Out(dst=("ATT", lambda g, h, i, j: (h, i, j + 1),
                                   "S"),
                              guard=lambda g, h, i, j: j < g.T - 1),
                      ptg.Out(dst=("NORM", lambda g, h, i, j: (h, i), "S"),
                              guard=lambda g, h, i, j: j == g.T - 1)]),
        ])

    NORM = tp.task_class(
        "NORM", params=("h", "i"),
        space=lambda g: ((h, i) for h in range(g.H) for i in range(g.T)),
        affinity=lambda g, h, i: (g.Qc, (h, i)),
        flows=[
            ptg.FlowSpec(
                "S", ptg.READ,
                ins=[ptg.In(src=("ATT", lambda g, h, i: (h, i, g.T - 1),
                                 "S"))]),
            ptg.FlowSpec(
                "O", ptg.WRITE,
                outs=[ptg.Out(dst=("GATH", lambda g, h, i: (i, h), "Hd"))]),
        ])

    GATH = tp.task_class(
        "GATH", params=("i", "h"),
        space=lambda g: ((i, h) for i in range(g.T) for h in range(g.H)),
        affinity=lambda g, i, h: (g.Qc, (0, i)),
        flows=[
            ptg.FlowSpec(
                "Hd", ptg.READ,
                ins=[ptg.In(src=("NORM", lambda g, i, h: (h, i), "O"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                ins=[ptg.In(new=lambda g, i, h: None,
                            guard=lambda g, i, h: h == 0),
                     ptg.In(src=("GATH", lambda g, i, h: (i, h - 1), "C"),
                            guard=lambda g, i, h: h > 0)],
                outs=[ptg.Out(dst=("GATH", lambda g, i, h: (i, h + 1), "C"),
                              guard=lambda g, i, h: h < g.H - 1),
                      ptg.Out(dst=("FFN", lambda g, i, h: (i,), "X"),
                              guard=lambda g, i, h: h == g.H - 1)]),
        ])

    FFN = tp.task_class(
        "FFN", params=("i",),
        space=lambda g: ((i,) for i in range(g.T)),
        affinity=lambda g, i: (g.Qc, (0, i)),
        flows=[
            ptg.FlowSpec(
                "X", ptg.RW,
                ins=[ptg.In(src=("GATH", lambda g, i: (i, g.H - 1), "C"))],
                outs=[ptg.Out(data=lambda g, i: (g.Y, (i,)))]),
        ])

    # TPU incarnation first: chore_for(TPU) picks it on TPU devices, CPU
    # devices fall through to the generic jnp body below — the reference
    # per-device BODY selection (jdf2c.c GPU hook, CUDA BODY sections).
    # The pallas flash kernel computes this tile's partial attention;
    # the result is merged into the carried online-softmax state via the
    # (o, lse) identity, so TPU- and CPU-executed links of one chain
    # interoperate on the same state representation.
    @ATT.body_tpu
    def att_body_tpu(task, Q, K, V, S):
        from ..ops.flash_attention import (flash_attention,
                                           merge_attention_states)
        acc, m, l = S
        o_j, lse_j = flash_attention(
            Q[:, None, :], K[:, None, :], V[:, None, :],
            scale=scale, return_lse=True)
        o_c = acc / jnp.maximum(l, 1e-30)[:, None]
        lse_c = m + jnp.log(jnp.maximum(l, 1e-30))
        o_m, lse_m = merge_attention_states(
            o_c, lse_c, o_j[:, 0].astype(jnp.float32), lse_j[:, 0])
        # back to the chain's (acc, m, l) invariants with m := lse and
        # l := 1 (acc = o·l); any later fold or NORM stays consistent
        return {"S": (o_m, lse_m, jnp.ones_like(lse_m))}

    @ATT.body
    def att_body(task, Q, K, V, S):
        acc, m, l = S
        s = jnp.matmul(Q, K.T, preferred_element_type=jnp.float32,
               precision=matmul_precision()) * scale
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + jnp.matmul(
            p, V, preferred_element_type=jnp.float32,
            precision=matmul_precision())
        return {"S": (acc_new, m_new, l_new)}

    @NORM.body
    def norm_body(task, S, O):
        acc, m, l = S
        return {"O": acc / l[:, None]}

    @GATH.body
    def gath_body(task, Hd, C):
        return {"C": Hd if C is None else jnp.concatenate([C, Hd], axis=-1)}

    @FFN.body
    def ffn_body(task, X):
        prec = matmul_precision()
        a = jnp.matmul(X, Wo, preferred_element_type=jnp.float32,
                       precision=prec)
        hdn = jnp.maximum(
            jnp.matmul(a, W1, preferred_element_type=jnp.float32,
                       precision=prec), 0.0)
        return {"X": a + jnp.matmul(hdn, W2,
                                    preferred_element_type=jnp.float32,
                                    precision=prec)}

    return tp


def reference_block(q, k, v, Wo, W1, W2):
    """Dense numpy reference: per-head softmax attention → concat →
    output proj → FFN with residual. q/k/v: (H, S, dh)."""
    import numpy as np
    H, S, dh = q.shape
    outs = []
    for h in range(H):
        s = (q[h] @ k[h].T) / math.sqrt(dh)
        p = np.exp(s - s.max(axis=-1, keepdims=True))
        p = p / p.sum(axis=-1, keepdims=True)
        outs.append(p @ v[h])
    concat = np.concatenate(outs, axis=-1)          # (S, H·dh)
    a = concat @ Wo
    return a + np.maximum(a @ W1, 0.0) @ W2
