"""Tiled QR factorization (flat-tree DPLASMA dgeqrf) as a PTG taskpool.

The BASELINE.md "PTG dgeqrf reduction-tree stress" config. Task classes
mirror the classic dgeqrf JDF (panel factorization + trailing update per
step k):

    GEQRT(k):     QR of diagonal tile            → Q_k, R
    TSQRT(m,k):   QR of [R; A(m,k)] stacked      → Q₂(m,k), updated R
                  (flat reduction tree down column k: m = k+1 .. MT-1)
    UNMQR(k,n):   row-panel update A(k,n) ← Q_kᵀ·A(k,n)
    TSMQR(m,n,k): stacked-pair update [C(k,n); A(m,n)] ← Q₂(m,k)ᵀ·[..]

On completion A holds R in its upper-triangular tile blocks and zeros
below (V/T storage is a compact-BLAS artifact the functional dataflow
does not keep — see ops/tile_kernels.py). Validation identity:
AᵀA = RᵀR (orthogonal-invariant, sign-independent).

Orthogonal factors flow task→task as values (no collection placement),
so this taskpool exercises the host runtime's value-flow path; flows that
live in A carry tile placements for distribution.
"""

from __future__ import annotations

from ..dsl import ptg
from ..data.matrix import TiledMatrix
from ..ops.tile_kernels import geqrt_tile, tsmqr_tile, tsqrt_tile, unmqr_tile


def build_geqrf(A: TiledMatrix) -> ptg.Taskpool:
    """Build the GEQRF taskpool over tiled matrix ``A`` (MT ≥ NT)."""
    MT, NT = A.mt, A.nt
    if MT < NT:
        raise ValueError("GEQRF needs MT >= NT (tall or square tile grid)")
    nb = A.nb
    # Scratch collections give the orthogonal-factor flows tile
    # placements so the compiled wavefront/tile-dict executors can run
    # the DAG (values would otherwise flow only task→task); the host
    # runtime ignores them. Qs holds the (nb,nb) diagonal factors keyed
    # (k, 0); Q2s the (2nb,2nb) TSQRT factors keyed (m, k) — only the
    # strictly-below-diagonal keys actually used, so the stacked store
    # doesn't materialize (or copy per wave) the unused upper half.
    Qs = TiledMatrix(NT * nb, nb, nb, nb, name=f"{A.name}_Qs")

    class _TSQRTFactors(TiledMatrix):
        def keys(self):
            return [(m, k) for k in range(NT)
                    for m in range(k + 1, MT)]

    Q2s = _TSQRTFactors(MT * 2 * nb, NT * 2 * nb, 2 * nb, 2 * nb,
                        name=f"{A.name}_Q2s")
    Qs.scratch = Q2s.scratch = True   # intra-DAG temporaries only
    tp = ptg.Taskpool("geqrf", A=A, MT=MT, NT=NT, Qs=Qs, Q2s=Q2s)

    GEQRT = tp.task_class(
        "GEQRT", params=("k",),
        space=lambda g: ((k,) for k in range(g.NT)),
        affinity=lambda g, k: (g.A, (k, k)),
        priority=lambda g, k: 4 * (g.NT - k) ** 2,
        flows=[
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, k: (g.A, (k, k)),
                ins=[ptg.In(data=lambda g, k: (g.A, (k, k)),
                            guard=lambda g, k: k == 0),
                     ptg.In(src=("TSMQR", lambda g, k: (k, k, k - 1), "A2"),
                            guard=lambda g, k: k > 0)]),
            ptg.FlowSpec(
                "Q", ptg.WRITE,
                tile=lambda g, k: (g.Qs, (k, 0)),
                outs=[ptg.Out(dst=("UNMQR",
                               lambda g, k: [(k, n)
                                             for n in range(k + 1, g.NT)],
                               "Q"))]),
            ptg.FlowSpec(
                "R", ptg.WRITE,
                tile=lambda g, k: (g.A, (k, k)),
                outs=[ptg.Out(dst=("TSQRT", lambda g, k: (k + 1, k), "R"),
                              guard=lambda g, k: k + 1 < g.MT),
                      ptg.Out(data=lambda g, k: (g.A, (k, k)),
                              guard=lambda g, k: k + 1 >= g.MT)]),
        ])

    TSQRT = tp.task_class(
        "TSQRT", params=("m", "k"),
        space=lambda g: ((m, k) for k in range(g.NT)
                         for m in range(k + 1, g.MT)),
        affinity=lambda g, m, k: (g.A, (m, k)),
        priority=lambda g, m, k: 3 * (g.NT - k) ** 2 - m,
        flows=[
            ptg.FlowSpec(
                "R", ptg.RW,
                tile=lambda g, m, k: (g.A, (k, k)),
                ins=[ptg.In(src=("GEQRT", lambda g, m, k: (k,), "R"),
                            guard=lambda g, m, k: m == k + 1),
                     ptg.In(src=("TSQRT", lambda g, m, k: (m - 1, k), "R"),
                            guard=lambda g, m, k: m > k + 1)],
                outs=[ptg.Out(dst=("TSQRT", lambda g, m, k: (m + 1, k), "R"),
                              guard=lambda g, m, k: m + 1 < g.MT),
                      ptg.Out(data=lambda g, m, k: (g.A, (k, k)),
                              guard=lambda g, m, k: m + 1 >= g.MT)]),
            ptg.FlowSpec(
                "A", ptg.READ,
                tile=lambda g, m, k: (g.A, (m, k)),
                ins=[ptg.In(data=lambda g, m, k: (g.A, (m, k)),
                            guard=lambda g, m, k: k == 0),
                     ptg.In(src=("TSMQR", lambda g, m, k: (m, k, k - 1),
                                 "A2"),
                            guard=lambda g, m, k: k > 0)]),
            ptg.FlowSpec(
                "Q2", ptg.WRITE,
                tile=lambda g, m, k: (g.Q2s, (m, k)),
                outs=[ptg.Out(dst=("TSMQR",
                               lambda g, m, k: [(m, n, k)
                                                for n in range(k + 1, g.NT)],
                               "Q2"))]),
            # the V block of A(m,k) is consumed; R lives strictly above
            ptg.FlowSpec(
                "Z", ptg.WRITE,
                tile=lambda g, m, k: (g.A, (m, k)),
                outs=[ptg.Out(data=lambda g, m, k: (g.A, (m, k)))]),
        ])

    UNMQR = tp.task_class(
        "UNMQR", params=("k", "n"),
        space=lambda g: ((k, n) for k in range(g.NT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, k, n: (g.A, (k, n)),
        priority=lambda g, k, n: 3 * (g.NT - k) ** 2 - n,
        flows=[
            ptg.FlowSpec(
                "Q", ptg.READ,
                tile=lambda g, k, n: (g.Qs, (k, 0)),
                ins=[ptg.In(src=("GEQRT", lambda g, k, n: (k,), "Q"))]),
            ptg.FlowSpec(
                "C", ptg.RW,
                tile=lambda g, k, n: (g.A, (k, n)),
                ins=[ptg.In(data=lambda g, k, n: (g.A, (k, n)),
                            guard=lambda g, k, n: k == 0),
                     ptg.In(src=("TSMQR", lambda g, k, n: (k, n, k - 1),
                                 "A2"),
                            guard=lambda g, k, n: k > 0)],
                outs=[ptg.Out(dst=("TSMQR",
                                   lambda g, k, n: (k + 1, n, k), "C1"),
                              guard=lambda g, k, n: k + 1 < g.MT),
                      ptg.Out(data=lambda g, k, n: (g.A, (k, n)),
                              guard=lambda g, k, n: k + 1 >= g.MT)]),
        ])

    TSMQR = tp.task_class(
        "TSMQR", params=("m", "n", "k"),
        space=lambda g: ((m, n, k) for k in range(g.NT)
                         for m in range(k + 1, g.MT)
                         for n in range(k + 1, g.NT)),
        affinity=lambda g, m, n, k: (g.A, (m, n)),
        priority=lambda g, m, n, k: (g.NT - k) ** 2 - m - n,
        flows=[
            ptg.FlowSpec(
                "Q2", ptg.READ,
                tile=lambda g, m, n, k: (g.Q2s, (m, k)),
                ins=[ptg.In(src=("TSQRT", lambda g, m, n, k: (m, k),
                                 "Q2"))]),
            # running row-k tile C(k,n), reduced down the column
            ptg.FlowSpec(
                "C1", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (k, n)),
                ins=[ptg.In(src=("UNMQR", lambda g, m, n, k: (k, n), "C"),
                            guard=lambda g, m, n, k: m == k + 1),
                     ptg.In(src=("TSMQR",
                                 lambda g, m, n, k: (m - 1, n, k), "C1"),
                            guard=lambda g, m, n, k: m > k + 1)],
                outs=[ptg.Out(dst=("TSMQR",
                                   lambda g, m, n, k: (m + 1, n, k), "C1"),
                              guard=lambda g, m, n, k: m + 1 < g.MT),
                      ptg.Out(data=lambda g, m, n, k: (g.A, (k, n)),
                              guard=lambda g, m, n, k: m + 1 >= g.MT)]),
            # trailing tile A(m,n)
            ptg.FlowSpec(
                "A2", ptg.RW,
                tile=lambda g, m, n, k: (g.A, (m, n)),
                ins=[ptg.In(data=lambda g, m, n, k: (g.A, (m, n)),
                            guard=lambda g, m, n, k: k == 0),
                     ptg.In(src=("TSMQR",
                                 lambda g, m, n, k: (m, n, k - 1), "A2"),
                            guard=lambda g, m, n, k: k > 0)],
                outs=[
                    ptg.Out(dst=("GEQRT", lambda g, m, n, k: (k + 1,), "A"),
                            guard=lambda g, m, n, k: m == k + 1 and
                            n == k + 1),
                    ptg.Out(dst=("TSQRT", lambda g, m, n, k: (m, k + 1), "A"),
                            guard=lambda g, m, n, k: m > k + 1 and
                            n == k + 1),
                    ptg.Out(dst=("UNMQR", lambda g, m, n, k: (k + 1, n), "C"),
                            guard=lambda g, m, n, k: m == k + 1 and
                            n > k + 1),
                    ptg.Out(dst=("TSMQR",
                                 lambda g, m, n, k: (m, n, k + 1), "A2"),
                            guard=lambda g, m, n, k: m > k + 1 and
                            n > k + 1),
                ]),
        ])

    @GEQRT.body
    def geqrt_body(task, A_, Qv, Rv):
        Q, R = geqrt_tile(A_)
        return {"Q": Q, "R": R}

    @TSQRT.body
    def tsqrt_body(task, R, A_, Q2v, Zv):
        import jax.numpy as jnp
        Q2, Rn = tsqrt_tile(R, A_)
        return {"R": Rn, "Q2": Q2, "Z": jnp.zeros_like(A_)}

    @UNMQR.body
    def unmqr_body(task, Q, C):
        return {"C": unmqr_tile(Q, C)}

    @TSMQR.body
    def tsmqr_body(task, Q2, C1, A2):
        nC1, nA2 = tsmqr_tile(Q2, C1, A2)
        return {"C1": nC1, "A2": nA2}

    return tp


def geqrf_flops(m: int, n: int) -> float:
    """Useful FLOPs of an m×n QR (LAPACK count, m ≥ n)."""
    return 2.0 * m * n * n - 2.0 * n ** 3 / 3.0 + m * n + n * n / 2.0
